#!/usr/bin/env python3
"""Latency-budget plane smoke test: drive zillow serve jobs through the
JobService with tracing ON and assert the ISSUE-19 acceptance chain —
every job's exclusive bucket vector sums to >= 90% of its end-to-end
wall (``unattributed_frac < 0.10``), the dominant bucket is stable
across two warm runs, and the SAME attribution reaches every surface:
the ``tuplex_critpath_*`` Prometheus families, the history ``critpath``
event the dashboard budget panel renders, and the `python -m tuplex_tpu
whyslow` readout.

Run directly (CI wires it as a tier-1 test via tests/test_critpath.py):

    JAX_PLATFORMS=cpu python scripts/critpath_smoke.py

Exits 0 and prints one `critpath-smoke OK ...` line on success; any
assertion failure is a non-zero exit. CRITPATH_SMOKE_ROWS overrides the
input size (default 400 — matching tests/test_zillow_model.py so a warm
AOT artifact cache skips the XLA compiles)."""

from __future__ import annotations

import io
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))          # run from anywhere

N_ROWS = int(os.environ.get("CRITPATH_SMOKE_ROWS", "400"))


def main() -> int:
    import tuplex_tpu
    from tuplex_tpu.models import zillow
    from tuplex_tpu.runtime import critpath, telemetry
    from tuplex_tpu.serve import JobService, request_from_dataset

    with tempfile.TemporaryDirectory() as d:
        data = os.path.join(d, "zillow.csv")
        zillow.generate_csv(data, N_ROWS, seed=7)
        ctx = tuplex_tpu.Context({
            "tuplex.scratchDir": os.path.join(d, "scratch"),
            "tuplex.logDir": d,
            "tuplex.webui.enable": True,
            "tuplex.tpu.trace": True,
        })
        assert critpath.enabled(), \
            "critpath disabled (TUPLEX_CRITPATH=0 set?) — nothing to smoke"
        svc = JobService(ctx.options_store, recorder=ctx.recorder)
        want = zillow.run_reference_python(data)

        budgets = {}
        # two warm-up jobs pay the compile plane (the general-path
        # resolve stage only compiles on first USE, so one warm-up still
        # leaves r1 paying its XLA leg); r1/r2 are the steady-state pair
        # the dominant-bucket stability check compares
        for name in ("warm", "warm2", "r1", "r2"):
            h = svc.submit(request_from_dataset(
                zillow.build_pipeline(ctx.csv(data)), name=name,
                tenant="smoke"))
            assert h.wait(1200) == "done", (name, h.state, h.error)
            assert h.result() == want, f"{name}: output changed"
            lb = h.latency_budget()
            assert lb and lb.get("buckets"), (name, lb)
            budgets[name] = lb

        # --- coverage: buckets sum to >= 90% of each job's wall --------
        for name, lb in budgets.items():
            s = sum(lb["buckets"].values())
            assert abs(s - lb["wall_s"]) < 1e-4, (name, s, lb["wall_s"])
            assert lb["unattributed_frac"] < 0.10, \
                (name, lb["unattributed_frac"], lb["buckets"])

        # --- stability: warm runs agree on the dominant bucket ---------
        d1, d2 = budgets["r1"]["dominant"], budgets["r2"]["dominant"]
        assert d1 == d2, f"dominant bucket unstable across warm runs: " \
            f"{d1} vs {d2} ({budgets['r1']['buckets']} vs " \
            f"{budgets['r2']['buckets']})"

        # --- surface parity 1: Prometheus families ---------------------
        text = telemetry.render_prometheus()
        for fam in ("tuplex_critpath_jobs", "tuplex_critpath_budget_seconds",
                    "tuplex_critpath_wall_ewma_seconds",
                    "tuplex_critpath_unattributed_frac"):
            assert fam in text, f"{fam} missing from /metrics exposition"
        assert 'tenant="smoke"' in text, "tenant label missing"
        # the exposed per-bucket gauge must carry the dominant bucket the
        # job budgets reported
        assert f'bucket="{d1}"' in text, (d1, "missing from /metrics")

        svc.close()

        # --- surface parity 2: history event + dashboard panel ---------
        hist = os.path.join(d, "tuplex_history.jsonl")
        cp_evs = []
        with open(hist) as fp:
            for line in fp:
                r = json.loads(line)
                if r.get("event") == "critpath":
                    cp_evs.append(r)
        assert len(cp_evs) == 4, (len(cp_evs), "critpath events")
        for ev in cp_evs:
            assert ev["buckets"] and ev["wall_s"] > 0, ev
        from tuplex_tpu.history.recorder import render_report

        html = open(render_report(d)).read()
        assert "latency budget" in html, "dashboard budget panel missing"
        assert "cptrack" in html, "budget strip missing"
        assert "onpath" in html, "waterfall critical-path outline missing"

        # --- surface parity 3: the whyslow CLI reads the same record ---
        from tuplex_tpu.utils.whyslow import main as whyslow_main

        buf = io.StringIO()
        stdout, sys.stdout = sys.stdout, buf
        try:
            whyslow_main(d)
        finally:
            sys.stdout = stdout
        out = buf.getvalue()
        assert "dominant " + d1 in out, (d1, out[:800])
        assert "critical path" in out, out[:800]
        # parity on the numbers, not just presence: whyslow prints the
        # dominant bucket's milliseconds from the same history record
        dom_ms = budgets["r2"]["buckets"][d2] * 1e3
        assert f"{dom_ms:.1f}" in out, (dom_ms, out[:1500])

        ctx.close()
        print(f"critpath-smoke OK — 4 job(s), dominant {d1}, "
              f"unattributed "
              f"{max(b['unattributed_frac'] for b in budgets.values()):.4f}"
              f" worst-case, surfaces agree (/metrics + dashboard + "
              f"whyslow)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
