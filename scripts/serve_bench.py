#!/usr/bin/env python3
"""p99 latency harness: N concurrent vs N serial jobs on one warm service.

Closes the ROADMAP item "a p99-latency benchmark harness comparing
concurrent submission vs serial": the same N isomorphic zillow jobs are
run twice through one `tuplex_tpu.serve.JobService` —

  * **serial**: submit, wait, submit, wait ... (a client with no
    concurrency; every job has the device to itself);
  * **concurrent**: submit all N, then wait — admission, the
    deficit-weighted scheduler and the shared compile plane all under
    load, which is what the service actually sees in production.

Per-job latency is END-TO-END (submission to terminal state, queue waits
included — the number a caller experiences, not device time). The
harness prints ONE BENCH-style JSON line with exact (sorted-sample)
percentiles per mode plus the service's own streaming-histogram readout
of the CONCURRENT mode (runtime/telemetry `serve_job_latency_seconds`,
isolated by mode-prefixed tenant labels) so the low-overhead telemetry
pipeline is cross-checked against ground truth every run:

    {"metric": "serve_zillow_p99_latency_s", "value": <concurrent p99>,
     "unit": "s", "n_jobs": N, "rows": R,
     "concurrent": {"p50":..,"p95":..,"p99":..,"max":..,"mean":..,
                    "wall_s":..,"jobs_per_s":..},
     "serial": {...}, "speedup_wall": serial_wall/concurrent_wall,
     "telemetry_p99": <histogram estimate>}

Usage:

    JAX_PLATFORMS=cpu python scripts/serve_bench.py            # 8 jobs
    python scripts/serve_bench.py --jobs 16 --rows 20000 --slots 2
    python scripts/serve_bench.py --smoke    # tiny tier-1 CI variant
    python scripts/serve_bench.py --out BENCH_SERVE.json
"""

from __future__ import annotations

import argparse
import json
import math
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))          # run from anywhere


def _pct(sorted_vals: list, q: float) -> float:
    """Exact linear-interpolated quantile of a sorted sample."""
    if not sorted_vals:
        return 0.0
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    pos = q * (len(sorted_vals) - 1)
    lo = math.floor(pos)
    hi = math.ceil(pos)
    frac = pos - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac


def _mode_report(latencies: list, wall_s: float) -> dict:
    vals = sorted(latencies)
    return {
        "p50": round(_pct(vals, 0.50), 4),
        "p95": round(_pct(vals, 0.95), 4),
        "p99": round(_pct(vals, 0.99), 4),
        "max": round(vals[-1], 4) if vals else 0.0,
        "mean": round(sum(vals) / len(vals), 4) if vals else 0.0,
        "wall_s": round(wall_s, 4),
        "jobs_per_s": round(len(vals) / wall_s, 3) if wall_s > 0 else 0.0,
    }


def _job_latency(handle) -> float:
    """End-to-end seconds: admission queue wait + running wall (the
    scheduler stamps both on the record)."""
    st = handle._rec.stats
    return float(st.get("queued_s") or 0.0) + float(st.get("wall_s") or 0.0)


def _run_mode(svc, reqs_fn, concurrent: bool, want) -> tuple[list, float]:
    t0 = time.perf_counter()
    if concurrent:
        handles = [svc.submit(r) for r in reqs_fn()]
        for h in handles:
            assert h.wait(1200) == "done", (h.name, h.state, h.error)
    else:
        handles = []
        for r in reqs_fn():
            h = svc.submit(r)
            assert h.wait(1200) == "done", (h.name, h.state, h.error)
            handles.append(h)
    wall = time.perf_counter() - t0
    for h in handles:
        assert h.result() == want, f"{h.name}: wrong output"
    return [_job_latency(h) for h in handles], wall


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="concurrent-vs-serial p99 latency through JobService")
    ap.add_argument("--jobs", type=int, default=8)
    ap.add_argument("--rows", type=int, default=5000,
                    help="zillow rows per job input")
    ap.add_argument("--slots", type=int, default=1,
                    help="tuplex.serve.slots (in-flight dispatches)")
    ap.add_argument("--respec", choices=("on", "off"), default="on",
                    help="tuplex.serve.respec for the A/B required by "
                         "the self-healing acceptance: p99 with the "
                         "respec controller active must be within noise "
                         "of respec-off")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny tier-1 CI variant (3 jobs x 200 rows)")
    ap.add_argument("--out", default=None,
                    help="also write the JSON line to this path")
    args = ap.parse_args(argv)
    if args.smoke:
        args.jobs, args.rows = 3, 200

    import tuplex_tpu
    from tuplex_tpu.models import zillow
    from tuplex_tpu.runtime import telemetry
    from tuplex_tpu.serve import JobService, request_from_dataset

    with tempfile.TemporaryDirectory() as d:
        csvs = []
        for i in range(args.jobs):
            p = os.path.join(d, f"zillow-{i}.csv")
            if i == 0:
                zillow.generate_csv(p, args.rows, seed=7)
            else:
                shutil.copy(csvs[0], p)    # isomorphic: one compile set
            csvs.append(p)
        want = zillow.run_reference_python(csvs[0])

        ctx = tuplex_tpu.Context({
            "tuplex.scratchDir": os.path.join(d, "scratch"),
            "tuplex.serve.slots": args.slots,
            "tuplex.serve.queueDepth": max(64, 2 * args.jobs),
            "tuplex.serve.respec": args.respec == "on",
            # span tracing feeds the latency-budget plane (runtime/
            # critpath): per-tenant bucket vectors ride the tenants block
            # below and bench_diff gates the interpreter share +
            # unattributed_frac
            "tuplex.tpu.trace": True,
        })
        svc = JobService(ctx.options_store)

        def reqs(mode):
            # the mode rides the tenant label so the streaming-histogram
            # cross-check below can read the CONCURRENT distribution
            # alone — a merged warm+serial+concurrent p99 would compare
            # apples to the whole fruit bowl
            return [request_from_dataset(
                zillow.build_pipeline(ctx.csv(csvs[i])), name=f"j{i}",
                tenant=f"{mode}-t{i % 4}") for i in range(args.jobs)]

        # warm the compile plane once so both modes measure dispatch, not
        # the first job's XLA compiles (the AOT store makes run 2 free)
        h = svc.submit(request_from_dataset(
            zillow.build_pipeline(ctx.csv(csvs[0])), name="warm"))
        assert h.wait(1200) == "done", (h.state, h.error)

        serial_lat, serial_wall = _run_mode(
            svc, lambda: reqs("ser"), False, want)
        conc_lat, conc_wall = _run_mode(
            svc, lambda: reqs("conc"), True, want)

        # the service's own streaming histogram for the CONCURRENT mode
        # only (its tenant labels carry the mode) — the cheap always-on
        # estimate next to the harness's exact sorted-sample numbers
        conc_hist = telemetry.Histogram()
        for (name, lk), h in telemetry.registry().histograms().items():
            if name == "serve_job_latency_seconds" \
                    and dict(lk).get("tenant", "").startswith("conc-"):
                conc_hist.merge(h)
        tele = conc_hist.percentiles()

        # per-tenant exception plane (runtime/excprof, scoped like the
        # xferstats counter families): the exception RATE and which
        # resolve tier the deviant rows landed on, per tenant — latency
        # percentiles alone can hide a tenant quietly paying the
        # interpreter tax on every row. bench_diff gates the dotted
        # exception_rate / tier_mix.interpreter keys like perf.
        from tuplex_tpu.runtime import excprof

        tenants = {}
        for t in sorted(excprof.scopes()):
            rep = excprof.scope_report(t)
            if not rep["rows"]:
                continue
            tenants[t] = {
                "exception_rate": round(rep["exception_rate"], 5),
                "tier_mix": {k: round(v, 4)
                             for k, v in rep["tier_mix"].items()},
                "drift_score": round(rep["drift_score"], 4),
            }

        # per-tenant latency budgets (runtime/critpath): the EWMA bucket
        # baseline each tenant converged to over its jobs, plus the
        # unattributed remainder — the dotted latency_budget.* keys gate
        # in bench_diff (interpreter-resolve share and unattributed_frac
        # must not grow)
        from tuplex_tpu.runtime import critpath

        if critpath.enabled():
            for t in critpath.tenants():
                rep = critpath.tenant_report(t)
                if not rep or not rep.get("jobs"):
                    continue
                row = tenants.setdefault(t, {})
                row["latency_budget"] = {
                    k: round(float(v), 6)
                    for k, v in (rep["baseline"] or {}).items()}
                row["unattributed_frac"] = round(
                    float(rep.get("unattributed_ewma") or 0.0), 4)

        result = {
            "metric": "serve_zillow_p99_latency_s",
            "value": round(_pct(sorted(conc_lat), 0.99), 4),
            "unit": "s",
            "n_jobs": args.jobs,
            "rows": args.rows,
            "slots": args.slots,
            "concurrent": _mode_report(conc_lat, conc_wall),
            "serial": _mode_report(serial_lat, serial_wall),
            "speedup_wall": round(serial_wall / conc_wall, 3)
            if conc_wall > 0 else 0.0,
            "telemetry_p99": round(tele["p99"], 4),
            "telemetry_count": tele["count"],
            "tenants": tenants,
        }
        svc.close()
        ctx.close()
    line = json.dumps(result)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as fp:
            fp.write(line + "\n")
    if args.smoke:
        # CI gate: the telemetry pipeline saw exactly the concurrent jobs
        # in its conc-* series, and its estimate agrees with the exact
        # concurrent p99 (log buckets are ±~12% + the exact-max clamp).
        # Skipped only under the TUPLEX_TELEMETRY=0 kill switch.
        from tuplex_tpu.runtime import telemetry as _T

        if _T.enabled():
            assert result["telemetry_count"] == args.jobs, result
            assert result["telemetry_p99"] >= 0.8 * result["value"], result
        from tuplex_tpu.runtime import excprof as _EX

        if _EX.enabled():
            # the exception plane saw every tenant: rows were attributed
            # per scope even when nothing erred (rate 0 is a statement,
            # not an absence)
            assert result["tenants"], result
        print("serve-bench OK", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
