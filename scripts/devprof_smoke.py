#!/usr/bin/env python3
"""Device-profiling smoke test: run the zillow model pipeline with cost
attribution ON (the default) and assert the ISSUE-12 acceptance chain —
every compiled stage carries a StageCost (XLA cost/memory analysis),
measured device seconds are positive, the roofline fraction is a real
fraction in (0, 1], and the SAME numbers appear in the Prometheus
/metrics exposition and the persisted stage index compilestats reads.

Run directly (CI wires it as a tier-1 test via tests/test_devprof.py):

    JAX_PLATFORMS=cpu python scripts/devprof_smoke.py

Exits 0 and prints one `devprof-smoke OK ...` line on success; any
assertion failure is a non-zero exit. DEVPROF_SMOKE_ROWS overrides the
input size (default 400 — matching tests/test_zillow_model.py so a warm
AOT artifact cache skips the XLA compiles)."""

from __future__ import annotations

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))          # run from anywhere

N_ROWS = int(os.environ.get("DEVPROF_SMOKE_ROWS", "400"))


def main() -> int:
    import tuplex_tpu
    from tuplex_tpu.models import zillow
    from tuplex_tpu.runtime import devprof, telemetry

    with tempfile.TemporaryDirectory() as d:
        data = os.path.join(d, "zillow.csv")
        zillow.generate_csv(data, N_ROWS, seed=7)
        ctx = tuplex_tpu.Context()
        assert devprof.enabled(), \
            "devprof disabled (TUPLEX_DEVPROF=0 set?) — nothing to smoke"
        got = zillow.build_pipeline(ctx.csv(data)).collect()
        assert got == zillow.run_reference_python(data), \
            "device profiling changed pipeline output"

        compiled = [m for m in ctx.metrics.stages
                    if m.get("tier") == "compiled"
                    and m.get("fast_path_s", 0) > 0]
        assert compiled, "no stage ran on the compiled tier"
        for i, m in enumerate(compiled):
            # every compiled stage: a dispatch window was measured ...
            assert m.get("device_dispatches", 0) > 0, (i, m)
            assert m.get("device_s", 0.0) > 0.0, (i, m)
            # ... the executable's StageCost was harvested or recovered
            assert m.get("flops", 0.0) > 0.0, \
                (i, "no StageCost (cost_analysis returned nothing?)", m)
            assert m.get("hbm_peak", 0) > 0, (i, m)
            # ... and the roofline math produced a real fraction
            rf = m.get("roofline_frac")
            assert rf is not None and 0.0 < rf <= 1.0, (i, rf, m)

        assert ctx.metrics.deviceTime() > 0.0
        assert ctx.metrics.as_dict()["device_s"] > 0.0

        # the same numbers reach the Prometheus exposition ...
        text = telemetry.render_prometheus()
        for fam in ("tuplex_devprof_stage_device_seconds",
                    "tuplex_devprof_stage_flops",
                    "tuplex_devprof_stage_hbm_peak_bytes",
                    "tuplex_devprof_stage_roofline_frac",
                    "tuplex_device_dispatch_seconds_bucket"):
            assert fam in text, f"{fam} missing from /metrics exposition"

        # ... and the persisted stage index `compilestats` queries
        idx = devprof.load_stage_index()
        with_cost = [e for e in idx.values()
                     if e.get("analysis") is not None]
        assert with_cost, f"stage index has no analysis records: {idx}"

        peaks = devprof.platform_peaks()
        print(f"devprof-smoke OK — {len(compiled)} compiled stage(s), "
              f"device {ctx.metrics.deviceTime() * 1e3:.1f} ms, "
              f"peaks {peaks.name} ({peaks.kind}), rows={len(got)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
