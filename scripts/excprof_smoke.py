#!/usr/bin/env python3
"""Exception-plane smoke test: run the zillow model pipeline with the
exception profiler ON (the default) and assert the ISSUE-13 acceptance
chain — the plan-time baseline was captured, every stage that saw rows
carries the excprof stage metrics (rows_seen / exception_rate / per-tier
retired counts), the dirty rows' codes are attributed per stage x op x
code AND land inside the plan-time expected inventory (zero unexpected
codes on the bundled generator), sampled deviant rows were captured, and
the SAME numbers appear in the Prometheus /metrics exposition, the
Metrics.as_dict() bench keys and the history excprof event the
dashboard + `excstats` CLI read.

Run directly (CI wires it as a tier-1 test via tests/test_excprof.py):

    JAX_PLATFORMS=cpu python scripts/excprof_smoke.py

Exits 0 and prints one `excprof-smoke OK ...` line on success; any
assertion failure is a non-zero exit. EXCPROF_SMOKE_ROWS overrides the
input size (default 400 — matching tests/test_zillow_model.py so a warm
AOT artifact cache skips the XLA compiles)."""

from __future__ import annotations

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))          # run from anywhere

N_ROWS = int(os.environ.get("EXCPROF_SMOKE_ROWS", "400"))


def main() -> int:
    import tuplex_tpu
    from tuplex_tpu.runtime import excprof, telemetry
    from tuplex_tpu.models import zillow

    with tempfile.TemporaryDirectory() as d:
        data = os.path.join(d, "zillow.csv")
        zillow.generate_csv(data, N_ROWS, seed=7)
        ctx = tuplex_tpu.Context({"tuplex.logDir": d,
                                  "tuplex.webui.enable": True})
        assert excprof.enabled(), \
            "excprof disabled (TUPLEX_EXCPROF=0 set?) — nothing to smoke"
        got = zillow.build_pipeline(ctx.csv(data)).collect()
        assert got == zillow.run_reference_python(data), \
            "exception profiling changed pipeline output"

        # plan-time baselines were captured for the executed stages
        bases = excprof.baselines()
        assert bases, "no plan-time baseline captured"

        # the zillow generator's ~4-6% dirt must show up as attributed
        # exception traffic: rows seen, a positive-but-small rate, codes
        # keyed (code, op) inside the plan inventory — zero unexpected
        reps = excprof.reports()
        assert reps, "no exception-plane reports"
        seen = sum(r["rows"] for r in reps.values())
        errs = sum(r["errs"] for r in reps.values())
        assert seen >= N_ROWS, (seen, N_ROWS)
        assert errs > 0, "zillow dirt produced no exception rows"
        coded = {k: r for k, r in reps.items() if r["codes"]}
        assert coded, "no per-code attribution"
        for key, r in coded.items():
            assert r["unexpected"] == 0, \
                (key, "codes outside the plan-time inventory", r)
            base = r.get("baseline")
            assert base is not None and base["codes"], (key, r)
        # ... and each erring row was attributed to a resolve tier
        tiers = {}
        for r in reps.values():
            for t, n in r["tiers"].items():
                tiers[t] = tiers.get(t, 0) + n
        assert tiers, "no resolve-tier attribution"

        # sampled deviant rows: bounded, repr-truncated
        samples = excprof.samples()
        assert samples, "no deviant rows sampled"
        for (key, code), caps in samples.items():
            assert 0 < len(caps) <= 3, (key, code, caps)
            assert all(len(c) <= 161 for c in caps), (key, code, caps)

        # the stage metrics carry the flat excprof keys -> bench JSON
        ex_stages = [m for m in ctx.metrics.stages if m.get("rows_seen")]
        assert ex_stages, "no stage metrics carry rows_seen"
        md = ctx.metrics.as_dict()
        assert md["exception_rate"] > 0.0, md["exception_rate"]
        assert 0.0 < md["exception_rate"] < 0.5, md["exception_rate"]
        mix = md["resolve_tier_mix"]
        assert abs(sum(mix.values()) - 1.0) < 1e-6, mix

        # the same numbers reach the Prometheus exposition ...
        text = telemetry.render_prometheus()
        for fam in ("tuplex_excprof_rows_total",
                    "tuplex_excprof_exception_rows",
                    "tuplex_excprof_exception_rate",
                    "tuplex_excprof_resolve_tier_rows",
                    "tuplex_excprof_drift_score",
                    "tuplex_excprof_respecialize_recommended"):
            assert fam in text, f"{fam} missing from /metrics exposition"

        # ... and the history excprof event the dashboard / excstats read
        hist = os.path.join(d, "tuplex_history.jsonl")
        exev = None
        with open(hist) as fp:
            for line in fp:
                r = json.loads(line)
                if r.get("event") == "excprof":
                    exev = r
        assert exev is not None, "no excprof event in the history file"
        assert exev["stages"] and exev["samples"], exev
        from tuplex_tpu.history.recorder import render_report

        html = open(render_report(d)).read()
        assert "exception plane" in html, "dashboard drift panel missing"

        print(f"excprof-smoke OK — {len(reps)} stage(s), "
              f"{errs}/{seen} rows off the fast path "
              f"(rate {md['exception_rate'] * 100:.2f}%), tiers {tiers}, "
              f"{len(samples)} sampled stage x code bucket(s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
