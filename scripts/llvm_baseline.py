#!/usr/bin/env python3
"""Record the reference LLVM engine's Zillow Z1 rows/s — the `vs_llvm`
denominator bench.py reports.

Two modes, honestly labeled:

  * **measured** — the real `tuplex` package (tuplex/tuplex, the LLVM
    engine) is importable: run its Z1 pipeline over the same synthetic
    zillow CSV bench.py uses (warmup + best-of-N, single thread to match
    this repo's single-core driver) and record actual rows/s.
  * **estimated** — the reference engine is not installed (this container
    has no C++ toolchain build of it): record
    ``interpreter_rows_per_sec x ESTIMATE_FACTOR`` where the interpreter
    number IS measured on this machine (the same pure-CPython Z1
    implementation bench.py uses as `vs_baseline`) and the factor is the
    order-of-magnitude single-thread compiled-vs-CPython speedup the
    SIGMOD'21 paper reports for Z1-class pipelines. The JSON and the
    BASELINE.md row both carry ``kind: estimated`` — an estimate is never
    silently presented as a measurement, and re-running this script on a
    machine with the reference installed upgrades it in place.

Writes BASELINE_LLVM.json (machine-readable, read by bench.py) and appends
a dated row to BASELINE.md.

Usage: python scripts/llvm_baseline.py [--rows 100000] [--runs 3]
"""

from __future__ import annotations

import argparse
import json
import os
import platform
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# single-thread compiled-over-CPython factor for Z1-class string-heavy
# cleaning pipelines, order of magnitude per the reference's SIGMOD'21
# evaluation (hand-optimized-C++-comparable vs interpreted rows/s). Kept
# deliberately conservative; override with TUPLEX_LLVM_ESTIMATE_FACTOR.
ESTIMATE_FACTOR = float(os.environ.get("TUPLEX_LLVM_ESTIMATE_FACTOR", "15"))


def _data_path(n_rows: int) -> str:
    import tempfile

    from tuplex_tpu.models import zillow

    cache = os.path.join(tempfile.gettempdir(), "tuplex_tpu_bench")
    os.makedirs(cache, exist_ok=True)
    path = os.path.join(cache, f"zillow_{n_rows}.csv")
    if not os.path.exists(path):
        zillow.generate_csv(path, n_rows, seed=42)
    return path


def measure_reference(n_rows: int, runs: int):
    """rows/s of the real LLVM engine, or None when it isn't installed."""
    try:
        import tuplex  # noqa: F401 - the reference package, not this repo
    except ImportError:
        return None
    from tuplex_tpu.models import zillow

    data = _data_path(n_rows)
    conf = {"executorCount": 0, "driverMemory": "1GB",
            "webui.enable": False}
    ctx = tuplex.Context(conf)

    def run():
        return zillow.build_pipeline(ctx.csv(data)).collect()

    run()                                   # warmup incl. LLVM compile
    best = min(_timed(run) for _ in range(runs))
    return n_rows / best


def measure_interpreter(n_rows: int, runs: int) -> float:
    from tuplex_tpu.models import zillow

    data = _data_path(n_rows)
    best = min(_timed(lambda: zillow.run_reference_python(data))
               for _ in range(runs))
    return n_rows / best


def _timed(fn) -> float:
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--rows", type=int, default=100000)
    ap.add_argument("--runs", type=int, default=3)
    args = ap.parse_args()

    measured = measure_reference(args.rows, args.runs)
    interp = measure_interpreter(args.rows, args.runs)
    if measured is not None:
        rec = {"zillow_rows_per_sec": round(measured, 1),
               "kind": "measured",
               "detail": "real tuplex (LLVM) engine, single thread, "
                         f"best of {args.runs}"}
    else:
        rec = {"zillow_rows_per_sec": round(interp * ESTIMATE_FACTOR, 1),
               "kind": "estimated",
               "detail": f"ESTIMATE: measured CPython Z1 "
                         f"({interp:.0f} rows/s on this host) x "
                         f"{ESTIMATE_FACTOR:g} (paper-order single-thread "
                         "LLVM-over-CPython factor); reference engine not "
                         "installed — rerun where it is for a measurement"}
    rec.update({"interp_rows_per_sec": round(interp, 1),
                "rows": args.rows, "runs": args.runs,
                "host": platform.machine(),
                "recorded": time.strftime("%Y-%m-%d")})
    out = os.path.join(REPO, "BASELINE_LLVM.json")
    with open(out, "w") as fp:
        json.dump(rec, fp, indent=1)
        fp.write("\n")
    with open(os.path.join(REPO, "BASELINE.md"), "a") as fp:
        fp.write(
            f"\n| LLVM engine Zillow Z1 ({rec['kind'].upper()}) "
            f"| {rec['zillow_rows_per_sec']:.0f} rows/s "
            f"| this host ({rec['host']}), {rec['recorded']} "
            f"| scripts/llvm_baseline.py — {rec['detail']} |\n")
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
