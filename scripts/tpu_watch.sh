#!/bin/bash
# Patiently probe the axon TPU tunnel until it answers, logging each attempt.
# One client at a time, generous per-attempt timeout, long sleeps between
# failures so a wedged server isn't hammered mid-recovery.
LOG=${1:-/tmp/tpu_watch.log}
while true; do
  ts=$(date +%H:%M:%S)
  raw=$(timeout 420 python -c "
import time; t0=time.time()
import jax
ds = jax.devices()
import jax.numpy as jnp
x = jnp.arange(1<<20, dtype=jnp.int32)
s = int(x.sum())
print('TPU_OK init+compute_s=%.1f platform=%s sum=%d' % (time.time()-t0, ds[0].platform, s))
" 2>&1)
  rc=$?
  out=$(echo "$raw" | grep -E "TPU_OK|Error|error" | tail -2)
  echo "$ts rc=$rc $out" >> "$LOG"
  if echo "$out" | grep -q TPU_OK; then
    echo "$ts TPU AVAILABLE — stopping watch" >> "$LOG"
    exit 0
  fi
  sleep 1500
done
