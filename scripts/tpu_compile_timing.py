#!/usr/bin/env python3
"""Time the full Zillow fused-stage compile on the real TPU, in variants,
with the persistent compilation cache enabled. Logs progressively so a
timeout still yields data.

Variants (sequential, same process):
  A. barriers OFF (TUPLEX_FUSION_BARRIERS=0 is set by the runner)
  B. run the compiled fn, time steady-state execution
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
CACHE = os.path.expanduser("~/.cache/jax_comp_cache")


def log(msg):
    print(f"[{time.strftime('%H:%M:%S')}] {msg}", flush=True)


def main():
    os.makedirs(CACHE, exist_ok=True)
    import jax
    jax.config.update("jax_compilation_cache_dir", CACHE)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    t0 = time.perf_counter()
    dev = jax.devices()[0]
    log(f"devices ok in {time.perf_counter() - t0:.1f}s platform={dev.platform}")

    import tempfile
    import tuplex_tpu
    from tuplex_tpu.models import zillow
    from tuplex_tpu.plan.physical import plan_stages
    from tuplex_tpu.api.dataset import _source_partitions
    from tuplex_tpu.runtime import columns as C

    cache_dir = os.path.join(tempfile.gettempdir(), "tuplex_tpu_bench")
    os.makedirs(cache_dir, exist_ok=True)
    data = os.path.join(cache_dir, "zillow_20000.csv")
    if not os.path.exists(data):
        zillow.generate_csv(data, 20000, seed=42)

    ctx = tuplex_tpu.Context()
    ds = zillow.build_pipeline(ctx.csv(data))
    st = plan_stages(ds._op, ctx.options_store)[0]
    part = list(_source_partitions(ctx, st))[0]
    batch = C.stage_partition(part, "pow2")
    log(f"staged batch rows={part.num_rows} arrays={len(batch.arrays)}")

    fn = st.build_device_fn(part.schema)
    t0 = time.perf_counter()
    lowered = jax.jit(fn).lower(batch.arrays)
    log(f"lowered in {time.perf_counter() - t0:.1f}s "
        f"({len(lowered.as_text().splitlines())} stablehlo lines, "
        f"barriers={os.environ.get('TUPLEX_FUSION_BARRIERS', 'auto')})")

    t0 = time.perf_counter()
    compiled = lowered.compile()
    log(f"COMPILED in {time.perf_counter() - t0:.1f}s")

    t0 = time.perf_counter()
    outs = compiled(batch.arrays)
    jax.block_until_ready(outs)
    log(f"first run in {time.perf_counter() - t0:.3f}s")

    times = []
    for _ in range(5):
        t0 = time.perf_counter()
        outs = compiled(batch.arrays)
        jax.block_until_ready(outs)
        times.append(time.perf_counter() - t0)
    log(f"steady runs s={[round(t, 4) for t in times]} "
        f"-> {part.num_rows / min(times):,.0f} rows/s on-device")
    log("ALL OK")


if __name__ == "__main__":
    main()
