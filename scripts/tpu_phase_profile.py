#!/usr/bin/env python3
"""Per-phase timing of one Zillow run on the real chip: isolates Arrow read,
host staging, H2D over the axon tunnel, device exec, D2H, and collect boxing
so perf work targets the real bottleneck."""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
ROWS = int(os.environ.get("ROWS", "100000"))


def t(label, t0):
    print(f"{label:28s} {time.perf_counter() - t0:8.3f}s", flush=True)
    return time.perf_counter()


def main():
    import tempfile

    import jax

    import tuplex_tpu
    from tuplex_tpu.api.dataset import _source_partitions
    from tuplex_tpu.models import zillow
    from tuplex_tpu.plan.physical import plan_stages
    from tuplex_tpu.runtime import columns as C

    print("platform:", jax.devices()[0].platform, flush=True)
    cache = os.path.join(tempfile.gettempdir(), "tuplex_tpu_bench")
    os.makedirs(cache, exist_ok=True)
    data = os.path.join(cache, f"zillow_{ROWS}.csv")
    if not os.path.exists(data):
        zillow.generate_csv(data, ROWS, seed=42)

    ctx = tuplex_tpu.Context()
    t0 = time.perf_counter()
    ds = zillow.build_pipeline(ctx.csv(data))
    st = plan_stages(ds._op, ctx.options_store)[0]
    t0 = t("plan(+sample trace)", t0)
    parts = list(_source_partitions(ctx, st))
    t0 = t("arrow read -> partitions", t0)
    part = parts[0]
    batch = C.stage_partition(part, "pow2")
    nbytes = sum(v.nbytes for v in batch.arrays.values())
    t0 = t(f"host stage ({nbytes/1e6:.1f} MB)", t0)
    fn = jax.jit(st.build_device_fn(part.schema))
    outs = fn(batch.arrays)            # numpy inputs: the PRODUCTION avals
    jax.block_until_ready(outs)
    t0 = t("compile+H2D+first exec", t0)
    outs = fn(batch.arrays)
    jax.block_until_ready(outs)
    t0 = t("steady H2D+exec", t0)
    host_outs = jax.device_get(outs)
    onb = sum(v.nbytes for v in host_outs.values())
    t0 = t(f"D2H ({onb/1e6:.1f} MB)", t0)

    # full framework run for comparison (includes merge + collect boxing)
    for i in range(3):
        t0 = time.perf_counter()
        out = zillow.build_pipeline(ctx.csv(data)).collect()
        t0 = t(f"full collect run{i} ({len(out)} rows)", t0)

    print("done", flush=True)


if __name__ == "__main__":
    main()
