#!/usr/bin/env python3
"""Tracing smoke test: run the zillow model pipeline with structured
tracing ON and assert the Chrome trace export is well-formed and covers
every layer the ISSUE-4 acceptance criteria name — plan, analyzer,
per-stage compile (with a cache verdict attribute), dispatch, resolve
tiers, and merge.

Run directly (CI wires it as a tier-1 test via tests/test_tracing.py):

    JAX_PLATFORMS=cpu python scripts/trace_smoke.py

Exits 0 and prints one `trace-smoke OK ...` line on success; any
assertion failure is a non-zero exit. TRACE_SMOKE_ROWS overrides the
input size (default 400 — matching tests/test_zillow_model.py so a warm
AOT artifact cache skips the XLA compiles)."""

from __future__ import annotations

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))          # run from anywhere

N_ROWS = int(os.environ.get("TRACE_SMOKE_ROWS", "400"))

# span names that must appear for a zillow run (ISSUE 4 acceptance):
# nested spans for plan, analyzer, per-stage compile, dispatch, resolve
# and merge. resolve:general / resolve:interpreter are data-dependent —
# at least one tier must fire on zillow's dirty rows.
REQUIRED = ("job", "plan", "plan:analyze-udf", "compile:trace",
            "partition:dispatch", "partition:collect-fast",
            "partition:merge", "stage:execute")
RESOLVE_ANY = ("resolve:general", "resolve:interpreter")
COMPILE_ANY = ("compile:xla", "compile:cache-hit", "compile:aot-load")


def main() -> int:
    import tuplex_tpu
    from tuplex_tpu.models import zillow
    from tuplex_tpu.runtime import tracing

    with tempfile.TemporaryDirectory() as d:
        data = os.path.join(d, "zillow.csv")
        zillow.generate_csv(data, N_ROWS, seed=7)
        ctx = tuplex_tpu.Context({"tuplex.tpu.trace": True})
        assert tracing.enabled(), "tuplex.tpu.trace did not enable tracing"
        got = zillow.build_pipeline(ctx.csv(data)).collect()
        assert got == zillow.run_reference_python(data), \
            "tracing changed pipeline output"

        out = os.path.join(d, "trace.json")
        ctx.metrics.export_trace(out)
        with open(out) as fp:
            doc = json.load(fp)

        evs = doc["traceEvents"]
        assert isinstance(evs, list) and evs, "empty traceEvents"
        names = set()        # complete ("X") span families
        all_names = set()    # includes instants — cache-hit is ph "i"
        for e in evs:
            # chrome trace-event schema: every event carries these
            for k in ("name", "ph", "pid", "tid"):
                assert k in e, f"event missing {k!r}: {e}"
            all_names.add(e["name"])
            if e["ph"] == "X":
                assert isinstance(e["ts"], (int, float)), e
                assert isinstance(e["dur"], (int, float)), e
                assert e["dur"] >= 0, e
                names.add(e["name"])
        missing = [n for n in REQUIRED if n not in names]
        assert not missing, f"missing span families: {missing}"
        assert any(n in names for n in RESOLVE_ANY), \
            f"no resolve-tier span fired (have: {sorted(names)})"
        assert any(n in all_names for n in COMPILE_ANY), \
            "no compile span (xla/cache-hit/aot-load) recorded"
        # per-stage compile spans must carry the cache verdict attribute
        cache_attrs = [e["args"].get("cache") for e in evs
                       if e.get("args") and "cache" in e["args"]]
        assert cache_attrs, "no span carries a cache hit/miss attribute"
        # spans must actually nest: some X event starts inside another on
        # the same thread
        xs = [e for e in evs if e["ph"] == "X"]
        nested = any(
            a is not b and a["tid"] == b["tid"]
            and a["ts"] <= b["ts"]
            and b["ts"] + b["dur"] <= a["ts"] + a["dur"] + 1e-6
            for a in xs for b in xs)
        assert nested, "no nested spans found"

        md = ctx.metrics.as_dict()
        assert "h2d_bytes" in md and "d2h_bytes" in md
        assert "counters" in md
        print(f"trace-smoke OK — {len(evs)} events, "
              f"{len(names)} span families, rows={len(got)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
