#!/usr/bin/env python3
"""Graphlint zero-false-positive gate: plan (never execute) every bundled
model pipeline and check the static jaxpr vetting verdicts against the
calibrated corpus (compiler/graphlint module docstring):

  * no CLEAN stage anywhere carries a wedge-severity finding — a false
    positive here silently degrades a healthy stage to the interpreter;
  * the flights airport build side is pre-degraded by EXACTLY the pinned
    rule ``wide-str-compaction`` (ROADMAP residue c);
  * re-analysis of the planned flights stages finds exactly one more
    carrier of the rule — the probe-side mega-segment whose production
    compile blows even a 300 s XLA:CPU deadline (the compile plane vets
    it at submission; tests/test_models.py proves zero kills end-to-end).

Plan-only: nothing compiles, nothing collects, so the gate runs in
tens of seconds. CI wires it as a tier-1 test via tests/test_graphlint.py:

    JAX_PLATFORMS=cpu python scripts/graphlint_smoke.py

Exits 0 and prints one `graphlint-smoke OK ...` line on success."""

from __future__ import annotations

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))          # run from anywhere

PINNED_RULE = "wide-str-compaction"


def _planned_stages(ctx, sink, tag):
    """(label, stage) for the top-level plan AND the lazily-planned join
    build sides (the airport wedge lives on one)."""
    from tuplex_tpu.plan.physical import (JoinStage, TransformStage,
                                          plan_stages)

    out = []
    stages = plan_stages(sink._op, ctx.options_store)
    for i, st in enumerate(stages):
        if isinstance(st, TransformStage):
            out.append((f"{tag}[{i}]", st))
        elif isinstance(st, JoinStage):
            for j, bs in enumerate(plan_stages(st.op.right,
                                               ctx.options_store)):
                if isinstance(bs, TransformStage):
                    out.append((f"{tag}[{i}].build[{j}]", bs))
    return out


def main() -> int:
    import tuplex_tpu
    from tuplex_tpu.compiler import graphlint as GL
    from tuplex_tpu.models import flights, logs, nyc311, tpch, zillow

    assert GL.enabled(), \
        "graphlint disabled (TUPLEX_GRAPHLINT=0 set?) — nothing to smoke"

    tmp = tempfile.mkdtemp(prefix="graphlint_smoke_")
    ctx = tuplex_tpu.Context({"tuplex.partitionSize": "256KB",
                              "tuplex.sample.maxDetectionRows": "64",
                              "tuplex.scratchDir": os.path.join(tmp, "s")})

    labelled = []
    zp = os.path.join(tmp, "z.csv")
    zillow.generate_csv(zp, 300, seed=4)
    labelled += _planned_stages(ctx, zillow.build_pipeline(ctx.csv(zp)),
                                "zillow")
    perf, car, air = (os.path.join(tmp, n)
                      for n in ("f.csv", "c.csv", "a.txt"))
    flights.generate_perf_csv(perf, 300, seed=2)
    flights.generate_carrier_csv(car)
    flights.generate_airport_db(air)
    labelled += _planned_stages(
        ctx, flights.build_pipeline(ctx, perf, car, air), "flights")
    tp = os.path.join(tmp, "li.csv")
    tpch.generate_csv(tp, 500, seed=4)
    labelled += _planned_stages(ctx, tpch.q6(ctx.csv(tp)), "tpch_q6")
    labelled += _planned_stages(ctx, tpch.q1(ctx.csv(tp)), "tpch_q1")
    np_ = os.path.join(tmp, "nyc.csv")
    nyc311.generate_csv(np_, 300, seed=3)
    labelled += _planned_stages(ctx, nyc311.build_pipeline(ctx, np_),
                                "nyc311")
    lg = os.path.join(tmp, "log.txt")
    logs.generate_log(lg, 300, seed=6)
    labelled += _planned_stages(ctx, logs.build_pipeline(ctx.text(lg),
                                                         "strip"),
                                "logs_strip")
    labelled += _planned_stages(ctx, logs.build_pipeline(ctx.text(lg),
                                                         "regex"),
                                "logs_regex")

    # 1) plan-time verdicts: a wedge finding is allowed ONLY on a stage
    #    the planner pre-degraded with the pinned rule
    pre_degraded = []
    for label, st in labelled:
        rule = getattr(st, "hazard_rule", None)
        rep = getattr(st, "graph_report", None)
        wedges = {f.rule for f in rep.findings
                  if f.severity == "wedge"} if rep is not None else set()
        if rule is not None:
            assert rule == PINNED_RULE, \
                f"{label}: unexpected pre-degrade rule {rule!r}"
            assert wedges == {PINNED_RULE}, \
                (f"{label}: pre-degraded stage must report exactly the "
                 f"pinned rule, got {sorted(wedges)}")
            pre_degraded.append(label)
        else:
            assert not wedges, \
                f"{label}: FALSE POSITIVE wedge finding(s) {sorted(wedges)}"
    assert pre_degraded and all(lbl.startswith("flights")
                                for lbl in pre_degraded), \
        (f"expected the flights airport build side (and only it) "
         f"pre-degraded at plan time, got {pre_degraded}")

    # 2) submission-plane preview: re-analyze every planned stage the
    #    compile plane would actually submit — the rule must fire on
    #    exactly one more stage, the flights probe-side mega-segment
    resubmit_wedges = []
    for label, st in labelled:
        if getattr(st, "force_interpret", False):
            continue
        rep = GL.analyze_stage(st, platform="cpu")
        if rep is not None and rep.wedge:
            resubmit_wedges.append((label, rep))
    assert len(resubmit_wedges) == 1, \
        (f"expected exactly the flights probe-side segment at the "
         f"compile plane, got {[lbl for lbl, _ in resubmit_wedges]}")
    lbl, rep = resubmit_wedges[0]
    assert lbl.startswith("flights"), lbl
    assert {f.rule for f in rep.findings
            if f.severity == "wedge"} == {PINNED_RULE}, lbl

    ctx.close()
    print(f"graphlint-smoke OK — {len(labelled)} stage(s) vetted, "
          f"plan-time pre-degrades: {pre_degraded}, "
          f"submission-plane wedge: {lbl} "
          f"(rule {PINNED_RULE}, zero false positives)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
