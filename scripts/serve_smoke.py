#!/usr/bin/env python3
"""Job-service smoke test: 3 concurrent small zillow jobs on one warm
backend through `tuplex_tpu.serve.JobService` (ISSUE-6 CI satellite).

Asserts:
  * all three jobs complete with the reference-python output;
  * total stage compiles across the 3 concurrent jobs <= one job's
    compile count + 1 (content-addressed dedup + in-flight join: N
    isomorphic tenants cost ~1 compile set);
  * per-tenant trace streams are disjoint (every span in a job's stream
    carries that job's tag; stream event sets don't overlap);
  * per-tenant counter families are isolated (scoped xferstats).

Run directly (CI wires it as a tier-1 test via tests/test_serve.py):

    JAX_PLATFORMS=cpu python scripts/serve_smoke.py

Exits 0 and prints one `serve-smoke OK ...` line on success. SMOKE_ROWS
overrides the input size (default 400, matching trace_smoke so a warm
AOT artifact cache skips the XLA compiles)."""

from __future__ import annotations

import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))          # run from anywhere

N_ROWS = int(os.environ.get("SMOKE_ROWS", "400"))


def main() -> int:
    import tuplex_tpu
    from tuplex_tpu.exec import compilequeue as CQ
    from tuplex_tpu.models import zillow
    from tuplex_tpu.runtime import tracing
    from tuplex_tpu.serve import JobService, request_from_dataset

    with tempfile.TemporaryDirectory() as d:
        csvs = []
        for i in range(3):
            p = os.path.join(d, f"zillow-{i}.csv")
            if i == 0:
                zillow.generate_csv(p, N_ROWS, seed=7)
            else:
                shutil.copy(csvs[0], p)    # identical data: isomorphic jobs
            csvs.append(p)
        want = zillow.run_reference_python(csvs[0])

        ctx = tuplex_tpu.Context({"tuplex.tpu.trace": True})
        assert tracing.enabled()
        svc = JobService(ctx.options_store)

        # one job alone: its compile count is the baseline
        snap = CQ.snapshot()
        h0 = svc.submit(request_from_dataset(
            zillow.build_pipeline(ctx.csv(csvs[0])), name="warm",
            tenant="t0"))
        assert h0.wait(600) == "done", (h0.state, h0.error)
        c1 = CQ.delta(snap)["stage_compiles"]

        # three concurrent isomorphic jobs, three tenants
        snap = CQ.snapshot()
        handles = [
            svc.submit(request_from_dataset(
                zillow.build_pipeline(ctx.csv(csvs[i])), name=f"job{i}",
                tenant=f"t{i + 1}"))
            for i in range(3)
        ]
        for h in handles:
            assert h.wait(600) == "done", (h.name, h.state, h.error)
            assert h.result() == want, f"{h.name}: wrong output"
        c3 = CQ.delta(snap)["stage_compiles"]
        assert c3 <= 1, (
            f"3 concurrent isomorphic jobs compiled {c3} stages "
            f"(baseline single job: {c1}) — the shared compile plane "
            f"is not deduping")

        # per-tenant trace streams: tagged, non-empty, disjoint
        streams = {h.id: h.trace_events() for h in handles}
        for h in handles:
            evs = streams[h.id]
            assert evs, f"{h.name}: empty span stream"
            assert all(e.get("stream") == h.id for e in evs), h.name
            assert any(e["name"] == "stage:execute" for e in evs), \
                f"{h.name}: no stage:execute span in its stream"
        keysets = [{(e["ts"], e["tid"], e["name"]) for e in evs}
                   for evs in streams.values()]
        for i in range(len(keysets)):
            for j in range(i + 1, len(keysets)):
                assert not (keysets[i] & keysets[j]), \
                    "cross-tenant span leakage"

        # per-tenant counter families: present and isolated
        for h in handles:
            cnt = h.counters()
            assert cnt, f"{h.name}: empty scoped counter family"
        svc.close()
        ctx.close()
        print(f"serve-smoke OK — 3 jobs x {len(want)} rows, "
              f"baseline compiles {c1}, concurrent-extra {c3}, "
              f"{sum(len(v) for v in streams.values())} tenant spans")
    return 0


if __name__ == "__main__":
    sys.exit(main())
