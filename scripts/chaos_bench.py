#!/usr/bin/env python3
"""Fault-injection harness: the zillow serve workload under chaos.

The fault-tolerance layer's acceptance proof (runtime/faults +
exec/compilequeue subprocess isolation + the serve retry ladder and
journal recovery): run the SAME zillow pipeline through the job service
while ``TUPLEX_FAULTS`` breaks a different plane each class, and assert
the dual-mode contract holds at the CONTROL plane too — every submitted
job terminates with correct results or a clean error, exactly once, and
the service's health returns to ok without operator intervention.

Fault classes:

  baseline        no faults (the latency yardstick)
  compile-hang    the first stage compile wedges (``compile:hang:once``);
                  the forked compile child is SIGKILLed at
                  tuplex.tpu.compileDeadlineS and the stage restarts on
                  one degraded tier — results must still be correct
  dispatch-flake  every third device dispatch raises
                  (``dispatch:raise:p=0.34``); the partition retry ->
                  degrade ladder absorbs it
  serve-retry     a worker-loop step raises a transient fault
                  (``serve:raise-step:once``); the job-level retry
                  ladder requeues and completes the job
  serve-crash     (full mode only) the serve PROCESS dies right after
                  admitting a job (``serve:crash-after-admit:once``); a
                  restarted process over the same root requeues it from
                  the journal exactly once and completes it
  drift           no injected faults — the chaos is the DATA: one
                  tenant's input distribution shifts mid-run (half the
                  rows' facts cell breaks), the exception-plane EWMA
                  (runtime/excprof) must trip respecialize_recommended
                  and the degraded `exception_drift` health state within
                  one window, and both must recover on their own once
                  the shift reverts (respec OFF: this class measures the
                  SENSOR alone)
  respec-drift    the CLOSED LOOP (serve/respec): the same shift, but the
                  traffic never reverts — the controller must background-
                  compile a re-speculated candidate, canary it on the
                  tenant's next job, hot-swap at the job boundary, and the
                  drift score + interpreter-tier share must recover below
                  threshold WITHOUT a restart and with every job's rows
                  still correct
  respec-poison   a fault-injected POISONED candidate: the first respec's
                  compile hangs (``respec:hang-compile``) and the second's
                  canary dispatch fails (``respec:raise-canary``) — both
                  must be quarantined (content-addressed `.respecquar`
                  markers, zero promotions), every job's results must stay
                  byte-identical to the incumbent path, and health must
                  return to ok

Each class reports wall seconds, jobs ok/failed, retries and compile
kills, and the worst + final health state. The output is one BENCH-style
JSON line ``scripts/bench_diff.py`` understands (dotted per-class keys;
``wall_s``/latency leaf keys gate directionally), so fault-path latency
regressions gate exactly like perf regressions:

    python scripts/chaos_bench.py                  # all classes
    python scripts/chaos_bench.py --smoke          # tier-1 CI variant
                                                   # (in-process classes)
    python scripts/chaos_bench.py --out CHAOS.json
    python scripts/bench_diff.py CHAOS_old.json CHAOS.json
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))          # run from anywhere

HEALTH_RANK = {"ok": 0, "degraded": 1, "unhealthy": 2}


def _build_requests(ctx, csvs, tag):
    from tuplex_tpu.models import zillow
    from tuplex_tpu.serve import request_from_dataset

    return [request_from_dataset(zillow.build_pipeline(ctx.csv(p)),
                                 name=f"{tag}-j{i}", tenant=f"{tag}")
            for i, p in enumerate(csvs)]


def _set_faults(spec: str, state_dir: str, name: str):
    from tuplex_tpu.runtime import faults

    if spec:
        os.environ["TUPLEX_FAULTS"] = spec
        os.environ["TUPLEX_FAULTS_STATE"] = os.path.join(
            state_dir, f"faults-{name}")
    else:
        os.environ.pop("TUPLEX_FAULTS", None)
        os.environ.pop("TUPLEX_FAULTS_STATE", None)
    faults.reset()


def _run_thread_class(name, spec, ctx, csvs, want, state_dir,
                      expect_ok=True, deadline=None):
    """One in-process fault class: a JobService + wire loop on threads,
    jobs submitted over the scratch-dir protocol, health polled live.
    `deadline` overrides tuplex.tpu.compileDeadlineS for this class only
    (the compile-hang class wants a tight one so the kill is fast; a
    tight deadline on the OTHER classes would kill their genuine zillow
    compiles and measure the wrong thing)."""
    from tuplex_tpu.core.options import ContextOptions
    from tuplex_tpu.exec import compilequeue as CQ
    from tuplex_tpu.runtime import telemetry
    from tuplex_tpu.serve import JobService
    from tuplex_tpu.serve import client as WC

    root = os.path.join(state_dir, f"root-{name}")
    os.makedirs(root, exist_ok=True)
    # per-class compile plane: a fresh AOT dir + cleared in-process
    # stores, so the compile-hang class really compiles (a dedup/aot hit
    # would dodge the injected wedge) and each class's stats are its own
    os.environ["TUPLEX_AOT_CACHE"] = os.path.join(state_dir, f"aot-{name}")
    CQ.clear()
    CQ._TIMEOUTS.clear()
    # ... and a fresh exception-plane slate: the drift windows/EWMA are
    # process-global and sticky, so a fault class that legitimately
    # pushes partitions to the interpreter (dispatch-flake) must not
    # inherit the previous class's anchor — or leave ITS drift score
    # pinning the exception_drift health check degraded at the final
    # health read of a later class
    from tuplex_tpu.runtime import excprof as _EXP

    _EXP.clear()
    _set_faults(spec, state_dir, name)
    opts = ContextOptions(ctx.options_store.to_dict())
    if deadline is not None:
        opts.set("tuplex.tpu.compileDeadlineS", deadline)
    # the injected-fault classes measure the FAULT machinery; a respec
    # controller reacting to their induced exception traffic would add a
    # nondeterministic actor (the respec-* classes exercise it on purpose)
    opts.set("tuplex.serve.respec", False)
    svc = JobService(opts)
    t0 = time.perf_counter()
    jids = [WC.submit(root, r) for r in _build_requests(ctx, csvs, name)]
    loop = threading.Thread(
        target=WC.service_loop, args=(root,),
        kwargs=dict(service=svc, max_idle_s=3.0), daemon=True)
    loop.start()
    worst = "ok"
    results = []

    def watch_health(stop):
        nonlocal worst
        while not stop.wait(0.05):
            st = telemetry.health()["state"] if telemetry.enabled() else "ok"
            if HEALTH_RANK.get(st, 1) > HEALTH_RANK[worst]:
                worst = st

    stop = threading.Event()
    w = threading.Thread(target=watch_health, args=(stop,), daemon=True)
    w.start()
    try:
        for jid in jids:
            results.append(WC.fetch(root, jid, timeout=600))
    finally:
        stop.set()
        w.join(5)
        open(os.path.join(root, "STOP"), "w").close()
        loop.join(60)
        final = telemetry.health()["state"] if telemetry.enabled() else "ok"
        svc.close()
        _set_faults("", state_dir, name)
    wall = time.perf_counter() - t0
    n_ok = sum(1 for r in results if r.get("ok"))
    clean_fail = sum(1 for r in results
                     if not r.get("ok") and r.get("error"))
    assert n_ok + clean_fail == len(jids), \
        f"{name}: {len(jids) - n_ok - clean_fail} job(s) vanished"
    if expect_ok:
        for r in results:
            assert r.get("ok"), f"{name}: job failed: {r.get('error')}"
            assert r["rows"] == want, f"{name}: wrong rows"
    assert final == "ok", f"{name}: health did not return to ok ({final})"
    retries = sum(len(r.get("attempts") or []) for r in results)
    stats = CQ.snapshot()     # this class's own delta (cleared at start)
    return {"wall_s": round(wall, 3), "jobs": len(jids), "jobs_ok": n_ok,
            "jobs_failed_clean": clean_fail, "retries": retries,
            "compiles_killed": stats.get("compiles_killed", 0),
            "deadline_timeouts": stats.get("deadline_timeouts", 0),
            "health_worst": worst, "health_final": final,
            "fault": spec or "none"}


def _shift_csv(src: str, dst: str, frac: float = 0.5) -> str:
    """The injected distribution shift: rewrite `frac` of the rows'
    "facts and features" cell to the generator's broken-facts shape, so
    extractBd/Ba/Sqft raise ValueError on them — same schema, same
    pipeline, radically different exception profile."""
    import csv

    from tuplex_tpu.models import zillow

    period = max(2, int(round(1.0 / max(frac, 1e-6))))
    with open(src, newline="") as fin, open(dst, "w", newline="") as fout:
        r = csv.DictReader(fin)
        w = csv.DictWriter(fout, fieldnames=zillow.COLUMNS)
        w.writeheader()
        for i, row in enumerate(r):
            if i % period == 0:
                row["facts and features"] = "-- , contact agent"
            w.writerow(row)
    return dst


def _run_drift_class(name, ctx, state_dir, rows):
    """The `drift` scenario (runtime/excprof acceptance): one tenant's
    input distribution shifts mid-run — the windowed EWMA must leave the
    plan-time-anchored baseline, trip ``respecialize_recommended`` and
    the degraded `exception_drift` health state within one window, then
    RECOVER to ok once the shift reverts. No injected faults: the chaos
    here is the data itself."""
    from tuplex_tpu.core.options import ContextOptions
    from tuplex_tpu.exec import compilequeue as CQ
    from tuplex_tpu.models import zillow
    from tuplex_tpu.runtime import excprof, telemetry
    from tuplex_tpu.serve import JobService, request_from_dataset

    clean = os.path.join(state_dir, "drift-clean.csv")
    zillow.generate_csv(clean, rows, seed=11)
    shifted = _shift_csv(clean, os.path.join(state_dir,
                                             "drift-shifted.csv"))
    want = zillow.run_reference_python(clean)
    # fresh compile plane (an inherited `.timeout` negative-cache marker
    # from the smoke classes' tight deadline would degrade the stage to
    # the interpreter WHOLESALE — rate 1.0 on clean traffic, no drift
    # signal left to measure) + fresh exception-plane state, with a short
    # window/half-life so the scenario runs in seconds
    os.environ["TUPLEX_AOT_CACHE"] = os.path.join(state_dir, f"aot-{name}")
    CQ.clear()
    CQ._TIMEOUTS.clear()
    _set_faults("", state_dir, name)
    excprof.clear()
    window_s = 0.4
    opts = ContextOptions(ctx.options_store.to_dict())
    opts.set("tuplex.serve.driftWindowS", window_s)
    opts.set("tuplex.tpu.excprofHalfLifeS", window_s)
    # this class measures the SENSOR alone: the closed-loop controller
    # would re-anchor the very signal whose trip/recover latency is the
    # metric (the respec-drift class measures the loop)
    opts.set("tuplex.serve.respec", False)
    tenant = "drifty"
    svc = JobService(opts)
    t0 = time.perf_counter()
    n_jobs = [0]

    def run_one(path):
        h = svc.submit(request_from_dataset(
            zillow.build_pipeline(ctx.csv(path)),
            name=f"{name}-j{n_jobs[0]}", tenant=tenant))
        n_jobs[0] += 1
        assert h.wait(1200) == "done", (h.name, h.state, h.error)
        return h

    def settle():
        time.sleep(window_s * 1.2)
        excprof.roll()

    try:
        # phase A — the plan-normal era: clean traffic calibrates the
        # anchor (first rolled window) and the EWMA
        h = run_one(clean)
        assert h.result() == want, "drift: wrong clean-phase output"
        settle()
        run_one(clean)
        settle()
        assert not excprof.respecialize_recommended(tenant), \
            f"drift: tripped on clean traffic " \
            f"(score {excprof.drift_score(tenant):.2f})"
        # phase B — the shift: same pipeline, dirty facts
        trip_windows = 0
        for _ in range(6):
            run_one(shifted)
            settle()
            trip_windows += 1
            if excprof.respecialize_recommended(tenant):
                break
        fired = excprof.respecialize_recommended(tenant)
        peak = excprof.drift_score(tenant)
        assert fired, f"drift: never tripped (score {peak:.2f})"
        health_shift = telemetry.health()["state"] \
            if telemetry.enabled() else "degraded"
        assert health_shift != "ok", \
            "drift: health stayed ok through the shift"
        # phase C — revert: clean traffic again, the EWMA must decay
        # below threshold and health must return to ok on its own
        recover_windows = 0
        for _ in range(30):
            run_one(clean)
            settle()
            recover_windows += 1
            if not excprof.respecialize_recommended(tenant):
                break
        assert not excprof.respecialize_recommended(tenant), \
            f"drift: never recovered " \
            f"(score {excprof.drift_score(tenant):.2f})"
        final = telemetry.health()["state"] \
            if telemetry.enabled() else "ok"
        assert final == "ok", f"drift: health did not recover ({final})"
    finally:
        svc.close()
    wall = time.perf_counter() - t0
    rep = excprof.scope_report(tenant)
    return {"wall_s": round(wall, 3), "jobs": n_jobs[0],
            "jobs_ok": n_jobs[0], "jobs_failed_clean": 0,
            "retries": 0, "respecialize_fired": int(fired),
            "drift_trip_windows": trip_windows,
            "drift_recover_windows": recover_windows,
            "drift_peak": round(peak, 3),
            "exception_rate": round(rep["exception_rate"], 4),
            "health_worst": health_shift, "health_final": final,
            "fault": "data-shift (no injected faults)"}


def _respec_service(ctx, state_dir, name, window_s, faults_spec="",
                    quarantine_s=600.0, compile_deadline_s=60.0):
    """Common setup for the two closed-loop respec classes: fresh compile
    + exception planes, short drift windows, an eager controller (no
    debounce slack, no cooldown) so the loop runs in seconds."""
    from tuplex_tpu.core.options import ContextOptions
    from tuplex_tpu.exec import compilequeue as CQ
    from tuplex_tpu.runtime import excprof
    from tuplex_tpu.serve import JobService

    os.environ["TUPLEX_AOT_CACHE"] = os.path.join(state_dir, f"aot-{name}")
    CQ.clear()
    CQ._TIMEOUTS.clear()
    _set_faults(faults_spec, state_dir, name)
    excprof.clear()
    opts = ContextOptions(ctx.options_store.to_dict())
    opts.set("tuplex.serve.driftWindowS", window_s)
    opts.set("tuplex.tpu.excprofHalfLifeS", window_s)
    opts.set("tuplex.serve.respec", True)
    opts.set("tuplex.serve.respecCheckS", 0.05)
    opts.set("tuplex.serve.respecDebounce", 1)
    opts.set("tuplex.serve.respecCooldownS", 0)
    opts.set("tuplex.serve.respecCanaryFrac", 1.0)
    opts.set("tuplex.serve.respecCompileDeadlineS", compile_deadline_s)
    opts.set("tuplex.serve.respecQuarantineS", quarantine_s)
    return JobService(opts)


def _run_respec_drift_class(name, ctx, state_dir, rows):
    """Closed-loop acceptance: the drift class's distribution shift, but
    the traffic NEVER reverts — recovery must come from the controller
    re-specializing the tenant (background compile → canary → hot-swap),
    not from the data going clean again. Gates: the drift score returns
    below threshold and the interpreter-tier share returns to its
    pre-shift level without a service restart, with every job's rows
    correct for its OWN input throughout."""
    from tuplex_tpu.models import zillow
    from tuplex_tpu.runtime import excprof, telemetry
    from tuplex_tpu.serve import request_from_dataset

    clean = os.path.join(state_dir, f"{name}-clean.csv")
    zillow.generate_csv(clean, rows, seed=11)
    shifted = _shift_csv(clean, os.path.join(state_dir,
                                             f"{name}-shifted.csv"))
    want_clean = zillow.run_reference_python(clean)
    want_shift = zillow.run_reference_python(shifted)
    window_s = 0.4
    svc = _respec_service(ctx, state_dir, name, window_s)
    tenant = "drifty-loop"
    t0 = time.perf_counter()
    n_jobs = [0]

    def run_one(path, want):
        h = svc.submit(request_from_dataset(
            zillow.build_pipeline(ctx.csv(path)),
            name=f"{name}-j{n_jobs[0]}", tenant=tenant))
        n_jobs[0] += 1
        assert h.wait(1200) == "done", (h.name, h.state, h.error)
        assert h.result() == want, f"{name}: wrong rows (job {h.name})"
        return h

    def settle():
        time.sleep(window_s * 1.2)
        excprof.roll()

    try:
        # phase A — plan-normal era: calibrate the anchor
        run_one(clean, want_clean)
        settle()
        run_one(clean, want_clean)
        settle()
        interp_before = excprof.scope_report(tenant)["tier_mix"].get(
            "interpreter", 0.0)
        # phase B — the shift, permanently: drive until the signal trips
        trip_jobs = 0
        for _ in range(8):
            run_one(shifted, want_shift)
            settle()
            trip_jobs += 1
            if excprof.respecialize_recommended(tenant):
                break
        assert excprof.respecialize_recommended(tenant), \
            f"{name}: drift never tripped"
        peak = excprof.drift_score(tenant)
        # phase C — keep the shifted traffic flowing; the controller must
        # re-specialize and promote WITHOUT any revert or restart
        promote_jobs = 0
        rep = svc.respec.tenant_report(tenant)
        for _ in range(40):
            run_one(shifted, want_shift)
            settle()
            promote_jobs += 1
            rep = svc.respec.tenant_report(tenant)
            if rep["promotions"] >= 1:
                break
        assert rep["promotions"] >= 1, \
            f"{name}: respec never promoted ({rep})"
        promote_ev = next((e for e in rep["history"]
                           if e["phase"] == "promote"), {})
        # phase D — the loop is closed: the score must sit below the
        # threshold on the SAME shifted traffic, and health returns to ok
        recover_windows = 0
        for _ in range(20):
            run_one(shifted, want_shift)
            settle()
            recover_windows += 1
            if not excprof.respecialize_recommended(tenant):
                break
        score_after = excprof.drift_score(tenant)
        assert not excprof.respecialize_recommended(tenant), \
            f"{name}: drift did not recover after promotion " \
            f"(score {score_after:.2f})"
        interp_after = excprof.scope_report(tenant)["tier_mix"].get(
            "interpreter", 0.0)
        assert interp_after <= interp_before + 0.05, \
            f"{name}: interpreter-tier share grew " \
            f"({interp_before:.3f} -> {interp_after:.3f})"
        final = telemetry.health()["state"] \
            if telemetry.enabled() else "ok"
        assert final == "ok", f"{name}: health did not recover ({final})"
    finally:
        svc.close()
        _set_faults("", state_dir, name)
    wall = time.perf_counter() - t0
    return {"wall_s": round(wall, 3), "jobs": n_jobs[0],
            "jobs_ok": n_jobs[0], "jobs_failed_clean": 0, "retries": 0,
            "respec_promotions": rep["promotions"],
            "respec_quarantines": rep["quarantines"],
            "respec_rollbacks": rep["rollbacks"],
            "promote_s": promote_ev.get("promote_s", 0.0),
            "respec_trip_jobs": trip_jobs,
            "respec_promote_jobs": promote_jobs,
            "drift_recover_windows": recover_windows,
            "drift_peak": round(peak, 3),
            "drift_after_promote": round(score_after, 3),
            "tier_mix": {"interpreter": round(interp_after, 4)},
            "health_final": final,
            "fault": "data-shift, never reverted (closed loop)"}


def _run_respec_poison_class(name, ctx, state_dir, rows):
    """Poisoned-candidate acceptance: the first candidate's compile hangs
    (killed by the controller's compile watchdog), the second's canary
    dispatch raises — BOTH quarantine, nothing promotes, every job's
    results stay byte-identical to the incumbent path (the canary job's
    output comes from the incumbent by construction), and health returns
    to ok once the traffic goes clean again."""
    from tuplex_tpu.models import zillow
    from tuplex_tpu.runtime import excprof, telemetry
    from tuplex_tpu.serve import request_from_dataset

    clean = os.path.join(state_dir, f"{name}-clean.csv")
    zillow.generate_csv(clean, rows, seed=11)
    shifted = _shift_csv(clean, os.path.join(state_dir,
                                             f"{name}-shifted.csv"))
    want_clean = zillow.run_reference_python(clean)
    want_shift = zillow.run_reference_python(shifted)
    window_s = 0.4
    # the hang outlives the compile deadline by far (the watchdog must
    # kill-quarantine it, not wait it out); the deadline still leaves a
    # healthy candidate 2 room for its one real background compile
    svc = _respec_service(
        ctx, state_dir, name, window_s,
        faults_spec=("respec:hang-compile:once:delay=120,"
                     "respec:raise-canary:once:kind=det"),
        quarantine_s=0.2, compile_deadline_s=8.0)
    tenant = "poisoned"
    t0 = time.perf_counter()
    n_jobs = [0]

    def run_one(path, want):
        h = svc.submit(request_from_dataset(
            zillow.build_pipeline(ctx.csv(path)),
            name=f"{name}-j{n_jobs[0]}", tenant=tenant))
        n_jobs[0] += 1
        assert h.wait(1200) == "done", (h.name, h.state, h.error)
        assert h.result() == want, \
            f"{name}: job {h.name} rows differ from the incumbent path"
        return h

    def settle():
        time.sleep(window_s * 1.2)
        excprof.roll()

    try:
        run_one(clean, want_clean)
        settle()
        run_one(clean, want_clean)
        settle()
        # shifted traffic: trips drift, and every respec attempt is
        # poisoned — first by the compile hang, then by the canary fault
        rep = svc.respec.tenant_report(tenant)
        for _ in range(60):
            run_one(shifted, want_shift)
            settle()
            rep = svc.respec.tenant_report(tenant)
            if rep["quarantines"] >= 2:
                break
        assert rep["quarantines"] >= 2, \
            f"{name}: expected both poisoned candidates quarantined " \
            f"({rep})"
        assert rep["promotions"] == 0, \
            f"{name}: a poisoned candidate was promoted ({rep})"
        canary_fail = any(
            "canary" in str(e.get("reason", ""))
            for e in rep["history"] if e["phase"] == "quarantine")
        assert canary_fail, \
            f"{name}: no quarantine records the canary fault ({rep})"
        # pause further triggers (the operator action after a double
        # quarantine): the revert phase below measures the SENSOR and
        # health decay, not a third candidate racing the clean traffic
        svc.respec.debounce_n = 1 << 30
        # content-addressed quarantine markers on disk (flap protection
        # survives the process)
        aot_dir = os.environ.get("TUPLEX_AOT_CACHE", "")
        markers = [f for f in os.listdir(aot_dir)
                   if f.endswith(".respecquar")] if aot_dir else []
        assert markers, f"{name}: no .respecquar marker written"
        # revert: clean traffic — the sensor decays, nothing is stuck,
        # health (exception_drift AND the respec check) returns to ok
        for _ in range(30):
            run_one(clean, want_clean)
            settle()
            if not excprof.respecialize_recommended(tenant):
                break
        final = telemetry.health()["state"] \
            if telemetry.enabled() else "ok"
        assert final == "ok", f"{name}: health did not return to ok " \
            f"({telemetry.health() if telemetry.enabled() else final})"
    finally:
        svc.close()
        _set_faults("", state_dir, name)
    wall = time.perf_counter() - t0
    return {"wall_s": round(wall, 3), "jobs": n_jobs[0],
            "jobs_ok": n_jobs[0], "jobs_failed_clean": 0, "retries": 0,
            "respec_promotions": rep["promotions"],
            "respec_quarantines": rep["quarantines"],
            "respec_rollbacks": rep["rollbacks"],
            "respec_markers": len(markers),
            "health_final": final,
            "fault": "respec:hang-compile + respec:raise-canary"}


def _run_crash_class(name, ctx, csvs, want, state_dir, conf_path):
    """The serve-crash class needs a REAL process to kill: launch
    `python -m tuplex_tpu serve`, let the injected crash take it down
    after admission, restart it fault-free over the same root, and fetch
    every job's exactly-once terminal response."""
    from tuplex_tpu.serve import client as WC

    root = os.path.join(state_dir, f"root-{name}")
    os.makedirs(root, exist_ok=True)
    t0 = time.perf_counter()
    jids = [WC.submit(root, r) for r in _build_requests(ctx, csvs, name)]
    base_env = {k: v for k, v in os.environ.items()
                if k not in ("TUPLEX_FAULTS", "TUPLEX_FAULTS_STATE")}
    base_env["JAX_PLATFORMS"] = "cpu"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    base_env["PYTHONPATH"] = repo + os.pathsep + \
        base_env.get("PYTHONPATH", "")
    argv = [sys.executable, "-m", "tuplex_tpu", "serve", root,
            "--conf", conf_path]
    p1 = subprocess.run(
        argv, env=dict(base_env,
                       TUPLEX_FAULTS="serve:crash-after-admit:once"),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, timeout=600)
    assert p1.returncode == 70, \
        f"{name}: server did not crash as injected " \
        f"(rc={p1.returncode}):\n{p1.stdout.decode()[-2000:]}"
    p2 = subprocess.Popen(argv, env=base_env, stdout=subprocess.PIPE,
                          stderr=subprocess.STDOUT)
    try:
        results = [WC.fetch(root, jid, timeout=600) for jid in jids]
    finally:
        open(os.path.join(root, "STOP"), "w").close()
        out2, _ = p2.communicate(timeout=120)
    wall = time.perf_counter() - t0
    for r in results:
        assert r.get("ok"), f"{name}: job failed: {r.get('error')}"
        assert r["rows"] == want, f"{name}: wrong rows"
    requeues = 0
    for jid in jids:
        j = WC._read_journal(os.path.join(root, "inbox", jid))
        assert j.get("state") == "done", (jid, j)
        requeues += int(j.get("requeues", 0))
    assert requeues >= 1, "no job was actually requeued from the journal"
    # the restarted process's final metrics.prom drop carries its health
    final = "ok"
    try:
        for line in open(os.path.join(root, "metrics.prom")):
            if line.startswith("tuplex_health_state "):
                final = {0: "ok", 1: "degraded",
                         2: "unhealthy"}.get(int(float(line.split()[1])),
                                             "unhealthy")
    except OSError:
        pass
    assert final == "ok", f"{name}: restarted service health {final}"
    return {"wall_s": round(wall, 3), "jobs": len(jids),
            "jobs_ok": len(results), "jobs_failed_clean": 0,
            "crash_requeues": requeues, "health_final": final,
            "fault": "serve:crash-after-admit:once"}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="zillow serve workload under injected faults")
    ap.add_argument("--jobs", type=int, default=3)
    ap.add_argument("--rows", type=int, default=2000,
                    help="zillow rows per job input")
    ap.add_argument("--smoke", action="store_true",
                    help="tier-1 CI variant: tiny inputs, in-process "
                         "classes only (the subprocess crash class has "
                         "its own tier-1 test)")
    ap.add_argument("--deadline", type=float, default=5.0,
                    help="tuplex.tpu.compileDeadlineS for the "
                         "compile-hang class (how long the wedge lives "
                         "before the kill)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)
    if args.smoke:
        args.jobs, args.rows = 2, 200

    import tuplex_tpu
    from tuplex_tpu.models import zillow

    state_dir = tempfile.mkdtemp(prefix="tpx-chaos-")
    try:
        csvs = []
        for i in range(args.jobs):
            p = os.path.join(state_dir, f"zillow-{i}.csv")
            if i == 0:
                zillow.generate_csv(p, args.rows, seed=7)
            else:
                shutil.copy(csvs[0], p)
            csvs.append(p)
        want = zillow.run_reference_python(csvs[0])
        conf = {
            "tuplex.scratchDir": os.path.join(state_dir, "scratch"),
            "tuplex.serve.retryBackoffS": 0.1,
            "tuplex.serve.metricsPromS": 1,
        }
        conf_path = os.path.join(state_dir, "chaos-conf.json")
        with open(conf_path, "w") as fp:
            json.dump(conf, fp)
        ctx = tuplex_tpu.Context(conf)

        classes = {}
        # full mode: only the compile-hang class runs under the tight
        # deadline (the others measure the compiled fault paths). Smoke
        # applies it everywhere: genuine zillow compiles then also die
        # at the deadline and the drill runs in seconds — it checks the
        # FAULT machinery end to end, not compiled-path latency.
        dflt = args.deadline if args.smoke else None
        plan = [
            ("baseline", "", dflt),
            ("compile-hang", "compile:hang:once", args.deadline),
            ("dispatch-flake", "dispatch:raise:p=0.34", dflt),
            ("serve-retry", "serve:raise-step:once", dflt),
        ]
        for name, spec, deadline in plan:
            print(f"[chaos] class {name} ({spec or 'no faults'})",
                  file=sys.stderr, flush=True)
            classes[name] = _run_thread_class(
                name, spec, ctx, csvs, want, state_dir,
                deadline=deadline)
        # the drift class runs WITHOUT the tight smoke deadline — its
        # genuine compiles must live, or the whole stage degrades to the
        # interpreter and the exception rate saturates at 1.0 for clean
        # traffic too (no signal left to trip on)
        print("[chaos] class drift (mid-run distribution shift)",
              file=sys.stderr, flush=True)
        classes["drift"] = _run_drift_class("drift", ctx, state_dir,
                                            args.rows)
        # the closed-loop classes (serve/respec) also run without the
        # tight smoke deadline: candidate compiles must live
        print("[chaos] class respec-drift (closed-loop self-healing)",
              file=sys.stderr, flush=True)
        classes["respec-drift"] = _run_respec_drift_class(
            "respec-drift", ctx, state_dir, args.rows)
        print("[chaos] class respec-poison (poisoned candidate)",
              file=sys.stderr, flush=True)
        classes["respec-poison"] = _run_respec_poison_class(
            "respec-poison", ctx, state_dir, args.rows)
        if not args.smoke:
            print("[chaos] class serve-crash (subprocess)",
                  file=sys.stderr, flush=True)
            classes["serve-crash"] = _run_crash_class(
                "serve-crash", ctx, csvs, want, state_dir, conf_path)

        base = classes["baseline"]["wall_s"]
        # the drift/respec classes' walls are dominated by deliberate
        # window sleeps + fresh compiles, not a fault path — they report
        # their own trip/promote/recover latencies instead of gating the
        # worst-class wall
        worst = max(v["wall_s"] for k, v in classes.items()
                    if k not in ("baseline", "drift", "respec-drift",
                                 "respec-poison"))
        result = {
            "metric": "chaos_zillow_worst_class_wall_s",
            "value": worst,
            "unit": "s",
            "n_jobs": args.jobs,
            "rows": args.rows,
            "baseline_wall_s": base,
            "worst_over_baseline": round(worst / base, 3) if base else 0.0,
            "compiles_killed": sum(v.get("compiles_killed", 0)
                                   for v in classes.values()),
            "deadline_timeouts": sum(v.get("deadline_timeouts", 0)
                                     for v in classes.values()),
            "classes": classes,
        }
        ctx.close()
    finally:
        _set_faults("", state_dir, "teardown")
        shutil.rmtree(state_dir, ignore_errors=True)
    line = json.dumps(result)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as fp:
            fp.write(line + "\n")
    if args.smoke:
        assert result["compiles_killed"] >= 1, \
            "compile-hang class never killed a compile child"
        assert classes["serve-retry"]["retries"] >= 1, \
            "serve-retry class never retried"
        assert classes["drift"]["respecialize_fired"] == 1, \
            "drift class never recommended respecialization"
        assert classes["respec-drift"]["respec_promotions"] >= 1, \
            "respec-drift class never promoted a candidate"
        assert classes["respec-poison"]["respec_quarantines"] >= 2, \
            "respec-poison class failed to quarantine both candidates"
        print("chaos-bench OK", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
