#!/usr/bin/env python3
"""Fault-injection harness: the zillow serve workload under chaos.

The fault-tolerance layer's acceptance proof (runtime/faults +
exec/compilequeue subprocess isolation + the serve retry ladder and
journal recovery): run the SAME zillow pipeline through the job service
while ``TUPLEX_FAULTS`` breaks a different plane each class, and assert
the dual-mode contract holds at the CONTROL plane too — every submitted
job terminates with correct results or a clean error, exactly once, and
the service's health returns to ok without operator intervention.

Fault classes:

  baseline        no faults (the latency yardstick)
  compile-hang    the first stage compile wedges (``compile:hang:once``);
                  the forked compile child is SIGKILLed at
                  tuplex.tpu.compileDeadlineS and the stage restarts on
                  one degraded tier — results must still be correct
  dispatch-flake  every third device dispatch raises
                  (``dispatch:raise:p=0.34``); the partition retry ->
                  degrade ladder absorbs it
  serve-retry     a worker-loop step raises a transient fault
                  (``serve:raise-step:once``); the job-level retry
                  ladder requeues and completes the job
  serve-crash     (full mode only) the serve PROCESS dies right after
                  admitting a job (``serve:crash-after-admit:once``); a
                  restarted process over the same root requeues it from
                  the journal exactly once and completes it
  drift           no injected faults — the chaos is the DATA: one
                  tenant's input distribution shifts mid-run (half the
                  rows' facts cell breaks), the exception-plane EWMA
                  (runtime/excprof) must trip respecialize_recommended
                  and the degraded `exception_drift` health state within
                  one window, and both must recover on their own once
                  the shift reverts

Each class reports wall seconds, jobs ok/failed, retries and compile
kills, and the worst + final health state. The output is one BENCH-style
JSON line ``scripts/bench_diff.py`` understands (dotted per-class keys;
``wall_s``/latency leaf keys gate directionally), so fault-path latency
regressions gate exactly like perf regressions:

    python scripts/chaos_bench.py                  # all classes
    python scripts/chaos_bench.py --smoke          # tier-1 CI variant
                                                   # (in-process classes)
    python scripts/chaos_bench.py --out CHAOS.json
    python scripts/bench_diff.py CHAOS_old.json CHAOS.json
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))          # run from anywhere

HEALTH_RANK = {"ok": 0, "degraded": 1, "unhealthy": 2}


def _build_requests(ctx, csvs, tag):
    from tuplex_tpu.models import zillow
    from tuplex_tpu.serve import request_from_dataset

    return [request_from_dataset(zillow.build_pipeline(ctx.csv(p)),
                                 name=f"{tag}-j{i}", tenant=f"{tag}")
            for i, p in enumerate(csvs)]


def _set_faults(spec: str, state_dir: str, name: str):
    from tuplex_tpu.runtime import faults

    if spec:
        os.environ["TUPLEX_FAULTS"] = spec
        os.environ["TUPLEX_FAULTS_STATE"] = os.path.join(
            state_dir, f"faults-{name}")
    else:
        os.environ.pop("TUPLEX_FAULTS", None)
        os.environ.pop("TUPLEX_FAULTS_STATE", None)
    faults.reset()


def _run_thread_class(name, spec, ctx, csvs, want, state_dir,
                      expect_ok=True, deadline=None):
    """One in-process fault class: a JobService + wire loop on threads,
    jobs submitted over the scratch-dir protocol, health polled live.
    `deadline` overrides tuplex.tpu.compileDeadlineS for this class only
    (the compile-hang class wants a tight one so the kill is fast; a
    tight deadline on the OTHER classes would kill their genuine zillow
    compiles and measure the wrong thing)."""
    from tuplex_tpu.core.options import ContextOptions
    from tuplex_tpu.exec import compilequeue as CQ
    from tuplex_tpu.runtime import telemetry
    from tuplex_tpu.serve import JobService
    from tuplex_tpu.serve import client as WC

    root = os.path.join(state_dir, f"root-{name}")
    os.makedirs(root, exist_ok=True)
    # per-class compile plane: a fresh AOT dir + cleared in-process
    # stores, so the compile-hang class really compiles (a dedup/aot hit
    # would dodge the injected wedge) and each class's stats are its own
    os.environ["TUPLEX_AOT_CACHE"] = os.path.join(state_dir, f"aot-{name}")
    CQ.clear()
    CQ._TIMEOUTS.clear()
    # ... and a fresh exception-plane slate: the drift windows/EWMA are
    # process-global and sticky, so a fault class that legitimately
    # pushes partitions to the interpreter (dispatch-flake) must not
    # inherit the previous class's anchor — or leave ITS drift score
    # pinning the exception_drift health check degraded at the final
    # health read of a later class
    from tuplex_tpu.runtime import excprof as _EXP

    _EXP.clear()
    _set_faults(spec, state_dir, name)
    opts = ContextOptions(ctx.options_store.to_dict())
    if deadline is not None:
        opts.set("tuplex.tpu.compileDeadlineS", deadline)
    svc = JobService(opts)
    t0 = time.perf_counter()
    jids = [WC.submit(root, r) for r in _build_requests(ctx, csvs, name)]
    loop = threading.Thread(
        target=WC.service_loop, args=(root,),
        kwargs=dict(service=svc, max_idle_s=3.0), daemon=True)
    loop.start()
    worst = "ok"
    results = []

    def watch_health(stop):
        nonlocal worst
        while not stop.wait(0.05):
            st = telemetry.health()["state"] if telemetry.enabled() else "ok"
            if HEALTH_RANK.get(st, 1) > HEALTH_RANK[worst]:
                worst = st

    stop = threading.Event()
    w = threading.Thread(target=watch_health, args=(stop,), daemon=True)
    w.start()
    try:
        for jid in jids:
            results.append(WC.fetch(root, jid, timeout=600))
    finally:
        stop.set()
        w.join(5)
        open(os.path.join(root, "STOP"), "w").close()
        loop.join(60)
        final = telemetry.health()["state"] if telemetry.enabled() else "ok"
        svc.close()
        _set_faults("", state_dir, name)
    wall = time.perf_counter() - t0
    n_ok = sum(1 for r in results if r.get("ok"))
    clean_fail = sum(1 for r in results
                     if not r.get("ok") and r.get("error"))
    assert n_ok + clean_fail == len(jids), \
        f"{name}: {len(jids) - n_ok - clean_fail} job(s) vanished"
    if expect_ok:
        for r in results:
            assert r.get("ok"), f"{name}: job failed: {r.get('error')}"
            assert r["rows"] == want, f"{name}: wrong rows"
    assert final == "ok", f"{name}: health did not return to ok ({final})"
    retries = sum(len(r.get("attempts") or []) for r in results)
    stats = CQ.snapshot()     # this class's own delta (cleared at start)
    return {"wall_s": round(wall, 3), "jobs": len(jids), "jobs_ok": n_ok,
            "jobs_failed_clean": clean_fail, "retries": retries,
            "compiles_killed": stats.get("compiles_killed", 0),
            "deadline_timeouts": stats.get("deadline_timeouts", 0),
            "health_worst": worst, "health_final": final,
            "fault": spec or "none"}


def _shift_csv(src: str, dst: str, frac: float = 0.5) -> str:
    """The injected distribution shift: rewrite `frac` of the rows'
    "facts and features" cell to the generator's broken-facts shape, so
    extractBd/Ba/Sqft raise ValueError on them — same schema, same
    pipeline, radically different exception profile."""
    import csv

    from tuplex_tpu.models import zillow

    period = max(2, int(round(1.0 / max(frac, 1e-6))))
    with open(src, newline="") as fin, open(dst, "w", newline="") as fout:
        r = csv.DictReader(fin)
        w = csv.DictWriter(fout, fieldnames=zillow.COLUMNS)
        w.writeheader()
        for i, row in enumerate(r):
            if i % period == 0:
                row["facts and features"] = "-- , contact agent"
            w.writerow(row)
    return dst


def _run_drift_class(name, ctx, state_dir, rows):
    """The `drift` scenario (runtime/excprof acceptance): one tenant's
    input distribution shifts mid-run — the windowed EWMA must leave the
    plan-time-anchored baseline, trip ``respecialize_recommended`` and
    the degraded `exception_drift` health state within one window, then
    RECOVER to ok once the shift reverts. No injected faults: the chaos
    here is the data itself."""
    from tuplex_tpu.core.options import ContextOptions
    from tuplex_tpu.exec import compilequeue as CQ
    from tuplex_tpu.models import zillow
    from tuplex_tpu.runtime import excprof, telemetry
    from tuplex_tpu.serve import JobService, request_from_dataset

    clean = os.path.join(state_dir, "drift-clean.csv")
    zillow.generate_csv(clean, rows, seed=11)
    shifted = _shift_csv(clean, os.path.join(state_dir,
                                             "drift-shifted.csv"))
    want = zillow.run_reference_python(clean)
    # fresh compile plane (an inherited `.timeout` negative-cache marker
    # from the smoke classes' tight deadline would degrade the stage to
    # the interpreter WHOLESALE — rate 1.0 on clean traffic, no drift
    # signal left to measure) + fresh exception-plane state, with a short
    # window/half-life so the scenario runs in seconds
    os.environ["TUPLEX_AOT_CACHE"] = os.path.join(state_dir, f"aot-{name}")
    CQ.clear()
    CQ._TIMEOUTS.clear()
    _set_faults("", state_dir, name)
    excprof.clear()
    window_s = 0.4
    opts = ContextOptions(ctx.options_store.to_dict())
    opts.set("tuplex.serve.driftWindowS", window_s)
    opts.set("tuplex.tpu.excprofHalfLifeS", window_s)
    tenant = "drifty"
    svc = JobService(opts)
    t0 = time.perf_counter()
    n_jobs = [0]

    def run_one(path):
        h = svc.submit(request_from_dataset(
            zillow.build_pipeline(ctx.csv(path)),
            name=f"{name}-j{n_jobs[0]}", tenant=tenant))
        n_jobs[0] += 1
        assert h.wait(1200) == "done", (h.name, h.state, h.error)
        return h

    def settle():
        time.sleep(window_s * 1.2)
        excprof.roll()

    try:
        # phase A — the plan-normal era: clean traffic calibrates the
        # anchor (first rolled window) and the EWMA
        h = run_one(clean)
        assert h.result() == want, "drift: wrong clean-phase output"
        settle()
        run_one(clean)
        settle()
        assert not excprof.respecialize_recommended(tenant), \
            f"drift: tripped on clean traffic " \
            f"(score {excprof.drift_score(tenant):.2f})"
        # phase B — the shift: same pipeline, dirty facts
        trip_windows = 0
        for _ in range(6):
            run_one(shifted)
            settle()
            trip_windows += 1
            if excprof.respecialize_recommended(tenant):
                break
        fired = excprof.respecialize_recommended(tenant)
        peak = excprof.drift_score(tenant)
        assert fired, f"drift: never tripped (score {peak:.2f})"
        health_shift = telemetry.health()["state"] \
            if telemetry.enabled() else "degraded"
        assert health_shift != "ok", \
            "drift: health stayed ok through the shift"
        # phase C — revert: clean traffic again, the EWMA must decay
        # below threshold and health must return to ok on its own
        recover_windows = 0
        for _ in range(30):
            run_one(clean)
            settle()
            recover_windows += 1
            if not excprof.respecialize_recommended(tenant):
                break
        assert not excprof.respecialize_recommended(tenant), \
            f"drift: never recovered " \
            f"(score {excprof.drift_score(tenant):.2f})"
        final = telemetry.health()["state"] \
            if telemetry.enabled() else "ok"
        assert final == "ok", f"drift: health did not recover ({final})"
    finally:
        svc.close()
    wall = time.perf_counter() - t0
    rep = excprof.scope_report(tenant)
    return {"wall_s": round(wall, 3), "jobs": n_jobs[0],
            "jobs_ok": n_jobs[0], "jobs_failed_clean": 0,
            "retries": 0, "respecialize_fired": int(fired),
            "drift_trip_windows": trip_windows,
            "drift_recover_windows": recover_windows,
            "drift_peak": round(peak, 3),
            "exception_rate": round(rep["exception_rate"], 4),
            "health_worst": health_shift, "health_final": final,
            "fault": "data-shift (no injected faults)"}


def _run_crash_class(name, ctx, csvs, want, state_dir, conf_path):
    """The serve-crash class needs a REAL process to kill: launch
    `python -m tuplex_tpu serve`, let the injected crash take it down
    after admission, restart it fault-free over the same root, and fetch
    every job's exactly-once terminal response."""
    from tuplex_tpu.serve import client as WC

    root = os.path.join(state_dir, f"root-{name}")
    os.makedirs(root, exist_ok=True)
    t0 = time.perf_counter()
    jids = [WC.submit(root, r) for r in _build_requests(ctx, csvs, name)]
    base_env = {k: v for k, v in os.environ.items()
                if k not in ("TUPLEX_FAULTS", "TUPLEX_FAULTS_STATE")}
    base_env["JAX_PLATFORMS"] = "cpu"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    base_env["PYTHONPATH"] = repo + os.pathsep + \
        base_env.get("PYTHONPATH", "")
    argv = [sys.executable, "-m", "tuplex_tpu", "serve", root,
            "--conf", conf_path]
    p1 = subprocess.run(
        argv, env=dict(base_env,
                       TUPLEX_FAULTS="serve:crash-after-admit:once"),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, timeout=600)
    assert p1.returncode == 70, \
        f"{name}: server did not crash as injected " \
        f"(rc={p1.returncode}):\n{p1.stdout.decode()[-2000:]}"
    p2 = subprocess.Popen(argv, env=base_env, stdout=subprocess.PIPE,
                          stderr=subprocess.STDOUT)
    try:
        results = [WC.fetch(root, jid, timeout=600) for jid in jids]
    finally:
        open(os.path.join(root, "STOP"), "w").close()
        out2, _ = p2.communicate(timeout=120)
    wall = time.perf_counter() - t0
    for r in results:
        assert r.get("ok"), f"{name}: job failed: {r.get('error')}"
        assert r["rows"] == want, f"{name}: wrong rows"
    requeues = 0
    for jid in jids:
        j = WC._read_journal(os.path.join(root, "inbox", jid))
        assert j.get("state") == "done", (jid, j)
        requeues += int(j.get("requeues", 0))
    assert requeues >= 1, "no job was actually requeued from the journal"
    # the restarted process's final metrics.prom drop carries its health
    final = "ok"
    try:
        for line in open(os.path.join(root, "metrics.prom")):
            if line.startswith("tuplex_health_state "):
                final = {0: "ok", 1: "degraded",
                         2: "unhealthy"}.get(int(float(line.split()[1])),
                                             "unhealthy")
    except OSError:
        pass
    assert final == "ok", f"{name}: restarted service health {final}"
    return {"wall_s": round(wall, 3), "jobs": len(jids),
            "jobs_ok": len(results), "jobs_failed_clean": 0,
            "crash_requeues": requeues, "health_final": final,
            "fault": "serve:crash-after-admit:once"}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="zillow serve workload under injected faults")
    ap.add_argument("--jobs", type=int, default=3)
    ap.add_argument("--rows", type=int, default=2000,
                    help="zillow rows per job input")
    ap.add_argument("--smoke", action="store_true",
                    help="tier-1 CI variant: tiny inputs, in-process "
                         "classes only (the subprocess crash class has "
                         "its own tier-1 test)")
    ap.add_argument("--deadline", type=float, default=5.0,
                    help="tuplex.tpu.compileDeadlineS for the "
                         "compile-hang class (how long the wedge lives "
                         "before the kill)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)
    if args.smoke:
        args.jobs, args.rows = 2, 200

    import tuplex_tpu
    from tuplex_tpu.models import zillow

    state_dir = tempfile.mkdtemp(prefix="tpx-chaos-")
    try:
        csvs = []
        for i in range(args.jobs):
            p = os.path.join(state_dir, f"zillow-{i}.csv")
            if i == 0:
                zillow.generate_csv(p, args.rows, seed=7)
            else:
                shutil.copy(csvs[0], p)
            csvs.append(p)
        want = zillow.run_reference_python(csvs[0])
        conf = {
            "tuplex.scratchDir": os.path.join(state_dir, "scratch"),
            "tuplex.serve.retryBackoffS": 0.1,
            "tuplex.serve.metricsPromS": 1,
        }
        conf_path = os.path.join(state_dir, "chaos-conf.json")
        with open(conf_path, "w") as fp:
            json.dump(conf, fp)
        ctx = tuplex_tpu.Context(conf)

        classes = {}
        # full mode: only the compile-hang class runs under the tight
        # deadline (the others measure the compiled fault paths). Smoke
        # applies it everywhere: genuine zillow compiles then also die
        # at the deadline and the drill runs in seconds — it checks the
        # FAULT machinery end to end, not compiled-path latency.
        dflt = args.deadline if args.smoke else None
        plan = [
            ("baseline", "", dflt),
            ("compile-hang", "compile:hang:once", args.deadline),
            ("dispatch-flake", "dispatch:raise:p=0.34", dflt),
            ("serve-retry", "serve:raise-step:once", dflt),
        ]
        for name, spec, deadline in plan:
            print(f"[chaos] class {name} ({spec or 'no faults'})",
                  file=sys.stderr, flush=True)
            classes[name] = _run_thread_class(
                name, spec, ctx, csvs, want, state_dir,
                deadline=deadline)
        # the drift class runs WITHOUT the tight smoke deadline — its
        # genuine compiles must live, or the whole stage degrades to the
        # interpreter and the exception rate saturates at 1.0 for clean
        # traffic too (no signal left to trip on)
        print("[chaos] class drift (mid-run distribution shift)",
              file=sys.stderr, flush=True)
        classes["drift"] = _run_drift_class("drift", ctx, state_dir,
                                            args.rows)
        if not args.smoke:
            print("[chaos] class serve-crash (subprocess)",
                  file=sys.stderr, flush=True)
            classes["serve-crash"] = _run_crash_class(
                "serve-crash", ctx, csvs, want, state_dir, conf_path)

        base = classes["baseline"]["wall_s"]
        # the drift class's wall is dominated by its deliberate window
        # sleeps + fresh compiles, not a fault path — it reports its own
        # trip/recover latencies instead of gating the worst-class wall
        worst = max(v["wall_s"] for k, v in classes.items()
                    if k not in ("baseline", "drift"))
        result = {
            "metric": "chaos_zillow_worst_class_wall_s",
            "value": worst,
            "unit": "s",
            "n_jobs": args.jobs,
            "rows": args.rows,
            "baseline_wall_s": base,
            "worst_over_baseline": round(worst / base, 3) if base else 0.0,
            "compiles_killed": sum(v.get("compiles_killed", 0)
                                   for v in classes.values()),
            "deadline_timeouts": sum(v.get("deadline_timeouts", 0)
                                     for v in classes.values()),
            "classes": classes,
        }
        ctx.close()
    finally:
        _set_faults("", state_dir, "teardown")
        shutil.rmtree(state_dir, ignore_errors=True)
    line = json.dumps(result)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as fp:
            fp.write(line + "\n")
    if args.smoke:
        assert result["compiles_killed"] >= 1, \
            "compile-hang class never killed a compile child"
        assert classes["serve-retry"]["retries"] >= 1, \
            "serve-retry class never retried"
        assert classes["drift"]["respecialize_fired"] == 1, \
            "drift class never recommended respecialization"
        print("chaos-bench OK", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
