#!/usr/bin/env python3
"""Tier-1 smoke for the closed respecialization loop (serve/respec).

Synthetic drift on the zillow workload: one tenant's "facts and
features" cell breaks on half its rows mid-run and NEVER reverts. The
self-healing contract under test, end to end and in seconds:

  1. the exception-plane EWMA (runtime/excprof) trips
     ``respecialize_recommended`` for the tenant;
  2. the controller builds a re-speculated candidate from the LIVE
     observed code distribution and compiles it on the BACKGROUND lane
     (zero foreground compile-pool slots);
  3. the tenant's next job canaries the candidate and the service
     hot-swaps at the job boundary;
  4. the drift score returns below ``excprofDriftThreshold`` — on the
     same shifted traffic, without a restart — and every job's rows stay
     correct for its own input throughout;
  5. the lifecycle is observable: ``serve_respec_*`` counters in the
     Prometheus exposition and a promote event in the tenant history.

Prints one BENCH-style JSON line (``scripts/bench_diff.py`` gates
``promote_s`` / ``drift_after_promote`` / ``respec_promotions``).

    python scripts/respec_smoke.py
    python scripts/respec_smoke.py --rows 400 --out RESPEC.json
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))          # run from anywhere


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="closed-loop respecialization smoke (zillow drift)")
    ap.add_argument("--rows", type=int, default=160)
    ap.add_argument("--window", type=float, default=0.3,
                    help="drift window seconds (drives the wall clock)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)

    state = tempfile.mkdtemp(prefix="tpx-respec-smoke-")
    prev_aot = os.environ.get("TUPLEX_AOT_CACHE")
    os.environ["TUPLEX_AOT_CACHE"] = os.path.join(state, "aot")
    try:
        import tuplex_tpu
        from tuplex_tpu.core.options import ContextOptions
        from tuplex_tpu.exec import compilequeue as CQ
        from tuplex_tpu.models import zillow
        from tuplex_tpu.runtime import excprof, telemetry
        from tuplex_tpu.serve import JobService, request_from_dataset

        CQ.clear()
        excprof.clear()
        clean = os.path.join(state, "clean.csv")
        zillow.generate_csv(clean, args.rows, seed=11)
        import csv as _csv

        shifted = os.path.join(state, "shifted.csv")
        with open(clean, newline="") as fin, \
                open(shifted, "w", newline="") as fout:
            r = _csv.DictReader(fin)
            w = _csv.DictWriter(fout, fieldnames=zillow.COLUMNS)
            w.writeheader()
            for i, row in enumerate(r):
                if i % 2 == 0:
                    row["facts and features"] = "-- , contact agent"
                w.writerow(row)
        want_clean = zillow.run_reference_python(clean)
        want_shift = zillow.run_reference_python(shifted)

        ctx = tuplex_tpu.Context(
            {"tuplex.scratchDir": os.path.join(state, "scratch")})
        opts = ContextOptions(ctx.options_store.to_dict())
        win = args.window
        opts.set("tuplex.serve.driftWindowS", win)
        opts.set("tuplex.tpu.excprofHalfLifeS", win)
        opts.set("tuplex.serve.respecCheckS", 0.05)
        opts.set("tuplex.serve.respecDebounce", 1)
        opts.set("tuplex.serve.respecCooldownS", 0)
        opts.set("tuplex.serve.respecCanaryFrac", 1.0)
        opts.set("tuplex.serve.respecCompileDeadlineS", 120)
        svc = JobService(opts)
        assert svc.respec is not None, "respec controller not running " \
            "(tuplex.serve.respec defaulted off?)"
        tenant = "smoke-drifty"
        fg_snap = CQ.snapshot()
        t0 = time.perf_counter()
        n_jobs = [0]

        def run_one(path, want):
            h = svc.submit(request_from_dataset(
                zillow.build_pipeline(ctx.csv(path)),
                name=f"smoke-j{n_jobs[0]}", tenant=tenant))
            n_jobs[0] += 1
            assert h.wait(900) == "done", (h.state, h.error)
            assert h.result() == want, "wrong rows (results must stay " \
                "on the incumbent path until promotion, and correct after)"

        def settle():
            time.sleep(win * 1.2)
            excprof.roll()

        try:
            run_one(clean, want_clean)
            settle()
            run_one(clean, want_clean)
            settle()
            assert not excprof.respecialize_recommended(tenant), \
                "tripped on clean traffic"
            # the shift — permanent; drive until the loop closes
            trip_jobs = 0
            for _ in range(8):
                run_one(shifted, want_shift)
                settle()
                trip_jobs += 1
                if excprof.respecialize_recommended(tenant):
                    break
            assert excprof.respecialize_recommended(tenant), \
                "drift never tripped"
            rep = svc.respec.tenant_report(tenant)
            for _ in range(40):
                run_one(shifted, want_shift)
                settle()
                rep = svc.respec.tenant_report(tenant)
                if rep["promotions"] >= 1:
                    break
            assert rep["promotions"] >= 1, \
                f"respec never promoted: {rep}"
            for _ in range(20):
                run_one(shifted, want_shift)
                settle()
                if not excprof.respecialize_recommended(tenant):
                    break
            score = excprof.drift_score(tenant)
            assert not excprof.respecialize_recommended(tenant), \
                f"drift did not clear after promotion (score {score:.2f})"
            # background-lane isolation: the candidate compile(s) rode
            # the background pool, never a foreground slot
            delta = CQ.delta(fg_snap)
            assert delta.get("background_compiles", 0) >= 1, \
                "candidate compile never used the background lane"
            promote_ev = next((e for e in rep["history"]
                               if e["phase"] == "promote"), {})
            # exposition parity: the lifecycle counters are scrapeable
            if telemetry.enabled():
                prom = telemetry.render_prometheus()
                assert "tuplex_serve_respec_promotions_total" in prom, \
                    "serve_respec_promotions missing from /metrics"
                assert "tuplex_serve_respec_triggered_total" in prom
        finally:
            svc.close()
            ctx.close()
        wall = time.perf_counter() - t0
        result = {
            "metric": "respec_smoke_promote_s",
            "value": promote_ev.get("promote_s", 0.0),
            "unit": "s",
            "rows": args.rows,
            "jobs": n_jobs[0],
            "respec_trip_jobs": trip_jobs,
            "respec_promotions": rep["promotions"],
            "respec_quarantines": rep["quarantines"],
            "respec_rollbacks": rep["rollbacks"],
            "promote_s": promote_ev.get("promote_s", 0.0),
            "drift_after_promote": round(score, 4),
            "background_compiles": delta.get("background_compiles", 0),
            "wall_s": round(wall, 3),
        }
        line = json.dumps(result)
        print(line, flush=True)
        if args.out:
            with open(args.out, "w") as fp:
                fp.write(line + "\n")
        print("respec-smoke OK", file=sys.stderr)
        return 0
    finally:
        if prev_aot is None:
            os.environ.pop("TUPLEX_AOT_CACHE", None)
        else:
            os.environ["TUPLEX_AOT_CACHE"] = prev_aot
        shutil.rmtree(state, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
