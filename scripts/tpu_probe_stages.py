#!/usr/bin/env python3
"""Staged TPU bring-up probe: compile+run each piece of the Zillow pipeline
separately on the real chip, timing every step, so we can see exactly which
kernel the axon tunnel chokes on (round 1/2 saw multi-minute hangs on the
full fused stage).

Run:  python scripts/tpu_probe_stages.py [--rows N]
Each step prints `STEP <name> compile_s=... run_s=...` as soon as it
finishes; run under `timeout` and the last printed STEP is the culprit.
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# persistent compile cache: repeat compiles of the same HLO become instant
CACHE = os.path.expanduser("~/.cache/jax_comp_cache")


def step(name):
    def deco(fn):
        def wrapped(*a, **k):
            t0 = time.perf_counter()
            out = fn(*a, **k)
            print(f"STEP {name} total_s={time.perf_counter() - t0:.2f}",
                  flush=True)
            return out
        return wrapped
    return deco


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=20000)
    args = ap.parse_args()

    os.makedirs(CACHE, exist_ok=True)
    import jax
    jax.config.update("jax_compilation_cache_dir", CACHE)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    t0 = time.perf_counter()
    dev = jax.devices()[0]
    print(f"STEP devices total_s={time.perf_counter() - t0:.2f} "
          f"platform={dev.platform}", flush=True)

    import jax.numpy as jnp

    @step("matmul_bf16")
    def _matmul():
        x = jnp.ones((1024, 1024), jnp.bfloat16)
        return (x @ x).sum().block_until_ready()
    _matmul()

    # --- byte-matrix string kernel: the core primitive of every stage ------
    import numpy as np
    from tuplex_tpu.ops import strings as S

    rng = np.random.default_rng(0)
    N, W = args.rows, 32
    data = rng.integers(48, 58, size=(N, W), dtype=np.uint8)
    lens = rng.integers(1, 19, size=(N,), dtype=np.int32)

    @step("parse_i64")
    def _parse():
        f = jax.jit(S.parse_i64)
        v, bad = f(jnp.asarray(data), jnp.asarray(lens))
        v.block_until_ready()
    _parse()

    @step("parse_f64")
    def _parsef():
        f = jax.jit(S.parse_f64)
        v, bad = f(jnp.asarray(data), jnp.asarray(lens))
        v.block_until_ready()
    _parsef()

    # --- zillow CSV decode stage (fused device CSV parse) ------------------
    import tempfile
    import tuplex_tpu
    from tuplex_tpu.models import zillow

    cache_dir = os.path.join(tempfile.gettempdir(), "tuplex_tpu_bench")
    os.makedirs(cache_dir, exist_ok=True)
    data_csv = os.path.join(cache_dir, f"zillow_{args.rows}.csv")
    if not os.path.exists(data_csv):
        zillow.generate_csv(data_csv, args.rows, seed=42)

    ctx = tuplex_tpu.Context()

    @step("zillow_source_only")
    def _src():
        return ctx.csv(data_csv).take(5)
    _src()

    @step("zillow_map_only")
    def _map():
        ds = ctx.csv(data_csv)
        return ds.mapColumn("zipcode", lambda z: z[:5]).take(5)
    _map()

    @step("zillow_full_take")
    def _full():
        ds = zillow.build_pipeline(ctx.csv(data_csv))
        return ds.take(5)
    _full()

    @step("zillow_full_collect")
    def _collect():
        ds = zillow.build_pipeline(ctx.csv(data_csv))
        return ds.collect()
    out = _collect()
    print(f"rows_out={len(out)}", flush=True)

    @step("zillow_full_collect_2nd")
    def _collect2():
        ds = zillow.build_pipeline(ctx.csv(data_csv))
        return ds.collect()
    _collect2()
    print("ALL OK", flush=True)


if __name__ == "__main__":
    main()
