#!/usr/bin/env python3
"""Compare two bench JSON files and fail on regressions.

The bench trajectory (BENCH_r01..r05, serve_bench output) has so far been
checked by eyeball; this makes it a gate:

    python scripts/bench_diff.py BENCH_r04.json BENCH_r05.json
    python scripts/bench_diff.py old.json new.json --threshold 0.05
    python scripts/bench_diff.py a.json b.json --keys value compile_s

Accepts either shape per file:
  * a driver wrapper ``{"parsed": {...}, ...}`` (the committed BENCH_r*
    files) — the ``parsed`` dict is compared;
  * a raw result line ``{"metric": ..., "value": ..., ...}`` (bench.py /
    scripts/serve_bench.py stdout).

Every numeric key present in BOTH files is compared with a per-key
direction (rows/s and speedups must not fall; compile seconds, transfer
bytes and latency percentiles must not rise). A move past ``--threshold``
(relative, default 10%) in the bad direction is a REGRESSION: it is
printed, and the exit code is 1 so CI and the driver can gate on it.
Improvements and within-threshold noise exit 0.
"""

from __future__ import annotations

import argparse
import json
import sys

#: direction per key: True = higher is better. Keys absent here are
#: compared informationally (printed, never a regression) because their
#: good direction is ambiguous. "value" is NOT here on purpose — the
#: primary metric's direction depends on its unit (rows/s throughput
#: rises, a latency-seconds p99 falls); see value_direction().
HIGHER_BETTER = {
    "vs_baseline": True,
    "vs_llvm": True,
    "jobs_per_s": True,
    "speedup_wall": True,
    "analyzer_inferred_ops": None,   # informational
    "compile_s": False,
    "stage_compiles": False,
    "d2h_bytes": False,
    "h2d_bytes": False,
    # device-plane cost attribution (runtime/devprof): measured device
    # seconds and the peak executable footprint must not rise; achieved
    # roofline fraction must not fall. The leaf-name rule makes the
    # per-stage dotted keys (stage_costs.0.device_s, ...) gate too.
    # flops/device_bytes are properties of the compiled graph, not
    # speed — a plan change legitimately moves them, so informational.
    "device_s": False,
    "device_cold_s": False,
    "hbm_peak": False,
    # informational: peak footprint vs the JOB's MemoryManager budget —
    # a host-side config change (tuplex.executorMemory) moves it with
    # zero device-side change, so it must not gate
    "hbm_budget_frac": None,
    "roofline_frac": True,
    "flops": None,                   # informational (plan-dependent)
    "device_bytes": None,            # informational (plan-dependent)
    "device_dispatches": None,
    # exception-plane observability (runtime/excprof): the fraction of
    # rows leaking off the compiled fast path must not grow, nor the
    # process-global drift vs the plan-time baseline — both regress like
    # perf (a rate jump means the normal-case speculation decayed). The
    # leaf-name rule gates serve_bench's per-tenant dotted twins
    # (tenants.<t>.exception_rate) too. Tier-mix fractions: rows falling
    # ALL the way to the interpreter must not grow; the exact-exit and
    # general shares are informational (a shift between them is a plan
    # change, not a regression — only the interpreter tail is pure tax).
    "exception_rate": False,
    "drift_score": False,
    # matched via the two-segment rule in direction(): the leaf
    # 'interpreter' alone is too generic to gate, so the tier-mix keys
    # register under their parent — "resolve_tier_mix.interpreter"
    # (Metrics.as_dict) and "tier_mix.interpreter" (serve_bench's
    # tenants.<t>.tier_mix.interpreter) both resolve here
    "resolve_tier_mix.interpreter": False,
    "tier_mix.interpreter": False,
    "resolve_tier_mix.exact_exit": None,
    "resolve_tier_mix.general": None,
    # latency-budget plane (runtime/critpath): the wall fraction the
    # sweep could NOT attribute must not grow (observability decaying is
    # a regression even when perf holds), nor the seconds burned on the
    # interpreter resolve tier — matched via the two-segment rule like
    # the tier-mix keys ('resolve_interpreter' could gate as a bare leaf,
    # but registering the dotted form keeps it scoped to bench budgets).
    # The other bucket seconds are informational: a plan change
    # legitimately moves time between compile/h2d/device/merge, and the
    # aggregate already gates through wall_s / p99 / rows-per-sec.
    "unattributed_frac": False,
    "latency_budget.resolve_interpreter": False,
    "coverage_frac": None,           # informational (tracks unattributed)
    "rows_seen": None,               # informational (dataset-dependent)
    # chaos drift scenario (scripts/chaos_bench.py): windows until the
    # respecialize signal trips after the shift / until health recovers
    # after the revert — detection and recovery latency gate like p99;
    # whether the signal fired at all must not fall (1 -> 0 is a break)
    "drift_trip_windows": False,
    "drift_recover_windows": False,
    "respecialize_fired": True,
    # closed-loop respecialization (serve/respec via chaos_bench's
    # respec-* classes and scripts/respec_smoke.py): trigger-to-promote
    # latency and the recovery window count gate like p99; promotions
    # must not fall (1 -> 0 means the loop stopped closing); rollback /
    # quarantine counts must not grow (a healthy candidate starting to
    # quarantine IS the regression); the residual drift after a promote
    # must not grow
    "promote_s": False,
    "respec_promotions": True,
    "respec_rollbacks": False,
    # "respec_quarantines" is deliberately NOT registered as a bare leaf:
    # the poison class INJECTS its quarantines (informational there), so
    # only the closed-loop class's two-segment form gates (leaf lookup
    # would win over the two-segment rule if both existed)
    "respec-drift.respec_quarantines": False,
    "respec_trip_jobs": False,
    "respec_promote_jobs": False,
    "drift_after_promote": False,
    "respec_markers": None,
    "analyzer_ms": False,
    "spread": False,
    "wall_s": False,
    "p50": False, "p95": False, "p99": False, "max": False, "mean": False,
    # chaos harness keys (scripts/chaos_bench.py): fault-path latency
    # gates like any other latency; recovery outcomes must not shrink
    "baseline_wall_s": False,
    "worst_over_baseline": False,    # chaos tax relative to no faults
    "jobs_ok": True,
    "jobs_failed_clean": None,       # informational (spec-dependent)
    "retries": None,                 # informational (spec-dependent)
    # static vetting (compiler/graphlint): a killed compile is a vetting
    # MISS — every wedge must be caught before submission, so
    # compiles_killed growing is a regression, while hazards_avoided may
    # grow (each one is a deadline+SIGKILL cycle that never happened).
    # graphlint_ms is the analysis cost and must not creep.
    "compiles_killed": False,
    "deadline_timeouts": False,
    "hazards_avoided": True,
    "hazards_found": None,           # informational (workload-dependent)
    "graphlint_ms": False,
    "crash_requeues": None,
}


def load_result(path: str) -> tuple[dict, dict]:
    """(flat, meta) from one bench file (wrapper or raw). Nested dicts
    (serve_bench's per-mode percentile blocks) flatten to dotted keys:
    ``concurrent.p99``. `meta` keeps the string fields ("metric",
    "unit") that decide the primary value's direction."""
    with open(path) as fp:
        data = json.load(fp)
    if isinstance(data, dict) and isinstance(data.get("parsed"), dict):
        data = data["parsed"]
    if not isinstance(data, dict):
        raise ValueError(f"{path}: not a bench result object")
    flat: dict = {}

    def walk(d: dict, prefix: str) -> None:
        for k, v in d.items():
            key = f"{prefix}{k}"
            if isinstance(v, dict):
                walk(v, key + ".")
            elif isinstance(v, (int, float)) and not isinstance(v, bool):
                flat[key] = float(v)

    walk(data, "")
    meta = {k: v for k, v in data.items() if isinstance(v, str)}
    return flat, meta


def value_direction(meta: dict):
    """Direction of the primary "value" from its declared unit: rates
    (rows/s, jobs/s, ops/s) must not fall; latency/seconds metrics must
    not rise; anything else is informational."""
    unit = str(meta.get("unit", "")).lower()
    metric = str(meta.get("metric", "")).lower()
    if "/s" in unit or "per_sec" in metric:
        return True
    if unit in ("s", "ms", "us", "seconds") or "latency" in metric:
        return False
    return None


def direction(key: str, meta: dict):
    """Direction for a (possibly dotted) key: the leaf name decides, so
    ``concurrent.p99`` compares like ``p99``; when the leaf alone is
    unknown the last TWO segments are tried (``tenants.a.tier_mix.
    interpreter`` gates like ``tier_mix.interpreter`` — 'interpreter'
    by itself is too generic to register); "value" defers to the file's
    unit/metric."""
    leaf = key.rsplit(".", 1)[-1]
    if leaf == "value":
        return value_direction(meta)
    if leaf in HIGHER_BETTER:
        return HIGHER_BETTER[leaf]
    leaf2 = ".".join(key.split(".")[-2:])
    return HIGHER_BETTER.get(leaf2, HIGHER_BETTER.get(key))


def compare(old: dict, new: dict, threshold: float,
            keys=None, meta=None) -> tuple[list, list]:
    """(rows, regressions). Each row: (key, old, new, delta_frac, verdict)."""
    rows, regressions = [], []
    meta = meta or {}
    shared = sorted(set(old) & set(new))
    if keys:
        # match full dotted keys, bare leaves, and the two-segment form
        # direction() resolves (tier_mix.interpreter under tenants.<t>.)
        shared = [k for k in shared if k in keys
                  or k.rsplit(".", 1)[-1] in keys
                  or ".".join(k.split(".")[-2:]) in keys]
    for k in shared:
        ov, nv = old[k], new[k]
        delta = (nv - ov) / abs(ov) if ov else (0.0 if nv == ov else
                                               float("inf") if nv > ov
                                               else float("-inf"))
        better = direction(k, meta)
        if better is None:
            verdict = "info"
        elif ov == 0 and nv == 0:
            verdict = "ok"
        else:
            worse = delta < -threshold if better else delta > threshold
            improved = delta > threshold if better else delta < -threshold
            verdict = ("REGRESSION" if worse
                       else "improved" if improved else "ok")
        rows.append((k, ov, nv, delta, verdict))
        if verdict == "REGRESSION":
            regressions.append(k)
    return rows, regressions


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="diff two bench JSON files; exit 1 on regression")
    ap.add_argument("old", help="baseline bench JSON")
    ap.add_argument("new", help="candidate bench JSON")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="relative move counting as a regression "
                         "(default 0.10 = 10%%)")
    ap.add_argument("--keys", nargs="*", default=None,
                    help="restrict the comparison to these keys "
                         "(leaf names match dotted keys)")
    args = ap.parse_args(argv)
    try:
        old, old_meta = load_result(args.old)
        new, new_meta = load_result(args.new)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"bench_diff: {e}", file=sys.stderr)
        return 2
    if old_meta.get("metric") != new_meta.get("metric"):
        print(f"bench_diff: warning — comparing different metrics "
              f"({old_meta.get('metric')} vs {new_meta.get('metric')})",
              file=sys.stderr)
    rows, regressions = compare(old, new, args.threshold, args.keys,
                                meta=new_meta)
    if not rows:
        print("bench_diff: no shared numeric keys to compare",
              file=sys.stderr)
        return 2
    width = max(len(r[0]) for r in rows)
    for k, ov, nv, delta, verdict in rows:
        print(f"{k:<{width}}  {ov:>14.4g}  ->  {nv:>14.4g}  "
              f"{delta:>+8.1%}  {verdict}")
    if regressions:
        print(f"\nbench_diff: {len(regressions)} regression(s) past "
              f"{args.threshold:.0%}: {', '.join(regressions)}",
              file=sys.stderr)
        return 1
    print(f"\nbench_diff: OK ({len(rows)} key(s) within "
          f"{args.threshold:.0%})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
