"""tuplex_tpu — a TPU-native data-processing framework.

A from-scratch re-design of the Tuplex architecture (Spark-like Python UDF
pipelines, data-driven compilation, dual-mode execution) where the compiled
fast path is a jax.jit-traced columnar program running on TPU instead of
LLVM-generated row loops, and distribution uses jax.sharding meshes + XLA
collectives instead of thread pools / AWS Lambda.

Public API mirrors the reference (reference: tuplex/python/tuplex/__init__.py:22-27):

    import tuplex_tpu as tuplex
    c = tuplex.Context()
    c.parallelize([1, 2, None, 4]).map(lambda x: (x, x * x)).collect()
"""

from .core.errors import TuplexException

__version__ = "0.1.0"

__all__ = ["Context", "DataSet", "Metrics", "LambdaContext",
           "TuplexException", "__version__"]


def __getattr__(name):
    # lazy: importing the package must not drag in jax (slow, device init)
    if name == "Context":
        from .api.context import Context
        return Context
    if name == "DataSet":
        from .api.dataset import DataSet
        return DataSet
    if name == "Metrics":
        from .api.metrics import Metrics
        return Metrics
    if name == "LambdaContext":
        from .api.context import LambdaContext
        return LambdaContext
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
