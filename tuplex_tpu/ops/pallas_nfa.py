"""Pallas kernel for the dense Glushkov NFA scan.

The dense engine (ops/nfa.py match_dense) advances an [N, P] f32 state
across string byte columns with one [P, P] matmul per column — already
MXU-shaped, but under plain `lax.scan` XLA round-trips the state through
HBM between steps. This kernel blocks rows into tiles and runs the WHOLE
width loop inside one kernel instance, keeping the state, the follow
matrix, and the class table resident in VMEM (the Pallas playbook:
sequential dependence inside the kernel, parallelism across the grid).

Selected with TUPLEX_NFA_IMPL=pallas. On CPU the kernel runs in Pallas
interpret mode (slow, for correctness tests); on TPU it compiles to
Mosaic. Position tables pad to sublane multiples (8); Mosaic handles the
lane-width relayout.
"""

from __future__ import annotations

import functools

import numpy as np

from ..runtime.jaxcfg import jax, jnp

_ROW_BLOCK = 256


@functools.lru_cache(maxsize=32)
def _build_kernel(P: int, w: int, anchored_start: bool, anchored_end: bool,
                  interpret: bool):
    from jax.experimental import pallas as pl

    # pad positions to a SUBLANE multiple (8); Mosaic relayouts the
    # 8-wide tiles onto 128-lane registers itself — padding P to 128
    # here would waste 16x matmul work for small patterns
    Pp = max(8, -(-P // 8) * 8)

    def kernel(bytes_ref, lens_ref, end_ref, m0_ref, follow_ref, class_ref,
               first_ref, last_ref, out_ref):
        S = jnp.zeros((_ROW_BLOCK, Pp), dtype=jnp.float32)
        matched = m0_ref[...] > 0.5
        lens = lens_ref[...]
        end_at = end_ref[...]
        follow = follow_ref[...]
        firstv = first_ref[...]
        lastv = last_ref[...]

        def body(j, carry):
            S, matched = carry
            byte_col = bytes_ref[:, j]
            if interpret:
                # gather is legal (and far cheaper) off-Mosaic
                cm = class_ref[byte_col, :]                   # [B, Pp]
            else:
                # class membership via one-hot matmul, not a ref gather:
                # Mosaic rejects int indexing on VMEM refs ("Cannot do int
                # indexing on TPU", mosaic/lowering.py — caught by
                # tpu_diag/aot_lower_tpu.py), and the [B,256]x[256,Pp]
                # product is MXU work anyway.
                b32 = byte_col.astype(jnp.int32)
                onehot = (b32[:, None] ==
                          jnp.arange(256, dtype=jnp.int32)[None, :]
                          ).astype(jnp.float32)               # [B, 256]
                cm = jnp.dot(onehot, class_ref[...],
                             preferred_element_type=jnp.float32)  # [B, Pp]
            nxt = jnp.dot(S, follow,
                          preferred_element_type=jnp.float32) > 0.5
            if anchored_start:
                seed = jnp.where(j == 0, firstv, 0.0)[None, :]
            else:
                seed = firstv[None, :]
            # f32 literals: under x64 a bare 1.0 is f64, and Mosaic has no
            # f64->f32 cast (finding 2 of 3 in tpu_diag/aot_lower_tpu.py;
            # TPU_DIAGNOSIS.md lists all three)
            one = jnp.float32(1.0)
            zero = jnp.float32(0.0)
            S2 = jnp.where((nxt | (seed > 0.5)) & (cm > 0.5), one, zero)
            inb = (j < lens)[:, None]
            S2 = jnp.where(inb, S2, zero)
            hit = jnp.max(S2 * lastv[None, :], axis=1) > 0.5
            if anchored_end:
                hit = hit & ((j + 1 == lens) | (j + 1 == end_at))
            return S2, matched | hit

        S, matched = jax.lax.fori_loop(0, w, body, (S, matched))
        out_ref[...] = matched

    def run(bytes_p, lens_p, end_p, m0_p, follow, classtab, firstv, lastv):
        n_blocks = bytes_p.shape[0] // _ROW_BLOCK
        return pl.pallas_call(
            kernel,
            grid=(n_blocks,),
            in_specs=[
                pl.BlockSpec((_ROW_BLOCK, w), lambda i: (i, 0)),
                pl.BlockSpec((_ROW_BLOCK,), lambda i: (i,)),
                pl.BlockSpec((_ROW_BLOCK,), lambda i: (i,)),
                pl.BlockSpec((_ROW_BLOCK,), lambda i: (i,)),
                pl.BlockSpec((Pp, Pp), lambda i: (0, 0)),
                pl.BlockSpec((256, Pp), lambda i: (0, 0)),
                pl.BlockSpec((Pp,), lambda i: (0,)),
                pl.BlockSpec((Pp,), lambda i: (0,)),
            ],
            out_specs=pl.BlockSpec((_ROW_BLOCK,), lambda i: (i,)),
            out_shape=jax.ShapeDtypeStruct((bytes_p.shape[0],), jnp.bool_),
            interpret=interpret,
        )(bytes_p, lens_p, end_p, m0_p, follow, classtab, firstv, lastv)

    return run, Pp


def match_pallas(rx, bytes_, lens, interpret=None):
    """Drive the kernel: pad rows to the block multiple and positions to
    sublane width, then slice the matches back. `interpret=None` picks
    automatically (Mosaic on TPU, interpret elsewhere); tpu_diag's AOT
    lowering passes False explicitly to force the Mosaic path from a CPU
    host."""
    n, w = bytes_.shape
    P = rx.n_pos
    if P == 0:          # pure-anchor pattern ('^$'): decided by matched0
        lens64, end_at = rx._end_masks(bytes_, lens, w)
        return rx._matched0(n, end_at)
    if interpret is None:
        # Mosaic is the only native target this kernel is tuned for (1D
        # blocks, VMEM-resident tables); every other backend interprets
        interpret = jax.default_backend() != "tpu"
    run, Pp = _build_kernel(P, w, rx.anchored_start, rx.anchored_end,
                            interpret)

    lens64, end_at = rx._end_masks(bytes_, lens, w)
    m0 = rx._matched0(n, end_at)

    npad = -(-max(n, 1) // _ROW_BLOCK) * _ROW_BLOCK

    def padrows(a, fill=0):
        return jnp.pad(a, ((0, npad - n),) + ((0, 0),) * (a.ndim - 1),
                       constant_values=fill)

    def padP(a):
        return jnp.pad(a, ((0, 0),) * (a.ndim - 1) + ((0, Pp - P),))

    # trace the kernel with x64 OFF: global x64 + pallas_call + the Mosaic
    # TPU lowering recurses without bound in jax 0.9 (RecursionError even at
    # limit 100k — minimized repro in tpu_diag/aot_lower_tpu.py notes). All
    # kernel inputs are explicitly 32-bit, so narrowing the promotion rules
    # changes nothing semantically. `jax.enable_x64` is the new-jax name;
    # older releases ship the same context manager as
    # jax.experimental.disable_x64.
    try:
        _x64_off = jax.enable_x64(False)
    except AttributeError:
        from jax.experimental import disable_x64 as _dx64

        _x64_off = _dx64()
    with _x64_off:
        out = run(
            padrows(bytes_), padrows(lens64.astype(jnp.int32)),
            padrows(end_at.astype(jnp.int32)),
            padrows(m0.astype(jnp.float32)),
            padP(jnp.asarray(np.pad(rx._follow_dense,
                                    ((0, Pp - P), (0, 0))))),
            padP(jnp.asarray(rx._classtab_dense)),
            padP(jnp.asarray(rx._first_dense)),
            padP(jnp.asarray(rx._last_dense)),
        )
    return out[:n]
