"""Vectorized unanchored regex matching: bit-parallel Glushkov NFA.

The anchored engine (ops/regex.py) compiles `^...` patterns with capture
groups but rejects unanchored searches and alternation. This module covers
the BOOLEAN half of that gap exactly (reference codegens re.search for
arbitrary use, codegen/include/FunctionRegistry.h:71-205): the pattern
becomes a Glushkov position automaton, the state set packs into ONE uint64
lane per row, and a single `lax.scan` over byte columns advances all rows'
state sets together:

    S' = (follow(S) | FIRST) & CLASSTAB[byte]       # unanchored restart
    matched |= S' & LAST (subject to a $-position check)

NFA simulation explores every alternative simultaneously, so there is no
backtracking approximation: `matched` is EXACT for the supported feature
set (literals, classes, '.', alternation, groups-as-grouping, ?, *, +,
{m,n} via expansion, ^ and $). No capture groups — a UDF that consumes
`.group()` on this path raises NotCompilable and the whole UDF interprets.

The scan body is traced once (graph cost ~P ops, not W*P), and the
transition is pure bitwise arithmetic on [N] uint64 — TPU-vector friendly.
"""

from __future__ import annotations

import functools

import numpy as np

from ..core.errors import NotCompilable
from ..runtime.jaxcfg import jnp, lax
from .regex import _category_spec, _in_spec, _byte_in_spec
from .strings import _mxu_gather


def _class_rows(tab, byte_col):
    """tab[byte_col] for a [256, P] 0/1 class table and [N] byte indices.
    The row gather runs on the TPU scalar core per element; the one-hot
    MXU contraction is exact for 0/1 entries (see strings._mxu_gather)."""
    if tab.dtype in (jnp.float32, jnp.bool_) and _mxu_gather():
        oh = byte_col[:, None] == jnp.arange(tab.shape[0],
                                             dtype=byte_col.dtype)[None, :]
        out = jnp.matmul(oh.astype(jnp.bfloat16), tab.astype(jnp.bfloat16),
                         preferred_element_type=jnp.float32)
        return out.astype(tab.dtype)
    return jnp.take(tab, byte_col, axis=0)

try:
    from re import _parser as _sre
    from re import _constants as _sc
except ImportError:  # pragma: no cover - older layout
    import sre_parse as _sre            # type: ignore
    import sre_constants as _sc         # type: ignore

_MAXREPEAT = _sc.MAXREPEAT
_MAX_POSITIONS = 64   # one uint64 lane
_MAX_EXPAND = 32      # {m,n} expansion cap


class _Frag:
    """Glushkov attributes of a subpattern: nullable, first/last position
    sets (bitmasks), with follow edges accumulated in the builder."""

    __slots__ = ("nullable", "first", "last")

    def __init__(self, nullable: bool, first: int, last: int):
        self.nullable = nullable
        self.first = first
        self.last = last


class _Builder:
    def __init__(self):
        self.specs: list[tuple] = []     # position -> class spec
        self.follow: list[int] = []      # position -> bitmask of successors

    def add_position(self, spec: tuple) -> int:
        p = len(self.specs)
        if p >= _MAX_POSITIONS:
            raise NotCompilable("regex too large for the NFA lane")
        self.specs.append(spec)
        self.follow.append(0)
        return p

    def link(self, lasts: int, firsts: int) -> None:
        p = 0
        while lasts:
            if lasts & 1:
                self.follow[p] |= firsts
            lasts >>= 1
            p += 1

    # -- construction over the sre parse tree ------------------------------
    def build_seq(self, seq) -> _Frag:
        frag = _Frag(True, 0, 0)
        for term in seq:
            nxt = self.build_term(term)
            self.link(frag.last, nxt.first)
            frag = _Frag(
                frag.nullable and nxt.nullable,
                frag.first | (nxt.first if frag.nullable else 0),
                nxt.last | (frag.last if nxt.nullable else 0),
            )
        return frag

    def build_term(self, term) -> _Frag:
        op, av = term
        opn = str(op)
        if opn.endswith("NOT_LITERAL"):
            p = self.add_position((("neg",), ("lit", av)))
            return _Frag(False, 1 << p, 1 << p)
        if opn.endswith("LITERAL"):
            p = self.add_position((("lit", av),))
            return _Frag(False, 1 << p, 1 << p)
        if opn.endswith("ANY"):
            p = self.add_position((("neg",), ("lit", 10)))   # '.'
            return _Frag(False, 1 << p, 1 << p)
        if opn.endswith("IN"):
            p = self.add_position(_in_spec(av))
            return _Frag(False, 1 << p, 1 << p)
        if opn.endswith("BRANCH"):
            _, branches = av
            frag = None
            for b in branches:
                f = self.build_seq(list(b))
                frag = f if frag is None else _Frag(
                    frag.nullable or f.nullable,
                    frag.first | f.first, frag.last | f.last)
            return frag if frag is not None else _Frag(True, 0, 0)
        if opn.endswith("SUBPATTERN"):
            g, addf, delf, sub = av
            if addf or delf:
                raise NotCompilable("regex inline flags")
            return self.build_seq(list(sub))
        if opn.endswith("MAX_REPEAT") or opn.endswith("MIN_REPEAT"):
            # MIN (lazy) repeats: laziness changes which match python picks,
            # not WHETHER one exists — boolean existence is identical
            mn, mx, item = av
            sub = list(item)
            if mx != _MAXREPEAT and mx > _MAX_EXPAND:
                raise NotCompilable("regex repeat bound too large")
            if mn > _MAX_EXPAND:
                raise NotCompilable("regex repeat bound too large")
            frag = _Frag(True, 0, 0)
            # m mandatory copies
            for _ in range(mn):
                nxt = self.build_seq(sub)
                self.link(frag.last, nxt.first)
                frag = _Frag(frag.nullable and nxt.nullable,
                             frag.first | (nxt.first if frag.nullable else 0),
                             nxt.last | (frag.last if nxt.nullable else 0))
            if mx == _MAXREPEAT:
                # one looping copy (E* after the mandatory prefix)
                nxt = self.build_seq(sub)
                self.link(frag.last, nxt.first)
                self.link(nxt.last, nxt.first)
                frag = _Frag(frag.nullable,
                             frag.first | (nxt.first if frag.nullable else 0),
                             frag.last | nxt.last)
            else:
                for _ in range(mx - mn):
                    nxt = self.build_seq(sub)
                    self.link(frag.last, nxt.first)
                    frag = _Frag(frag.nullable,
                                 frag.first |
                                 (nxt.first if frag.nullable else 0),
                                 frag.last | nxt.last)
            return frag
        raise NotCompilable(f"regex op {op} (NFA)")


class NFARegex:
    """match(bytes [N, W], lens [N]) -> matched [N] bool (exact)."""

    def __init__(self, pattern: str, anchored_start: bool = False):
        try:
            tree = _sre.parse(pattern)
        except Exception as e:
            raise NotCompilable(f"regex parse: {e}")
        import re as _pyre

        if tree.state.flags & ~_pyre.UNICODE.value:
            raise NotCompilable("regex flags")
        if any(ord(c) > 127 for c in pattern):
            raise NotCompilable("non-ASCII regex pattern")
        terms = list(tree)
        self.anchored_start = anchored_start
        self.anchored_end = False
        # leading ^ / trailing $ (only at the top level)
        # NB: the sre op name for anchors is exactly "AT" — endswith would
        # also hit MAX_REPEAT
        if terms and str(terms[0][0]) == "AT":
            name = str(terms[0][1])
            # \A (AT_BEGINNING_STRING) == ^ without MULTILINE (flags are
            # rejected above)
            if "AT_BEGINNING" in name:
                self.anchored_start = True
                terms = terms[1:]
            else:
                raise NotCompilable(f"regex anchor {terms[0][1]}")
        if terms and str(terms[-1][0]) == "AT":
            name = str(terms[-1][1])
            if name.endswith("AT_END"):
                self.anchored_end = True
                terms = terms[:-1]
            else:
                raise NotCompilable(f"regex anchor {terms[-1][1]}")
        if any(str(op) == "AT" for op, _ in terms):
            raise NotCompilable("regex anchor mid-pattern")
        b = _Builder()
        frag = b.build_seq(terms)
        self.nullable = frag.nullable
        self.first = frag.first
        self.last = frag.last
        self.follow = list(b.follow)
        self.n_pos = len(b.specs)
        # CLASSTAB[c] = bitmask of positions whose class contains byte c
        tab = np.zeros(256, dtype=np.uint64)
        for p, spec in enumerate(b.specs):
            for c in range(256):
                if _byte_in_spec(c, spec):
                    tab[c] |= np.uint64(1 << p)
        self._classtab = tab
        self._follow_np = np.asarray(self.follow, dtype=np.uint64)

    # dense (MXU) formulation tables: TPUs emulate 64-bit ints, so the
    # bitmask scan is the CPU engine; on TPU the state is [N, P] f32 and
    # the position-transition is a MATMUL on the systolic array. Built
    # LAZILY (the CPU default never reads them) from the packed tables.
    @functools.cached_property
    def _dense_tables(self):
        P = self.n_pos
        qbits = np.arange(P, dtype=np.uint64)
        unpack = lambda v: ((np.uint64(v) >> qbits) &
                            np.uint64(1)).astype(np.float32)
        follow = np.stack([unpack(m) for m in self.follow]) if P else \
            np.zeros((0, 0), np.float32)
        classtab = ((self._classtab[:, None] >> qbits[None, :]) &
                    np.uint64(1)).astype(np.float32)
        return follow, classtab, unpack(self.first), unpack(self.last)

    @property
    def _follow_dense(self):
        return self._dense_tables[0]

    @property
    def _classtab_dense(self):
        return self._dense_tables[1]

    @property
    def _first_dense(self):
        return self._dense_tables[2]

    @property
    def _last_dense(self):
        return self._dense_tables[3]

    def match(self, bytes_, lens):
        impl = _nfa_impl()
        if impl == "dense":
            return self.match_dense(bytes_, lens)
        if impl == "pallas":
            from .pallas_nfa import match_pallas

            return match_pallas(self, bytes_, lens)
        return self.match_bitmask(bytes_, lens)

    def _end_masks(self, bytes_, lens, w):
        """(lens64, end_at): '$' also matches just before one trailing
        newline (python semantics)."""
        lens64 = lens.astype(jnp.int64)
        lastpos = jnp.clip(lens64 - 1, 0, max(w - 1, 0))
        trailing_nl = (lens64 > 0) & (
            jnp.take_along_axis(bytes_, lastpos[:, None].astype(jnp.int32),
                                axis=1)[:, 0] == 10)
        return lens64, jnp.where(trailing_nl, lens64 - 1, lens64)

    def _matched0(self, n, end_at):
        if self.nullable:
            if self.anchored_start and self.anchored_end:
                return end_at == 0
            return jnp.ones(n, dtype=bool)
        return jnp.zeros(n, dtype=bool)

    def match_dense(self, bytes_, lens):
        """Dense-state engine: S is [N, P] f32 and the Glushkov transition
        is S @ FOLLOW — a matmul the TPU MXU eats directly (the bitmask
        engine's uint64 ops are EMULATED on TPU). Same observable results
        as match_bitmask (shared golden tests run both)."""
        n, w = bytes_.shape
        P = self.n_pos
        if P == 0:      # pure-anchor pattern ('^$'): decided by matched0
            lens64, end_at = self._end_masks(bytes_, lens, w)
            return self._matched0(n, end_at)
        follow = jnp.asarray(self._follow_dense)
        classtab = jnp.asarray(self._classtab_dense)
        firstv = jnp.asarray(self._first_dense)
        lastv = jnp.asarray(self._last_dense)
        lens64, end_at = self._end_masks(bytes_, lens, w)
        matched0 = self._matched0(n, end_at)
        xs = (jnp.transpose(bytes_).astype(jnp.int32),
              jnp.arange(w, dtype=jnp.int64))

        def step(carry, x):
            S, matched = carry
            byte_col, j = x
            cm = _class_rows(classtab, byte_col)           # [N, P]
            nxt = jnp.dot(S, follow,
                          preferred_element_type=jnp.float32) > 0.5
            if self.anchored_start:
                seed = jnp.where(j == 0, firstv, 0.0)[None, :]
            else:
                seed = firstv[None, :]
            S2 = jnp.where((nxt | (seed > 0.5)) & (cm > 0.5), 1.0, 0.0)
            inb = (j < lens64)[:, None]
            S2 = jnp.where(inb, S2, 0.0)
            hit = jnp.max(S2 * lastv[None, :], axis=1) > 0.5
            if self.anchored_end:
                hit = hit & ((j + 1 == lens64) | (j + 1 == end_at))
            return (S2.astype(jnp.float32), matched | hit), None

        (S, matched), _ = lax.scan(
            step, (jnp.zeros((n, P), dtype=jnp.float32), matched0), xs)
        return matched

    _START_MAX_POS = 32   # [N, P, P] broadcast cap for start tracking

    def match_start(self, bytes_, lens):
        """(matched [N] bool, start [N] int32): the LEFTMOST match start —
        exactly python re.search's scan order (min over all accepting
        threads' seed positions). Min-plus formulation of the Glushkov
        transition: state is [N, P] int32 where the value is the earliest
        seed position reaching that NFA position (INF = inactive):

            S'[p] = min( min_{q: q->p} S[q],  j if p in FIRST )  if byte in
                    class(p) else INF
            best  = min(best, min_{p in LAST} S'[p])   (subject to '$')

        Powers the two-pass unanchored capture-group path (the anchored
        engine re-runs at the found offset — emitter._re_search) and the
        general re.sub loop. Nullable patterns (zero-width match) are not
        representable in a consuming scan — NotCompilable, caller falls
        back. Reference parity target: FunctionRegistry.h:184-205 codegens
        general re.search/re.sub."""
        if self.nullable:
            raise NotCompilable("start tracking over nullable pattern")
        P = self.n_pos
        if P == 0 or P > self._START_MAX_POS:
            raise NotCompilable("pattern outside start-tracking bounds")
        n, w = bytes_.shape
        INF = jnp.int32(1 << 29)   # INF+INF stays inside int32
        follow, classtab, firstv, lastv = self._dense_tables
        cost = jnp.asarray(
            np.where(follow > 0.5, 0, 1 << 29).astype(np.int32))
        cmtab = jnp.asarray(classtab > 0.5)
        first_b = jnp.asarray(firstv > 0.5)
        last_b = jnp.asarray(lastv > 0.5)
        lens64, end_at = self._end_masks(bytes_, lens, w)
        xs = (jnp.transpose(bytes_).astype(jnp.int32),
              jnp.arange(w, dtype=jnp.int64))

        def step(carry, x):
            S, best = carry
            byte_col, j = x
            cm = _class_rows(cmtab, byte_col)                 # [N, P]
            nxt = jnp.min(S[:, :, None] + cost[None, :, :], axis=1)
            if self.anchored_start:
                seed = jnp.where(first_b & (j == 0),
                                 jnp.int32(0), INF)
            else:
                seed = jnp.where(first_b, j.astype(jnp.int32), INF)
            S2 = jnp.minimum(nxt, seed[None, :])
            inb = (j < lens64)[:, None]
            S2 = jnp.where(cm & inb, S2, INF)
            hit = jnp.min(jnp.where(last_b[None, :], S2, INF), axis=1)
            if self.anchored_end:
                at_end = (j + 1 == lens64) | (j + 1 == end_at)
                hit = jnp.where(at_end, hit, INF)
            return (S2, jnp.minimum(best, hit)), None

        (S, best), _ = lax.scan(
            step, (jnp.full((n, P), INF, jnp.int32),
                   jnp.full((n,), INF, jnp.int32)), xs)
        matched = best < (1 << 29)
        return matched, jnp.where(matched, best, 0).astype(jnp.int32)

    def match_bitmask(self, bytes_, lens):
        n, w = bytes_.shape
        classtab = jnp.asarray(self._classtab)
        first = jnp.uint64(self.first)
        last = jnp.uint64(self.last)
        follow_masks = [jnp.uint64(m) for m in self.follow]
        lens64, end_at = self._end_masks(bytes_, lens, w)
        # nullable: an empty match exists at position 0 (and, for
        # '$'-anchored searches, at the end); only the doubly-anchored
        # nullable case ('^$', '^a*$') constrains it to end_at == 0
        matched0 = self._matched0(n, end_at)

        xs = (jnp.transpose(bytes_).astype(jnp.int32),
              jnp.arange(w, dtype=jnp.int64))

        def step(carry, x):
            S, matched = carry
            byte_col, j = x
            cm = jnp.take(classtab, byte_col)
            inb = j < lens64
            nxt = jnp.zeros(n, dtype=jnp.uint64)
            for p, fm in enumerate(follow_masks):
                bit = (S >> np.uint64(p)) & jnp.uint64(1)
                nxt = nxt | jnp.where(bit.astype(bool), fm, jnp.uint64(0))
            if self.anchored_start:
                seed = jnp.where(j == 0, first, jnp.uint64(0))
            else:
                seed = first          # restart at every position
            S2 = (nxt | seed) & cm
            S2 = jnp.where(inb, S2, jnp.uint64(0))
            hit = (S2 & last) != 0
            if self.anchored_end:
                # python's $ matches at end-of-string AND just before one
                # trailing newline — a match may consume that newline too
                hit = hit & ((j + 1 == lens64) | (j + 1 == end_at))
            return (S2, matched | hit), None

        (S, matched), _ = lax.scan(
            step, (jnp.zeros(n, dtype=jnp.uint64), matched0), xs)
        return matched


def _nfa_impl() -> str:
    """Engine choice: 'bitmask' (uint64 bit-parallel; best on CPU),
    'dense' (state [N,P] f32, transition = matmul; rides the TPU MXU where
    64-bit ints are emulated), or 'pallas' (dense formulation as a Pallas
    kernel, row-blocked, state held in VMEM across the width loop).
    TUPLEX_NFA_IMPL overrides; auto = dense on TPU, bitmask elsewhere."""
    import os

    mode = os.environ.get("TUPLEX_NFA_IMPL", "auto")
    if mode in ("bitmask", "dense", "pallas"):
        return mode
    if mode != "auto":
        raise ValueError(f"TUPLEX_NFA_IMPL={mode!r}: expected "
                         "bitmask|dense|pallas|auto")
    from ..runtime.jaxcfg import jax

    return "dense" if jax.default_backend() not in ("cpu",) else "bitmask"


_NFA_CACHE: dict[tuple, NFARegex] = {}


def compile_nfa(pattern: str, anchored_start: bool = False) -> NFARegex:
    key = (pattern, anchored_start)
    rx = _NFA_CACHE.get(key)
    if rx is None:
        rx = NFARegex(pattern, anchored_start)
        if len(_NFA_CACHE) > 256:
            _NFA_CACHE.clear()
        _NFA_CACHE[key] = rx
    return rx
