"""Vectorized string kernels over fixed-width byte matrices.

The TPU-native replacement for the reference's compiled string runtime
(reference: tuplex/runtime/src/StringFunctions.cc:76-439 — SIMD strLower etc.,
and codegen'd str methods in codegen/include/FunctionRegistry.h:71-205).

Representation: a batch of N strings is (bytes: uint8 [N, W] zero-padded,
lens: int32 [N]). All kernels are shape-static jnp programs — constant
needles/widths are baked into the trace (they come from UDF constants, which
the data-driven compiler specializes on, exactly like the reference bakes
constants into LLVM IR).

Conventions:
  * kernels never raise — they return (result..., err) or sentinel values;
    the emitter turns sentinels into error-code lattice updates
  * positions use int32; -1 means "not found" (Python find semantics)
"""

from __future__ import annotations

import numpy as np

from ..runtime.jaxcfg import jnp, lax


def const_bytes(s: str) -> np.ndarray:
    return np.frombuffer(s.encode("utf-8"), dtype=np.uint8)


def broadcast_const(s: str, n: int, width: int | None = None):
    """Materialize a python str constant as an [n, W] batch."""
    b = const_bytes(s)
    w = max(len(b), 1) if width is None else width
    mat = np.zeros((1, w), dtype=np.uint8)
    mat[0, : len(b)] = b
    return (
        jnp.broadcast_to(jnp.asarray(mat), (n, w)),
        jnp.full((n,), len(b), dtype=jnp.int32),
    )


def _pos_mask(width: int, lens):
    """[N, width] bool — True where position < len."""
    return jnp.arange(width, dtype=jnp.int32)[None, :] < lens[:, None]


import contextvars

# per-trace override: _CpuJit (exec/local.py) traces host-CPU executables
# while the process default backend is still the accelerator, so the
# backend check below would wrongly pick the MXU formulations there
_MXU_OVERRIDE: contextvars.ContextVar = contextvars.ContextVar(
    "tuplex_mxu_gather", default=None)


def mxu_gather_override(value):
    """Context manager forcing the MXU-gather decision during a trace."""
    import contextlib

    @contextlib.contextmanager
    def _cm():
        tok = _MXU_OVERRIDE.set(value)
        try:
            yield
        finally:
            _MXU_OVERRIDE.reset(tok)

    return _cm()


def _mxu_gather() -> bool:
    """Whether per-row byte gathers/scatters reformulate as one-hot bf16
    matmuls. XLA-TPU lowers take_along_axis/scatter on [N, W] matrices to
    the scalar core (~49 ms for u8[81920, 56] measured on a v5e via the
    profiler, tpu_diag/gather_probe2.py); the identical one-hot contraction
    runs on the MXU in 0.27 ms. Byte values (< 256) are exact in bf16 and
    exactly one one-hot term fires per output element, so the rewrite is
    bit-exact. CPU keeps the native gather (the matmul costs W x more
    compute there). TUPLEX_MXU_GATHER=0/1 overrides."""
    import os

    ov = _MXU_OVERRIDE.get()
    if ov is not None:
        return ov
    mode = os.environ.get("TUPLEX_MXU_GATHER", "auto")
    if mode in ("0", "1"):
        return mode == "1"
    from ..runtime.jaxcfg import jax

    return jax.default_backend() != "cpu"


# contraction chunk for the one-hot rewrites: bounds the materialized
# one-hot slab at N x Wout x 128 whatever the matrix width (an unchunked
# [61440, 512, 512] one-hot wedged the flights stage on the v5e — XLA
# declined to fuse it into the dot and tried to materialize ~16 GB)
_OH_CHUNK = 128
_OH_MAX_W = 1024      # beyond this the scalar gather wins back


def take_cols(mat, idx):
    """take_along_axis(mat, idx, axis=1) with a TPU-fast path.

    For u8/bool matrices on accelerator backends the gather becomes a
    one-hot MXU contraction (see _mxu_gather), chunked along the
    contraction dim to bound memory. idx must already be clipped to
    [0, W) — same contract as every call site's jnp.clip."""
    w = mat.shape[1]
    if mat.dtype in (jnp.uint8, jnp.bool_) and w <= _OH_MAX_W \
            and _mxu_gather():
        acc = None
        for k0 in range(0, w, _OH_CHUNK):
            k1 = min(k0 + _OH_CHUNK, w)
            oh = idx[:, :, None] == jnp.arange(k0, k1,
                                               dtype=jnp.int32)[None, None, :]
            part = jnp.einsum("njk,nk->nj", oh.astype(jnp.bfloat16),
                              mat[:, k0:k1].astype(jnp.bfloat16),
                              preferred_element_type=jnp.float32)
            acc = part if acc is None else acc + part
        return acc.astype(mat.dtype)
    return jnp.take_along_axis(mat, idx, axis=1)


def table_lookup(table, idx):
    """table[idx] for a small (<=256-entry) u8/bool/small-int table and u8
    indices of any shape — the byte-classification primitive (class
    membership, digit values). The element gather runs on the TPU scalar
    core; the one-hot contraction against the table runs on the MXU and is
    exact for values < 256."""
    table = jnp.asarray(table)
    t = table.shape[0]
    if (table.dtype in (jnp.uint8, jnp.bool_, jnp.int8)
            and t <= 256 and _mxu_gather()):
        flat = idx.reshape(-1, idx.shape[-1]) if idx.ndim > 1 \
            else idx.reshape(1, -1)
        oh = flat[:, :, None] == jnp.arange(t, dtype=flat.dtype)[None, None, :]
        out = jnp.einsum("nkt,t->nk", oh.astype(jnp.bfloat16),
                         table.astype(jnp.bfloat16),
                         preferred_element_type=jnp.float32)
        return out.astype(table.dtype).reshape(idx.shape)
    return jnp.take(table, idx)


# ---------------------------------------------------------------------------
# search
# ---------------------------------------------------------------------------

def find_const(bytes_, lens, needle: str, start=None, reverse: bool = False):
    """str.find / str.rfind with a constant needle. Returns int32 [N], -1 if
    absent. Empty needle matches at `start` (Python semantics: ''.find -> 0)."""
    n, w = bytes_.shape
    nb = const_bytes(needle)
    m = len(nb)
    if m == 0:
        if reverse:
            return lens.astype(jnp.int32)  # s.rfind('') == len(s)
        base = jnp.zeros(n, dtype=jnp.int32) if start is None else start
        return jnp.where(base > lens, -1, base).astype(jnp.int32)
    if m > w:
        return jnp.full(n, -1, dtype=jnp.int32)
    # match[i, p] = bytes[i, p:p+m] == needle, for p in [0, w-m]
    npos = w - m + 1
    match = jnp.ones((n, npos), dtype=bool)
    for j in range(m):  # m is a compile-time constant: unrolled, XLA fuses
        match = match & (bytes_[:, j : j + npos] == nb[j])
    pos = jnp.arange(npos, dtype=jnp.int32)[None, :]
    inside = pos + m <= lens[:, None]
    match = match & inside
    if start is not None:
        # Python semantics: negative start counts from the end
        nstart = jnp.where(start < 0, jnp.maximum(start + lens, 0), start)
        match = match & (pos >= nstart[:, None])
    if reverse:
        found = jnp.max(jnp.where(match, pos, -1), axis=1)
    else:
        big = npos + 1
        first = jnp.min(jnp.where(match, pos, big), axis=1)
        found = jnp.where(first >= big, -1, first)
    return found.astype(jnp.int32)


def contains_const(bytes_, lens, needle: str):
    return find_const(bytes_, lens, needle) >= 0


def startswith_const(bytes_, lens, prefix: str):
    nb = const_bytes(prefix)
    m = len(nb)
    n, w = bytes_.shape
    if m == 0:
        return jnp.ones(n, dtype=bool)
    if m > w:
        return jnp.zeros(n, dtype=bool)
    ok = lens >= m
    for j in range(m):
        ok = ok & (bytes_[:, j] == nb[j])
    return ok


def endswith_const(bytes_, lens, suffix: str):
    nb = const_bytes(suffix)
    m = len(nb)
    n, w = bytes_.shape
    if m == 0:
        return jnp.ones(n, dtype=bool)
    if m > w:
        return jnp.zeros(n, dtype=bool)
    ok = lens >= m
    start = lens - m
    idx = start[:, None] + jnp.arange(m, dtype=jnp.int32)[None, :]
    idx = jnp.clip(idx, 0, w - 1)
    got = take_cols(bytes_, idx)
    ok = ok & jnp.all(got == jnp.asarray(nb)[None, :], axis=1)
    return ok


# ---------------------------------------------------------------------------
# slicing / substring
# ---------------------------------------------------------------------------

def normalize_index(idx, lens):
    """Python index semantics: negatives count from the end."""
    return jnp.where(idx < 0, idx + lens, idx)


def slice_(bytes_, lens, start, stop, out_width: int | None = None):
    """s[start:stop] with per-row dynamic bounds (already normalized, may be
    None for defaults). Returns (bytes [N, Wout], lens [N]).

    Prefix slices (`s[:x]`, start=None) skip the per-row gather entirely —
    the bytes don't move, only the length shrinks. XLA-CPU lowers
    take_along_axis to a scalar row loop, so this one special case removes
    the dominant cost of the zillow extract kernels (`val[:max_idx]`)."""
    n, w = bytes_.shape
    zeros = jnp.zeros(n, dtype=jnp.int32)
    if stop is None:
        stop = lens
    stop = jnp.clip(jnp.where(stop < 0, stop + lens, stop), 0, lens)
    wout = w if out_width is None else out_width
    cols = jnp.arange(wout, dtype=jnp.int32)[None, :]
    if start is None:
        out_len = stop
        src = bytes_[:, :wout] if wout <= w else \
            jnp.pad(bytes_, ((0, 0), (0, wout - w)))
        keep = cols < out_len[:, None]
        return (jnp.where(keep, src, 0).astype(jnp.uint8),
                out_len.astype(jnp.int32))
    start = jnp.clip(jnp.where(start < 0, start + lens, start), 0, lens)
    out_len = jnp.maximum(stop - start, 0)
    idx = start[:, None] + cols
    idx_c = jnp.clip(idx, 0, w - 1)
    out = take_cols(bytes_, idx_c)
    keep = cols < out_len[:, None]
    return jnp.where(keep, out, 0).astype(jnp.uint8), out_len.astype(jnp.int32)


def char_at(bytes_, lens, idx):
    """s[i] -> (bytes [N,1], len [N]=1, err_oob [N] bool)."""
    n, w = bytes_.shape
    nidx = normalize_index(idx, lens)
    oob = (nidx < 0) | (nidx >= lens)
    safe = jnp.clip(nidx, 0, w - 1)
    ch = take_cols(bytes_, safe[:, None])
    return ch.astype(jnp.uint8), jnp.ones(n, dtype=jnp.int32), oob


# ---------------------------------------------------------------------------
# case / strip / replace / concat
# ---------------------------------------------------------------------------

def lower(bytes_, lens):
    is_up = (bytes_ >= 65) & (bytes_ <= 90)
    return jnp.where(is_up, bytes_ + 32, bytes_).astype(jnp.uint8), lens


def upper(bytes_, lens):
    is_lo = (bytes_ >= 97) & (bytes_ <= 122)
    return jnp.where(is_lo, bytes_ - 32, bytes_).astype(jnp.uint8), lens


def swapcase(bytes_, lens):
    is_up = (bytes_ >= 65) & (bytes_ <= 90)
    is_lo = (bytes_ >= 97) & (bytes_ <= 122)
    out = jnp.where(is_up, bytes_ + 32, jnp.where(is_lo, bytes_ - 32, bytes_))
    return out.astype(jnp.uint8), lens


_WHITESPACE = np.array([9, 10, 11, 12, 13, 32], dtype=np.uint8)


def _is_space(bytes_):
    acc = jnp.zeros(bytes_.shape, dtype=bool)
    for c in _WHITESPACE:
        acc = acc | (bytes_ == c)
    return acc


def _is_in_charset(bytes_, chars: str):
    cs = const_bytes(chars)
    acc = jnp.zeros(bytes_.shape, dtype=bool)
    for c in cs:
        acc = acc | (bytes_ == c)
    return acc


def strip(bytes_, lens, chars: str | None = None, left=True, right=True):
    n, w = bytes_.shape
    strippable = _is_space(bytes_) if chars is None else _is_in_charset(bytes_, chars)
    pos = jnp.arange(w, dtype=jnp.int32)[None, :]
    inside = pos < lens[:, None]
    keepable = ~strippable & inside
    if left:
        big = w + 1
        first_keep = jnp.min(jnp.where(keepable, pos, big), axis=1)
        start = jnp.where(first_keep >= big, lens, first_keep)
    else:
        start = jnp.zeros(n, dtype=jnp.int32)
    if right:
        last_keep = jnp.max(jnp.where(keepable, pos, -1), axis=1)
        stop = jnp.where(last_keep < 0, start, last_keep + 1)
    else:
        stop = lens
    return slice_(bytes_, lens, start, jnp.maximum(stop, start))


def replace_const(bytes_, lens, old: str, new: str):
    """str.replace with constant old/new.

    Fast paths: len(old)==len(new) (in-place mask) and new=='' (compaction).
    General case grows the width by the worst-case expansion factor.
    """
    ob, nb = const_bytes(old), const_bytes(new)
    m, k = len(ob), len(nb)
    n, w = bytes_.shape
    if m == 0:
        raise NotImplementedError("replace with empty pattern")
    # match starts
    npos = w - m + 1
    if npos <= 0:
        return bytes_, lens
    match = jnp.ones((n, npos), dtype=bool)
    for j in range(m):
        match = match & (bytes_[:, j : j + npos] == ob[j])
    pos = jnp.arange(npos, dtype=jnp.int32)[None, :]
    match = match & (pos + m <= lens[:, None])
    # resolve overlaps with Python's greedy left-to-right scan: a match is
    # real iff no real match starts in the previous m-1 positions. Greedy
    # selection is sequential — scan over columns with vectorized row state.
    if m > 1:
        from ..runtime.jaxcfg import lax

        def step(next_ok, col_match):
            real_col = col_match & (next_ok <= 0)
            next_ok = jnp.where(real_col, m - 1, next_ok - 1)
            return next_ok, real_col

        init = jnp.zeros(n, dtype=jnp.int32)
        _, real_t = lax.scan(step, init, jnp.transpose(match))
        match = jnp.transpose(real_t)
    # output positions: each input byte either copied or consumed; matched
    # start produces k bytes instead of m.
    is_start = jnp.pad(match, ((0, 0), (0, w - npos)))  # [n, w]
    if k == m:
        # same-length replacement: bytes never move — overwrite in place
        out = bytes_
        for j in range(k):
            at_j = jnp.pad(is_start[:, : w - j], ((0, 0), (j, 0)))
            out = jnp.where(at_j, jnp.uint8(nb[j]), out)
        return out.astype(jnp.uint8), lens
    consumed = jnp.zeros((n, w), dtype=bool)
    for j in range(m):
        consumed = consumed | jnp.pad(is_start[:, : w - j], ((0, 0), (j, 0)))
    inside = _pos_mask(w, lens)
    copied = inside & ~consumed
    if k == 0:
        # pure deletion = stable compaction of the kept bytes. A sort of
        # the kept positions + one gather beats the scatter formulation
        # ~3.4x on CPU (XLA-CPU lowers scatter to a scalar row loop).
        key = jnp.where(copied, jnp.arange(w, dtype=jnp.int32)[None, :], w)
        sk = jnp.sort(key, axis=1)
        out = take_cols(bytes_, jnp.clip(sk, 0, w - 1))
        out_len = jnp.sum(copied, axis=1).astype(jnp.int32)
        mask = jnp.arange(w, dtype=jnp.int32)[None, :] < out_len[:, None]
        return jnp.where(mask, out, 0).astype(jnp.uint8), out_len
    # contribution of each input position to output length
    contrib = jnp.where(is_start & inside, k, jnp.where(copied, 1, 0))
    out_start = jnp.cumsum(contrib, axis=1) - contrib  # exclusive prefix
    out_len = jnp.sum(contrib, axis=1).astype(jnp.int32)
    grow = max(1, -(-k // m))  # ceil(k/m) worst-case expansion
    wout = w * grow if k > m else w
    out = jnp.zeros((n, wout), dtype=jnp.uint8)
    # scatter copied bytes
    rows = jnp.arange(n)[:, None]
    tgt = jnp.where(copied, out_start, wout)  # park non-copied at off-end
    out = _scatter_cols(out, rows, tgt, bytes_, wout)
    # scatter replacement bytes
    for j in range(k):
        tgt_j = jnp.where(is_start & inside, out_start + j, wout)
        src = jnp.full((n, w), nb[j], dtype=jnp.uint8)
        out = _scatter_cols(out, rows, tgt_j, src, wout)
    return out, out_len


def _scatter_cols(out, rows, tgt, src, wout):
    """out[rows, tgt] = src where tgt < wout (off-end writes dropped).
    Call sites guarantee distinct in-range targets per row, so on TPU the
    scatter becomes the transposed one-hot MXU contraction (<=1 term per
    output element -> exact; see _mxu_gather)."""
    if out.dtype == jnp.uint8 and wout <= _OH_MAX_W and _mxu_gather():
        k = tgt.shape[1]
        vals = None
        hit = None
        for k0 in range(0, k, _OH_CHUNK):   # chunk the contraction dim
            k1 = min(k0 + _OH_CHUNK, k)
            oh = tgt[:, k0:k1, None] == jnp.arange(
                wout, dtype=jnp.int32)[None, None, :]
            part = jnp.einsum("nkj,nk->nj", oh.astype(jnp.bfloat16),
                              src[:, k0:k1].astype(jnp.bfloat16),
                              preferred_element_type=jnp.float32)
            h = oh.any(axis=1)
            vals = part if vals is None else vals + part
            hit = h if hit is None else (hit | h)
        return jnp.where(hit, vals.astype(out.dtype), out)
    pad_out = jnp.zeros((out.shape[0], wout + 1), dtype=out.dtype)
    pad_out = pad_out.at[:, :wout].set(out)
    tgt_c = jnp.clip(tgt, 0, wout)
    pad_out = pad_out.at[rows, tgt_c].set(src.astype(out.dtype), mode="drop")
    return pad_out[:, :wout]


def concat(a_bytes, a_lens, b_bytes, b_lens):
    n, wa = a_bytes.shape
    _, wb = b_bytes.shape
    wout = wa + wb
    out = jnp.zeros((n, wout), dtype=jnp.uint8)
    out = out.at[:, :wa].set(a_bytes)
    # place b at offset a_lens via gather from b with shifted index
    pos = jnp.arange(wout, dtype=jnp.int32)[None, :]
    b_idx = pos - a_lens[:, None]
    valid_b = (b_idx >= 0) & (b_idx < b_lens[:, None])
    b_gathered = take_cols(b_bytes, jnp.clip(b_idx, 0, wb - 1))
    out = jnp.where(valid_b, b_gathered, out)
    # zero anything past a_lens that isn't b payload (stale a padding)
    inside = (pos < a_lens[:, None]) | valid_b
    out = jnp.where(inside, out, 0)
    return out.astype(jnp.uint8), (a_lens + b_lens).astype(jnp.int32)


# ---------------------------------------------------------------------------
# comparisons
# ---------------------------------------------------------------------------

def _pad_common(a_bytes, b_bytes):
    wa, wb = a_bytes.shape[1], b_bytes.shape[1]
    w = max(wa, wb)
    if wa < w:
        a_bytes = jnp.pad(a_bytes, ((0, 0), (0, w - wa)))
    if wb < w:
        b_bytes = jnp.pad(b_bytes, ((0, 0), (0, w - wb)))
    return a_bytes, b_bytes


def equals(a_bytes, a_lens, b_bytes, b_lens):
    # zero-tail invariant (bytes beyond lens are 0): equal lens + equal
    # bytes over the NARROWER width decide it — a string longer than the
    # narrow side's width fails the length check, and in-width tails are
    # zero on both sides. Comparing x == "-" then reads [N, 1], not the
    # [N, W] the wide side would force.
    w = min(a_bytes.shape[1], b_bytes.shape[1])
    a, b = _pad_common(a_bytes[:, :w], b_bytes[:, :w])
    same = jnp.all(a == b, axis=1)
    return same & (a_lens == b_lens)


def compare_lt(a_bytes, a_lens, b_bytes, b_lens, or_equal: bool = False):
    """Lexicographic a < b (byte-wise, matching Python for ASCII)."""
    a, b = _pad_common(a_bytes, b_bytes)
    w = a.shape[1]
    pos = jnp.arange(w, dtype=jnp.int32)[None, :]
    va = pos < a_lens[:, None]
    vb = pos < b_lens[:, None]
    ab = jnp.where(va, a, 0).astype(jnp.int32)
    bb = jnp.where(vb, b, 0).astype(jnp.int32)
    diff = ab != bb
    big = w + 1
    first = jnp.min(jnp.where(diff, pos, big), axis=1)
    no_diff = first >= big
    fa = take_cols(ab, jnp.clip(first, 0, w - 1)[:, None])[:, 0]
    fb = take_cols(bb, jnp.clip(first, 0, w - 1)[:, None])[:, 0]
    lt = jnp.where(no_diff, a_lens < b_lens, fa < fb)
    if or_equal:
        return lt | (no_diff & (a_lens == b_lens))
    return lt


# ---------------------------------------------------------------------------
# parse / format
# ---------------------------------------------------------------------------

# post-strip width cap for numeric parses: i64 needs <= 20 chars, every
# practically-occurring float literal <= 26; longer rows route (fail-safe)
_PARSE_WIN = 32


def _narrowed_parse(core, bytes_, lens):
    """Run a numeric parse core on a _PARSE_WIN-wide stripped window.

    Instead of materializing a stripped copy (strip = reductions + a
    full-width gather through slice_), locate the non-space span with two
    reductions and gather ONLY the window the core reads. Wide columns
    (regex-group slices come in at the source width, e.g. [N, 96] on the
    logs pipeline) would otherwise waste 3-4x the work in strip +
    validity/digit masks. Rows whose non-space span exceeds the window can
    still be valid CPython numbers ('0'*40 + '7', float('1'+'0'*40)) —
    those ROUTE to the interpreter instead of claiming ValueError."""
    n, w = bytes_.shape
    pos = jnp.arange(w, dtype=jnp.int32)[None, :]
    inside = pos < lens[:, None]
    core_m = inside & ~_is_space(bytes_)
    fs = jnp.min(jnp.where(core_m, pos, w + 1), axis=1)
    ls = jnp.max(jnp.where(core_m, pos, -1), axis=1)
    span = jnp.maximum(ls - fs + 1, 0)      # 0 = empty / all-space
    win = min(w, _PARSE_WIN)
    idx = fs[:, None] + jnp.arange(win, dtype=jnp.int32)[None, :]
    sb = take_cols(bytes_, jnp.clip(idx, 0, w - 1))
    sl = jnp.minimum(span, win)
    sb = jnp.where(jnp.arange(win, dtype=jnp.int32)[None, :] < sl[:, None],
                   sb, 0).astype(jnp.uint8)
    val, bad, route = core(sb, sl)
    long_rows = span > win
    return val, bad & ~long_rows, route | long_rows


def parse_i64(bytes_, lens):
    """int(s) semantics: optional surrounding spaces, optional sign, digits.
    Returns (val int64 [N], bad bool [N], route bool [N]): `bad` rows are
    EXACT CPython ValueErrors (syntactically not an int); `route` rows are
    valid Python ints that don't fit i64 (arbitrary precision territory) and
    must resolve on the interpreter — conflating them would report
    ValueError where CPython succeeds (advisor finding, round 1)."""
    n, w = bytes_.shape
    if w <= _PARSE_WIN:
        return _parse_i64_core(bytes_, lens)
    # wide columns: span-based window extraction (the core is strip-free,
    # so a pre-stripped window just means fs=0 inside the core); routing is
    # on the non-space SPAN, so heavy space padding still parses on-device
    return _narrowed_parse(_parse_i64_core, bytes_, lens)


def _parse_i64_core(sb, sl):
    """Strip-free core: instead of materializing a stripped copy of the
    bytes (full-width gather), locate the non-space span [fs, ls] with two
    reductions and read the <=20-byte digit window straight out of the
    original matrix — measured ~2x the strip+parse formulation on CPU
    (29.5ms -> 15.5ms at 100k x 25)."""
    n, w = sb.shape
    pos = jnp.arange(w, dtype=jnp.int32)[None, :]
    inside = pos < sl[:, None]
    sp = _is_space(sb)
    core_m = inside & ~sp
    fs = jnp.min(jnp.where(core_m, pos, w + 1), axis=1)
    ls = jnp.max(jnp.where(core_m, pos, -1), axis=1)
    empty = ls < 0                      # all spaces / empty string
    # any whitespace strictly inside the span is invalid ("1 2")
    inner_sp = jnp.any(sp & (pos >= fs[:, None]) & (pos <= ls[:, None]),
                       axis=1)
    first = take_cols(sb, jnp.clip(fs, 0, w - 1)[:, None])[:, 0]
    has_sign = (first == 43) | (first == 45)  # + -
    neg = first == 45
    digit_start = fs + jnp.where(has_sign, 1, 0)
    ndigits = ls - digit_start + 1
    # Vectorized positional sum over a GATHERED digit window: i64 holds
    # <= 19 digits, so only the first 20 positions after the sign matter.
    # Every term d * 10^e is exact and partial sums of positive terms never
    # exceed the total, so for in-range values this equals the sequential
    # Horner exactly — in ~6 ops instead of a 20-step dependent chain.
    win = min(w, 20)
    pos_w = digit_start[:, None] + jnp.arange(win, dtype=jnp.int32)[None, :]
    wb = take_cols(sb, jnp.clip(pos_w, 0, w - 1))
    in_zone_w = pos_w <= ls[:, None]
    is_digit_w = (wb >= 48) & (wb <= 57)
    # invalid if: any non-digit inside the digit zone, or no digits at all
    bad = jnp.any(in_zone_w & ~is_digit_w, axis=1) | (ndigits <= 0) \
        | empty | inner_sp
    # digits beyond the window only occur when ndigits > 19, which routes
    dw = jnp.where(in_zone_w, (wb - 48).astype(jnp.int64), 0)
    exp = ndigits[:, None] - 1 - jnp.arange(win, dtype=jnp.int32)[None, :]
    term_ok = in_zone_w & (exp >= 0) & (exp <= 18)
    p10 = jnp.asarray(np.array([10 ** k for k in range(19)],
                               dtype=np.int64))
    val = jnp.sum(jnp.where(term_ok,
                            dw * jnp.take(p10, jnp.clip(exp, 0, 18)), 0),
                  axis=1)
    # 19-digit magnitudes above i64 max would wrap: lexicographic compare
    # against the max literal routes them to the interpreter (advisor
    # finding, round 1). The one representable edge (-2**63) is
    # conservatively routed too.
    if win >= 19:
        lit = jnp.asarray(np.frombuffer(b"9223372036854775807", np.uint8)
                          .astype(np.int64) - 48)
        diff = dw[:, :19] - lit[None, :]
        nz = diff != 0
        first = jnp.argmax(nz, axis=1)
        over19 = nz.any(axis=1) & \
            (take_cols(diff, first[:, None])[:, 0] > 0)
        ovf = (ndigits == 19) & over19
    else:
        ovf = jnp.zeros(n, dtype=jnp.bool_)  # w < 19: no 19-digit values
    # CPython accepts grammar outside this kernel: PEP 515 underscores
    # ("1_0" == 10) and non-ASCII digits/whitespace (int("١٢"),
    # "\xa012\xa0"). Those rows ROUTE to the interpreter — claiming
    # ValueError would silently drop rows CPython converts.
    outside = jnp.any(inside & ((sb == 95) | (sb >= 128)), axis=1)
    bad = bad & ~outside
    route = (ovf | (ndigits > 19) | outside) & ~bad
    val = jnp.where(neg, -val, val)
    # materialize: the Horner chain must not be re-inlined (and per-element
    # recomputed) into every downstream consumer fusion
    return lax.optimization_barrier((val, bad, route))


def parse_f64(bytes_, lens):
    """float(s): [sign] digits [.digits] [e[sign]digits].
    Returns (val f64 [N], bad bool [N], route bool [N]): `bad` rows are
    EXACT CPython ValueErrors; `route` rows are inf/infinity/nan literals
    (CPython accepts them, this kernel doesn't evaluate them) and must
    resolve on the interpreter."""
    return _narrowed_parse(_parse_f64_core, bytes_, lens)


def _parse_f64_core(sb, sl):
    n, w = sb.shape
    pos = jnp.arange(w, dtype=jnp.int32)[None, :]
    inside = pos < sl[:, None]
    is_digit = (sb >= 48) & (sb <= 57)
    dot = sb == 46
    e_chr = (sb == 101) | (sb == 69)
    sign = (sb == 43) | (sb == 45)
    big = w + 1
    # landmark positions
    dot_pos = jnp.min(jnp.where(dot & inside, pos, big), axis=1)
    e_pos = jnp.min(jnp.where(e_chr & inside, pos, big), axis=1)
    has_dot = dot_pos < big
    has_e = e_pos < big
    mant_end = jnp.where(has_e, e_pos, sl)
    first = sb[:, 0] if w > 0 else jnp.zeros(n, dtype=jnp.uint8)
    lead_sign = (first == 43) | (first == 45)
    neg = first == 45
    int_start = jnp.where(lead_sign, 1, 0)
    int_end = jnp.where(has_dot & (dot_pos < mant_end), dot_pos, mant_end)
    frac_start = jnp.where(has_dot, dot_pos + 1, mant_end)
    # validity: every char inside must be digit / single dot / single e / sign
    # in legal spot
    ok_char = is_digit | (dot & (pos == dot_pos[:, None])) | \
        (e_chr & (pos == e_pos[:, None])) | \
        (sign & ((pos == 0) | (pos == (e_pos + 1)[:, None])))
    bad = jnp.any(inside & ~ok_char, axis=1)
    n_int = int_end - int_start
    n_frac = jnp.where(has_dot, mant_end - frac_start, 0)
    bad = bad | ((n_int <= 0) & (n_frac <= 0)) | (sl <= 0)
    bad = bad | (has_e & (has_dot & (dot_pos > e_pos)))
    d = jnp.where(is_digit, (sb - 48).astype(jnp.float64), 0.0)
    # mantissa via a rank-based positional sum (replaces a w-step dependent
    # Horner chain — hundreds of sequential ops for wide columns). Each
    # digit's weight is 10^(n_mant - rank); for <= 15-16 digit mantissas
    # every term and partial sum is an exact f64 integer, identical to
    # Horner; beyond that both are approximations (see the fast-path note
    # below).
    in_mant = (pos >= int_start[:, None]) & (pos < mant_end[:, None]) & \
        inside & is_digit
    rank = jnp.cumsum(in_mant.astype(jnp.int32), axis=1)  # 1-based in-mask
    n_mant = rank[:, -1] if w else jnp.zeros(n, dtype=jnp.int32)
    m_exp = n_mant[:, None] - rank
    # exact powers via lookup below 2^53's reach; huge mantissas clamp (the
    # value overflows f64 integer precision there regardless)
    _MAXP = 63
    p10f = jnp.asarray(np.array([10.0 ** k for k in range(_MAXP + 1)],
                                dtype=np.float64))
    mant = jnp.sum(jnp.where(in_mant,
                             d * jnp.take(p10f, jnp.clip(m_exp, 0, _MAXP)),
                             0.0), axis=1)
    scale = jnp.where(has_dot, (mant_end - frac_start).astype(jnp.float64), 0.0)
    # exponent digits: same rank trick (exponents are tiny integers, exact)
    exp_sign_pos = e_pos + 1
    exp_first = take_cols(sb, jnp.clip(exp_sign_pos, 0, w - 1)[:, None])[:, 0]
    exp_has_sign = has_e & ((exp_first == 43) | (exp_first == 45))
    exp_neg = has_e & (exp_first == 45)
    exp_start = jnp.where(exp_has_sign, e_pos + 2, e_pos + 1)
    in_exp = has_e[:, None] & (pos >= exp_start[:, None]) & inside & is_digit
    erank = jnp.cumsum(in_exp.astype(jnp.int32), axis=1)
    e_ndig = erank[:, -1] if w else jnp.zeros(n, dtype=jnp.int32)
    e_exp = e_ndig[:, None] - erank
    exp_val = jnp.sum(jnp.where(in_exp,
                                d * jnp.take(p10f,
                                             jnp.clip(e_exp, 0, _MAXP)),
                                0.0), axis=1)
    n_exp_digits = jnp.where(has_e, sl - exp_start, 1)
    bad = bad | (has_e & (n_exp_digits <= 0))
    exp_val = jnp.where(exp_neg, -exp_val, exp_val)
    # correctly-rounded decimal->binary for the common case: the integer
    # mantissa is exact (< 2^53) and 10^|e| is exact for |e| <= 22, so ONE
    # f64 multiply or divide yields the same bits as CPython's strtod
    # (the classic Gay fast path). |e| > 22 falls back to powers (rare in
    # data files; tiny ulp error possible there).
    e = exp_val - scale
    small = jnp.abs(e) <= 22.0
    # exact powers of ten via lookup (jnp.power lowers to exp*log and is NOT
    # exact even for integer exponents)
    p10 = jnp.asarray(np.array([10.0 ** k for k in range(23)],
                               dtype=np.float64))
    abs_e = jnp.clip(jnp.abs(e), 0.0, 22.0).astype(jnp.int32)
    pow_abs = jnp.take(p10, abs_e)
    val_small = jnp.where(e >= 0, mant * pow_abs, mant / pow_abs)
    # 0 * inf = NaN for zero mantissas with overflowing exponents ('0e400'
    # is 0.0 in CPython): pin the zero-mantissa case
    val_big = jnp.where(mant == 0.0, 0.0, mant * jnp.power(10.0, e))
    val = jnp.where(small, val_small, val_big)
    val = jnp.where(neg, -val, val)

    # float('inf') / 'Infinity' / 'nan' (any case, optional sign) are valid
    # CPython floats outside this kernel's grammar: route, don't ValueError
    def _word_at(word):
        if w == 0:
            return jnp.zeros(n, dtype=jnp.bool_)
        L = len(word)
        idxs = int_start[:, None] + jnp.arange(L, dtype=jnp.int32)[None, :]
        ch = take_cols(sb, jnp.clip(idxs, 0, w - 1))
        m = (sl - int_start) == L
        for j, c in enumerate(word):
            m = m & ((ch[:, j] | 32) == ord(c))
        return m

    # PEP 515 underscores and non-ASCII digits/whitespace are valid CPython
    # float grammar this kernel doesn't evaluate: route, don't ValueError.
    # Mantissas spanning more digits than the power table ROUTE too — the
    # clamped weights would silently shrink the value (review finding:
    # '1'+'0'*69 parsed to 1e63)
    outside = jnp.any(inside & ((sb == 95) | (sb >= 128)), axis=1)
    route = _word_at("inf") | _word_at("infinity") | _word_at("nan") | \
        outside | (n_mant > _MAXP + 1)
    bad = bad & ~route
    return lax.optimization_barrier((val, bad, route))


_I64_MAX_DIGITS = 20  # sign + 19 digits


def format_i64(vals, width: int = 0, pad_zero: bool = False):
    """str(i) / '%0Nd' % i -> (bytes [N, W], lens [N])."""
    n = vals.shape[0]
    w = max(_I64_MAX_DIGITS, width)
    neg = vals < 0
    # careful: abs(i64 min) overflows; data pipelines don't hit it — clamp
    mag = jnp.where(neg, -vals, vals).astype(jnp.uint64)
    # right-aligned digits in ONE broadcast divide: digit j = mag // 10^k
    # % 10 (the old per-digit loop was ~60 sequential div/mod/scatter ops —
    # a measurable slice of the stage graph and of the TPU-tunnel compile)
    wd = min(w, _I64_MAX_DIGITS)  # uint64 has <= 20 decimal digits
    p10 = jnp.asarray(
        np.array([10 ** k for k in range(wd - 1, -1, -1)], dtype=np.uint64))
    digits = ((mag[:, None] // p10[None, :]) % 10).astype(jnp.uint8) + 48
    if w > wd:  # width request beyond any uint64: left-fill with '0's
        digits = jnp.concatenate(
            [jnp.full((n, w - wd), 48, dtype=jnp.uint8), digits], axis=1)
    ndig = jnp.maximum(
        w - jnp.sum(jnp.cumsum(digits != 48, axis=1) == 0, axis=1), 1
    ).astype(jnp.int32)
    if pad_zero and width > 0:
        ndig = jnp.maximum(ndig, width - jnp.where(neg, 1, 0))
    out_len = ndig + jnp.where(neg, 1, 0)
    # build output: optional '-', then the last `ndig` digits
    pos = jnp.arange(w + 1, dtype=jnp.int32)[None, :]
    digit_idx = pos - jnp.where(neg, 1, 0)[:, None] + (w - ndig)[:, None]
    gathered = take_cols(jnp.pad(digits, ((0, 0), (0, 1))),
                         jnp.clip(digit_idx, 0, w))
    out = jnp.where(
        (pos == 0) & neg[:, None], 45, gathered
    )
    inside = pos < out_len[:, None]
    out = jnp.where(inside, out, 0)
    # materialize: the digit-division chain must not re-inline into every
    # downstream consumer (1D consumers like lengths otherwise recompute
    # the whole [N, W] loop per element)
    return lax.optimization_barrier(
        (out.astype(jnp.uint8), out_len.astype(jnp.int32)))


def from_numpy_strings(values: list[str | None]):
    """Host helper for tests."""
    enc = [(v.encode() if v is not None else b"") for v in values]
    w = max((len(b) for b in enc), default=1) or 1
    mat = np.zeros((len(enc), w), dtype=np.uint8)
    lens = np.zeros(len(enc), dtype=np.int32)
    for i, b in enumerate(enc):
        mat[i, : len(b)] = np.frombuffer(b, np.uint8)
        lens[i] = len(b)
    return jnp.asarray(mat), jnp.asarray(lens)


def to_python_strings(bytes_, lens) -> list[str]:
    b = np.asarray(bytes_)
    l = np.asarray(lens)
    return [bytes(b[i, : l[i]]).decode("utf-8", errors="replace")
            for i in range(b.shape[0])]


# ---------------------------------------------------------------------------
# counting / char classes / casing extras
# ---------------------------------------------------------------------------

def count_const(bytes_, lens, needle: str):
    """str.count with constant needle (non-overlapping, Python semantics)."""
    n, w = bytes_.shape
    nb = const_bytes(needle)
    m = len(nb)
    if m == 0:
        return (lens + 1).astype(jnp.int64)
    if m > w:
        return jnp.zeros(n, dtype=jnp.int64)
    npos = w - m + 1
    match = jnp.ones((n, npos), dtype=bool)
    for j in range(m):
        match = match & (bytes_[:, j : j + npos] == nb[j])
    pos = jnp.arange(npos, dtype=jnp.int32)[None, :]
    match = match & (pos + m <= lens[:, None])
    if m > 1:
        from ..runtime.jaxcfg import lax

        def step(next_ok, col_match):
            real_col = col_match & (next_ok <= 0)
            next_ok = jnp.where(real_col, m - 1, next_ok - 1)
            return next_ok, real_col

        _, real_t = lax.scan(step, jnp.zeros(n, dtype=jnp.int32),
                             jnp.transpose(match))
        match = jnp.transpose(real_t)
    return jnp.sum(match, axis=1).astype(jnp.int64)


def char_class_all(bytes_, lens, kind: str):
    """isdigit/isdecimal/isnumeric/isalpha/isalnum/isspace — ASCII
    semantics (the caller's ascii guard routes multibyte rows), all chars
    in class AND non-empty."""
    is_digit = (bytes_ >= 48) & (bytes_ <= 57)
    is_alpha = ((bytes_ >= 65) & (bytes_ <= 90)) | \
        ((bytes_ >= 97) & (bytes_ <= 122))
    if kind in ("isdigit", "isdecimal", "isnumeric"):
        cls = is_digit     # identical over ASCII
    elif kind == "isalpha":
        cls = is_alpha
    elif kind == "isalnum":
        cls = is_digit | is_alpha
    elif kind == "isspace":
        cls = _is_space(bytes_)
    else:
        raise ValueError(kind)
    inside = _pos_mask(bytes_.shape[1], lens)
    return jnp.all(cls | ~inside, axis=1) & (lens > 0)


def case_pred(bytes_, lens, kind: str):
    """islower/isupper/istitle — ASCII semantics (ascii-guarded callers).

    python: islower = at least one cased char and no uppercase; isupper
    symmetric; istitle = at least one cased char, uppercase only at the
    start of cased runs, lowercase only inside them."""
    inside = _pos_mask(bytes_.shape[1], lens)
    up = (bytes_ >= 65) & (bytes_ <= 90) & inside
    lo = (bytes_ >= 97) & (bytes_ <= 122) & inside
    cased = up | lo
    has_cased = jnp.any(cased, axis=1)
    if kind == "islower":
        return has_cased & ~jnp.any(up, axis=1)
    if kind == "isupper":
        return has_cased & ~jnp.any(lo, axis=1)
    if kind == "istitle":
        prev_cased = jnp.pad(cased[:, :-1], ((0, 0), (1, 0)))
        bad = (up & prev_cased) | (lo & ~prev_cased)
        return has_cased & ~jnp.any(bad, axis=1)
    raise ValueError(kind)


def capitalize(bytes_, lens):
    """First char upper, rest lower."""
    lb, ll = lower(bytes_, lens)
    first = lb[:, 0:1]
    is_lo = (first >= 97) & (first <= 122)
    ub = jnp.where(is_lo, first - 32, first)
    out = jnp.concatenate([ub, lb[:, 1:]], axis=1)
    return out.astype(jnp.uint8), ll


def title(bytes_, lens):
    """str.title: uppercase letters starting a word (after non-alpha)."""
    n, w = bytes_.shape
    is_alpha = ((bytes_ >= 65) & (bytes_ <= 90)) | \
        ((bytes_ >= 97) & (bytes_ <= 122))
    prev_alpha = jnp.pad(is_alpha[:, :-1], ((0, 0), (1, 0)))
    starts = is_alpha & ~prev_alpha
    lb, _ = lower(bytes_, lens)
    ub, _ = upper(bytes_, lens)
    return jnp.where(starts, ub, lb).astype(jnp.uint8), lens


def zfill(bytes_, lens, width: int):
    """str.zfill(width): left-pad digits with '0' after any sign."""
    n, w = bytes_.shape
    wout = max(w, width)
    first = bytes_[:, 0] if w else jnp.zeros(n, jnp.uint8)
    has_sign = ((first == 43) | (first == 45)) & (lens > 0)
    out_len = jnp.maximum(lens, width)
    nzeros = out_len - lens
    pos = jnp.arange(wout, dtype=jnp.int32)[None, :]
    sign_col = (pos == 0) & has_sign[:, None]
    # source index into original string for each output position
    body_start = jnp.where(has_sign, 1, 0)
    src_idx = pos - nzeros[:, None]
    src_idx = jnp.where(sign_col, 0, jnp.where(
        pos < (body_start + nzeros)[:, None], -1, src_idx))
    is_zero = (src_idx < 0) & ~sign_col & (pos < out_len[:, None])
    gathered = take_cols(jnp.pad(bytes_, ((0, 0), (0, max(0, wout - w + 1)))),
        jnp.clip(src_idx, 0, w))[:, :wout]
    out = jnp.where(sign_col, first[:, None], jnp.where(is_zero, 48, gathered))
    inside = pos < out_len[:, None]
    out = jnp.where(inside, out, 0)
    return out.astype(jnp.uint8), out_len.astype(jnp.int32)


def pad_left(bytes_, lens, width: int, fillchar: str = " "):
    """Right-align into a field of `width` (str.rjust / '%Nd' space pad)."""
    n, w = bytes_.shape
    wout = max(w, width)
    fill = const_bytes(fillchar)[0]
    out_len = jnp.maximum(lens, width)
    shift = out_len - lens
    pos = jnp.arange(wout, dtype=jnp.int32)[None, :]
    src_idx = pos - shift[:, None]
    in_pad = (src_idx < 0) & (pos < out_len[:, None])
    padded_src = jnp.pad(bytes_, ((0, 0), (0, max(0, wout - w + 1))))
    gathered = take_cols(padded_src, jnp.clip(src_idx, 0, w))[:, :wout]
    out = jnp.where(in_pad, fill, gathered)
    inside = pos < out_len[:, None]
    return jnp.where(inside, out, 0).astype(jnp.uint8), out_len.astype(jnp.int32)


def non_ascii_rows(bytes_, lens):
    """[N] bool — rows containing any non-ASCII byte inside their length.
    Index-space string ops (len, find, slicing) operate on UTF-8 BYTES; for
    multibyte rows that diverges from Python's codepoint semantics, so those
    rows must take the interpreter path (normal-case violation)."""
    inside = _pos_mask(bytes_.shape[1], lens)
    return jnp.any(inside & (bytes_ >= 128), axis=1)


def capwords(bytes_, lens):
    """string.capwords(s): split on whitespace, capitalize each word, join
    with single spaces (collapses runs + strips ends)."""
    n, w = bytes_.shape
    pos = jnp.arange(w, dtype=jnp.int32)[None, :]
    inside = pos < lens[:, None]
    ws = _is_space(bytes_) & inside
    nonws = ~ws & inside
    # capitalize: lower everything, upper at word starts
    prev_nonws = jnp.pad(nonws[:, :-1], ((0, 0), (1, 0)))
    word_start = nonws & ~prev_nonws
    lb, _ = lower(bytes_, lens)
    is_lo = (lb >= 97) & (lb <= 122)
    cased = jnp.where(word_start & is_lo, lb - 32, lb)
    # keep: all non-ws bytes, plus ONE space between words (a ws byte whose
    # previous kept char is non-ws and which has a non-ws later)
    nonws_after = jnp.flip(jnp.cumsum(jnp.flip(nonws, 1), axis=1), 1) - nonws
    sep = ws & prev_nonws & (nonws_after > 0)
    keep = nonws | sep
    out_char = jnp.where(sep, 32, cased)
    contrib = keep.astype(jnp.int32)
    out_start = jnp.cumsum(contrib, axis=1) - contrib
    out_len = jnp.sum(contrib, axis=1).astype(jnp.int32)
    out = jnp.zeros((n, w), dtype=jnp.uint8)
    rows = jnp.arange(n)[:, None]
    tgt = jnp.where(keep, out_start, w)
    out = _scatter_cols(out, rows, tgt, out_char, w)
    return out.astype(jnp.uint8), out_len


def pad_right(bytes_, lens, width: int, fillchar: str = " "):
    """Left-align into a field of `width` (str.ljust / '{:5}' on strings)."""
    n, w = bytes_.shape
    wout = max(w, width)
    fill = const_bytes(fillchar)[0]
    out_len = jnp.maximum(lens, width)
    if wout > w:
        bytes_ = jnp.pad(bytes_, ((0, 0), (0, wout - w)))
    pos = jnp.arange(wout, dtype=jnp.int32)[None, :]
    in_pad = (pos >= lens[:, None]) & (pos < out_len[:, None])
    out = jnp.where(in_pad, fill, bytes_)
    inside = pos < out_len[:, None]
    return jnp.where(inside, out, 0).astype(jnp.uint8), out_len.astype(jnp.int32)


def center(bytes_, lens, width: int, fillchar: str = " "):
    """str.center(width[, fillchar]) with CPython's left-margin rule
    (marg // 2 + (marg & width & 1))."""
    n, w = bytes_.shape
    wout = max(w, width)
    fill = const_bytes(fillchar)[0]
    marg = jnp.maximum(width - lens, 0)
    left = marg // 2 + (marg & width & 1)
    out_len = jnp.maximum(lens, width)
    pos = jnp.arange(wout, dtype=jnp.int32)[None, :]
    src_idx = pos - left[:, None]
    in_body = (src_idx >= 0) & (src_idx < lens[:, None])
    padded = jnp.pad(bytes_, ((0, 0), (0, max(0, wout - w + 1))))
    gathered = take_cols(padded, jnp.clip(src_idx, 0, w))[:, :wout]
    inside = pos < out_len[:, None]
    out = jnp.where(in_body, gathered, jnp.where(inside, fill, 0))
    return out.astype(jnp.uint8), out_len.astype(jnp.int32)


def _ws_token_marks(bytes_, lens):
    """(starts, nonws) masks for whitespace-separated tokens."""
    inside = _pos_mask(bytes_.shape[1], lens)
    nonws = inside & ~_is_space(bytes_)
    prev = jnp.pad(nonws[:, :-1], ((0, 0), (1, 0)))
    return nonws & ~prev, nonws


def ws_token_count(bytes_, lens):
    """Number of whitespace-separated tokens per row (len(s.split()))."""
    starts, _ = _ws_token_marks(bytes_, lens)
    return jnp.sum(starts, axis=1).astype(jnp.int64)


def ws_token_bounds(bytes_, lens, k: int):
    """(start, stop, missing) of the k-th whitespace-separated token.
    start==w sentinel rows are reported via `missing`."""
    n, w = bytes_.shape
    starts, nonws = _ws_token_marks(bytes_, lens)
    ordn = jnp.cumsum(starts, axis=1)
    pos = jnp.arange(w, dtype=jnp.int32)[None, :]
    cand = jnp.where(starts & (ordn == k + 1), pos, w)
    start = jnp.min(cand, axis=1).astype(jnp.int32)
    missing = start >= w
    after = pos >= start[:, None]
    cand2 = jnp.where(after & ~nonws, pos, w)
    stop = jnp.minimum(jnp.min(cand2, axis=1).astype(jnp.int32), lens)
    return start, stop, missing


def format_f64(vals, prec: int):
    """%.Nf fixed-point rendering (reference: FunctionRegistry float
    formatting; the reference leans on snprintf — here the digits come from
    scaled integer math). Returns (bytes, lens, suspect): `suspect` rows
    (near-tie rounding where binary-vs-decimal double rounding could
    diverge from CPython, |v| >= 1e15, or non-finite) must take the
    interpreter path."""
    scale_i = int(10 ** prec)
    neg = jnp.signbit(vals)        # CPython renders -0.0 as "-0.00"
    a = jnp.abs(vals)
    scaled_f = a * float(scale_i)
    scaled = jnp.rint(scaled_f).astype(jnp.int64)
    frac = scaled_f - jnp.floor(scaled_f)
    # tie window: a few ULPs of the scaled value (the one rounding the
    # scaling multiply can introduce), NOT a relative 1e-9 — that would
    # mark every value past ~5e8 suspect and silently de-compile them
    tie = jnp.abs(frac - 0.5) <= 16 * 2.2e-16 * jnp.maximum(scaled_f, 1.0)
    suspect = tie | (a >= 1e15) | ~jnp.isfinite(vals)
    ip = scaled // scale_i
    ib, il = format_i64(ip)
    if prec > 0:
        fp = scaled % scale_i
        db, dl = broadcast_const(".", vals.shape[0])
        fb, fl = format_i64(fp, width=prec, pad_zero=True)
        ib, il = concat(*concat(ib, il, db, dl), fb, fl)
    sb, sl_full = broadcast_const("-", vals.shape[0])
    sl = jnp.where(neg, sl_full, 0)
    ob, ol = concat(sb, sl, ib, il)
    return ob, ol, suspect


def splice_spans(bytes_, lens, starts, ends, valid, new: str):
    """Delete the (ordered, non-overlapping) spans [starts[:,k], ends[:,k])
    and insert `new` at each — the output assembler for general re.sub
    (emitter._re_sub's NFA match loop finds the spans; reference:
    FunctionRegistry re.sub codegen). starts/ends are [N, K] int32, valid
    [N, K] bool; invalid spans are ignored. Returns (out_bytes, out_lens)
    at width W + K*max(len(new)-1, 0)."""
    n, w = bytes_.shape
    k = starts.shape[1] if starts.ndim == 2 else 0
    nb = const_bytes(new)
    r = len(new.encode("utf-8"))
    wout = w + k * max(r - 1, 0)
    pos = jnp.arange(w, dtype=jnp.int32)[None, :]
    starts = jnp.where(valid, starts, jnp.int32(w + 1))
    ends = jnp.where(valid, ends, jnp.int32(w + 1))
    span_len = jnp.maximum(ends - starts, 0)
    inside = jnp.zeros((n, w), dtype=bool)
    removed_before = jnp.zeros((n, w), dtype=jnp.int32)
    spans_before = jnp.zeros((n, w), dtype=jnp.int32)
    for j in range(k):
        st = starts[:, j][:, None]
        en = ends[:, j][:, None]
        inside = inside | ((pos >= st) & (pos < en))
        past = en <= pos
        removed_before = removed_before + jnp.where(
            past, (en - st)[:, 0][:, None], 0)
        spans_before = spans_before + past.astype(jnp.int32)
    keep = (pos < lens[:, None]) & ~inside
    out_pos = pos - removed_before + r * spans_before
    # per-row scatters (kept bytes land on distinct output slots; insertion
    # slots are disjoint from them by construction) — _scatter_cols picks
    # the MXU one-hot path on TPU, the .at[].set scatter on CPU
    rows2 = jnp.arange(n, dtype=jnp.int32)[:, None]
    tgt = jnp.where(keep, out_pos, wout)
    out = _scatter_cols(jnp.zeros((n, wout), dtype=bytes_.dtype),
                        rows2, tgt, bytes_, wout)
    # replacement copies: span j inserts at st_j - removed(st_j) + r*j
    cum_removed = jnp.cumsum(span_len, axis=1) - span_len   # removed before j
    for j in range(k):
        base = starts[:, j] - cum_removed[:, j] + r * j
        ok = valid[:, j]
        for rr in range(r):
            tgt_c = jnp.where(ok, base + rr, wout)[:, None]
            src = jnp.full((n, 1), nb[rr], dtype=bytes_.dtype)
            out = _scatter_cols(out, rows2, tgt_c, src, wout)
    total_removed = jnp.sum(jnp.where(valid, span_len, 0), axis=1)
    n_spans = jnp.sum(valid.astype(jnp.int32), axis=1)
    out_lens = lens - total_removed + r * n_spans
    return out, out_lens.astype(lens.dtype)


def replace_class_runs(bytes_, lens, table: np.ndarray, new: str):
    """re.sub('[class]+', new, s): each maximal run of class-member bytes
    becomes `new` (reference: FunctionRegistry re.sub codegen; the common
    data-cleaning subset — full regex replacement stays interpreter).
    `table` is a [256] bool membership table."""
    nb = const_bytes(new)
    k = len(nb)
    n, w = bytes_.shape
    inside = _pos_mask(w, lens)
    member = table_lookup(jnp.asarray(table), bytes_.astype(jnp.int32)) & inside
    prev = jnp.pad(member[:, :-1], ((0, 0), (1, 0)))
    run_start = member & ~prev
    copied = inside & ~member
    contrib = jnp.where(run_start, k, jnp.where(copied, 1, 0))
    out_start = jnp.cumsum(contrib, axis=1) - contrib
    out_len = jnp.sum(contrib, axis=1).astype(jnp.int32)
    wout = w * k if k > 1 else max(w, 1)
    rows = jnp.arange(n)[:, None]
    out = jnp.zeros((n, wout), dtype=jnp.uint8)
    tgt = jnp.where(copied, out_start, wout)   # park non-copied off-end
    out = _scatter_cols(out, rows, tgt, bytes_, wout)
    for j in range(k):   # k is a small compile-time constant
        tgt_j = jnp.where(run_start, out_start + j, wout)
        rep = jnp.full((n, w), nb[j], dtype=jnp.uint8)
        out = _scatter_cols(out, rows, tgt_j, rep, wout)
    return out.astype(jnp.uint8), out_len


def group_thousands(bytes_, lens):
    """Insert ',' every three digits from the right ('{:,}' grouping).
    Input rows are sign+digits (format_i64 output)."""
    n, w = bytes_.shape
    pos = jnp.arange(w, dtype=jnp.int32)[None, :]
    has_sign = (bytes_[:, 0] == 45) | (bytes_[:, 0] == 43)
    sign = has_sign.astype(jnp.int32)
    ndig = lens - sign
    # digit index from the LEFT for each position (sign occupies slot 0)
    didx = pos - sign[:, None]
    inside = (pos < lens[:, None]) & (didx >= 0)
    # commas inserted before this digit = number of complete 3-groups to
    # its right that start after it = (ndig-1-didx) // 3 subtracted from
    # the total; equivalently commas to the LEFT of digit didx:
    total_commas = jnp.maximum(ndig - 1, 0) // 3
    commas_right = jnp.where(inside, (ndig[:, None] - 1 - didx) // 3, 0)
    commas_left = total_commas[:, None] - commas_right
    tgt = jnp.where(inside, pos + commas_left, -1)
    # the sign char stays at position 0 (its didx is -1)
    is_sign_pos = (pos == 0) & has_sign[:, None]
    tgt = jnp.where(is_sign_pos, 0, tgt)
    out_len = (lens + total_commas).astype(jnp.int32)
    wout = w + (max(w, 1) + 2) // 3
    rows = jnp.arange(n)[:, None]
    out = jnp.full((n, wout), ord(","), dtype=jnp.uint8)
    out = _scatter_cols(out, rows, jnp.where(tgt >= 0, tgt, wout),
                        bytes_, wout)
    keep = jnp.arange(wout, dtype=jnp.int32)[None, :] < out_len[:, None]
    return jnp.where(keep, out, 0).astype(jnp.uint8), out_len


def parse_int_base(bytes_, lens, base: int):
    """int(s, base) with a constant base in 2..36. Accepts optional
    surrounding whitespace, one sign, and the matching 0x/0o/0b prefix.
    Returns (value i64, bad bool, overflow bool): `bad` rows raise
    ValueError, `overflow` rows need arbitrary precision (interpreter)."""
    sb, sl = strip(bytes_, lens)
    n, w = sb.shape
    first = sb[:, 0]
    has_sign = ((first == 43) | (first == 45)) & (sl > 0)
    neg = (first == 45) & has_sign
    start = has_sign.astype(jnp.int32)
    prefix = {16: (120, 88), 8: (111, 79), 2: (98, 66)}.get(base)
    if prefix is not None:
        idx0 = jnp.clip(start, 0, w - 1)
        idx1 = jnp.clip(start + 1, 0, w - 1)
        c0 = take_cols(sb, idx0[:, None])[:, 0]
        c1 = take_cols(sb, idx1[:, None])[:, 0]
        has_pref = (c0 == 48) & ((c1 == prefix[0]) | (c1 == prefix[1])) & \
            (sl >= start + 2)
        start = start + jnp.where(has_pref, 2, 0)
    # digit value table: 255 = invalid for this base
    tab = np.full(256, 255, dtype=np.uint8)
    for c in range(256):
        v = None
        if 48 <= c <= 57:
            v = c - 48
        elif 97 <= c <= 122:
            v = c - 87
        elif 65 <= c <= 90:
            v = c - 55
        if v is not None and v < base:
            tab[c] = v
    dig = table_lookup(jnp.asarray(tab), sb.astype(jnp.int32))
    pos = jnp.arange(w, dtype=jnp.int32)[None, :]
    in_digits = (pos >= start[:, None]) & (pos < sl[:, None])
    # CPython accepts '_' separators between digits: exact handling needs
    # positional rules, so underscore rows route to the interpreter
    # (overflow flag) instead of raising
    has_us = jnp.any(in_digits & (sb == 95), axis=1)
    bad = (jnp.any(in_digits & (dig == 255) & (sb != 95), axis=1)
           | (sl <= start))
    # digits such that base**k fits i64 comfortably
    max_digits = 1
    while base ** (max_digits + 1) < 2 ** 62:
        max_digits += 1
    ndig = sl - start
    overflow = (ndig > max_digits) | has_us
    # positional power sum over a bounded window (same technique as
    # parse_i64: no W-step carry chain)
    widx = start[:, None] + jnp.arange(max_digits, dtype=jnp.int32)[None, :]
    wd = take_cols(jnp.where(dig == 255, jnp.uint8(0), dig),
                   jnp.clip(widx, 0, w - 1)).astype(jnp.int64)
    j = jnp.arange(max_digits, dtype=jnp.int32)[None, :]
    exp = jnp.clip(ndig[:, None] - 1 - j, 0, max_digits - 1)
    powers = jnp.asarray(
        np.array([base ** k for k in range(max_digits)], dtype=np.int64))
    term = wd * jnp.take(powers, exp) * (j < ndig[:, None])
    acc = jnp.sum(term, axis=1)
    return jnp.where(neg, -acc, acc), bad, overflow


def int_to_base(vals, base: int, prefix: bool = True):
    """hex()/oct()/bin() rendering: sign + 0x/0o/0b + digits (python
    semantics: hex(-255) == '-0xff'); prefix=False renders the %x/%o
    shape (sign + digits). Returns (bytes, lens)."""
    pref = {16: "0x", 8: "0o", 2: "0b"}[base] if prefix else ""
    n = vals.shape[0]
    neg = vals < 0
    a = jnp.where(neg, -vals, vals).astype(jnp.uint64)
    ndigits = 1
    while base ** ndigits < 2 ** 64:
        ndigits += 1
    digs = []
    cur = a
    for _ in range(ndigits):
        d = (cur % base).astype(jnp.int32)
        digs.append(d)
        cur = cur // base
    # digs[0] = least significant; render most-significant first with
    # leading-zero suppression
    chars = []
    for d in reversed(digs):
        chars.append(jnp.where(d < 10, 48 + d, 87 + d).astype(jnp.uint8))
    mat = jnp.stack(chars, axis=1)                     # [n, ndigits]
    sig = jnp.stack(list(reversed(digs)), axis=1) != 0
    first_sig = jnp.argmax(sig, axis=1).astype(jnp.int32)
    nz = jnp.any(sig, axis=1)
    first_sig = jnp.where(nz, first_sig, ndigits - 1)  # 0 renders '0'
    out_ndig = ndigits - first_sig
    # assemble: sign + prefix + digits (shift digits left)
    head = ("-" + pref, pref)
    hb_neg, hl_neg = broadcast_const(head[0], n)
    hb_pos, hl_pos = broadcast_const(head[1], n, width=hb_neg.shape[1])
    hb = jnp.where(neg[:, None], hb_neg, hb_pos)
    hl = jnp.where(neg, hl_neg, hl_pos)
    db, dl = slice_(mat, jnp.full(n, ndigits, jnp.int32),
                    first_sig, first_sig + out_ndig)
    return concat(hb, hl, db, dl)
