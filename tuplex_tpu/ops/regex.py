"""Vectorized regex matching over byte-matrix columns.

The reference codegens re.search/re.sub into the compiled pipeline
(reference: codegen/include/FunctionRegistry.h:71-205;
StandardModules.cc:30-129 types the `re` module). The TPU equivalent here
compiles an ANCHORED regex subset into a sequence of whole-column kernel
steps over [N, W] byte matrices:

  * literals, char classes (\\d \\s \\w, [..] sets/ranges/negation, '.')
  * greedy quantifiers ? * + {m} {m,n}
  * capturing groups, ^ and $ anchors

Backtracking policy: one retreat level. When a greedy class run collides
with a following single-char literal (e.g. `(\\S*)\\s*"` where '"' is itself
non-space), the matcher retreats to the LAST literal occurrence inside the
run — exactly the first position python's backtracking would try. Rows where
the remaining pattern still fails are reported unmatched, and the caller
routes them to the interpreter: the compiled path therefore never SUCCEEDS
with a different answer than CPython, it can only fail-safe. Patterns
outside the subset raise NotCompilable (whole UDF interprets).
"""

from __future__ import annotations

import re as _pyre
from dataclasses import dataclass, field
from typing import Optional

from ..core.errors import NotCompilable
from ..runtime.jaxcfg import jnp

try:
    from re import _parser as _sre
    from re import _constants as _sc
except ImportError:  # pragma: no cover - older layout
    import sre_parse as _sre            # type: ignore
    import sre_constants as _sc         # type: ignore

_MAXREPEAT = _sc.MAXREPEAT


# ---------------------------------------------------------------------------
# pattern -> step list
# ---------------------------------------------------------------------------

@dataclass
class _Step:
    kind: str                    # "lit" | "class" | "open" | "close" | "end"
    spec: tuple = ()             # class spec items
    min: int = 1
    max: Optional[int] = 1      # None = unbounded
    group: int = -1
    # retreat plan (set on single-char lit steps during analysis)
    retreat_from: int = -1       # index of the greedy step to retreat into
    retreat_min: int = 0         # the greedy step's min (can't retreat past)
    retreat_groups: tuple = ()   # group ids whose END moves with the retreat


def _category_spec(cat) -> tuple:
    name = str(cat).rsplit("_", 1)[-1].lower()
    table = {
        "digit": (("range", 48, 57),),
        "space": (("lit", 9), ("lit", 10), ("lit", 11), ("lit", 12),
                  ("lit", 13), ("lit", 32)),
        "word": (("range", 48, 57), ("range", 65, 90), ("range", 97, 122),
                 ("lit", 95)),
    }
    neg = "not_" in str(cat).lower()
    base = table.get(name)
    if base is None:
        raise NotCompilable(f"regex category {cat}")
    return ((("neg",),) if neg else ()) + base


def _in_spec(items) -> tuple:
    spec: list = []
    neg = False
    for op, av in items:
        opn = str(op)
        if opn.endswith("NEGATE"):
            neg = True
        elif opn.endswith("LITERAL"):
            spec.append(("lit", av))
        elif opn.endswith("RANGE"):
            spec.append(("range", av[0], av[1]))
        elif opn.endswith("CATEGORY"):
            sub = _category_spec(av)
            if sub and sub[0] == ("neg",):
                # negated category inside a set: only as the whole set
                if len(items) != 1:
                    raise NotCompilable("negated category in mixed set")
                return sub
            spec.extend(sub)
        else:
            raise NotCompilable(f"regex set item {op}")
    return (("neg",),) + tuple(spec) if neg else tuple(spec)


def _flatten(tree, steps: list) -> None:
    for op, av in tree:
        opn = str(op)
        if opn.endswith("NOT_LITERAL"):
            # NOT_LITERAL must match before the LITERAL suffix check
            steps.append(_Step("class", (("neg",), ("lit", av))))
        elif opn.endswith("LITERAL"):
            steps.append(_Step("lit", (("lit", av),)))
        elif opn.endswith("ANY"):
            steps.append(_Step("class", (("neg",), ("lit", 10))))  # '.'
        elif opn.endswith("IN"):
            steps.append(_Step("class", _in_spec(av)))
        elif opn.endswith("MAX_REPEAT"):
            mn, mx, item = av
            if len(item) != 1:
                raise NotCompilable("regex repeat of a sequence")
            iop, iav = item[0]
            iopn = str(iop)
            if iopn.endswith("NOT_LITERAL"):
                spec = (("neg",), ("lit", iav))
            elif iopn.endswith("LITERAL"):
                spec = (("lit", iav),)
            elif iopn.endswith("IN"):
                spec = _in_spec(iav)
            elif iopn.endswith("ANY"):
                spec = (("neg",), ("lit", 10))
            else:
                raise NotCompilable(f"regex repeat of {iop}")
            steps.append(_Step("class", spec, min=mn,
                               max=None if mx == _MAXREPEAT else mx))
        elif opn.endswith("SUBPATTERN"):
            g, addf, delf, sub = av
            if addf or delf:
                raise NotCompilable("regex inline flags")
            steps.append(_Step("open", group=g))
            _flatten(sub, steps)
            steps.append(_Step("close", group=g))
        elif opn.endswith("AT"):
            name = str(av)
            if name.endswith("AT_BEGINNING"):
                if any(s.kind not in ("open",) for s in steps):
                    raise NotCompilable("^ not at pattern start")
            elif name.endswith("AT_END"):
                steps.append(_Step("end"))
            else:
                raise NotCompilable(f"regex anchor {av}")
        else:
            raise NotCompilable(f"regex op {op}")


def _byte_in_spec(byte: int, spec: tuple) -> bool:
    neg = bool(spec) and spec[0] == ("neg",)
    items = spec[1:] if neg else spec
    hit = any((it[0] == "lit" and byte == it[1]) or
              (it[0] == "range" and it[1] <= byte <= it[2]) for it in items)
    return hit != neg


def _byte_set(spec: tuple) -> frozenset:
    return frozenset(c for c in range(256) if _byte_in_spec(c, spec))


def _suspect_threshold(steps: list) -> int:
    """First step index at/after which a death may have unexplored
    backtracking alternatives (see CompiledRegex.__init__). Walks each
    variable-length run's reachable followers (skipping groups and min-0
    disjoint classes) looking for class overlap."""
    soft = len(steps)
    for j, stj in enumerate(steps):
        if stj.kind != "class" or \
                (stj.max is not None and stj.min == stj.max):
            continue    # fixed-width or non-run: no alternatives
        bs = _byte_set(stj.spec)
        k = j + 1
        while k < len(steps):
            sk = steps[k]
            if sk.kind in ("open", "close"):
                k += 1
                continue
            if sk.kind == "end":
                break                       # '$' consumes nothing
            if bs & _byte_set(sk.spec):
                if sk.retreat_from == j:
                    # one retreat level is exact; deeper lit occurrences
                    # are not — deaths from the lit onward are suspect
                    soft = min(soft, k)
                else:
                    soft = min(soft, j)
                break
            if sk.min >= 1:
                break   # must consume a char the run can't supply: rigid
            k += 1      # min-0 disjoint class can be empty: keep walking
    return soft


def _analyze_retreats(steps: list) -> None:
    """Mark single-char literal steps that can retreat into a preceding
    unbounded greedy class run (see module docstring for the exactness
    argument)."""
    for i, st in enumerate(steps):
        if st.kind != "lit" and not (st.kind == "class" and st.min == 1
                                     and st.max == 1
                                     and len(st.spec) == 1
                                     and st.spec[0][0] == "lit"):
            continue
        lit_byte = st.spec[-1][1] if st.spec[-1][0] == "lit" else None
        if lit_byte is None:
            continue
        groups: list = []
        j = i - 1
        while j >= 0:
            pj = steps[j]
            if pj.kind in ("open", "close"):
                if pj.kind == "close":
                    groups.append(pj.group)
                j -= 1
                continue
            if pj.kind == "class" and pj.min == 0 and \
                    not _byte_in_spec(lit_byte, pj.spec):
                j -= 1          # zero-width-able class disjoint from lit
                continue
            break
        if j >= 0 and steps[j].kind == "class" and steps[j].max is None \
                and _byte_in_spec(lit_byte, steps[j].spec):
            st.retreat_from = j
            st.retreat_min = steps[j].min
            st.retreat_groups = tuple(groups)


# ---------------------------------------------------------------------------
# runtime
# ---------------------------------------------------------------------------

def _class_mask(bytes_, spec: tuple):
    neg = bool(spec) and spec[0] == ("neg",)
    items = spec[1:] if neg else spec
    m = jnp.zeros(bytes_.shape, dtype=bool)
    for it in items:
        if it[0] == "lit":
            m = m | (bytes_ == it[1])
        else:
            m = m | ((bytes_ >= it[1]) & (bytes_ <= it[2]))
    return ~m if neg else m


class CompiledRegex:
    """Anchored matcher: match(bytes [N,W], lens) -> (matched [N],
    group_start [N, G+1], group_end [N, G+1]). Group 0 is the whole match."""

    def __init__(self, pattern: str):
        self.pattern = pattern
        try:
            tree = _sre.parse(pattern)
        except Exception as e:
            raise NotCompilable(f"regex parse: {e}")
        if tree.state.flags & ~(_pyre.UNICODE.value):
            raise NotCompilable("regex flags")
        steps: list[_Step] = []
        _flatten(list(tree), steps)
        if not pattern.startswith("^"):
            raise NotCompilable("only anchored (^) regex compiles")
        _analyze_retreats(steps)
        self.steps = steps
        self.n_groups = tree.state.groups - 1
        # fail-safety threshold: a greedy class-run whose reachable
        # follower is DISJOINT from its class is RIGID — no shorter run can
        # satisfy the follower (the boundary char stays in the run's
        # class), so deaths behind it are authoritative no-matches, not
        # suspects. Only runs with an OVERLAPPING follower admit deeper
        # backtracking: with single-char lit overlap the retreat explores
        # exactly python's first alternative (deaths at/after that lit are
        # suspect); other overlaps are unexplored (suspect from the run
        # itself). The logs headline's 3% malformed lines die at rigid
        # boundaries and now stay on device instead of routing (r4).
        self.first_var = _suspect_threshold(steps)

    def match(self, bytes_, lens):
        n, w = bytes_.shape
        pos = jnp.zeros(n, dtype=jnp.int32)
        alive = jnp.ones(n, dtype=bool)
        ng = self.n_groups
        gs = [jnp.zeros(n, dtype=jnp.int32) for _ in range(ng + 1)]
        ge = [jnp.zeros(n, dtype=jnp.int32) for _ in range(ng + 1)]
        positions = jnp.arange(w, dtype=jnp.int32)[None, :]
        greedy_state: dict[int, tuple] = {}   # step idx -> (start_pos)
        died_late = jnp.zeros(n, dtype=bool)  # failed at/after first_var

        def byte_at(p):
            idx = jnp.clip(p, 0, w - 1)
            return jnp.take_along_axis(bytes_, idx[:, None], 1)[:, 0]

        def note_deaths(si, before, after):
            # deaths BEFORE the suspect threshold are authoritative (every
            # earlier run is rigid); at/after it, unexplored backtracking
            # may exist
            nonlocal died_late
            if si >= self.first_var:
                died_late = died_late | (before & ~after)
            return after

        for si, st in enumerate(self.steps):
            if st.kind == "open":
                gs[st.group] = pos
                continue
            if st.kind == "close":
                ge[st.group] = pos
                continue
            if st.kind == "end":
                # python's $ also matches just before a trailing '\n'
                at_end = (pos == lens) | \
                    ((pos == lens - 1) & (byte_at(pos) == 10))
                alive = note_deaths(si, alive, alive & at_end)
                continue
            if st.kind == "lit" or (st.min == 1 and st.max == 1):
                inb = pos < lens
                ok = inb & _class_mask(byte_at(pos)[:, None],
                                       st.spec)[:, 0]
                if st.retreat_from >= 0:
                    # retreat into the greedy run: last lit occurrence
                    # (single max-reduce; hit == last >= 0 spares the any())
                    start = greedy_state[st.retreat_from]
                    lit = st.spec[-1][1]
                    window = (positions >=
                              (start + st.retreat_min)[:, None]) & \
                        (positions < pos[:, None]) & (bytes_ == lit)
                    last = jnp.max(jnp.where(window, positions, -1), axis=1)
                    hit = last >= 0
                    use = alive & ~ok & hit
                    # group ends recorded at the greedy end move back too
                    for g in st.retreat_groups:
                        ge[g] = jnp.where(use, last, ge[g])
                    pos = jnp.where(use, last, pos)
                    ok = ok | use
                alive = note_deaths(si, alive, alive & ok)
                pos = jnp.where(alive, pos + 1, pos)
                continue
            # greedy class run
            cmask = _class_mask(bytes_, st.spec)
            blocked = (~cmask) | (positions >= lens[:, None])
            beyond = blocked & (positions >= pos[:, None])
            first_stop = jnp.min(
                jnp.where(beyond, positions, w), axis=1)
            runlen = first_stop - pos
            if st.max is not None:
                runlen = jnp.minimum(runlen, st.max)
            alive = note_deaths(si, alive, alive & (runlen >= st.min))
            greedy_state[si] = pos
            pos = jnp.where(alive, pos + runlen, pos)
        ge[0] = pos
        suspect = died_late
        return alive, suspect, gs, ge


_REGEX_CACHE: dict[str, CompiledRegex] = {}


def compile_regex(pattern: str) -> CompiledRegex:
    rx = _REGEX_CACHE.get(pattern)
    if rx is None:
        rx = CompiledRegex(pattern)
        if len(_REGEX_CACHE) > 256:
            _REGEX_CACHE.clear()
        _REGEX_CACHE[pattern] = rx
    return rx
