"""Job history / observability.

Re-designs the reference's history server (reference: tuplex/historyserver —
Flask+SocketIO+MongoDB; driver posts via HistoryServerConnector.cc:102-198)
without external services: jobs append JSON-lines records under
`tuplex.logDir`, and `render_report()` produces a static self-contained HTML
dashboard. `serve()` exposes it on the webui port via stdlib http.server.
"""

from .recorder import JobRecorder, render_report, serve  # noqa: F401
