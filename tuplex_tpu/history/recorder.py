"""JSON-lines job records + static HTML dashboard."""

from __future__ import annotations

import html
import json
import os
import time
import uuid
from typing import Optional


class JobRecorder:
    """Appends job/stage/exception events to <logDir>/tuplex_history.jsonl
    (reference events: job/stage/task/exception updates, thserver/rest.py)."""

    def __init__(self, log_dir: str, enabled: bool = True,
                 exception_display_limit: int = 5):
        self.exception_display_limit = exception_display_limit
        self.enabled = enabled
        self.path = os.path.join(log_dir or ".", "tuplex_history.jsonl")
        self.job_id = uuid.uuid4().hex[:12]
        self._stage_no = 0

    def _new_job(self) -> None:
        self.job_id = uuid.uuid4().hex[:12]
        self._stage_no = 0

    def _write(self, rec: dict) -> None:
        if not self.enabled:
            return
        rec["job"] = self.job_id
        rec["ts"] = round(time.time(), 3)
        try:
            with open(self.path, "a") as fp:
                fp.write(json.dumps(rec, default=str) + "\n")
        except OSError:
            pass

    def job_started(self, action: str, plan: list) -> None:
        self._new_job()  # each action is its own job in the dashboard
        previews = []
        for st in plan:
            for op in getattr(st, "ops", []) or []:
                for exc_name, row_repr in getattr(
                        op, "sample_exceptions", [])[
                            : self.exception_display_limit]:
                    previews.append({"op": type(op).__name__, "op_id": op.id,
                                     "exc": exc_name, "row": row_repr})
        self._write({"event": "job_start", "action": action,
                     "stages": [type(s).__name__ for s in plan],
                     # sample-time exception previews (reference:
                     # SampleProcessor feeding the webui BEFORE execution)
                     "sample_exception_previews": previews})

    def stage_started(self, stage) -> None:
        """LIVE event: a stage began executing (reference: the driver posts
        task/stage updates to the history server DURING the job,
        HistoryServerConnector.cc:102-198 — not only at completion).
        Carries the fused op count and the split tuner's predicted compile
        seconds so a dashboard watcher can tell a long compile from a hung
        stage BEFORE the stage completes."""
        rec = {"event": "stage_start", "no": self._stage_no + 1,
               "kind": type(stage).__name__}
        ops = getattr(stage, "ops", None)
        if ops:
            rec["n_ops"] = len(ops)
            pred = getattr(stage, "predicted_compile_s", None)
            if pred is None:
                try:
                    from ..plan.splittuner import model_for

                    pred = model_for().predict(len(ops))
                except Exception:
                    pred = None
            if pred is not None:
                rec["predicted_compile_s"] = round(float(pred), 3)
        self._write(rec)
        self._last_progress = 0.0

    def task_progress(self, parts_done: int, rows: int) -> None:
        """LIVE event: partition-level progress inside the running stage.
        Throttled (0.2s) so tight partition loops don't swamp the log."""
        now = time.time()
        if now - getattr(self, "_last_progress", 0.0) < 0.2:
            return
        self._last_progress = now
        self._write({"event": "progress", "no": self._stage_no + 1,
                     "parts": parts_done, "rows": rows})

    def stage_done(self, stage, metrics: dict, exceptions: list) -> None:
        self._stage_no += 1
        sample = [(getattr(e, "trace", None) or repr(e))[:800]
                  for e in exceptions[: self.exception_display_limit]]
        self._write({"event": "stage", "no": self._stage_no,
                     "kind": type(stage).__name__,
                     "metrics": metrics, "exception_sample": sample})

    def worker_task_event(self, task: int, rec: dict) -> None:
        """LIVE event from a fan-out worker (started / finished, rows,
        exception count) — streamed off the task's events.jsonl by the
        driver's poll loop, so remote tasks are visible in the dashboard
        WHILE the job runs (reference: executors push per-task status to
        the history server, HistoryServerConnector.cc:102-198)."""
        self._write({**{k: v for k, v in rec.items()
                        if k not in ("event", "task", "kind", "no")},
                     "event": "task", "task": task,
                     "no": self._stage_no + 1,
                     "kind": rec.get("event", "update")})

    def job_done(self, rows: int, wall_s: float, exc_counts: dict) -> None:
        self._write({"event": "job_done", "rows": rows,
                     "wall_s": round(wall_s, 4),
                     "exception_counts": exc_counts})


def render_report(log_dir: str = ".", out_path: Optional[str] = None) -> str:
    """Static HTML dashboard over the history file (webui analog)."""
    out_path = out_path or os.path.join(log_dir or ".",
                                        "tuplex_history.html")
    with open(out_path, "w") as fp:
        fp.write(_render_doc(log_dir, live=False))
    return out_path


def _render_doc(log_dir: str, live: bool) -> str:
    """Dashboard document; `live` adds the auto-refresh tag (served pages
    only — the on-disk report stays a static archival artifact)."""
    src = os.path.join(log_dir or ".", "tuplex_history.jsonl")
    recs = []
    if os.path.exists(src):
        with open(src) as fp:
            for line in fp:
                try:
                    recs.append(json.loads(line))
                except json.JSONDecodeError:
                    pass
    jobs: dict = {}
    for r in recs:
        jobs.setdefault(r.get("job", "?"), []).append(r)

    rows_html = []
    for job_id, events in jobs.items():
        done = next((e for e in events if e["event"] == "job_done"), {})
        stages = [e for e in events if e["event"] == "stage"]
        excs = done.get("exception_counts") or {}
        fast = sum(e["metrics"].get("fast_path_s", 0) for e in stages)
        slow = sum(e["metrics"].get("slow_path_s", 0) for e in stages)
        if not done and live:
            # in-flight job on a LIVE poll: surface the stage_start/
            # progress events (the reference webui's live task updates).
            # The static archival report keeps the plain row — a crashed
            # job must not read as perpetually RUNNING there.
            start = next((e for e in events if e["event"] == "job_start"),
                         {})
            n_stages = len(start.get("stages", [])) or "?"
            cur = max((e["no"] for e in events
                       if e["event"] in ("stage_start", "stage")), default=0)
            prog = next((e for e in reversed(events)
                         if e["event"] == "progress"), {})
            status = (f"RUNNING — stage {cur}/{n_stages}, "
                      f"{prog.get('parts', 0)} partition(s), "
                      f"{prog.get('rows', 0)} rows so far")
            rows_html.append(
                f"<tr class=running><td><code>{html.escape(job_id)}"
                f"</code></td><td>{len(stages)}</td>"
                f"<td colspan=4>{html.escape(status)}</td>"
                f"<td>—</td></tr>")
        else:
            rows_html.append(
                f"<tr><td><code>{html.escape(job_id)}</code></td>"
                f"<td>{len(stages)}</td>"
                f"<td>{done.get('rows', '—')}</td>"
                f"<td>{done.get('wall_s', '—')}</td>"
                f"<td>{fast:.3f}</td><td>{slow:.3f}</td>"
                f"<td>{html.escape(json.dumps(excs)) if excs else '—'}"
                f"</td></tr>")
        # per-task rows from fan-out workers (serverless/multihost)
        tasks: dict = {}
        for e in events:
            if e.get("event") == "task":
                # key on (stage, task): a job with several fan-out stages
                # reuses task numbers per stage
                tasks.setdefault((e.get("no"), e.get("task")), []).append(e)
        multi_stage = len({k[0] for k in tasks}) > 1
        for t in sorted(tasks, key=lambda x: (x[0] is None, x[0],
                                              x[1] is None, x[1])):
            last = tasks[t][-1]
            if last.get("kind") == "done":
                desc = (f"done — {last.get('rows', '?')} rows, "
                        f"{last.get('exceptions', 0)} exception(s), "
                        f"{last.get('wall_s', '?')}s")
            elif last.get("kind") == "fallback":
                desc = (f"failed after {last.get('attempt', '?')} "
                        f"attempt(s) — completed on the driver")
            else:
                desc = f"{last.get('kind', 'running')} (pid {last.get('pid', '?')})"
            label = (f"stage {t[0]} task {t[1]}" if multi_stage
                     else f"task {t[1]}")
            rows_html.append(
                f"<tr class=task><td colspan=7>&nbsp;&nbsp;"
                f"{html.escape(label)}: {html.escape(desc)}</td></tr>")
        for e in stages:
            for s in e.get("exception_sample", []):
                rows_html.append(
                    f"<tr class=exc><td colspan=7>↳ "
                    f"{html.escape(s)}</td></tr>")

    refresh = '<meta http-equiv="refresh" content="2">' if live else ""
    doc = f"""<!doctype html><meta charset="utf-8">
{refresh}
<title>tuplex_tpu history</title>
<style>
 body {{ font: 14px system-ui, sans-serif; margin: 2rem; color: #1a1a1a; }}
 table {{ border-collapse: collapse; width: 100%; }}
 th, td {{ text-align: left; padding: .4rem .7rem;
           border-bottom: 1px solid #ddd; }}
 th {{ background: #f5f5f5; }}
 tr.exc td {{ color: #a33; font-size: 12px; border-bottom: none; }}
 tr.task td {{ color: #567; font-size: 12px; border-bottom: none; }}
 tr.running td {{ color: #0a6; font-style: italic; }}
 code {{ background: #f0f0f0; padding: 0 .3em; }}
</style>
<h1>tuplex_tpu job history</h1>
<p>{len(jobs)} job(s) · {html.escape(src)}</p>
<table>
<tr><th>job</th><th>stages</th><th>rows out</th><th>wall s</th>
<th>fast-path s</th><th>slow-path s</th><th>exceptions</th></tr>
{''.join(rows_html)}
</table>"""
    return doc


def _make_server(log_dir: str, port: int, host: str):
    import http.server

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802
            body = _render_doc(log_dir, live=True).encode("utf-8")
            self.send_response(200)
            self.send_header("Content-Type", "text/html; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # quiet
            pass

    return http.server.HTTPServer((host, port), Handler)


def serve(log_dir: str = ".", port: int = 5000,
          host: str = "127.0.0.1"):
    """Serve ONLY the rendered dashboard via stdlib http.server (blocking).

    Binds loopback by default and never exposes the filesystem — every GET
    re-renders and returns the dashboard document (auto-refreshing, so an
    open browser tab shows live job progress — the reference's Flask/
    SocketIO/Mongo webui collapsed to the stdlib)."""
    with _make_server(log_dir, port, host) as srv:
        srv.serve_forever()


def start_server(log_dir: str = ".", port: int = 5000,
                 host: str = "127.0.0.1"):
    """Background-thread variant (reference: ensure_webui autostart).
    Returns (server, url); call server.shutdown() to stop. port=0 picks a
    free port."""
    import threading

    srv = _make_server(log_dir, port, host)
    t = threading.Thread(target=srv.serve_forever, daemon=True,
                         name="tuplex-history-server")
    t.start()
    return srv, f"http://{host}:{srv.server_address[1]}/"
