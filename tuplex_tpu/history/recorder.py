"""JSON-lines job records + static HTML dashboard."""

from __future__ import annotations

import html
import json
import os
import time
import uuid
from typing import Optional


class JobRecorder:
    """Appends job/stage/exception events to <logDir>/tuplex_history.jsonl
    (reference events: job/stage/task/exception updates, thserver/rest.py)."""

    #: spans per job embedded into the history file (waterfall rendering +
    #: `python -m tuplex_tpu trace` replay); the tracing ring buffer keeps
    #: the full stream — this is the per-job slice the dashboard needs
    SPAN_EVENT_CAP = 400

    def __init__(self, log_dir: str, enabled: bool = True,
                 exception_display_limit: int = 5):
        self.exception_display_limit = exception_display_limit
        self.enabled = enabled
        self.path = os.path.join(log_dir or ".", "tuplex_history.jsonl")
        self.job_id = uuid.uuid4().hex[:12]
        self._stage_no = 0
        self._warned_write = False
        self._trace_mark = 0.0

    def _new_job(self) -> None:
        self.job_id = uuid.uuid4().hex[:12]
        self._stage_no = 0

    def _write(self, rec: dict) -> None:
        if not self.enabled:
            return
        # an explicit job id wins: the job SERVICE (serve/) interleaves
        # many concurrent jobs through one recorder, so its events carry
        # their own id instead of riding the single-job cursor
        rec.setdefault("job", self.job_id)
        rec["ts"] = round(time.time(), 3)
        try:
            with open(self.path, "a") as fp:
                fp.write(json.dumps(rec, default=str) + "\n")
        except OSError as e:
            # warn ONCE so a bad logDir is diagnosable, then stay quiet —
            # a recorder failure must never spam or kill the job
            if not self._warned_write:
                self._warned_write = True
                from ..utils.logging import get_logger

                get_logger("history").warning(
                    "history write to %s failed (%s: %s); further "
                    "failures will be silent", self.path,
                    type(e).__name__, e)

    def job_started(self, action: str, plan: list,
                    trace_mark: Optional[float] = None) -> None:
        self._new_job()  # each action is its own job in the dashboard
        from ..runtime import tracing

        # job_done slices spans from here; the caller passes a mark taken
        # BEFORE its job span opened so job/plan spans make the slice
        self._trace_mark = trace_mark if trace_mark is not None \
            else tracing.now_us()
        previews = []
        if self.enabled:
            from ..plan.logical import preview_sample_exceptions

            for st in plan:
                for op in getattr(st, "ops", []) or []:
                    # on-demand preview pass for operators whose schema
                    # came statically (sample-free specialization skipped
                    # the trace the previews used to ride on); traced ops
                    # return their recorded previews unchanged
                    try:
                        excs = preview_sample_exceptions(op)
                    except Exception:   # pragma: no cover - advisory
                        excs = list(getattr(op, "sample_exceptions", [])
                                    or [])
                    for exc_name, row_repr in excs[
                            : self.exception_display_limit]:
                        previews.append({"op": type(op).__name__,
                                         "op_id": op.id,
                                         "exc": exc_name, "row": row_repr})
        self._write({"event": "job_start", "action": action,
                     "stages": [type(s).__name__ for s in plan],
                     # sample-time exception previews (reference:
                     # SampleProcessor feeding the webui BEFORE execution)
                     "sample_exception_previews": previews,
                     # per-operator static-analyzer findings (the lint-
                     # driven authoring loop: `python -m tuplex_tpu lint`
                     # verdicts rendered per op in the dashboard)
                     "lint": _plan_lint_findings(plan)})

    def stage_started(self, stage) -> None:
        """LIVE event: a stage began executing (reference: the driver posts
        task/stage updates to the history server DURING the job,
        HistoryServerConnector.cc:102-198 — not only at completion).
        Carries the fused op count and the split tuner's predicted compile
        seconds so a dashboard watcher can tell a long compile from a hung
        stage BEFORE the stage completes."""
        rec = {"event": "stage_start", "no": self._stage_no + 1,
               "kind": type(stage).__name__}
        ops = getattr(stage, "ops", None)
        if ops:
            rec["n_ops"] = len(ops)
            pred = getattr(stage, "predicted_compile_s", None)
            if pred is None:
                try:
                    from ..plan.splittuner import model_for

                    pred = model_for().predict(len(ops))
                except Exception:
                    pred = None
            if pred is not None:
                rec["predicted_compile_s"] = round(float(pred), 3)
            # plan-time resolve-tier pick (plan/physical.ResolvePlan):
            # which resolve tiers this stage can reach, decided from the
            # analyzer inventory before any row executes
            try:
                rec["resolve_tier"] = stage.resolve_plan().tier
            except Exception:   # pragma: no cover - advisory surface
                pass
            # plan-time static-vetting verdict (compiler/graphlint): the
            # hazard score — and, for a vetoed wedge, WHICH rule fired —
            # visible before the stage runs a single row
            rep = getattr(stage, "graph_report", None)
            if rep is not None:
                rec["hazard_score"] = round(min(rep.hazard_score, 1e9), 2)
            if getattr(stage, "hazard_rule", None):
                rec["hazard_rule"] = stage.hazard_rule
        self._write(rec)
        self._last_progress = 0.0

    def task_progress(self, parts_done: int, rows: int) -> None:
        """LIVE event: partition-level progress inside the running stage.
        Throttled (0.2s) so tight partition loops don't swamp the log."""
        now = time.time()
        if now - getattr(self, "_last_progress", 0.0) < 0.2:
            return
        self._last_progress = now
        self._write({"event": "progress", "no": self._stage_no + 1,
                     "parts": parts_done, "rows": rows})

    def stage_done(self, stage, metrics: dict, exceptions: list) -> None:
        self._stage_no += 1
        sample = [(getattr(e, "trace", None) or repr(e))[:800]
                  for e in exceptions[: self.exception_display_limit]]
        self._write({"event": "stage", "no": self._stage_no,
                     "kind": type(stage).__name__,
                     "metrics": metrics, "exception_sample": sample})

    def worker_task_event(self, task: int, rec: dict) -> None:
        """LIVE event from a fan-out worker (started / finished, rows,
        exception count) — streamed off the task's events.jsonl by the
        driver's poll loop, so remote tasks are visible in the dashboard
        WHILE the job runs (reference: executors push per-task status to
        the history server, HistoryServerConnector.cc:102-198)."""
        self._write({**{k: v for k, v in rec.items()
                        if k not in ("event", "task", "kind", "no")},
                     "event": "task", "task": task,
                     "no": self._stage_no + 1,
                     "kind": rec.get("event", "update")})

    def job_done(self, rows: int, wall_s: float, exc_counts: dict) -> None:
        self._write_job_spans()
        self._write_excprof()
        self._write({"event": "job_done", "rows": rows,
                     "wall_s": round(wall_s, 4),
                     "exception_counts": exc_counts})

    def _write_excprof(self) -> None:
        """Embed the exception-plane readout (runtime/excprof) into the
        history file at the job's terminal turn: per-stage x code x op
        counts + resolve-tier mix vs the plan-time baseline, the global
        drift readout, and the sampled deviant rows — the dashboard drift
        panel and the `excstats` CLI read it from here. The counters are
        live process state (cumulative across jobs sharing the process),
        so the panel is a snapshot AT this job's end, not a per-job
        delta; serve jobs instead get per-tenant rows from the service's
        own terminal event."""
        if not self.enabled:
            return
        try:
            from ..core.errors import exception_name
            from ..runtime import excprof

            if not excprof.enabled():
                return
            reps = excprof.reports()
            if not reps:
                return
            stages = {}
            for key, r in reps.items():
                d = {"rows": r["rows"], "rate": round(r["rate"], 4),
                     "fallback": r["fallback"],
                     "unexpected": r["unexpected"],
                     "codes": {f"{exception_name(c)}#op{op}": n
                               for (c, op), n in sorted(r["codes"].items())},
                     "tiers": r["tiers"]}
                base = r.get("baseline")
                if base is not None:
                    d["baseline"] = {
                        "codes": [exception_name(c)
                                  for c in base["codes"]],
                        "tier": base["tier"], "pruned": base["pruned"]}
                stages[key] = d
            samples: dict = {}
            for (key, code), caps in excprof.samples().items():
                samples.setdefault(key, {})[exception_name(code)] = caps
            self._write({"event": "excprof",
                         "drift": excprof.scope_report(None),
                         "stages": stages, "samples": samples})
        except Exception:   # pragma: no cover - the panel is advisory
            pass

    def serve_job_event(self, job_id: str, event: str, **fields) -> None:
        """Dashboard row for a JOB-SERVICE job (serve/): same event shapes
        as the single-job path (`job_start`/`stage`/`job_done`) but keyed
        by the service job's own id, so N concurrent tenants render as N
        independent job rows instead of colliding on the recorder's
        cursor."""
        self._write({**fields, "event": event, "job": str(job_id)})

    def respec_event(self, tenant: str, phase: str, **fields) -> None:
        """Re-specialization lifecycle row (serve/respec): one record per
        per-tenant transition — trigger / candidate-ready / canary-start
        / promote / quarantine / rollback — keyed by a synthetic
        per-tenant job id so the dashboard renders each tenant's plan-
        generation history as its own timeline."""
        self._write({**fields, "event": "respec", "phase": str(phase),
                     "tenant": str(tenant),
                     "job": f"respec:{tenant}"})

    def _write_job_spans(self) -> None:
        """Embed this job's span slice (runtime/tracing, when enabled) into
        the history file — the dashboard waterfall and the `trace` CLI
        replay read it from here, so the timeline survives the process."""
        if not self.enabled:
            return
        from ..runtime import tracing

        evts = tracing.events_since(self._trace_mark)
        if not evts:
            return
        spans, n_total, n_dropped = _span_slice(evts, self.SPAN_EVENT_CAP)
        self._write({"event": "spans", "n_total": n_total,
                     "n_dropped": n_dropped, "spans": spans})

    def serve_job_spans(self, job_id: str, evts: list,
                        tenant: Optional[str] = None) -> None:
        """Embed a JOB-SERVICE job's tenant-tagged span stream
        (``tracing.events_for_stream(job_id)``) keyed by the job's own id,
        so serve jobs get the same dashboard waterfall and `python -m
        tuplex_tpu trace` replay lane as single-job runs — previously only
        in-process jobs' streams survived into the replay."""
        if not self.enabled or not evts:
            return
        spans, n_total, n_dropped = _span_slice(evts, self.SPAN_EVENT_CAP)
        rec = {"event": "spans", "job": str(job_id), "n_total": n_total,
               "n_dropped": n_dropped, "spans": spans}
        if tenant is not None:
            rec["tenant"] = tenant
        self._write(rec)


def _span_slice(evts: list, cap: int) -> tuple:
    """The embedded per-job span slice: (spans, n_total, n_dropped).
    Past `cap` events, truncate DEEPEST-SUBTREE-FIRST: only spans that are
    currently leaves of the containment forest are eligible to drop
    (deepest first, shortest first within a depth), and dropping a span
    can make its parent a leaf for the next round — so the embedded slice
    is always a connected tree. A keep-by-duration policy would sever
    trees: a long leaf could survive while its shorter parent dropped,
    and every consumer that reconstructs the hierarchy by containment
    (the dashboard waterfall, the `trace` replay, runtime/critpath's
    orphan detection) would misfile the orphan as degraded input.
    Structural spans (job, stage executes, compiles) are interior nodes,
    so they survive by construction. Truncation is never silent: the
    dropped count rides the record (the waterfall panel renders it) and
    bumps the ``trace_spans_dropped`` counter (runtime/xferstats —
    visible in Metrics counters and the Prometheus scrape)."""
    n_total = len(evts)
    n_dropped = max(0, n_total - cap)
    if n_dropped:
        evts = _prune_deepest(evts, n_dropped)
        from ..runtime import xferstats

        xferstats.bump("trace_spans_dropped", n_dropped, tag="embed_cap")
    spans = [{"name": e["name"], "cat": e.get("cat", ""),
              "ts": round(float(e["ts"]), 1),
              "dur": round(float(e["dur"]), 1)
              if e.get("dur") is not None else 0.0,
              "tid": e.get("tid", 0), "depth": e.get("depth", 0),
              **({"args": e["args"]} if e.get("args") else {})}
             for e in evts]
    return spans, n_total, n_dropped


def _prune_deepest(evts: list, n_drop: int) -> list:
    """Drop exactly ``n_drop`` spans, leaves-of-the-containment-forest
    first (deepest, then shortest), so what remains is always a connected
    tree per thread lane. Parent links come from interval containment on
    each tid's timeline — the same reconstruction the waterfall uses —
    not from the recorded ``depth`` field, so a slice stays connected
    even when cross-thread spans carry surprising depths."""
    import heapq

    order = sorted(range(len(evts)),
                   key=lambda i: (evts[i].get("tid", 0),
                                  float(evts[i]["ts"]),
                                  -(evts[i].get("dur") or 0.0)))
    parent = [-1] * len(evts)
    nkids = [0] * len(evts)
    sdepth = [0] * len(evts)
    stack: list = []          # open-span indices for the current tid
    cur_tid = object()
    eps = 0.05                # µs slack for rounded/coincident edges
    for i in order:
        e = evts[i]
        tid = e.get("tid", 0)
        if tid != cur_tid:
            cur_tid, stack = tid, []
        ts = float(e["ts"])
        end = ts + float(e.get("dur") or 0.0)
        # pop every frame this span is NOT contained in — handles both
        # disjoint predecessors and partial overlap (a straddling span
        # becomes a sibling of the frame it overlaps, not its child)
        while stack and end > stack[-1][1] + eps:
            stack.pop()
        if stack:
            parent[i] = stack[-1][0]
            nkids[parent[i]] += 1
            sdepth[i] = sdepth[parent[i]] + 1
        else:
            # no containment parent: drop at the recorded depth so a
            # cross-thread orphan still yields before shallower spans
            sdepth[i] = int(e.get("depth") or 0)
        stack.append((i, end))
    dropped = [False] * len(evts)
    # heapq is a min-heap: (-depth, dur) pops deepest-then-shortest first
    heap = [(-sdepth[i], evts[i].get("dur") or 0.0, i)
            for i in range(len(evts)) if nkids[i] == 0]
    heapq.heapify(heap)
    left = n_drop
    while left > 0 and heap:
        _, _, i = heapq.heappop(heap)
        dropped[i] = True
        left -= 1
        p = parent[i]
        if p >= 0:
            nkids[p] -= 1
            if nkids[p] == 0 and not dropped[p]:
                heapq.heappush(
                    heap, (-sdepth[p], evts[p].get("dur") or 0.0, p))
    return sorted((evts[i] for i in range(len(evts)) if not dropped[i]),
                  key=lambda e: e["ts"])


_LINT_CAP = 80


def _plan_lint_findings(plan: list) -> list:
    """Per-operator static-analyzer findings for the job_start record
    (compiler/analyzer.py UDFReports, already memoized on the stages).
    Best-effort: a lint failure must never block a job from starting."""
    out: list = []
    for st in plan:
        reports = getattr(st, "udf_reports", None)
        if reports is None:
            continue
        try:
            for op, attr, rep in reports():
                # "statically typed: yes/no + why not" per operator
                # (sample-free specialization, compiler/typeinfer.py)
                tl = rep.typed_line()
                if tl is not None and len(out) < _LINT_CAP:
                    out.append({
                        "op": type(op).__name__, "op_id": op.id,
                        "udf": f"{rep.name}.{attr}" if attr != "udf"
                        else rep.name,
                        "kind": "typed", "reason": tl,
                        "loc": f"{rep.filename}:{rep.line_base}",
                        "conditional": False})
                for f in rep.findings:
                    if len(out) >= _LINT_CAP:
                        return out
                    out.append({
                        "op": type(op).__name__, "op_id": op.id,
                        "udf": f"{rep.name}.{attr}" if attr != "udf"
                        else rep.name,
                        "kind": f.kind, "reason": f.reason,
                        "loc": rep.loc(f),
                        "conditional": bool(f.conditional)})
            dead = getattr(st, "dead_resolver_findings", None)
            if dead is not None:
                for rop, gop, reason in dead():
                    if len(out) >= _LINT_CAP:
                        return out
                    out.append({
                        "op": type(rop).__name__, "op_id": rop.id,
                        "udf": f"guards #{gop.id}",
                        "kind": "dead-resolver", "reason": reason,
                        "loc": "", "conditional": False})
            sug = getattr(st, "resolver_suggestions", None)
            if sug is not None:
                # positive twin of the dead-resolver row: the inventory
                # proves only exact Python classes can fire, yet no
                # resolver is attached
                for reason in sug():
                    if len(out) >= _LINT_CAP:
                        return out
                    out.append({
                        "op": type(st).__name__, "op_id": "-",
                        "udf": "", "kind": "suggestion",
                        "reason": reason, "loc": "",
                        "conditional": False})
        except Exception:   # pragma: no cover - lint is advisory
            continue
    return out


def _fmt_eng(v) -> str:
    """Engineering-notation cell for the device-utilization table
    (flops/bytes counts), em-dash when absent; the ladder itself is
    shared with compilestats (runtime/devprof.fmt_eng)."""
    if v is None:
        return "—"
    from ..runtime.devprof import fmt_eng

    return fmt_eng(v)


def _excprof_html(ev: dict) -> str:
    """Exception-plane drift panel for one job: drift score vs the
    plan-time baseline (bar + respecialize badge), resolve-tier mix,
    per-stage x code counts against the expected inventory, and the
    sampled deviant rows. Renders both shapes: the single-job recorder's
    terminal `excprof` event (drift/stages/samples) and the job
    service's per-tenant row (flat scope_report fields + tenant)."""
    drift = ev.get("drift") or ev
    score = float(drift.get("drift_score", 0.0) or 0.0)
    resp = bool(drift.get("respecialize_recommended"))
    rate = float(drift.get("exception_rate", 0.0) or 0.0)
    mix = drift.get("tier_mix") or {}
    tenant = ev.get("tenant")
    pct = max(0.0, min(1.0, score)) * 100
    # the respecialize badge is a LIFECYCLE now (serve/respec): when the
    # service's controller annotated this row, show where the tenant is
    # in drift → candidate → canary → promote/quarantine instead of the
    # bare recommendation
    rstate = ev.get("respec_state")
    rgen = ev.get("respec_generation")
    if rstate and (rstate != "idle" or resp):
        label = f"respec: {rstate}"
        if rgen:
            label += f" (gen {rgen})"
        badge = f' <span class=respbadge>{html.escape(label)}</span>'
    else:
        badge = (' <span class=respbadge>respecialize recommended</span>'
                 if resp else "")
    mix_s = ", ".join(f"{k} {v * 100:.1f}%" for k, v in sorted(mix.items())
                      if v) or "—"
    who = f"tenant {html.escape(str(tenant))}" if tenant else "global"
    head = (f"exception plane — {who}: drift "
            f"<span class=driftbar><span class=driftfill "
            f"style=\"width:{pct:.1f}%\"></span></span> {score:.2f}"
            f"{badge} · exc rate {rate * 100:.2f}% · tier mix {mix_s}")
    body: list = []
    stages = ev.get("stages") or {}
    if stages:
        body.append("<table class=exctab><tr><th>stage</th><th>rows</th>"
                    "<th>exc rate</th><th>unexpected</th>"
                    "<th>codes (observed)</th><th>expected</th>"
                    "<th>tiers</th></tr>")
        for key, s in sorted(stages.items()):
            codes = ", ".join(f"{c}:{n}" for c, n in
                              sorted((s.get("codes") or {}).items())) or "—"
            tiers = ", ".join(f"{t}:{n}" for t, n in
                              sorted((s.get("tiers") or {}).items())) or "—"
            base = s.get("baseline") or {}
            exp = ", ".join(base.get("codes") or []) or "none"
            if base.get("tier"):
                exp += f" → {base['tier']}"
            unexpected = int(s.get("unexpected", 0))
            ucls = " class=unexp" if unexpected else ""
            body.append(
                f"<tr><td><code>{html.escape(str(key)[:16])}</code></td>"
                f"<td>{s.get('rows', 0)}</td>"
                f"<td>{float(s.get('rate', 0.0)) * 100:.2f}%</td>"
                f"<td{ucls}>{unexpected}</td>"
                f"<td>{html.escape(codes)}</td>"
                f"<td>{html.escape(exp)}</td>"
                f"<td>{html.escape(tiers)}</td></tr>")
        body.append("</table>")
    for key, by_code in sorted((ev.get("samples") or {}).items()):
        for code, caps in sorted(by_code.items()):
            for r in caps:
                body.append(
                    f"<div class=excsample>↳ <b>{html.escape(str(code))}"
                    f"</b> @ <code>{html.escape(str(key)[:16])}</code>: "
                    f"{html.escape(str(r))}</div>")
    return (f"<details class=excplane><summary>{head}</summary>"
            f"{''.join(body)}</details>")


def _critpath_html(ev: dict) -> str:
    """Latency-budget panel for one job (runtime/critpath `critpath`
    event): the exclusive bucket vector as a proportional budget strip +
    table against the tenant's EWMA baseline, the slow-job blame verdict,
    and the SLO line when one is declared. The same numbers `python -m
    tuplex_tpu whyslow` prints — the panels must agree because they read
    the same record."""
    buckets = ev.get("buckets") or {}
    wall = float(ev.get("wall_s") or 0.0)
    if not buckets or wall <= 0:
        return ""
    tenant = ev.get("tenant")
    who = f"tenant {html.escape(str(tenant))}" if tenant else "job"
    dom = str(ev.get("dominant") or "?")
    cov = float(ev.get("coverage_frac") or 0.0) * 100
    badge = ""
    if ev.get("slow"):
        blame = str(ev.get("blame") or "?")
        badge = (f' <span class=slowbadge>SLOW — blame '
                 f'{html.escape(blame)}</span>')
    if ev.get("degraded"):
        badge += ' <span class=degbadge>degraded trace</span>'
    slo = ""
    if float(ev.get("slo_ms") or 0.0) > 0:
        ok = ev.get("slo_ok")
        slo = (f" · SLO {float(ev['slo_ms']):.0f}ms "
               f"{'met' if ok else 'MISSED' if ok is not None else '?'}")
    head = (f"latency budget — {who}: wall {wall * 1e3:.1f}ms, dominant "
            f"<b>{html.escape(dom)}</b>, coverage {cov:.1f}%{slo}{badge}")
    # proportional budget strip: one segment per nonzero bucket, in
    # canonical order, colored like the waterfall categories
    strip, left = [], 0.0
    order = [b for b in _CP_ORDER if b in buckets] + \
            [b for b in buckets if b not in _CP_ORDER]
    for b in order:
        frac = float(buckets.get(b) or 0.0) / wall
        if frac <= 0:
            continue
        w = min(frac, 1.0 - left / 100.0) * 100.0
        strip.append(f'<span class="cpseg cp-{html.escape(b)}" '
                     f'style="left:{left:.2f}%;width:{max(w, 0.1):.2f}%" '
                     f'title="{html.escape(b)} '
                     f'{float(buckets[b]) * 1e3:.1f}ms"></span>')
        left += w
    base = ev.get("baseline") or {}
    rows = ["<table class=cptab><tr><th>bucket</th><th>ms</th>"
            "<th>share</th><th>baseline ms</th><th>Δ ms</th></tr>"]
    for b in order:
        v = float(buckets.get(b) or 0.0)
        bl = base.get(b)
        if v <= 0 and not bl:
            continue
        cls = " class=cpdom" if b == dom else ""
        if ev.get("slow") and b == ev.get("blame"):
            cls = " class=cpblame"
        d = "" if bl is None else f"{(v - float(bl)) * 1e3:+.1f}"
        rows.append(
            f"<tr{cls}><td><code>{html.escape(b)}</code></td>"
            f"<td>{v * 1e3:.1f}</td><td>{v / wall * 100:.1f}%</td>"
            f"<td>{'—' if bl is None else f'{float(bl) * 1e3:.1f}'}</td>"
            f"<td>{d or '—'}</td></tr>")
    rows.append("</table>")
    return (f"<details class=critpath><summary>{head}</summary>"
            f"<div class=cptrack>{''.join(strip)}</div>"
            f"{''.join(rows)}</details>")


# canonical bucket order for the budget panel (mirrors critpath.BUCKETS
# without importing the runtime module into the static dashboard path)
_CP_ORDER = ("admission_wait", "queue_wait", "compile_trace",
             "compile_lower", "compile_xla", "h2d", "device",
             "resolve_general", "resolve_interpreter", "d2h", "merge",
             "scheduler_other", "unattributed")


_WF_CAP = 120      # bars per job (longest-first keeps the picture honest)


def _waterfall_html(sp_ev: dict, cp_ev: Optional[dict] = None) -> str:
    """Span waterfall for one job: proportional bars over the job's trace
    window, indented by nesting depth, colored by category. When the
    job's `critpath` record is available, bars owning a critical-path
    segment get the `onpath` outline so the budget panel's attribution
    is visible in the timeline itself."""
    spans = sp_ev.get("spans", [])
    if not spans:
        return ""
    t0 = min(s["ts"] for s in spans)
    t1 = max(s["ts"] + (s.get("dur") or 0.0) for s in spans)
    total = max(t1 - t0, 1e-6)
    shown = sorted(spans, key=lambda s: -(s.get("dur") or 0.0))[:_WF_CAP]
    shown.sort(key=lambda s: (s["ts"], s.get("depth", 0)))
    # critical-path segments from the budget record: [ts, dur, bucket,
    # name] on the same trace clock as the embedded spans
    path = (cp_ev or {}).get("path") or []
    bars = []
    n_onpath = 0
    for s in shown:
        left = (s["ts"] - t0) / total * 100.0
        width = max((s.get("dur") or 0.0) / total * 100.0, 0.15)
        dur_ms = (s.get("dur") or 0.0) / 1e3
        cat = str(s.get("cat") or "exec")
        s_end = s["ts"] + (s.get("dur") or 0.0)
        onpath = any(p[3] == s["name"] and p[0] >= s["ts"] - 0.2
                     and p[0] + p[1] <= s_end + 0.2 for p in path)
        n_onpath += onpath
        label = f"{s['name']} {dur_ms:.1f}ms"
        indent = int(s.get("depth", 0)) * 10
        bars.append(
            f'<div class=wfrow style="padding-left:{indent}px">'
            f'<span class=wflabel>{html.escape(label)}</span>'
            f'<span class=wftrack><span class="wfbar cat-'
            f'{html.escape(cat)}{" onpath" if onpath else ""}" '
            f'style="left:{left:.2f}%;'
            f'width:{width:.2f}%"></span></span></div>')
    n_total = sp_ev.get("n_total", len(spans))
    n_dropped = sp_ev.get("n_dropped", 0)
    head = (f"span waterfall — {len(shown)} of {n_total} span(s) shown, "
            f"{total / 1e3:.1f}ms window")
    if n_onpath:
        head += f", {n_onpath} on the critical path (outlined)"
    if n_dropped:
        # the recorder capped the embedded slice: say so instead of
        # letting a truncated panel read as the whole timeline
        head += (f" ({n_dropped} shortest span(s) dropped at the "
                 f"{len(spans)}-span embed cap)")
    return (f"<details open class=waterfall><summary>{html.escape(head)}"
            f"</summary>{''.join(bars)}</details>")


def render_report(log_dir: str = ".", out_path: Optional[str] = None) -> str:
    """Static HTML dashboard over the history file (webui analog)."""
    out_path = out_path or os.path.join(log_dir or ".",
                                        "tuplex_history.html")
    with open(out_path, "w") as fp:
        fp.write(_render_doc(log_dir, live=False))
    return out_path


def _load_jobs(log_dir: str) -> dict:
    """Parse <logDir>/tuplex_history.jsonl into {job_id: [events]} (insert
    order preserved; undecodable lines skipped). Shared by the dashboard
    and the Chrome-trace replay so the two read one format."""
    src = os.path.join(log_dir or ".", "tuplex_history.jsonl")
    jobs: dict = {}
    if not os.path.exists(src):
        raise FileNotFoundError(src)
    with open(src) as fp:
        for line in fp:
            try:
                r = json.loads(line)
            except json.JSONDecodeError:
                continue
            jobs.setdefault(r.get("job", "?"), []).append(r)
    return jobs


def _render_doc(log_dir: str, live: bool) -> str:
    """Dashboard document; `live` adds the auto-refresh tag (served pages
    only — the on-disk report stays a static archival artifact)."""
    src = os.path.join(log_dir or ".", "tuplex_history.jsonl")
    try:
        jobs = _load_jobs(log_dir)
    except FileNotFoundError:
        jobs = {}

    rows_html = []
    for job_id, events in jobs.items():
        if job_id.startswith("respec:"):
            # re-specialization lifecycle lane (serve/respec): the
            # tenant's plan-generation history as one timeline row —
            # drift trigger → candidate → canary → promote/quarantine
            revs = [e for e in events if e.get("event") == "respec"]
            if revs:
                tenant = revs[0].get("tenant", job_id[len("respec:"):])
                steps = []
                for e in revs[-16:]:
                    s = str(e.get("phase", "?"))
                    if e.get("gen") is not None:
                        s += f" g{e['gen']}"
                    if e.get("reason"):
                        s += f" ({html.escape(str(e['reason'])[:60])})"
                    steps.append(html.escape(s) if "(" not in s else s)
                last = revs[-1]
                rows_html.append(
                    f"<tr class=respec><td colspan=7>⟳ respec lifecycle"
                    f" — tenant <code>{html.escape(str(tenant))}</code>"
                    f" [{html.escape(str(last.get('phase', '?')))}]: "
                    f"{' → '.join(steps)}</td></tr>")
            continue
        done = next((e for e in events if e["event"] == "job_done"), {})
        stages = [e for e in events if e["event"] == "stage"]
        start = next((e for e in events if e["event"] == "job_start"), {})
        excs = done.get("exception_counts") or {}
        fast = sum(e["metrics"].get("fast_path_s", 0) for e in stages)
        slow = sum(e["metrics"].get("slow_path_s", 0) for e in stages)
        if not done and live:
            # in-flight job on a LIVE poll: surface the stage_start/
            # progress events (the reference webui's live task updates).
            # The static archival report keeps the plain row — a crashed
            # job must not read as perpetually RUNNING there.
            n_stages = len(start.get("stages", [])) or "?"
            cur = max((e["no"] for e in events
                       if e["event"] in ("stage_start", "stage")), default=0)
            prog = next((e for e in reversed(events)
                         if e["event"] == "progress"), {})
            status = (f"RUNNING — stage {cur}/{n_stages}, "
                      f"{prog.get('parts', 0)} partition(s), "
                      f"{prog.get('rows', 0)} rows so far")
            rows_html.append(
                f"<tr class=running><td><code>{html.escape(job_id)}"
                f"</code></td><td>{len(stages)}</td>"
                f"<td colspan=4>{html.escape(status)}</td>"
                f"<td>—</td></tr>")
        else:
            rows_html.append(
                f"<tr><td><code>{html.escape(job_id)}</code></td>"
                f"<td>{len(stages)}</td>"
                f"<td>{done.get('rows', '—')}</td>"
                f"<td>{done.get('wall_s', '—')}</td>"
                f"<td>{fast:.3f}</td><td>{slow:.3f}</td>"
                f"<td>{html.escape(json.dumps(excs)) if excs else '—'}"
                f"</td></tr>")
        # per-task rows from fan-out workers (serverless/multihost)
        tasks: dict = {}
        for e in events:
            if e.get("event") == "task":
                # key on (stage, task): a job with several fan-out stages
                # reuses task numbers per stage
                tasks.setdefault((e.get("no"), e.get("task")), []).append(e)
        multi_stage = len({k[0] for k in tasks}) > 1
        for t in sorted(tasks, key=lambda x: (x[0] is None, x[0],
                                              x[1] is None, x[1])):
            last = tasks[t][-1]
            if last.get("kind") == "done":
                desc = (f"done — {last.get('rows', '?')} rows, "
                        f"{last.get('exceptions', 0)} exception(s), "
                        f"{last.get('wall_s', '?')}s")
            elif last.get("kind") == "fallback":
                desc = (f"failed after {last.get('attempt', '?')} "
                        f"attempt(s) — completed on the driver")
            else:
                desc = f"{last.get('kind', 'running')} (pid {last.get('pid', '?')})"
            label = (f"stage {t[0]} task {t[1]}" if multi_stage
                     else f"task {t[1]}")
            rows_html.append(
                f"<tr class=task><td colspan=7>&nbsp;&nbsp;"
                f"{html.escape(label)}: {html.escape(desc)}</td></tr>")
        # per-stage device utilization (runtime/devprof metrics riding the
        # stage record): measured device seconds, XLA flops/bytes, peak
        # executable footprint and the achieved roofline fraction
        dev = [e for e in stages if e["metrics"].get("device_s")]
        if dev:
            cells = ["<table class=devtab><tr><th>stage</th>"
                     "<th>device s</th><th>dispatches</th><th>FLOPs</th>"
                     "<th>bytes</th><th>peak mem</th><th>roofline</th>"
                     "</tr>"]
            for e in dev:
                m = e["metrics"]
                rf = m.get("roofline_frac")
                bar = ""
                if rf is not None:
                    pct = max(0.0, min(1.0, float(rf))) * 100
                    bar = (f"<span class=rlbar><span class=rlfill "
                           f"style=\"width:{pct:.2f}%\"></span></span> "
                           f"{pct:.2f}%")
                cells.append(
                    f"<tr><td>{e.get('no', '?')} "
                    f"[{html.escape(str(e.get('kind', '')))}]</td>"
                    f"<td>{m.get('device_s', 0):.4f}</td>"
                    f"<td>{int(m.get('device_dispatches', 0))}</td>"
                    f"<td>{_fmt_eng(m.get('flops'))}</td>"
                    f"<td>{_fmt_eng(m.get('device_bytes'))}</td>"
                    f"<td>{_fmt_eng(m.get('hbm_peak'))}</td>"
                    f"<td>{bar or '—'}</td></tr>")
            cells.append("</table>")
            rows_html.append(
                f"<tr class=dev><td colspan=7><details><summary>device "
                f"utilization — {len(dev)} stage(s)</summary>"
                f"{''.join(cells)}</details></td></tr>")
        # static-vetting verdicts (compiler/graphlint metrics riding the
        # stage record): lint cost and the hazards found/avoided per
        # stage — a vetoed wedge shows up HERE, not as a compile kill
        for e in stages:
            m = e["metrics"]
            if not (m.get("hazards_found") or m.get("hazards_avoided")
                    or m.get("graphlint_ms")):
                continue
            rule = m.get("hazard_rule", "")
            desc = (f"graphlint {m.get('graphlint_ms', 0):.1f} ms — "
                    f"{int(m.get('hazards_found', 0))} hazard(s) found, "
                    f"{int(m.get('hazards_avoided', 0))} compile(s) "
                    f"avoided")
            if rule:
                desc += f" (rule {rule})"
            rows_html.append(
                f"<tr class=lint><td colspan=7>⚠ stage {e.get('no', '?')}"
                f" [{html.escape(str(e.get('kind', '')))}]: "
                f"{html.escape(desc)}</td></tr>")
        for e in stages:
            for s in e.get("exception_sample", []):
                rows_html.append(
                    f"<tr class=exc><td colspan=7>↳ "
                    f"{html.escape(s)}</td></tr>")
        # per-operator lint findings (job_start 'lint': the static
        # analyzer's verdicts, rendered like the reference webui's
        # per-operator detail rows)
        for f in start.get("lint", []) or []:
            cold = " [cold-arm]" if f.get("conditional") else ""
            rows_html.append(
                f"<tr class=lint><td colspan=7>⚐ "
                f"{html.escape(str(f.get('op', '?')))}"
                f"#{html.escape(str(f.get('op_id', '?')))} "
                f"{html.escape(str(f.get('udf', '')))} — "
                f"<b>{html.escape(str(f.get('kind', '')))}</b>: "
                f"{html.escape(str(f.get('reason', '')))}"
                f" ({html.escape(str(f.get('loc', '')))}){cold}</td></tr>")
        # exception-plane drift panel (runtime/excprof): the terminal
        # `excprof` event — the single-job recorder's full readout or
        # the job service's per-tenant scope_report row
        exev = next((e for e in reversed(events)
                     if e.get("event") == "excprof"), None)
        if exev:
            rows_html.append(
                f"<tr class=excp><td colspan=7>{_excprof_html(exev)}"
                f"</td></tr>")
        # latency-budget panel (runtime/critpath `critpath` event): the
        # exclusive bucket vector, blame verdict and SLO line — rendered
        # before the waterfall so the budget reads first, and handed to
        # the waterfall so critical-path bars get the outline
        cp_ev = next((e for e in reversed(events)
                      if e.get("event") == "critpath"), None)
        if cp_ev:
            cp_html = _critpath_html(cp_ev)
            if cp_html:
                rows_html.append(
                    f"<tr class=cp><td colspan=7>{cp_html}</td></tr>")
        # span waterfall (the 'spans' event job_done embeds when tracing
        # was on): one bar per span, offset/width proportional to the
        # job's trace window, lane color by category
        sp_ev = next((e for e in events if e.get("event") == "spans"), None)
        if sp_ev and sp_ev.get("spans"):
            rows_html.append(
                f"<tr class=wf><td colspan=7>"
                f"{_waterfall_html(sp_ev, cp_ev)}</td></tr>")

    refresh = '<meta http-equiv="refresh" content="2">' if live else ""
    doc = f"""<!doctype html><meta charset="utf-8">
{refresh}
<title>tuplex_tpu history</title>
<style>
 body {{ font: 14px system-ui, sans-serif; margin: 2rem; color: #1a1a1a; }}
 table {{ border-collapse: collapse; width: 100%; }}
 th, td {{ text-align: left; padding: .4rem .7rem;
           border-bottom: 1px solid #ddd; }}
 th {{ background: #f5f5f5; }}
 tr.exc td {{ color: #a33; font-size: 12px; border-bottom: none; }}
 tr.task td {{ color: #567; font-size: 12px; border-bottom: none; }}
 tr.running td {{ color: #0a6; font-style: italic; }}
 tr.lint td {{ color: #865; font-size: 12px; border-bottom: none; }}
 tr.wf td {{ border-bottom: none; }}
 tr.dev td {{ border-bottom: none; }}
 tr.dev summary {{ font-size: 12px; color: #456; cursor: pointer; }}
 tr.excp td {{ border-bottom: none; }}
 .excplane summary {{ font-size: 12px; color: #456; cursor: pointer; }}
 table.exctab {{ width: auto; font-size: 12px; margin: .3rem 0 .3rem 1rem; }}
 table.exctab th, table.exctab td {{ padding: .15rem .6rem; }}
 table.exctab td.unexp {{ color: #a33; font-weight: bold; }}
 .driftbar {{ display: inline-block; width: 80px; height: 8px;
              background: #eee; vertical-align: middle; }}
 .driftfill {{ display: block; height: 8px; background: #c2703a; }}
 .respbadge {{ background: #a33; color: #fff; font-size: 11px;
               padding: 0 .4em; border-radius: 3px; }}
 tr.respec td {{ color: #375; font-size: 12px; background: #f4faf4; }}
 .excsample {{ color: #765; font-size: 11px; margin-left: 1rem;
               overflow: hidden; white-space: nowrap;
               text-overflow: ellipsis; }}
 table.devtab {{ width: auto; font-size: 12px; margin: .3rem 0 .3rem 1rem; }}
 table.devtab th, table.devtab td {{ padding: .15rem .6rem; }}
 .rlbar {{ display: inline-block; width: 80px; height: 8px;
           background: #eee; vertical-align: middle; }}
 .rlfill {{ display: block; height: 8px; background: #5a9e6f; }}
 code {{ background: #f0f0f0; padding: 0 .3em; }}
 .waterfall summary {{ font-size: 12px; color: #456; cursor: pointer; }}
 .wfrow {{ display: flex; align-items: center; font-size: 11px;
           line-height: 1.4; }}
 .wflabel {{ flex: 0 0 260px; overflow: hidden; white-space: nowrap;
             text-overflow: ellipsis; color: #345; }}
 .wftrack {{ flex: 1; position: relative; height: 10px;
             background: #f4f4f4; }}
 .wfbar {{ position: absolute; top: 1px; height: 8px; min-width: 1px;
           background: #8ab; }}
 .wfbar.cat-plan {{ background: #7b6bd6; }}
 .wfbar.cat-compile {{ background: #d6906b; }}
 .wfbar.cat-exec {{ background: #5a9e6f; }}
 .wfbar.cat-xfer {{ background: #4a90c2; }}
 .wfbar.cat-mem {{ background: #c25a8a; }}
 .wfbar.cat-job {{ background: #778; }}
 .wfbar.onpath {{ outline: 2px solid #c23a3a; outline-offset: 1px; }}
 tr.cp td {{ border-bottom: none; }}
 .critpath summary {{ font-size: 12px; color: #456; cursor: pointer; }}
 .slowbadge {{ background: #c23a3a; color: #fff; font-size: 11px;
               padding: 0 .4em; border-radius: 3px; }}
 .degbadge {{ background: #b90; color: #fff; font-size: 11px;
              padding: 0 .4em; border-radius: 3px; }}
 .cptrack {{ position: relative; height: 14px; background: #f4f4f4;
             margin: .3rem 0 .3rem 1rem; }}
 .cpseg {{ position: absolute; top: 1px; height: 12px; min-width: 1px;
           background: #8ab; }}
 .cp-admission_wait, .cp-queue_wait {{ background: #aab; }}
 .cp-compile_trace, .cp-compile_lower, .cp-compile_xla
   {{ background: #d6906b; }}
 .cp-h2d, .cp-d2h {{ background: #4a90c2; }}
 .cp-device {{ background: #5a9e6f; }}
 .cp-resolve_general {{ background: #c2a23a; }}
 .cp-resolve_interpreter {{ background: #c2703a; }}
 .cp-merge {{ background: #7b6bd6; }}
 .cp-scheduler_other {{ background: #99a; }}
 .cp-unattributed {{ background: repeating-linear-gradient(45deg, #ddd,
                     #ddd 3px, #bbb 3px, #bbb 6px); }}
 table.cptab {{ width: auto; font-size: 12px; margin: .3rem 0 .3rem 1rem; }}
 table.cptab th, table.cptab td {{ padding: .15rem .6rem; }}
 table.cptab tr.cpdom td {{ font-weight: bold; }}
 table.cptab tr.cpblame td {{ color: #c23a3a; font-weight: bold; }}
</style>
<h1>tuplex_tpu job history</h1>
<p>{len(jobs)} job(s) · {html.escape(src)}</p>
<table>
<tr><th>job</th><th>stages</th><th>rows out</th><th>wall s</th>
<th>fast-path s</th><th>slow-path s</th><th>exceptions</th></tr>
{''.join(rows_html)}
</table>"""
    return doc


def history_to_chrome(log_dir: str = ".", out_path: str =
                      "tuplex_trace.json") -> str:
    """Replay the history file as one Chrome trace-event JSON: each job
    becomes a pid lane (normalized to its own start), using the embedded
    span slices (`spans` events, written when ``tuplex.tpu.trace`` was on)
    and falling back to coarse stage bars synthesized from the job/stage
    event wall-clock timestamps when a job ran without tracing."""
    jobs = _load_jobs(log_dir)

    trace_events: list = []
    for lane, (job_id, events) in enumerate(jobs.items(), start=1):
        # serve-submitted jobs carry a tenant on their rows (serve_job_
        # event / serve_job_spans): label the lane with it so a
        # multi-tenant replay separates by eye
        tenant = next((e["tenant"] for e in events if e.get("tenant")),
                      None)
        lane_name = f"job {job_id}" + (f" ({tenant})" if tenant else "")
        trace_events.append({"name": "process_name", "ph": "M", "pid": lane,
                             "tid": 0, "args": {"name": lane_name}})
        sp_ev = next((e for e in events if e.get("event") == "spans"), None)
        if sp_ev and sp_ev.get("spans"):
            t0 = min(s["ts"] for s in sp_ev["spans"])
            for s in sp_ev["spans"]:
                ev = {"name": s["name"], "cat": s.get("cat") or "exec",
                      "ph": "X", "ts": round(s["ts"] - t0, 1),
                      "dur": round(s.get("dur") or 0.0, 1),
                      "pid": lane, "tid": s.get("tid", 0)}
                if s.get("args"):
                    ev["args"] = s["args"]
                trace_events.append(ev)
            continue
        # no spans recorded: coarse bars off the event wall clocks
        start = next((e for e in events if e.get("event") == "job_start"),
                     None)
        done = next((e for e in events if e.get("event") == "job_done"),
                    None)
        if start is None:
            continue
        t0 = float(start["ts"])
        if done is not None:
            trace_events.append({
                "name": f"job:{start.get('action', '?')}", "cat": "job",
                "ph": "X", "ts": 0.0,
                "dur": round((float(done["ts"]) - t0) * 1e6, 1),
                "pid": lane, "tid": 0,
                "args": {"rows": done.get("rows"),
                         "wall_s": done.get("wall_s")}})
        starts = [e for e in events if e.get("event") == "stage_start"]
        for st in events:
            if st.get("event") != "stage":
                continue
            s0 = next((s for s in starts if s.get("no") == st.get("no")),
                      None)
            ts0 = float(s0["ts"]) if s0 is not None else float(st["ts"])
            trace_events.append({
                "name": f"stage{st.get('no', '?')}:"
                        f"{st.get('kind', '?')}",
                "cat": "exec", "ph": "X",
                "ts": round((ts0 - t0) * 1e6, 1),
                "dur": round((float(st["ts"]) - ts0) * 1e6, 1),
                "pid": lane, "tid": 0,
                "args": {k: v for k, v in
                         (st.get("metrics") or {}).items()
                         if isinstance(v, (int, float))}})
    # multihost: merge per-host span streams (tuplex_trace_host<idx>.jsonl,
    # dumped by every process at job end) into the same timeline. Each
    # stream's events carry their host index as pid (tracing.set_host) —
    # offset into a disjoint range so host lanes never collide with the
    # job lanes numbered 1..N above. Host streams keep their own clock
    # epoch (exact within a host; see runtime/tracing docstring).
    import glob as _glob

    from ..runtime.tracing import load_jsonl as _load_jsonl

    _HOST_LANE_BASE = 1000
    for hp in sorted(_glob.glob(os.path.join(log_dir or ".",
                                             "tuplex_trace_host*.jsonl"))):
        try:
            stream = _load_jsonl(hp)
        except OSError:
            continue
        for ev in stream:
            try:
                ev["pid"] = _HOST_LANE_BASE + int(ev.get("pid", 0))
            except (TypeError, ValueError):
                ev["pid"] = _HOST_LANE_BASE
        trace_events.extend(stream)
    obj = {"traceEvents": trace_events, "displayTimeUnit": "ms"}
    with open(out_path, "w") as fp:
        json.dump(obj, fp)
    return out_path


def _make_server(log_dir: str, port: int, host: str):
    import http.server

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802
            body = _render_doc(log_dir, live=True).encode("utf-8")
            self.send_response(200)
            self.send_header("Content-Type", "text/html; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # quiet
            pass

    return http.server.HTTPServer((host, port), Handler)


def serve(log_dir: str = ".", port: int = 5000,
          host: str = "127.0.0.1"):
    """Serve ONLY the rendered dashboard via stdlib http.server (blocking).

    Binds loopback by default and never exposes the filesystem — every GET
    re-renders and returns the dashboard document (auto-refreshing, so an
    open browser tab shows live job progress — the reference's Flask/
    SocketIO/Mongo webui collapsed to the stdlib)."""
    with _make_server(log_dir, port, host) as srv:
        srv.serve_forever()


def start_server(log_dir: str = ".", port: int = 5000,
                 host: str = "127.0.0.1"):
    """Background-thread variant (reference: ensure_webui autostart).
    Returns (server, url); call server.shutdown() to stop. port=0 picks a
    free port."""
    import threading

    srv = _make_server(log_dir, port, host)
    t = threading.Thread(target=srv.serve_forever, daemon=True,
                         name="tuplex-history-server")
    t.start()
    return srv, f"http://{host}:{srv.server_address[1]}/"
