"""Aggregation operators: unique / aggregate / aggregateByKey.

Re-designs the reference's aggregate machinery (reference:
logical/AggregateOperator.cc AGG_GENERAL/AGG_UNIQUE/AGG_BYKEY;
physical/AggregateFunctions.cc:16-178 — codegen'd agg_init/agg_combine/
agg_agg; LocalBackend.cc:911-919,1673,2219 — thread-local tables combined at
stage end) the TPU way:

  * the reference requires `combine` to be associative for parallelism; we
    exploit the same contract to VECTORIZE: aggregate UDFs matching
    associative fold patterns (acc + f(row), tuple-of-folds, min/max) are
    recognized on the AST and compiled to whole-column reductions /
    segment-sums on device — the MXU/VPU-sized replacement for the per-row
    compiled loop
  * aggregateByKey groups via key factorization + jax segment_sum over ICI-
    shardable codes (psum across a mesh combines per-device partials)
  * UDFs outside the recognizable subset fold on host (interpreter path),
    preserving semantics exactly
"""

from __future__ import annotations

import ast
from typing import Any, Callable, Optional, Sequence

import numpy as np

from ..core import typesys as T
from ..core.errors import TuplexException
from ..core.row import Row
from ..utils.reflection import get_udf_source
from . import logical as L


class UniqueOperator(L.LogicalOperator):
    """Distinct rows, first-occurrence order (reference: dataset.py:36
    unique → AGG_UNIQUE hashtable)."""

    def __init__(self, parent: L.LogicalOperator):
        super().__init__([parent])

    def is_breaker(self) -> bool:
        return True

    def schema(self) -> T.RowType:
        return self.parent.schema()

    def columns(self):
        return self.parent.columns()

    def sample(self) -> list[Row]:
        seen = set()
        out = []
        for r in self.parent.cached_sample():
            k = tuple(r.values)
            try:
                if k in seen:
                    continue
                seen.add(k)
            except TypeError:
                pass
            out.append(r)
        return out


class AggregateOperator(L.LogicalOperator):
    """General aggregate over the whole dataset (reference: dataset.py:593).

    combine(agg, agg) -> agg must be associative; aggregate(agg, row) -> agg.
    """

    def __init__(self, parent: L.LogicalOperator, combine: Callable,
                 aggregate: Callable, initial: Any):
        super().__init__([parent])
        self.combine_udf = get_udf_source(combine)
        self.aggregate_udf = get_udf_source(aggregate)
        self.initial = initial

    def is_breaker(self) -> bool:
        return True

    def schema(self) -> T.RowType:
        t = T.infer_type(self.initial)
        if isinstance(t, T.TupleType):
            return T.row_of([f"_{i}" for i in range(len(t.elements))],
                            t.elements)
        return T.row_of(["_0"], [t])

    def columns(self):
        return None

    def sample(self) -> list[Row]:
        acc = self.initial
        for r in self.parent.cached_sample():
            try:
                acc = _apply_agg(self.aggregate_udf, acc, r)
            except Exception:
                pass
        return [Row.from_value(acc)]


class AggregateByKeyOperator(L.LogicalOperator):
    """Grouped aggregate (reference: dataset.py:644 aggregateByKey)."""

    def __init__(self, parent: L.LogicalOperator, combine: Callable,
                 aggregate: Callable, initial: Any,
                 key_columns: Sequence[str]):
        super().__init__([parent])
        self.combine_udf = get_udf_source(combine)
        self.aggregate_udf = get_udf_source(aggregate)
        self.initial = initial
        self.key_columns = list(key_columns)

    def is_breaker(self) -> bool:
        return True

    def schema(self) -> T.RowType:
        ps = self.parent.schema()
        key_types = [ps.col_type(c) for c in self.key_columns]
        t = T.infer_type(self.initial)
        agg_types = list(t.elements) if isinstance(t, T.TupleType) else [t]
        agg_names = [f"_{i}" for i in range(len(agg_types))]
        return T.row_of(self.key_columns + agg_names, key_types + agg_types)

    def columns(self):
        return tuple(self.key_columns +
                     [f"_{i}" for i in
                      range(len(self.schema().types) - len(self.key_columns))])

    def sample(self) -> list[Row]:
        ps = self.parent.schema()
        kidx = [ps.columns.index(c) for c in self.key_columns]
        groups: dict = {}
        for r in self.parent.cached_sample():
            k = tuple(r.values[i] for i in kidx)
            acc = groups.get(k, self.initial)
            try:
                groups[k] = _apply_agg(self.aggregate_udf, acc, r)
            except Exception:
                pass
        out = []
        for k, acc in groups.items():
            accs = acc if isinstance(acc, tuple) else (acc,)
            out.append(Row(list(k) + list(accs), self.schema().columns))
        return out


def _apply_agg(udf, acc, row: Row):
    f = udf.func
    return f(acc, row if row.columns else
             (row.values[0] if len(row.values) == 1 else tuple(row.values)))


# ---------------------------------------------------------------------------
# associative-fold pattern recognition (the vectorization contract)
# ---------------------------------------------------------------------------

class FoldSpec:
    """aggregate(acc, row) recognized as k independent folds:
    acc'[i] = acc[i] REDUCER_i exprs_i(row). REDUCER in {sum, min, max}."""

    def __init__(self, reducers: list[str], exprs: list[ast.expr],
                 row_param: str, acc_param: str, globals_: dict,
                 scalar: bool):
        self.reducers = reducers
        self.exprs = exprs
        self.row_param = row_param
        self.acc_param = acc_param
        self.globals = globals_
        self.scalar = scalar


def recognize_fold(udf) -> Optional[FoldSpec]:
    """Match `lambda acc, row: <acc-update>` where the update is a tuple of
    (or single) `acc[i] + f(row)` / `min(acc[i], f(row))` / `max(...)` /
    `acc + f(row)` terms with f not referencing acc."""
    tree = udf.tree
    if isinstance(tree, ast.Lambda):
        body = tree.body
        params = [a.arg for a in tree.args.args]
    elif isinstance(tree, ast.FunctionDef):
        stmts = [s for s in tree.body
                 if not (isinstance(s, ast.Expr)
                         and isinstance(s.value, ast.Constant)
                         and isinstance(s.value.value, str))]  # docstrings
        if len(stmts) != 1 or not isinstance(stmts[0], ast.Return):
            return None
        body = stmts[0].value
        params = [a.arg for a in tree.args.args]
    else:
        return None
    if len(params) != 2 or body is None:
        return None
    acc_p, row_p = params

    def refs(node: ast.expr, name: str) -> bool:
        return any(isinstance(n, ast.Name) and n.id == name
                   for n in ast.walk(node))

    def match_term(node: ast.expr, index: Optional[int]):
        """-> (reducer, expr) or None. index=None: scalar acc."""

        def is_acc_ref(n: ast.expr) -> bool:
            if index is None:
                return isinstance(n, ast.Name) and n.id == acc_p
            return (isinstance(n, ast.Subscript)
                    and isinstance(n.value, ast.Name)
                    and n.value.id == acc_p
                    and isinstance(n.slice, ast.Constant)
                    and n.slice.value == index)

        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
            for accside, other in ((node.left, node.right),
                                   (node.right, node.left)):
                if is_acc_ref(accside) and not refs(other, acc_p):
                    return ("sum", other)
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id in ("min", "max") and len(node.args) == 2:
            a0, a1 = node.args
            for accside, other in ((a0, a1), (a1, a0)):
                if is_acc_ref(accside) and not refs(other, acc_p):
                    return (node.func.id, other)
        return None

    if isinstance(body, ast.Tuple):
        reducers, exprs = [], []
        for i, elt in enumerate(body.elts):
            m = match_term(elt, i)
            if m is None:
                return None
            reducers.append(m[0])
            exprs.append(m[1])
        return FoldSpec(reducers, exprs, row_p, acc_p, udf.globals, False)
    m = match_term(body, None)
    if m is None:
        return None
    return FoldSpec([m[0]], [m[1]], row_p, acc_p, udf.globals, True)


# ---------------------------------------------------------------------------
# general aggregate-UDF compilation: sequential device fold via lax.scan
# (reference: AggregateFunctions.cc:16-178 codegens agg_agg_f for ANY
# aggregate UDF — the per-task fold is sequential there too; parallelism
# comes from combining per-task partials, which we keep via combine())
# ---------------------------------------------------------------------------

def _acc_value_cv(t: T.Type, v):
    """[1]-batch CV for an accumulator python value under type t."""
    from ..compiler.values import CV, dtype_for, tuple_cv
    from ..core.errors import NotCompilable
    from ..runtime.jaxcfg import jnp

    if t.is_optional():
        # a None-able accumulator needs validity threaded through the scan
        # carry; until then the interpreter keeps exact semantics
        raise NotCompilable("Option accumulator not device-foldable")
    base = t
    if isinstance(base, T.TupleType):
        if not isinstance(v, tuple) or len(v) != len(base.elements):
            raise NotCompilable("aggregate initial/type mismatch")
        return tuple_cv([_acc_value_cv(e, vv)
                         for e, vv in zip(base.elements, v)])
    if base in (T.I64, T.F64, T.BOOL):
        _check_acc_scalar(base, v)
        return CV(t=base, data=jnp.full(1, v, dtype=dtype_for(base)))
    raise NotCompilable(f"aggregate accumulator type {t} not device-foldable")


def _check_acc_scalar(base: T.Type, v) -> None:
    """Strict value/type conformance: jnp.full would TRUNCATE a float into
    an int carry silently — a drifted accumulator must fall back to the
    interpreter instead (review r7)."""
    from ..core.errors import NotCompilable

    if base is T.BOOL:
        ok = isinstance(v, bool)
    elif base is T.I64:
        ok = isinstance(v, int) and not isinstance(v, bool)
    else:   # F64 accepts int or float (exact widening)
        ok = isinstance(v, (int, float)) and not isinstance(v, bool)
    if not ok:
        raise NotCompilable(f"accumulator value {v!r} does not conform "
                            f"to {base}")


def _acc_leaf_types(t: T.Type) -> list:
    """Flattened leaf base types in cv_arrays order."""
    base = t.without_option() if t.is_optional() else t
    if isinstance(base, T.TupleType):
        out: list = []
        for e in base.elements:
            out.extend(_acc_leaf_types(e))
        return out
    return [base]


def _flatten_acc(v, t: T.Type) -> list:
    """Python accumulator value -> validated scalar list (leaf order)."""
    from ..core.errors import NotCompilable

    base = t.without_option() if t.is_optional() else t
    if isinstance(base, T.TupleType):
        if not isinstance(v, tuple) or len(v) != len(base.elements):
            raise NotCompilable("accumulator arity mismatch")
        out: list = []
        for e, vv in zip(base.elements, v):
            out.extend(_flatten_acc(vv, e))
        return out
    _check_acc_scalar(base, v)
    return [v]


def _unflatten_acc(scalars: list, t: T.Type, pos: list) -> Any:
    base = t.without_option() if t.is_optional() else t
    if isinstance(base, T.TupleType):
        return tuple(_unflatten_acc(scalars, e, pos) for e in base.elements)
    v = scalars[pos[0]]
    pos[0] += 1
    if base is T.BOOL:
        return bool(v)
    if base is T.F64:
        return float(v)
    return int(v)


def _zero_of(t: T.Type):
    base = t.without_option() if t.is_optional() else t
    if isinstance(base, T.TupleType):
        return tuple(_zero_of(e) for e in base.elements)
    if base is T.BOOL:
        return False
    if base is T.F64:
        return 0.0
    return 0


def _coerce_cv(cv, t: T.Type):
    """Cast a traced CV to the stable accumulator type (numeric widening
    only); structure mismatches are NotCompilable."""
    from ..compiler.values import CV, dtype_for, materialize, tuple_cv
    from ..core.errors import NotCompilable

    if cv.is_const:
        cv = materialize(cv, 1)
    base = t.without_option() if t.is_optional() else t
    if isinstance(base, T.TupleType):
        if cv.elts is None or len(cv.elts) != len(base.elements):
            raise NotCompilable("aggregate result arity changed")
        return tuple_cv([_coerce_cv(e, et)
                         for e, et in zip(cv.elts, base.elements)])
    if base in (T.I64, T.F64, T.BOOL) and cv.data is not None:
        return CV(t=base, data=cv.data.astype(dtype_for(base)))
    raise NotCompilable(f"aggregate result type {cv.t} != {t}")


def _dummy_row_arrays(schema: T.RowType):
    """[1]-batch zero arrays for a row schema (type-fixpoint tracing)."""
    import numpy as np

    from ..runtime import columns as C
    from ..runtime.jaxcfg import jnp

    arrays = {"#rowvalid": jnp.ones(1, dtype=bool)}
    for ci, ct in enumerate(schema.types):
        for path, lt in C.flatten_type(ct, str(ci)):
            base = lt.without_option() if lt.is_optional() else lt
            opt = lt.is_optional()
            if path.endswith("#opt"):
                arrays[path] = jnp.ones(1, dtype=bool)
                continue
            if base is T.STR:
                arrays[path + "#bytes"] = jnp.zeros((1, 8), dtype=jnp.uint8)
                arrays[path + "#len"] = jnp.zeros(1, dtype=jnp.int32)
            elif base is T.BOOL:
                arrays[path] = jnp.zeros(1, dtype=bool)
            elif base is T.F64:
                arrays[path] = jnp.zeros(1, dtype=jnp.float64)
            else:
                arrays[path] = jnp.zeros(1, dtype=jnp.int64)
            if opt and not path.endswith("#opt"):
                arrays[path + "#valid"] = jnp.ones(1, dtype=bool)
    return arrays


class ScanFold:
    """Compiled general aggregate: one lax.scan over the batch whose body is
    the emitter-traced aggregate(acc, row) UDF. Rows that err (or are boxed)
    keep the accumulator unchanged and report in the bad mask — the host
    folds them on the interpreter, preserving exact semantics."""

    def __init__(self, op, row_schema: T.RowType, acc_t: T.Type):
        self.op = op
        self.row_schema = row_schema
        self.acc_t = acc_t

    @classmethod
    def try_build(cls, op, row_schema: T.RowType) -> "Optional[ScanFold]":
        from ..compiler.emitter import EmitCtx, Emitter
        from ..compiler.stagefn import input_row_cv
        from ..core.errors import NotCompilable

        udf = op.aggregate_udf
        if udf.tree is None or len(udf.params) != 2:
            return None
        acc_t = T.infer_type(op.initial)
        try:
            arrays = _dummy_row_arrays(row_schema)
            for _ in range(3):
                ctx = EmitCtx(1, arrays["#rowvalid"])
                em = Emitter(ctx, udf.globals)
                try:
                    acc_cv = _acc_value_cv(acc_t, op.initial)
                except (NotCompilable, TypeError, ValueError):
                    acc_cv = _acc_value_cv(acc_t, _zero_of(acc_t))
                row_cv = input_row_cv(arrays, row_schema)
                res = em.eval_udf(udf, [acc_cv, row_cv])
                res_t = res.t if not res.is_const else T.infer_type(res.const)
                if res_t.name == acc_t.name:
                    return cls(op, row_schema, acc_t)
                acc_t = T.super_type(acc_t, res_t)
                _acc_value_cv(acc_t, _zero_of(acc_t))  # still foldable?
        except NotCompilable:
            return None
        except Exception:
            return None
        return None   # accumulator type never stabilized

    def _trace_row(self, carry_leaves, row_arrays):
        """Shared one-row trace for both scan variants: rebuild the acc CV
        from carry leaves, run the UDF on the [1]-lifted row, coerce the
        result. Returns (new_leaves, bad_scalar)."""
        from ..compiler.emitter import EmitCtx, Emitter
        from ..compiler.stagefn import input_row_cv
        from ..compiler.values import cv_arrays, cv_rebuild

        template = _acc_value_cv(self.acc_t, _zero_of(self.acc_t))
        arrays1 = {k: v[None] for k, v in row_arrays.items()}
        ctx = EmitCtx(1, arrays1["#rowvalid"])
        em = Emitter(ctx, self.op.aggregate_udf.globals)
        acc_cv = cv_rebuild(template, iter(carry_leaves))
        row_cv = input_row_cv(arrays1, self.row_schema)
        res = em.eval_udf(self.op.aggregate_udf, [acc_cv, row_cv])
        res = _coerce_cv(res, self.acc_t)
        new_leaves: list = []
        cv_arrays(res, new_leaves)
        bad = (ctx.err[0] != 0) | ~row_arrays["#rowvalid"]
        return new_leaves, bad

    def build_fn(self):
        """jit-able: (arrays[B], acc_leaves_in) -> (acc_leaf_0[1], ...,
        bad[B]). The accumulator CHAINS across calls — the caller seeds the
        first partition with op.initial and every later one with the running
        value, so the initial counts exactly once (matching the pattern and
        interpreter tiers)."""
        from ..runtime.jaxcfg import jnp, lax

        def fn(arrays, acc_in):
            # scan over batched leaves only; 0-d scalars ('#seed') can't ride
            # the scanned axis
            xs = {k: v for k, v in arrays.items() if jnp.ndim(v)}

            def step(carry, x):
                new_leaves, bad = self._trace_row(carry, x)
                out = tuple(jnp.where(bad, old, new)
                            for old, new in zip(carry, new_leaves))
                return out, bad

            final, bads = lax.scan(step, tuple(acc_in), xs)
            return final + (bads,)

        return fn

    def encode_acc(self, value) -> tuple:
        """python accumulator value -> carry leaves (seeding a scan)."""
        from ..compiler.values import cv_arrays

        cv = _acc_value_cv(self.acc_t, value)
        leaves: list = []
        cv_arrays(cv, leaves)
        return tuple(leaves)

    def decode_acc(self, leaves) -> Any:
        """Final accumulator leaves -> python value."""
        import numpy as np

        from ..compiler.values import cv_rebuild

        template = _acc_value_cv(self.acc_t, _zero_of(self.acc_t))
        cv = cv_rebuild(template, iter([np.asarray(x) for x in leaves]))

        def unbox(c):
            if c.elts is not None:
                return tuple(unbox(e) for e in c.elts)
            v = np.asarray(c.data)[0]
            if c.t is T.BOOL:
                return bool(v)
            if c.t is T.F64:
                return float(v)
            return int(v)

        return unbox(cv)


# -- segmented scan fold (aggregateByKey with arbitrary UDFs) ---------------

def _seg_build_fn(scan: "ScanFold"):
    """(arrays[B], codes[B], seg_init leaves [nseg_b]) ->
    (seg leaves..., bad[B]). Rows whose code falls outside [0, nseg) (boxed /
    padding) are bad and leave the table untouched."""
    from ..runtime.jaxcfg import jnp, lax

    def fn(arrays, codes, seg_init):
        nseg_b = seg_init[0].shape[0]
        arrays = {k: v for k, v in arrays.items() if jnp.ndim(v)}

        def step(carry, x):
            code = x["code"]
            cc = jnp.clip(code, 0, nseg_b - 1)
            cur = tuple(c[cc][None] for c in carry)
            new_leaves, bad = scan._trace_row(cur, x["a"])
            bad = bad | (code < 0) | (code >= nseg_b)
            out = tuple(
                c.at[cc].set(jnp.where(bad, c[cc], nl[0]))
                for c, nl in zip(carry, new_leaves))
            return out, bad

        final, bads = lax.scan(step, tuple(seg_init),
                               {"a": arrays, "code": codes})
        return final + (bads,)

    return fn


_ACC_NP_DTYPES = {T.BOOL: np.bool_, T.I64: np.int64, T.F64: np.float64}


def _scanfold_encode_segments(scan: "ScanFold", values: list, nseg_b: int):
    """One accumulator python value per segment -> stacked carry leaves,
    zero-padded to nseg_b segments (pow2 bucket bounds retraces). Pure
    numpy — one host array per leaf, no per-segment device dispatches."""
    leaf_ts = _acc_leaf_types(scan.acc_t)
    flat = [_flatten_acc(v, scan.acc_t) for v in values]   # validates types
    cols = []
    for li, lt in enumerate(leaf_ts):
        col = np.zeros(nseg_b, dtype=_ACC_NP_DTYPES[lt])
        col[:len(values)] = [fv[li] for fv in flat]
        cols.append(col)
    return tuple(cols)


def _scanfold_decode_segments(scan: "ScanFold", leaves, nseg: int) -> list:
    """Final segment table -> one python accumulator value per segment."""
    cols = [np.asarray(x)[:nseg].tolist() for x in leaves]
    out = []
    for si in range(nseg):
        pos = [0]
        out.append(_unflatten_acc([c[si] for c in cols], scan.acc_t, pos))
    return out
