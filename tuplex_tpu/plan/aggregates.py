"""Aggregation operators: unique / aggregate / aggregateByKey.

Re-designs the reference's aggregate machinery (reference:
logical/AggregateOperator.cc AGG_GENERAL/AGG_UNIQUE/AGG_BYKEY;
physical/AggregateFunctions.cc:16-178 — codegen'd agg_init/agg_combine/
agg_agg; LocalBackend.cc:911-919,1673,2219 — thread-local tables combined at
stage end) the TPU way:

  * the reference requires `combine` to be associative for parallelism; we
    exploit the same contract to VECTORIZE: aggregate UDFs matching
    associative fold patterns (acc + f(row), tuple-of-folds, min/max) are
    recognized on the AST and compiled to whole-column reductions /
    segment-sums on device — the MXU/VPU-sized replacement for the per-row
    compiled loop
  * aggregateByKey groups via key factorization + jax segment_sum over ICI-
    shardable codes (psum across a mesh combines per-device partials)
  * UDFs outside the recognizable subset fold on host (interpreter path),
    preserving semantics exactly
"""

from __future__ import annotations

import ast
from typing import Any, Callable, Optional, Sequence

from ..core import typesys as T
from ..core.errors import TuplexException
from ..core.row import Row
from ..utils.reflection import get_udf_source
from . import logical as L


class UniqueOperator(L.LogicalOperator):
    """Distinct rows, first-occurrence order (reference: dataset.py:36
    unique → AGG_UNIQUE hashtable)."""

    def __init__(self, parent: L.LogicalOperator):
        super().__init__([parent])

    def is_breaker(self) -> bool:
        return True

    def schema(self) -> T.RowType:
        return self.parent.schema()

    def columns(self):
        return self.parent.columns()

    def sample(self) -> list[Row]:
        seen = set()
        out = []
        for r in self.parent.sample():
            k = tuple(r.values)
            try:
                if k in seen:
                    continue
                seen.add(k)
            except TypeError:
                pass
            out.append(r)
        return out


class AggregateOperator(L.LogicalOperator):
    """General aggregate over the whole dataset (reference: dataset.py:593).

    combine(agg, agg) -> agg must be associative; aggregate(agg, row) -> agg.
    """

    def __init__(self, parent: L.LogicalOperator, combine: Callable,
                 aggregate: Callable, initial: Any):
        super().__init__([parent])
        self.combine_udf = get_udf_source(combine)
        self.aggregate_udf = get_udf_source(aggregate)
        self.initial = initial

    def is_breaker(self) -> bool:
        return True

    def schema(self) -> T.RowType:
        t = T.infer_type(self.initial)
        if isinstance(t, T.TupleType):
            return T.row_of([f"_{i}" for i in range(len(t.elements))],
                            t.elements)
        return T.row_of(["_0"], [t])

    def columns(self):
        return None

    def sample(self) -> list[Row]:
        acc = self.initial
        for r in self.parent.sample():
            try:
                acc = _apply_agg(self.aggregate_udf, acc, r)
            except Exception:
                pass
        return [Row.from_value(acc)]


class AggregateByKeyOperator(L.LogicalOperator):
    """Grouped aggregate (reference: dataset.py:644 aggregateByKey)."""

    def __init__(self, parent: L.LogicalOperator, combine: Callable,
                 aggregate: Callable, initial: Any,
                 key_columns: Sequence[str]):
        super().__init__([parent])
        self.combine_udf = get_udf_source(combine)
        self.aggregate_udf = get_udf_source(aggregate)
        self.initial = initial
        self.key_columns = list(key_columns)

    def is_breaker(self) -> bool:
        return True

    def schema(self) -> T.RowType:
        ps = self.parent.schema()
        key_types = [ps.col_type(c) for c in self.key_columns]
        t = T.infer_type(self.initial)
        agg_types = list(t.elements) if isinstance(t, T.TupleType) else [t]
        agg_names = [f"_{i}" for i in range(len(agg_types))]
        return T.row_of(self.key_columns + agg_names, key_types + agg_types)

    def columns(self):
        return tuple(self.key_columns +
                     [f"_{i}" for i in
                      range(len(self.schema().types) - len(self.key_columns))])

    def sample(self) -> list[Row]:
        ps = self.parent.schema()
        kidx = [ps.columns.index(c) for c in self.key_columns]
        groups: dict = {}
        for r in self.parent.sample():
            k = tuple(r.values[i] for i in kidx)
            acc = groups.get(k, self.initial)
            try:
                groups[k] = _apply_agg(self.aggregate_udf, acc, r)
            except Exception:
                pass
        out = []
        for k, acc in groups.items():
            accs = acc if isinstance(acc, tuple) else (acc,)
            out.append(Row(list(k) + list(accs), self.schema().columns))
        return out


def _apply_agg(udf, acc, row: Row):
    f = udf.func
    return f(acc, row if row.columns else
             (row.values[0] if len(row.values) == 1 else tuple(row.values)))


# ---------------------------------------------------------------------------
# associative-fold pattern recognition (the vectorization contract)
# ---------------------------------------------------------------------------

class FoldSpec:
    """aggregate(acc, row) recognized as k independent folds:
    acc'[i] = acc[i] REDUCER_i exprs_i(row). REDUCER in {sum, min, max}."""

    def __init__(self, reducers: list[str], exprs: list[ast.expr],
                 row_param: str, acc_param: str, globals_: dict,
                 scalar: bool):
        self.reducers = reducers
        self.exprs = exprs
        self.row_param = row_param
        self.acc_param = acc_param
        self.globals = globals_
        self.scalar = scalar


def recognize_fold(udf) -> Optional[FoldSpec]:
    """Match `lambda acc, row: <acc-update>` where the update is a tuple of
    (or single) `acc[i] + f(row)` / `min(acc[i], f(row))` / `max(...)` /
    `acc + f(row)` terms with f not referencing acc."""
    tree = udf.tree
    if isinstance(tree, ast.Lambda):
        body = tree.body
        params = [a.arg for a in tree.args.args]
    elif isinstance(tree, ast.FunctionDef):
        stmts = [s for s in tree.body
                 if not isinstance(s, (ast.Expr,))]  # skip docstrings
        if len(stmts) != 1 or not isinstance(stmts[0], ast.Return):
            return None
        body = stmts[0].value
        params = [a.arg for a in tree.args.args]
    else:
        return None
    if len(params) != 2 or body is None:
        return None
    acc_p, row_p = params

    def refs(node: ast.expr, name: str) -> bool:
        return any(isinstance(n, ast.Name) and n.id == name
                   for n in ast.walk(node))

    def match_term(node: ast.expr, index: Optional[int]):
        """-> (reducer, expr) or None. index=None: scalar acc."""

        def is_acc_ref(n: ast.expr) -> bool:
            if index is None:
                return isinstance(n, ast.Name) and n.id == acc_p
            return (isinstance(n, ast.Subscript)
                    and isinstance(n.value, ast.Name)
                    and n.value.id == acc_p
                    and isinstance(n.slice, ast.Constant)
                    and n.slice.value == index)

        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
            for accside, other in ((node.left, node.right),
                                   (node.right, node.left)):
                if is_acc_ref(accside) and not refs(other, acc_p):
                    return ("sum", other)
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id in ("min", "max") and len(node.args) == 2:
            a0, a1 = node.args
            for accside, other in ((a0, a1), (a1, a0)):
                if is_acc_ref(accside) and not refs(other, acc_p):
                    return (node.func.id, other)
        return None

    if isinstance(body, ast.Tuple):
        reducers, exprs = [], []
        for i, elt in enumerate(body.elts):
            m = match_term(elt, i)
            if m is None:
                return None
            reducers.append(m[0])
            exprs.append(m[1])
        return FoldSpec(reducers, exprs, row_p, acc_p, udf.globals, False)
    m = match_term(body, None)
    if m is None:
        return None
    return FoldSpec([m[0]], [m[1]], row_p, acc_p, udf.globals, True)
