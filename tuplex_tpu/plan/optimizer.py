"""Logical optimizations: projection pushdown into file sources.

Re-designs the reference's logical optimizer (reference:
core/src/logical/LogicalPlan.cc — optimizeFilters/projection pushdown via
ColumnRewriteVisitor; csv.selectionPushdown option): we statically analyze
which source columns each UDF actually reads (dict-style subscripts with
constant keys) and prune everything else at the Arrow read — unread columns
are never parsed, decoded, or shipped to the device.
"""

from __future__ import annotations

import ast
from typing import Optional

from ..core import typesys as T
from . import logical as L

ALL = None  # sentinel: reads the whole row


def udf_read_columns(udf) -> Optional[set[str]]:
    """Column names a single-param UDF reads via x['col'] subscripts, or ALL
    if the row escapes (used whole, iterated, multi-param...)."""
    params = udf.params
    if len(params) != 1:
        return ALL
    p = params[0]
    if udf.source == "":
        return ALL
    reads = _param_subscript_reads(udf.tree, p)
    if reads is ALL:
        return ALL
    # any OTHER use of the param leaks the whole row
    leaks = _param_leaks(udf.tree, p)
    return ALL if leaks else reads


def _param_subscript_reads(tree: ast.AST, p: str):
    """Constant-string subscript reads of param `p` (`p['col']`), or ALL
    when any subscript of `p` has a non-const-str key. Shared by the
    single-param (udf_read_columns) and aggregate row-param
    (agg_required_columns) analyses."""
    reads: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Subscript) and \
                isinstance(node.value, ast.Name) and node.value.id == p:
            if isinstance(node.slice, ast.Constant) and \
                    isinstance(node.slice.value, str):
                reads.add(node.slice.value)
            else:
                return ALL
    return reads


def _param_leaks(tree: ast.AST, p: str) -> bool:
    """True if `p` is used anywhere except as `p['const']`."""
    class V(ast.NodeVisitor):
        def __init__(self):
            self.leak = False
            self.root = tree   # the UDF's own lambda/def binds p by design

        def visit_Subscript(self, node: ast.Subscript):
            if isinstance(node.value, ast.Name) and node.value.id == p and \
                    isinstance(node.slice, ast.Constant) and \
                    isinstance(node.slice.value, str):
                self.visit(node.slice)
                return  # safe use; don't descend into node.value
            self.generic_visit(node)

        def visit_Name(self, node: ast.Name):
            if node.id == p:
                self.leak = True

        def _nested_scope(self, node):
            # a nested lambda/def whose own parameter SHADOWS the row param
            # creates a new binding: subscripts inside it are not row reads,
            # but the walk in _param_subscript_reads can't tell them apart —
            # treat the whole UDF as reading the full row (ast.arg is not a
            # Name, so visit_Name alone never sees the shadowing)
            if node is self.root:
                self.generic_visit(node)
                return node
            from ..compiler.analyzer import _all_params

            if p in _all_params(node):
                self.leak = True
                return node
            self.generic_visit(node)
            return node

        def visit_Lambda(self, node: ast.Lambda):
            return self._nested_scope(node)

        def visit_FunctionDef(self, node):
            return self._nested_scope(node)

        def visit_AsyncFunctionDef(self, node):
            return self._nested_scope(node)

    v = V()
    v.visit(tree)
    return v.leak


def op_reads(op: L.LogicalOperator, current_columns) -> Optional[set[str]]:
    """Columns (by their CURRENT names) an operator reads."""
    if isinstance(op, L.MapColumnOperator):
        return {op.column}
    if isinstance(op, (L.MapOperator, L.FilterOperator,
                       L.WithColumnOperator)):
        return udf_read_columns(op.udf)
    if isinstance(op, L.ResolveOperator):
        return udf_read_columns(op.udf)
    if isinstance(op, L.SelectColumnsOperator):
        out = set()
        for c in op.selected:
            if isinstance(c, int):
                if current_columns is None or c >= len(current_columns):
                    return ALL
                out.add(current_columns[c])
            else:
                out.add(c)
        return out
    if isinstance(op, (L.RenameColumnOperator, L.IgnoreOperator,
                       L.TakeOperator, L.DecodeOperator)):
        return set()
    return ALL  # unknown operator: be safe


def agg_required_columns(agg_op) -> Optional[set[str]]:
    """Columns an aggregate breaker reads from its input stage's OUTPUT:
    key columns + the row-param subscripts of the aggregate UDF (the `x`
    in `lambda a, x: ...`). None = whole row (unique, leaking UDFs).
    Feeds projection pushdown across the stage boundary — tpch q1's
    lineitem tax/shipdate columns stop being decoded/staged."""
    from . import aggregates as A

    if not isinstance(agg_op, (A.AggregateOperator,
                               A.AggregateByKeyOperator)):
        return None
    udf = agg_op.aggregate_udf
    if udf.source == "" or len(udf.params) != 2:
        return None
    p = udf.params[1]
    if _param_leaks(udf.tree, p):
        return None
    reads = _param_subscript_reads(udf.tree, p)
    if reads is ALL:
        return None
    reads.update(getattr(agg_op, "key_columns", []) or [])
    return reads


def required_source_columns(source_columns: tuple[str, ...],
                            ops: list[L.LogicalOperator],
                            output_required: Optional[set] = None
                            ) -> Optional[list[str]]:
    """Minimal subset of source columns the chain needs, in source order;
    None if the whole row is required somewhere. `output_required` narrows
    the stage-output liveness to the columns a downstream breaker
    actually consumes (everything, when None)."""
    alias: dict[str, Optional[str]] = {c: c for c in source_columns}
    required: set[str] = set()
    cur_cols: Optional[list[str]] = list(source_columns)

    def add_reads(reads) -> bool:
        if reads is ALL:
            return False
        for r in reads:
            src = alias.get(r)
            if src:
                required.add(src)
        return True

    for i, op in enumerate(ops):
        reads = op_reads(op, cur_cols)
        if not add_reads(reads):
            return None
        if isinstance(op, L.MapOperator):
            # the map consumes the row — but its resolvers receive the
            # PRE-map row, so account for their reads before stopping
            j = i + 1
            while j < len(ops) and isinstance(
                    ops[j], (L.ResolveOperator, L.IgnoreOperator)):
                if not add_reads(op_reads(ops[j], cur_cols)):
                    return None
                j += 1
            return [c for c in source_columns if c in required]
        if isinstance(op, L.WithColumnOperator):
            alias[op.column] = None  # derived (or overwritten) column
            if cur_cols is not None and op.column not in cur_cols:
                cur_cols.append(op.column)
        elif isinstance(op, L.RenameColumnOperator):
            old = op.old if isinstance(op.old, str) else (
                cur_cols[op.old] if cur_cols else None)
            if old is None:
                return None
            alias[op.new] = alias.pop(old, None)
            if cur_cols is not None:
                cur_cols = [op.new if c == old else c for c in cur_cols]
        elif isinstance(op, L.SelectColumnsOperator):
            sel = []
            for c in op.selected:
                sel.append(cur_cols[c] if isinstance(c, int) and cur_cols
                           else c)
            alias = {c: alias.get(c) for c in sel}
            cur_cols = list(sel)
    # stage-output liveness: everything that survives — or, when the
    # downstream breaker declared its reads, just those columns
    if output_required is None:
        required |= {s for s in alias.values() if s}
    else:
        for name in output_required:
            src = alias.get(name)
            if src:
                required.add(src)
    return [c for c in source_columns if c in required]


def split_filter_conjunctions(ops: list) -> list:
    """FilterBreakdownVisitor analog (reference: FilterBreakdownVisitor.cc;
    LogicalPlan.cc emitPartialFilters): a filter whose body is `a and b`
    splits into SEQUENTIAL filters — order between the clauses is preserved
    (short-circuit intact relative to each other), but each clause can now
    hop over unrelated operators independently during pushdown."""
    out: list = []
    for i, op in enumerate(ops):
        nxt = ops[i + 1] if i + 1 < len(ops) else None
        if isinstance(op, L.FilterOperator) and not isinstance(
                nxt, (L.ResolveOperator, L.IgnoreOperator)):
            parts = _split_filter(op)
            if parts is not None:
                out.extend(parts)
                continue
        out.append(op)
    return out


def _split_filter(op) -> Optional[list]:
    from ..utils.reflection import UDFSource

    udf = op.udf
    tree = udf.tree
    if udf.source == "" or len(udf.params) != 1:
        return None
    if isinstance(tree, ast.Lambda):
        body = tree.body
    elif isinstance(tree, ast.FunctionDef):
        # strip DOCSTRINGS only — a bare-call Expr has side effects that a
        # split would silently drop
        stmts = [s for s in tree.body
                 if not (isinstance(s, ast.Expr)
                         and isinstance(s.value, ast.Constant)
                         and isinstance(s.value.value, str))]
        if len(stmts) != 1 or not isinstance(stmts[0], ast.Return):
            return None
        body = stmts[0].value
    else:
        return None
    if not isinstance(body, ast.BoolOp) or not isinstance(body.op, ast.And):
        return None
    # walrus bindings can flow between clauses: splitting unbinds them
    if any(isinstance(n, ast.NamedExpr) for n in ast.walk(body)):
        return None
    p = udf.params[0]
    filters: list = []
    for k, clause in enumerate(body.values):
        try:
            src = f"lambda {p}: ({ast.unparse(clause)})"
            fn = eval(compile(src, f"<filter-split-{udf.name}>", "eval"),
                      dict(udf.globals))
            sub_tree = ast.parse(src, mode="eval").body
            fop = L.FilterOperator(op.parent, fn)
        except Exception:
            return None
        fop.udf = UDFSource(fn, src, sub_tree, dict(udf.globals),
                            f"{udf.name}#and{k}")
        filters.append(fop)
    return filters


def filter_pushdown(ops: list) -> list:
    """Move filters ahead of operators whose outputs they don't read
    (reference: LogicalPlan.cc optimizeFilters — pushing filters toward the
    source shrinks every downstream operator's working set).

    A filter hops over a preceding op when:
      * the op is a Map: never (row shape changes);
      * the op is a WithColumn/MapColumn: the filter doesn't read the
        written column;
      * the op is Rename/Select: names translate through;
    and neither op has resolvers attached (resolver semantics bind to
    operator order).
    """
    guarded: set[int] = set()
    for i, op in enumerate(ops):
        if isinstance(op, (L.ResolveOperator, L.IgnoreOperator)) and i > 0:
            guarded.add(id(ops[i - 1]))
            guarded.add(id(op))

    result = list(ops)
    changed = True
    while changed:
        changed = False
        for i in range(1, len(result)):
            f = result[i]
            prev = result[i - 1]
            if not isinstance(f, L.FilterOperator):
                continue
            if id(f) in guarded or id(prev) in guarded:
                continue
            reads = udf_read_columns(f.udf)
            if reads is ALL:
                continue
            if isinstance(prev, L.WithColumnOperator):
                if prev.column in reads:
                    continue
            elif isinstance(prev, L.MapColumnOperator):
                if prev.column in reads:
                    continue
            elif isinstance(prev, L.RenameColumnOperator):
                if prev.new in reads:
                    continue  # name doesn't exist before the rename
            else:
                continue  # Map/Select/Decode/aggregates: don't hop
            result[i - 1], result[i] = f, prev
            changed = True
    return result


def push_filters_through_joins(chain: list) -> list:
    """Push single-side filters ACROSS join boundaries (reference:
    FilterBreakdownVisitor.cc + LogicalPlan.cc optimizeFilters/
    emitPartialFilters — key-side predicates move through join build/probe
    sides so the join materializes fewer rows).

    `chain` is plan_stages' source→sink operator list. A filter downstream
    of a join pushes when every column it reads traces (through renames /
    untouched withColumn/mapColumn outputs) to ONE side of the join:

      * LEFT (probe) side — sound for inner AND left joins: the clone runs
        before the join in the same chain;
      * RIGHT (build) side — inner joins only (a left join keeps unmatched
        probe rows, so dropping build rows early changes nulls): the join
        node is shallow-copied with the clone spliced above its build
        parent (the user's DAG is never mutated; JoinStage plans the build
        side recursively from that parent).

    Column names rewrite via AST (x['CarrierName'] -> x['AirlineName'] ->
    undecorated side name). Resolvers/ignores between filter and join
    block the push (the filter must see resolved rows). Same
    exception-semantics caveat as in-stage pushdown, same option gate
    (tuplex.optimizer.filterPushdown)."""
    import copy

    from .joins import JoinOperator

    def attached_resolver(i: int) -> bool:
        nxt = chain[i + 1] if i + 1 < len(chain) else None
        return isinstance(nxt, (L.ResolveOperator, L.IgnoreOperator))

    changed = True
    while changed:
        changed = False
        for fi, f in enumerate(chain):
            if not isinstance(f, L.FilterOperator) or attached_resolver(fi):
                continue
            reads = udf_read_columns(f.udf)
            if reads is ALL or not reads:
                continue
            # walk upstream translating names until the nearest join
            mapping = {r: r for r in reads}     # filter name -> name at op k
            ji = None
            for k in range(fi - 1, -1, -1):
                op = chain[k]
                if isinstance(op, JoinOperator):
                    ji = k
                    break
                if isinstance(op, (L.ResolveOperator, L.IgnoreOperator)):
                    ji = None
                    break
                if isinstance(op, L.FilterOperator):
                    continue
                if isinstance(op, L.RenameColumnOperator):
                    if op.old in mapping.values():
                        ji = None   # upstream-only name already in use
                        break
                    mapping = {r: (op.old if n == op.new else n)
                               for r, n in mapping.items()}
                    continue
                if isinstance(op, (L.WithColumnOperator,
                                   L.MapColumnOperator)):
                    if op.column in mapping.values():
                        ji = None   # reads a column this op writes
                        break
                    continue
                if isinstance(op, L.SelectColumnsOperator):
                    sel = set(c for c in op.selected if isinstance(c, str))
                    if any(isinstance(c, int) for c in op.selected) or \
                            not set(mapping.values()) <= sel:
                        ji = None
                        break
                    continue
                ji = None           # Map / aggregate / unknown: stop
                break
            if ji is None:
                continue
            j = chain[ji]
            side_map = _classify_join_side(j, set(mapping.values()))
            if side_map is None:
                continue
            side, names = side_map
            if side == "right" and j.how != "inner":
                continue
            full_map = {r: names[n] for r, n in mapping.items()}
            parent = j.parents[0] if side == "left" else j.parents[1]
            clone = _rename_filter(f, full_map, parent)
            if clone is None:
                continue
            if side == "left":
                del chain[fi]
                chain.insert(ji, clone)
            else:
                j2 = copy.copy(j)
                j2.parents = [j.parents[0], clone]
                chain[ji] = j2
                del chain[fi]
            changed = True
            break
    return chain


def _classify_join_side(j, names: set):
    """Which join side ALL `names` (join-output columns) come from:
    ("left"|"right", {output name -> side-local name}) or None if mixed."""
    ls = j.left.schema()
    rs = j.right.schema()
    lk = ls.columns.index(j.left_column)
    rk = rs.columns.index(j.right_column)
    left_names = {j._decorate(c, 0): c
                  for i, c in enumerate(ls.columns) if i != lk}
    left_names[j.left_column] = j.left_column
    right_names = {j._decorate(c, 1): c
                   for i, c in enumerate(rs.columns) if i != rk}
    # the key column is both sides' key: usable on either
    right_key_alias = {j.left_column: j.right_column}
    if names <= set(left_names):
        return "left", left_names
    if names <= set(right_names) | set(right_key_alias):
        return "right", {**right_names, **right_key_alias}
    return None


def _rename_filter(f, mapping: dict, parent):
    """Clone a filter with its UDF's x['col'] subscripts renamed."""
    import copy

    from ..utils.reflection import UDFSource

    udf = f.udf
    if udf.source == "" or len(udf.params) != 1:
        return None
    p = udf.params[0]
    tree = copy.deepcopy(udf.tree)

    class R(ast.NodeTransformer):
        def visit_Subscript(self, node: ast.Subscript):
            self.generic_visit(node)
            if isinstance(node.value, ast.Name) and node.value.id == p and \
                    isinstance(node.slice, ast.Constant) and \
                    node.slice.value in mapping:
                node.slice = ast.Constant(mapping[node.slice.value])
            return node

    tree = ast.fix_missing_locations(R().visit(tree))
    try:
        if isinstance(tree, ast.Lambda):
            src = ast.unparse(tree)
            fn = eval(compile(src, f"<join-push-{udf.name}>", "eval"),
                      dict(udf.globals))
        elif isinstance(tree, ast.FunctionDef):
            src = ast.unparse(tree)
            ns = dict(udf.globals)
            exec(compile(src, f"<join-push-{udf.name}>", "exec"), ns)
            fn = ns[tree.name]
        else:
            return None
    except Exception:
        return None
    fop = L.FilterOperator(parent, fn)
    fop.udf = UDFSource(fn, src, tree, dict(udf.globals),
                        f"{udf.name}#joinpush")
    return fop


def reorder_filters(ops: list) -> list:
    """Operator reordering (reference: LogicalPlan.cc's
    tuplex.optimizer.operatorReordering, off by default there too): order
    CONSECUTIVE runs of filters by estimated selectivity so the most
    selective predicate runs first and shrinks the working set for the rest.

    Selectivity is estimated by running each filter's UDF over its
    operator's traced sample; rows that raise count as passing (they must
    still reach the filter that raises for exception parity). Like the
    reference, this is opt-in: reordering changes WHICH filter first drops
    (or raises on) a row, so per-operator exception attribution can shift.
    """
    result = list(ops)
    i = 0
    while i < len(result):
        if not isinstance(result[i], L.FilterOperator):
            i += 1
            continue
        j = i
        while j < len(result) and isinstance(result[j], L.FilterOperator):
            j += 1
        # resolvers bind to the preceding operator: a guarded run stays put
        if j < len(result) and isinstance(
                result[j], (L.ResolveOperator, L.IgnoreOperator)):
            i = j + 1
            continue
        if j - i > 1:
            run = result[i:j]
            run.sort(key=_filter_selectivity)
            result[i:j] = run
        i = j
    return result


def _filter_selectivity(op) -> float:
    """Estimated pass fraction of a filter over its traced sample (lower =
    more selective = runs earlier); 1.0 when no sample is available."""
    from .logical import apply_udf_python

    try:
        sample = op.parent.cached_sample()
    except Exception:
        return 1.0
    if not sample:
        return 1.0
    passed = 0
    for row in sample:
        try:
            if apply_udf_python(op.udf, row):
                passed += 1
        except Exception:
            passed += 1  # must reach this filter to raise: treat as pass
    return passed / len(sample)
