"""Logical operator DAG.

Re-designs the reference's logical layer (reference: core/src/logical/ — one
class per operator with output-schema inference and sampling,
LogicalOperator.cc:39-50 compute()). Schema inference here IS the sample
tracer: operators run their UDF on the parent's sample rows via CPython
(reference: TraceVisitor semantics — execute on sample to annotate types,
core/include/TraceVisitor.h:25-80) and speculate the normal-case output type.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Optional, Sequence

from ..core import typesys as T
from ..core.errors import TuplexException
from ..core.row import Row
from ..utils.reflection import UDFSource, get_udf_source

_op_ids = itertools.count(1)

# cross-job memo: chain identity -> sample rows / inferred schema. Rebuilding
# a content-identical pipeline over fingerprintable sources skips re-running
# every UDF over the sample (the reference reuses per-UDF hint results the
# same way via its source_vault + JIT cache keying). LRU-bounded: the old
# grow-then-.clear() pattern dropped every warm schema the moment one insert
# crossed the cap (utils/lru.py).
from ..utils.lru import LruDict

_cross_job_samples: LruDict = LruDict(256)
_cross_job_branchprofs: LruDict = LruDict(256)
_cross_job_schemas: LruDict = LruDict(512)


SAMPLE_EXC_CAP = 16   # recorder slices to tuplex.webui.exceptionDisplayLimit


def record_sample_exc(op: "LogicalOperator", e: Exception, row) -> None:
    """Sample-time exception preview (reference: SampleProcessor running
    sample rows through real UDFs to give the webui per-operator exception
    previews, include/physical/SampleProcessor.h:26-103). Deduplicated and
    capped, attached to the operator, surfaced via the job recorder (the
    same row fails in both schema inference AND sampling — one entry)."""
    lst = getattr(op, "sample_exceptions", None)
    if lst is None:
        lst = op.sample_exceptions = []
    entry = (type(e).__name__, repr(getattr(row, "values", row))[:200])
    if len(lst) < SAMPLE_EXC_CAP and entry not in lst:
        lst.append(entry)


def apply_udf_python(udf: UDFSource, row: Row, func=None) -> Any:
    """Interpreter-path calling convention shared by sampling and the
    fallback pipeline (reference: PythonPipelineBuilder's generated Row class,
    core/src/physical/PythonPipelineBuilder.cc:1-60). `func` substitutes an
    instrumented clone of the UDF (branch profiling) under the same
    convention."""
    f = func if func is not None else udf.func
    nparams = len(udf.params) if udf.params else 1
    if nparams > 1 and len(row.values) == nparams:
        return f(*row.values)
    if row.columns is not None:
        return f(row)
    if len(row.values) == 1:
        return f(row.values[0])
    return f(tuple(row.values))


class LogicalOperator:
    """Base: parent links + output schema + sample rows."""

    def __init__(self, parents: Sequence["LogicalOperator"]):
        self.id = next(_op_ids)
        self.parents = list(parents)
        self.name = type(self).__name__.replace("Operator", "").lower()

    @property
    def parent(self) -> "LogicalOperator":
        return self.parents[0]

    # -- overridables --------------------------------------------------------
    def schema(self) -> T.RowType:
        raise NotImplementedError

    def columns(self) -> Optional[tuple[str, ...]]:
        from ..runtime.columns import user_columns

        return user_columns(self.schema())

    def sample(self) -> list[Row]:
        raise NotImplementedError

    def source_key(self) -> Optional[str]:
        """Content identity of a SOURCE operator's data, or None when the
        data has no cheap stable fingerprint (e.g. parallelize over a live
        python list). Non-None keys enable the cross-job sample/schema memo:
        rebuilding the identical pipeline (the bench builds a fresh DataSet
        per run; the reference JIT-caches per stage the same way) skips
        re-running every UDF over the sample."""
        return None

    def chain_key(self) -> Optional[str]:
        """Content identity of this operator INCLUDING its whole upstream
        chain; None disables cross-job memoization for this subtree."""
        ck = getattr(self, "_chain_key_memo", False)
        if ck is not False:
            return ck
        from ..compiler.analyzer import op_nondeterministic

        if op_nondeterministic(self):
            # purity gate (compiler/analyzer.py): a nondeterministic UDF
            # (random/time) makes content identity meaningless — rebuilding
            # the pipeline must re-run its samples, not reuse memoized ones
            self._chain_key_memo = None
            return None
        import hashlib

        from .physical import _op_identity

        h = hashlib.sha256()
        if not self.parents:
            sk = self.source_key()
            if sk is None:
                self._chain_key_memo = None
                return None
            h.update(sk.encode())
        for p in self.parents:
            pk = p.chain_key()
            if pk is None:
                self._chain_key_memo = None
                return None
            h.update(pk.encode())
        h.update(_op_identity(self).encode())
        ck = self._chain_key_memo = h.hexdigest()[:24]
        return ck

    def cached_sample(self) -> list[Row]:
        """Memoized sample(): every consumer (child schema inference, child
        samples, speculation probes) shares ONE trace per operator instead of
        re-running the whole upstream UDF chain per call — planning was
        measurably O(ops²) in sample applications without this (reference:
        TraceVisitor runs once per operator too). Content-identical chains
        over fingerprintable sources additionally share across jobs."""
        memo = getattr(self, "_sample_memo", None)
        if memo is None:
            ck = self.chain_key()
            hit = _cross_job_samples.get(ck) if ck is not None else None
            if hit is not None:
                memo, excs = hit
                if excs:   # previews travel with the memo: a rebuilt
                    # identical pipeline skips the UDF re-runs but must
                    # still show its sample exceptions
                    self.sample_exceptions = list(excs)
            else:
                memo = self.sample()
                if ck is not None:
                    _cross_job_samples[ck] = (
                        memo, list(getattr(self, "sample_exceptions", [])))
            self._sample_memo = memo
        return memo

    def is_breaker(self) -> bool:
        """Pipeline breaker => stage boundary (reference:
        PhysicalPlan.cc:60-238 — joins/aggregates end stages)."""
        return False

    def __repr__(self):
        return f"{type(self).__name__}(#{self.id})"


class ParallelizeOperator(LogicalOperator):
    """In-memory input (reference: core/src/logical/ParallelizeOperator.cc)."""

    def __init__(self, data: list, schema: T.RowType, sample_size: int = 256):
        super().__init__([])
        self.data = data
        self._schema = schema
        self._sample_size = sample_size

    def schema(self) -> T.RowType:
        return self._schema

    def sample(self) -> list[Row]:
        from ..runtime.columns import user_columns

        cols = user_columns(self._schema)
        return [Row.from_value(v, cols) for v in self.data[: self._sample_size]]


class UDFOperator(LogicalOperator):
    """Base for operators carrying a UDF (reference: logical/UDFOperator.cc)."""

    def __init__(self, parent: LogicalOperator, func: Callable):
        super().__init__([parent])
        self.udf = get_udf_source(func)
        self._schema_cache: Optional[T.RowType] = None

    def branch_profile(self) -> dict:
        """Which if/else arms the operator's sample observed (reference:
        TraceVisitor branch annotations feeding RemoveDeadBranchesVisitor).
        Keyed by (kind, lineno, col) of the udf.tree node; memoized — the
        instrumented re-run costs one python pass over the sample."""
        memo = getattr(self, "_branch_prof_memo", None)
        if memo is None:
            from ..compiler.analyzer import op_analysis

            rep = op_analysis(self)
            if rep is not None and not rep.deterministic:
                # purity gate: a nondeterministic UDF's sample run is not
                # representative of execution — pruning arms it happened
                # not to take would bounce live rows to the interpreter
                self._branch_prof_memo = {}
                return {}
            ck = self.chain_key()
            hit = _cross_job_branchprofs.get(ck) if ck is not None else None
            if hit is not None:
                memo = hit
            else:
                from ..compiler.branchprof import profile_branches

                rows = self.parent.cached_sample()
                # too little evidence to call any arm dead
                memo = {} if len(rows) < 32 else profile_branches(
                    self.udf, rows, self._profile_call)
                if ck is not None:
                    _cross_job_branchprofs[ck] = memo
            self._branch_prof_memo = memo
        return memo

    def _profile_call(self, f, row) -> None:
        apply_udf_python(self.udf, row, func=f)

    def schema(self) -> T.RowType:
        if self._schema_cache is None:
            ck = self.chain_key()
            if ck is not None:
                hit = _cross_job_schemas.get(ck)
                if hit is not None:
                    self._schema_cache = hit
                    return hit
            # sample-free specialization (compiler/typeinfer.py): when the
            # abstract interpreter decides the output type EXACTLY from the
            # UDF's AST, skip the CPython sample trace entirely. The static
            # verdict is sound w.r.t. the trace (mismatch ⇒ widened to
            # undecidable ⇒ None here), so memo keys/values stay compatible
            # with traced runs.
            from ..compiler.typeinfer import static_op_schema

            static = static_op_schema(self)
            if static is not None:
                from ..compiler.analyzer import STATS

                STATS["sample_traces_skipped"] += 1
                # the webui's sample exception previews were a side effect
                # of the trace this skips; the recorder re-runs them on
                # demand (preview_sample_exceptions) only when enabled
                self._sample_trace_skipped = True
                self._schema_cache = static
            else:
                self._schema_cache = self._infer_schema()
            if ck is not None:
                _cross_job_schemas[ck] = self._schema_cache
        return self._schema_cache

    def _infer_schema(self) -> T.RowType:
        raise NotImplementedError


class MapOperator(UDFOperator):
    def _infer_schema(self) -> T.RowType:
        outs = []
        for r in self.parent.cached_sample():
            try:
                outs.append(apply_udf_python(self.udf, r))
            except Exception as e:
                record_sample_exc(self, e, r)
        if not outs:
            # UDF failed on EVERY sample row: job still runs, all rows become
            # exception rows (schema degrades to pyobject)
            return T.row_of(["_0"], [T.PYOBJECT])
        if all(isinstance(o, tuple) for o in outs) and outs and \
                len({len(o) for o in outs}) == 1:
            k = len(outs[0])
            types = [T.normal_case_type([o[i] for o in outs])[0]
                     for i in range(k)]
            return T.row_of([f"_{i}" for i in range(k)], types)
        # dict results keep column names (reference: map with dict output)
        if all(isinstance(o, dict) for o in outs) and outs:
            keys = list(outs[0].keys())
            if all(list(o.keys()) == keys for o in outs):
                types = [T.normal_case_type([o[k] for o in outs])[0]
                         for k in keys]
                return T.row_of(keys, types)
        nc, _, _ = T.normal_case_type(outs)
        return T.row_of(["_0"], [nc])

    def sample(self) -> list[Row]:
        out = []
        cols = self.columns()
        for r in self.parent.cached_sample():
            try:
                v = apply_udf_python(self.udf, r)
            except Exception as e:
                record_sample_exc(self, e, r)
                continue
            if isinstance(v, dict):
                out.append(Row(list(v.values()), list(v.keys())))
            else:
                out.append(Row.from_value(v, cols))
        return out


class FilterOperator(UDFOperator):
    def _infer_schema(self) -> T.RowType:
        return self.parent.schema()

    def columns(self):
        return self.parent.columns()

    def sample(self) -> list[Row]:
        out = []
        for r in self.parent.cached_sample():
            try:
                if apply_udf_python(self.udf, r):
                    out.append(r)
            except Exception as e:
                record_sample_exc(self, e, r)
        return out


class WithColumnOperator(UDFOperator):
    """Adds or replaces a named column (reference: logical/WithColumnOperator.cc)."""

    def __init__(self, parent: LogicalOperator, column: str, func: Callable):
        self.column = column
        super().__init__(parent, func)

    def _infer_schema(self) -> T.RowType:
        from ..runtime.columns import user_columns

        ps = self.parent.schema()
        if user_columns(ps) is None:
            raise TuplexException("withColumn requires named columns")
        outs = []
        for r in self.parent.cached_sample():
            try:
                outs.append(apply_udf_python(self.udf, r))
            except Exception as e:
                record_sample_exc(self, e, r)
        nc = T.PYOBJECT if not outs else T.normal_case_type(outs)[0]
        cols = list(ps.columns)
        types = list(ps.types)
        if self.column in cols:
            types[cols.index(self.column)] = nc
        else:
            cols.append(self.column)
            types.append(nc)
        return T.row_of(cols, types)

    def sample(self) -> list[Row]:
        schema = self.schema()
        out = []
        for r in self.parent.cached_sample():
            try:
                v = apply_udf_python(self.udf, r)
            except Exception as e:
                record_sample_exc(self, e, r)
                continue
            d = dict(zip(r.columns, r.values))
            d[self.column] = v
            out.append(Row([d[c] for c in schema.columns], schema.columns))
        return out


class MapColumnOperator(UDFOperator):
    """UDF over ONE column's value (reference: logical/MapColumnOperator.cc)."""

    def __init__(self, parent: LogicalOperator, column: str, func: Callable):
        self.column = column
        super().__init__(parent, func)

    def _infer_schema(self) -> T.RowType:
        ps = self.parent.schema()
        if self.column not in (ps.columns or ()):
            raise TuplexException(f"unknown column {self.column!r}")
        ci = ps.columns.index(self.column)
        outs = []
        for r in self.parent.cached_sample():
            try:
                outs.append(self.udf.func(r.values[ci]))
            except Exception as e:
                record_sample_exc(self, e, r)
        nc = T.PYOBJECT if not outs else T.normal_case_type(outs)[0]
        types = list(ps.types)
        types[ci] = nc
        return T.row_of(ps.columns, types)

    def sample(self) -> list[Row]:
        ps = self.parent.schema()
        ci = ps.columns.index(self.column)
        out = []
        for r in self.parent.cached_sample():
            try:
                v = self.udf.func(r.values[ci])
            except Exception as e:
                record_sample_exc(self, e, r)
                continue
            vals = list(r.values)
            vals[ci] = v
            out.append(Row(vals, r.columns))
        return out

    def _profile_call(self, f, row) -> None:
        ci = getattr(self, "_prof_ci", None)
        if ci is None:
            ci = self._prof_ci = \
                self.parent.schema().columns.index(self.column)
        f(row.values[ci])


class SelectColumnsOperator(LogicalOperator):
    def __init__(self, parent: LogicalOperator, columns: Sequence):
        super().__init__([parent])
        self.selected = list(columns)

    def _resolve_indices(self) -> list[int]:
        ps = self.parent.schema()
        idx = []
        for c in self.selected:
            if isinstance(c, int):
                idx.append(c if c >= 0 else len(ps.types) + c)
            else:
                if c not in ps.columns:
                    raise TuplexException(f"unknown column {c!r}")
                idx.append(ps.columns.index(c))
        return idx

    def schema(self) -> T.RowType:
        ps = self.parent.schema()
        idx = self._resolve_indices()
        return T.row_of([ps.columns[i] for i in idx],
                        [ps.types[i] for i in idx])

    def sample(self) -> list[Row]:
        idx = self._resolve_indices()
        s = self.schema()
        return [Row([r.values[i] for i in idx], s.columns)
                for r in self.parent.cached_sample()]


class RenameColumnOperator(LogicalOperator):
    def __init__(self, parent: LogicalOperator, old, new: str):
        super().__init__([parent])
        self.old = old
        self.new = new

    def schema(self) -> T.RowType:
        ps = self.parent.schema()
        if isinstance(self.old, int):
            i = self.old
        else:
            if self.old not in (ps.columns or ()):
                raise TuplexException(f"unknown column {self.old!r}")
            i = ps.columns.index(self.old)
        cols = list(ps.columns)
        cols[i] = self.new
        return T.row_of(cols, ps.types)

    def sample(self) -> list[Row]:
        s = self.schema()
        return [Row(r.values, s.columns) for r in self.parent.cached_sample()]


class ResolveOperator(LogicalOperator):
    """Attaches an exception resolver to the previous operator (reference:
    logical/ResolveOperator.cc; dataset.py:162)."""

    def __init__(self, parent: LogicalOperator, exc_class: type, func: Callable):
        super().__init__([parent])
        self.exc_class = exc_class
        self.udf = get_udf_source(func)

    def schema(self) -> T.RowType:
        return self.parent.schema()

    def sample(self) -> list[Row]:
        return self.parent.cached_sample()


class IgnoreOperator(LogicalOperator):
    """Silently drops rows raising exc_class at the previous operator
    (reference: logical/IgnoreOperator.cc; dataset.py:319)."""

    def __init__(self, parent: LogicalOperator, exc_class: type):
        super().__init__([parent])
        self.exc_class = exc_class

    def schema(self) -> T.RowType:
        return self.parent.schema()

    def sample(self) -> list[Row]:
        return self.parent.cached_sample()


class TakeOperator(LogicalOperator):
    def __init__(self, parent: LogicalOperator, limit: int):
        super().__init__([parent])
        self.limit = limit

    def schema(self) -> T.RowType:
        return self.parent.schema()

    def sample(self) -> list[Row]:
        s = self.parent.cached_sample()
        return s if self.limit < 0 else s[: self.limit]


class DecodeOperator(LogicalOperator):
    """Typed decode of raw string cells against the speculated normal-case
    schema — fused into the stage so parsing runs on device (reference:
    JITCSVSourceTaskBuilder / CSVParseRowGenerator fuse parse into the
    pipeline). The interpreter path implements the GENERAL case: cells that
    fail the normal-case parse stay raw strings, exactly like the reference's
    general-case row type preserves un-specialized columns."""

    def __init__(self, parent: LogicalOperator, declared: T.RowType,
                 null_values: Sequence[str],
                 general: "Optional[T.RowType]" = None):
        super().__init__([parent])
        self.declared = declared
        self.null_values = tuple(null_values)
        # general-case row type (supertype of the sample): the compiled
        # middle tier decodes under THESE types so normal-case violations
        # stay vectorized (reference: StageBuilder.cc:1145
        # generateResolveCodePath over the general-case schema)
        self.general = general if general is not None and \
            general.name != declared.name else None

    def schema(self) -> T.RowType:
        return self.declared

    def sample(self) -> list[Row]:
        out = []
        cols = self.declared.columns
        sel = None   # parent-row indices when this decode is projection-
        # pruned: the parent sample still carries the FULL source row, so
        # cells must be selected by name — a positional zip would silently
        # decode the wrong columns (and feed garbage to every downstream
        # sample, e.g. filter selectivities of 0 for compaction planning)
        for r in self.parent.cached_sample():
            if sel is None:
                if cols and r.columns and tuple(r.columns) != tuple(cols) \
                        and all(c in r.columns for c in cols):
                    sel = [r.columns.index(c) for c in cols]
                else:
                    sel = []
            vin = [r.values[i] for i in sel] if sel else r.values
            vals = [decode_cell_python(v, t, self.null_values)
                    for v, t in zip(vin, self.declared.types)]
            out.append(Row(vals, cols))
        return out


def preview_sample_exceptions(op) -> list:
    """Sample exception previews for the webui, run ON DEMAND for operators
    whose schema came from the static verdict (sample-free specialization
    skips the trace whose side effect they were). Reference-faithful: the
    SampleProcessor runs only when the history server is attached, so the
    recorder — not schema inference — pays for previews. No-op for
    operators the trace (or a memo hit) already populated."""
    if not getattr(op, "_sample_trace_skipped", False) \
            or getattr(op, "sample_exceptions", None) is not None:
        return list(getattr(op, "sample_exceptions", []) or [])
    try:
        rows = op.parent.cached_sample()
        if isinstance(op, MapColumnOperator):
            ci = op.parent.schema().columns.index(op.column)
            for r in rows:
                try:
                    op.udf.func(r.values[ci])
                except Exception as e:
                    record_sample_exc(op, e, r)
        else:
            for r in rows:
                try:
                    apply_udf_python(op.udf, r)
                except Exception as e:
                    record_sample_exc(op, e, r)
    except Exception:   # pragma: no cover - previews are advisory
        pass
    if getattr(op, "sample_exceptions", None) is None:
        # mark the pass done even when nothing raised — record_sample_exc
        # only creates the list on an exception, and without the marker
        # every later job would re-run the whole sample per clean UDF
        op.sample_exceptions = []
    return list(op.sample_exceptions)


def decode_cell_python(cell, t: T.Type, null_values) -> Any:
    """General-case decode: normal-case parse if possible, else the raw
    string survives (so downstream interpreter UDFs can still handle it)."""
    if cell is None:
        return None
    if not isinstance(cell, str):
        return cell
    if cell in null_values:
        return None
    base = t.without_option() if t.is_optional() else t
    try:
        if base is T.I64:
            return int(cell)
        if base is T.F64:
            return float(cell)
        if base is T.BOOL:
            low = cell.strip().lower()
            if low == "true":
                return True
            if low == "false":
                return False
            return cell
    except ValueError:
        return cell
    return cell
