"""Join operator (inner / left).

Reference semantics (reference: core/src/logical/JoinOperator.cc:250,
python/tuplex/dataset.py:384 join / :442 leftJoin): the key column appears
once; output columns are the non-key left columns + key + non-key right
columns, with optional prefixes/suffixes to disambiguate. The build side is
fully materialized and broadcast (there is NO shuffle in the reference —
PhysicalPlan.cc:145-178); we keep that model: build side partitions are
merged, probe runs partition-parallel.
"""

from __future__ import annotations

from typing import Optional, Sequence

from ..core import typesys as T
from ..core.errors import TuplexException
from ..core.row import Row
from . import logical as L


class JoinOperator(L.LogicalOperator):
    def __init__(self, left: L.LogicalOperator, right: L.LogicalOperator,
                 left_column: str, right_column: str, how: str = "inner",
                 prefixes: Optional[Sequence[str]] = None,
                 suffixes: Optional[Sequence[str]] = None):
        super().__init__([left, right])
        self.left_column = left_column
        self.right_column = right_column
        self.how = how
        self.prefixes = tuple(prefixes) if prefixes else ("", "")
        self.suffixes = tuple(suffixes) if suffixes else ("", "")

    @property
    def left(self) -> L.LogicalOperator:
        return self.parents[0]

    @property
    def right(self) -> L.LogicalOperator:
        return self.parents[1]

    def is_breaker(self) -> bool:
        return True

    # -- schema ---------------------------------------------------------
    def _sides(self):
        ls = self.left.schema()
        rs = self.right.schema()
        if self.left_column not in (ls.columns or ()):
            raise TuplexException(f"unknown left key {self.left_column!r}")
        if self.right_column not in (rs.columns or ()):
            raise TuplexException(f"unknown right key {self.right_column!r}")
        return ls, rs

    def _decorate(self, name: str, side: int) -> str:
        p = self.prefixes[side] or ""
        s = self.suffixes[side] or ""
        return f"{p}{name}{s}"

    def schema(self) -> T.RowType:
        ls, rs = self._sides()
        lk = ls.columns.index(self.left_column)
        rk = rs.columns.index(self.right_column)
        key_t = T.super_type(ls.types[lk], rs.types[rk])
        cols: list[str] = []
        types: list[T.Type] = []
        for i, (c, t) in enumerate(zip(ls.columns, ls.types)):
            if i == lk:
                continue
            cols.append(self._decorate(c, 0))
            types.append(t)
        cols.append(self.left_column)
        types.append(key_t)
        for i, (c, t) in enumerate(zip(rs.columns, rs.types)):
            if i == rk:
                continue
            cols.append(self._decorate(c, 1))
            # left join: unmatched rows get None for right columns
            types.append(T.option(t) if self.how == "left" else t)
        return T.row_of(cols, types)

    def columns(self):
        return self.schema().columns

    def sample(self) -> list[Row]:
        ls, rs = self._sides()
        lk = ls.columns.index(self.left_column)
        rk = rs.columns.index(self.right_column)
        build: dict = {}
        for r in self.right.cached_sample():
            build.setdefault(r.values[rk], []).append(r)
        out = []
        cols = self.schema().columns
        for r in self.left.cached_sample():
            key = r.values[lk]
            matches = build.get(key, [])
            lvals = [v for i, v in enumerate(r.values) if i != lk]
            if matches:
                for m in matches:
                    rvals = [v for i, v in enumerate(m.values) if i != rk]
                    out.append(Row(lvals + [key] + rvals, cols))
            elif self.how == "left":
                rvals = [None] * (len(rs.columns) - 1)
                out.append(Row(lvals + [key] + rvals, cols))
        return out
