"""Physical planning: stage splitting + fused stage functions.

Re-designs the reference's physical layer (reference:
core/src/physical/PhysicalPlan.cc:60-238 — split DAG into stages at pipeline
breakers; StageBuilder.cc — fuse the stage's operators into one compiled
function). Here a TransformStage compiles to ONE jax function over a staged
column batch: every fused operator contributes ops to the same trace, so XLA
sees the whole pipeline and fuses it into a handful of kernels (the TPU analog
of the reference's single LLVM row-loop).
"""

from __future__ import annotations

import hashlib
from typing import Callable, Optional

import dataclasses

from ..compiler.emitter import EmitCtx, Emitter, Frame
from ..compiler.stagefn import input_row_cv, result_arrays
from ..compiler.values import CV, tuple_cv
from ..core import typesys as T
from ..core.errors import NotCompilable, exception_class_for_code
from ..runtime.jaxcfg import jnp
from . import logical as L


@dataclasses.dataclass(frozen=True)
class ResolvePlan:
    """Plan-time resolve-tier decision for one TransformStage, derived
    from the analyzer's exception-site inventory and the static type
    verdicts (see TransformStage.resolve_plan). The backend consults it
    instead of inspecting error codes after D2H:

    * ``use_general`` — whether the compiled general-case tier is worth
      dispatching at all (a widened decode exists AND a decode-speculation
      code is in the inventory). False skips the build attempt entirely —
      previously every stage paid one doomed NotCompilable trace to learn
      this.
    * ``interpreter_possible`` — whether any DEVICE-coded row can reach
      the per-row interpreter (input-boxed fallback rows are a runtime
      property and always interpret).
    * ``new_buffers()`` — per-code row buckets shaped by the inventory,
      instantiated per partition at D2H unpack time.
    """

    codes: tuple                 # sorted possible codes (ints)
    exact_codes: frozenset       # codes that are exact Python classes
    use_general: bool
    interpreter_possible: bool
    tier: str                    # none | general | interpreter | both

    def new_buffers(self) -> "ResolveBuffers":
        return ResolveBuffers(self.codes)


class ResolveBuffers:
    """Per-code resolve buckets: row index -> (code, operator id) grouped
    by exception-class code, preallocated from the plan-time inventory.
    Codes the inventory missed land in ``other`` — attribution degrades
    to the catch-all, correctness (every row is still routed) does not."""

    __slots__ = ("by_code", "other")

    def __init__(self, codes):
        self.by_code: dict[int, list] = {int(c): [] for c in codes}
        self.other: list = []

    def add(self, idx: int, code: int, op_id: int) -> None:
        buf = self.by_code.get(code)
        (buf if buf is not None else self.other).append((idx, code, op_id))

    def add_many(self, idx, packed) -> None:
        """Vectorized bucket fill from the device error lattice: `idx` are
        row positions, `packed` the raw int32 lattice values (class code in
        the low byte, operator id above — core/errors pack_device_code)."""
        import numpy as np

        idx = np.asarray(idx)
        packed = np.asarray(packed)
        codes = packed & 0xFF
        opids = packed >> 8
        known = np.zeros(len(idx), dtype=bool)
        for c, buf in self.by_code.items():
            m = codes == c
            if m.any():
                known |= m
                buf.extend(zip(idx[m].tolist(), codes[m].tolist(),
                               opids[m].tolist()))
        m = ~known
        if m.any():
            self.other.extend(zip(idx[m].tolist(), codes[m].tolist(),
                                  opids[m].tolist()))

    def internal_rows(self) -> list:
        """(idx, code, op_id) for rows whose code is NOT an exact Python
        exception class — the compiled general tier's candidate set."""
        out = [t
               for c, buf in self.by_code.items()
               if exception_class_for_code(c) is None
               for t in buf]
        out.extend(t for t in self.other
                   if exception_class_for_code(t[1]) is None)
        out.sort()
        return out

    def exact_rows(self) -> list:
        """(idx, code, op_id) for rows whose code IS an exact Python
        exception class (the no-resolver fast exit's candidate set)."""
        out = [t
               for c, buf in self.by_code.items()
               if exception_class_for_code(c) is not None
               for t in buf]
        out.extend(t for t in self.other
                   if exception_class_for_code(t[1]) is not None)
        out.sort()
        return out


class TransformStage:
    """A fused chain of row operators over one input source.

    `ops` excludes the source; Resolve/Ignore operators ride along for the
    host resolve path but emit nothing on device (reference: slow-path-only
    resolvers, StageBuilder.cc generateResolveCodePath).
    """

    def __init__(self, source: Optional[L.LogicalOperator],
                 ops: list[L.LogicalOperator], limit: int = -1,
                 input_schema: Optional[T.RowType] = None,
                 input_op: Optional[L.LogicalOperator] = None):
        self.source = source       # None => consumes previous stage's output
        self.ops = ops
        self.limit = limit
        src_like = source if source is not None else input_op
        self.input_schema = input_schema if input_schema is not None \
            else src_like.schema()
        last = ops[-1] if ops else src_like
        self.output_schema = last.schema()
        self.output_columns = last.columns()

    force_interpret = False   # set on segments around non-compilable ops
    route_reason = ""         # why force_interpret was set (analyzer verdict)
    cpu_compile = False       # compile-budget degrade (plan/splittuner):
                              # build the stage fn on the host CPU backend
    split_decision = None     # splittuner.SplitDecision when the tuner ran
    predicted_compile_s = None  # tuner-predicted compile seconds for THIS
                                # stage/segment (history + compilestats)
    fold_op = None            # AggregateOperator whose pattern fold is fused
                              # into this stage's device fn (plan_stages)
    speculate_branches = True  # prune if/else arms the sample never took
                              # (tuplex.optimizer.speculateBranches)
    extra_expected_codes = ()  # re-specialization overlay (serve/respec):
                              # exception codes OBSERVED in live traffic
                              # folded into this stage's plan inventory —
                              # the re-speculated plan EXPECTS them, so
                              # they widen the resolve-buffer preallocation
                              # and the excprof baseline instead of reading
                              # as out-of-inventory drift forever
    respec_salt = ""          # per-tenant plan-generation salt (respec
                              # overlay): distinct stage.key() per
                              # generation so baselines/executable-cache
                              # entries never alias across generations or
                              # across tenants at different generations

    @property
    def has_resolvers(self) -> bool:
        """Whether any resolver/ignore rides this stage. Without one, a row
        whose device error code is an exact Python exception class needs no
        interpreter re-run at all — the reference likewise serializes
        (operator id, code) exception partitions straight from compiled code
        when no resolver exists (ResolveTask only runs for resolution)."""
        return any(isinstance(op, (L.ResolveOperator, L.IgnoreOperator))
                   for op in self.ops)

    def udf_reports(self) -> list:
        """Static-analysis reports for every UDF fused in this stage:
        [(op, udf attr, UDFReport)] (compiler/analyzer.py). Memoized — the
        per-UDF analysis itself is memoized per code object, so this is the
        stage-level view physical planning and explain(lint=True) share."""
        memo = getattr(self, "_udf_reports_memo", None)
        if memo is None:
            from ..compiler.analyzer import op_reports

            memo = self._udf_reports_memo = [
                (op, attr, rep)
                for op in self.ops
                for attr, rep in op_reports(op)]
        return memo

    def possible_exception_codes(self) -> list:
        """Every ExceptionCode rows of this stage can carry, known at PLAN
        time from the analyzer's exception-site inventory (no sampling):
        per-UDF sites, decode codes for fused decodes, NORMALCASEVIOLATION
        when branch speculation may prune a cold arm (rows entering one
        raise it), PYTHON_FALLBACK when any part of the stage routes to
        the interpreter."""
        from ..core.errors import ExceptionCode as EC

        codes: set = set()
        for c in self.extra_expected_codes or ():
            try:        # live-observed codes adopted by re-specialization
                codes.add(EC(int(c)))
            except ValueError:
                continue   # unknown device code: nothing to preallocate
        if self.force_interpret:
            codes.add(EC.PYTHON_FALLBACK)
        for op in self.ops:
            if isinstance(op, L.DecodeOperator):
                codes |= {EC.NULLERROR, EC.BADPARSE_STRING_INPUT,
                          EC.NORMALCASEVIOLATION}
        if self.speculation_pruned():
            codes.add(EC.NORMALCASEVIOLATION)
        for op, attr, rep in self.udf_reports():
            if isinstance(op, (L.ResolveOperator, L.IgnoreOperator)):
                continue   # slow-path-only UDFs never emit device codes
            codes |= rep.exception_codes()
            if rep.must_fallback:
                codes.add(EC.PYTHON_FALLBACK)
            # Option-typed inputs raise TypeError on the None rows
            # wherever a compiled expression consumes them (emitter
            # _unwrap_option: Python `None + 1` semantics) — a property
            # of the schema MEETING the UDF, invisible to the per-UDF
            # AST pass above. Narrowed by the column-reads analysis
            # when it has a verdict; over-approximated to any Option
            # column otherwise (soundness: the exception-plane drift
            # detector treats out-of-inventory codes as stale
            # speculation, so missing a reachable code is the worse
            # error).
            if EC.TYPEERROR not in codes:
                try:
                    sch = op.parent.schema()
                    names = list(getattr(sch, "columns", None) or [])
                    types = list(getattr(sch, "types", None) or [])
                    any_opt = any(t.is_optional() for t in types)
                    if any_opt:
                        from .optimizer import udf_read_columns

                        reads = udf_read_columns(getattr(op, attr, None))
                        if reads is None or not names:
                            codes.add(EC.TYPEERROR)
                        elif {n for n, t in zip(names, types)
                              if t.is_optional()} & set(reads):
                            codes.add(EC.TYPEERROR)
                except Exception:   # unknown schema: stay sound
                    codes.add(EC.TYPEERROR)
        return sorted(codes)

    def speculation_pruned(self) -> bool:
        """Whether branch speculation may have pruned a cold arm in this
        stage (some fused UDF's sample profile never took an arm). Over-
        approximates the emitter's arm-weight gate — sound for the resolve
        plan: the general tier stays available wherever pruned-arm rows
        could need the non-speculating vectorized re-run."""
        if not self.speculate_branches:
            return False
        for op in self.ops:
            if isinstance(op, (L.ResolveOperator, L.IgnoreOperator)):
                continue
            bp = getattr(op, "branch_profile", None)
            if bp is None:
                continue
            try:
                prof = bp()
            except Exception:
                continue
            if any(False in v for v in prof.values()):
                return True
        return False

    def resolve_plan(self) -> "ResolvePlan":
        """Plan-time resolve-tier decision (ROADMAP "per-code resolve
        preallocation"): the analyzer's exception inventory + the static
        type verdicts bound which error codes this stage can emit, so the
        backend picks its resolve tiers and preallocates per-code row
        buffers BEFORE any D2H — instead of discovering after the fetch
        that (say) the stage has no general-case decode to re-run, or
        scanning every error row twice to classify it. Memoized: the plan
        is a pure function of the stage."""
        memo = getattr(self, "_resolve_plan_memo", None)
        if memo is None:
            from ..core.errors import ExceptionCode as EC

            codes = self.possible_exception_codes()
            # the compiled general tier retires exactly two speculation
            # failure kinds, both decidable at plan time: normal-case
            # DECODE violations (needs a widened decode to re-run under)
            # and pruned-BRANCH violations (needs the non-speculating
            # re-compile, no decode required)
            has_general_decode = any(
                isinstance(op, L.DecodeOperator) and op.general is not None
                for op in self.ops)
            retirable = {EC.NORMALCASEVIOLATION, EC.BADPARSE_STRING_INPUT,
                         EC.NULLERROR}
            spec_pruned = self.speculation_pruned()
            use_general = (not self.force_interpret
                           and (spec_pruned
                                or (has_general_decode
                                    and any(c in retirable
                                            for c in codes))))
            exact_codes = frozenset(
                int(c) for c in codes
                if exception_class_for_code(int(c)) is not None)
            internal = [c for c in codes if int(c) not in exact_codes]
            # the per-row interpreter is reachable when the stage is routed
            # there outright, a resolver/ignore must run, or an internal
            # code can survive the general tier (input-boxed fallback rows
            # are a runtime property and always interpret — `statically`
            # here bounds the DEVICE-code paths only)
            interpreter_possible = bool(
                self.force_interpret or self.has_resolvers or internal)
            # fully statically typed + empty inventory: the inference
            # verdict says no device code fires at all ("none" tier)
            if not codes and not self.force_interpret:
                tier = "none"
            elif use_general and interpreter_possible:
                tier = "general+interpreter"
            elif use_general:
                tier = "general"
            elif interpreter_possible:
                tier = "interpreter"
            else:
                # only exact Python-class codes and no resolver: error rows
                # take the no-resolver exact exit, nothing ever re-runs
                tier = "exact-exit"
            memo = self._resolve_plan_memo = ResolvePlan(
                codes=tuple(int(c) for c in codes),
                exact_codes=exact_codes,
                use_general=use_general,
                interpreter_possible=interpreter_possible,
                tier=tier)
        return memo

    def resolver_suggestions(self) -> list:
        """Positive lint twin of the dead-resolver warning (ROADMAP
        lint-loop remainder): when the exception inventory proves this
        stage can ONLY raise exact Python exception classes and the
        author attached no resolver/ignore, suggest one — those rows take
        the no-resolver exact exit today and surface as unresolved
        exceptions the author may not know are recoverable. Suggested
        only when every inventoried code maps to a Python class: a stage
        that can also raise internal codes (NORMALCASEVIOLATION,
        PYTHON_FALLBACK...) gets no "can only raise" claim."""
        memo = getattr(self, "_resolver_suggestions_memo", None)
        if memo is None:
            memo = []
            if not self.has_resolvers and not self.force_interpret:
                codes = self.possible_exception_codes()
                if codes and all(
                        exception_class_for_code(int(c)) is not None
                        for c in codes):
                    names = "/".join(c.name for c in codes)
                    memo.append(
                        f"this stage can only raise {names} — consider a "
                        f".resolve() or .ignore() so those rows recover "
                        f"instead of surfacing as exceptions")
            self._resolver_suggestions_memo = memo
        return memo

    def dead_resolver_findings(self) -> list:
        """Plan-time dead-resolver lint (ROADMAP "lint-driven authoring
        loop"): [(resolver op, guarded op, reason)] for every resolver or
        ignore whose target exception code the guarded operator's
        exception inventory proves it can never raise. Advisory — dead
        resolvers cost a per-row class check on the slow path and usually
        indicate the author guards the wrong operator."""
        memo = getattr(self, "_dead_resolvers_memo", None)
        if memo is None:
            from ..compiler.analyzer import dead_resolver_reason, op_analysis

            memo = []
            for i, op in enumerate(self.ops):
                if not isinstance(op, (L.ResolveOperator, L.IgnoreOperator)):
                    continue
                # the guarded operator: nearest preceding non-resolver
                guarded = None
                for prev in reversed(self.ops[:i]):
                    if not isinstance(prev, (L.ResolveOperator,
                                             L.IgnoreOperator)):
                        guarded = prev
                        break
                if guarded is None or not isinstance(guarded, L.UDFOperator):
                    continue
                rep = op_analysis(guarded)
                if rep is None:
                    continue
                # the "no unknown callee" proof must be the call-whitelist
                # walk, NOT the type verdict's exactness: the abstract
                # interpreter swallows Undecidable in type-total contexts
                # (int()/len() args, comparisons, bare expressions), so an
                # exact verdict can coexist with an unknown call that DOES
                # raise the resolver's target
                import types as _types

                from ..compiler.analyzer import _calls_all_known

                udf = guarded.udf
                module_names = {
                    k: m.__name__.split(".")[0]
                    for k, m in getattr(udf, "globals", {}).items()
                    if isinstance(m, _types.ModuleType)}
                tree = getattr(udf, "tree", None)
                reason = dead_resolver_reason(
                    rep, op.exc_class,
                    fully_typed=tree is not None
                    and _calls_all_known(tree, module_names))
                if reason:
                    memo.append((op, guarded, reason))
            self._dead_resolvers_memo = memo
        return memo

    def python_pipeline(self, input_names: Optional[tuple] = None):
        """Cached per-stage compiled Python fallback pipeline (reference:
        PythonPipelineBuilder.cc generates one function per stage; ROUND 1
        interpreted the op list per row instead). Keyed by the RUNTIME input
        column names — the source tier binds column positions at build."""
        cache = getattr(self, "_py_pipelines", None)
        if cache is None:
            cache = self._py_pipelines = {}
        key = tuple(input_names) if input_names else None
        pipe = cache.get(key)
        if pipe is None:
            from ..compiler.pypipeline import build_python_pipeline

            pipe = cache[key] = build_python_pipeline(self.ops, key)
        return pipe

    def key(self) -> str:
        """Cache key for the jit'd executable: operator chain + UDF sources +
        captured globals + input schema (specialization contract of the
        emitter)."""
        h = hashlib.sha256()
        h.update(self.input_schema.name.encode())
        if self.respec_salt:
            # per-generation key: a re-specialized stage must not share
            # baselines / jit-cache entries with its incumbent (the XLA
            # executable still dedups content-addressed in compilequeue,
            # so identical jaxprs cost one compile regardless)
            h.update(b"respec:")
            h.update(str(self.respec_salt).encode())
        if self.extra_expected_codes:
            h.update(repr(tuple(sorted(
                int(c) for c in self.extra_expected_codes))).encode())
        for op in self.ops:
            h.update(_op_identity(op).encode())
        if self.fold_op is not None:
            h.update(b"fold")
            h.update(_op_identity(self.fold_op).encode())
        if self.speculate_branches:
            # the emitted kernel is specialized on the (data-dependent)
            # sample branch profile — a different dataset with the same UDF
            # chain must not reuse a kernel pruned for this one's sample
            h.update(b"specbr")
            for op in self.ops:
                h.update(_branch_profile_sig(op).encode())
        return h.hexdigest()[:16]

    # ------------------------------------------------------------------
    def build_device_fn(self, input_schema: Optional[T.RowType] = None,
                        general: bool = False,
                        compaction: bool = False,
                        fused_fold: bool = True) -> Callable:
        """The fused fast-path function: staged arrays -> output arrays +
        '#err' + '#keep'. Raises NotCompilable if any fused UDF can't compile
        (the backend then interprets every row).

        `input_schema` overrides the planned schema with the RUNTIME schema
        of the actual partitions (post-breaker/segment stages and projection-
        pruned sources differ from sample speculation).

        `general=True` builds the COMPILED middle tier: the fused decode
        types columns under the general-case (supertype) schema so normal-
        case violations stay vectorized before any per-row python
        (reference: StageBuilder.cc:1145 generateResolveCodePath;
        ResolveTask.h:31-98 tries resolve_f before the interpreter).

        `compaction=True` inserts selection-vector compaction after
        selective filters: surviving rows are gathered to the front of a
        smaller (sample-estimated, bucketed) batch so every downstream op
        touches fewer rows — the vectorized-engine analog of the
        reference's per-row short-circuit on filtered rows (its LLVM row
        loop simply skips them; a SIMD batch can't, so we shrink the
        batch). Outputs gain '#rowidx' ([B'] original positions, ascending;
        sentinel=padded input size for dead slots) and '#overflow' (bool:
        survivors exceeded the estimated bucket — host must discard and
        re-run without compaction)."""
        schema = input_schema if input_schema is not None else self.input_schema
        ops = [op for op in self.ops
               if not isinstance(op, (L.ResolveOperator, L.IgnoreOperator,
                                      L.TakeOperator))]
        out_schema = self.output_schema

        if self.force_interpret:
            raise NotCompilable(self.route_reason
                                or "stage segment forced to interpreter")
        from ..compiler.stagefn import require_traceable

        # plan-time traceability verdict (compiler/analyzer.py): raise
        # BEFORE any emitter work for UDFs statically known untraceable.
        # The general tier never speculates, so cold-arm findings that
        # branch pruning might hide on the fast path count against it.
        require_traceable(ops,
                          speculate=self.speculate_branches and not general)
        if general and not any(
                isinstance(op, L.DecodeOperator) and op.general is not None
                for op in ops) and not self.speculation_pruned():
            # nothing for a general re-run to widen: no supertype decode
            # AND no speculation-pruned arm to re-compile without pruning
            raise NotCompilable("stage has no general-case decode")

        plan = _compaction_plan(ops) if (compaction and not general) else {}
        fold_spec = None
        if fused_fold and self.fold_op is not None and not general:
            from . import aggregates as A

            fold_spec = A.recognize_fold(self.fold_op.aggregate_udf)

        def fn(arrays: dict):
            b = arrays["#rowvalid"].shape[0]
            ctx = EmitCtx(b, arrays["#rowvalid"], seed=arrays.get("#seed"))
            keep = arrays["#rowvalid"]
            row = input_row_cv(arrays, schema)
            from ..runtime.columns import user_columns

            names = user_columns(schema)
            rowidx = None          # [B'] original positions after compaction
            full_err = None        # [b] error codes incl. compacted-away rows
            overflow = None
            bcur = b
            for op in ops:
                ctx.cur_op = op.id
                row, keep, names = _emit_op(ctx, op, row, keep, names,
                                            general=general,
                                            speculate=self.speculate_branches)
                row, keep = _fusion_barrier(ctx, row, keep)
                frac = plan.get(op.id)   # already margin-padded
                if frac is not None and bcur >= 8192:
                    from ..runtime.columns import bucket_size

                    target = int(b * frac) + 64
                    b2 = bucket_size(min(bcur, target), "q8")
                    if b2 < bcur:
                        (row, keep, rowidx, full_err,
                         overflow) = _compact_rows(ctx, row, keep, rowidx,
                                                   full_err, overflow,
                                                   b2, b)
                        bcur = b2
            outs, out_t = result_arrays(row, bcur)
            outs = dict(outs)
            fin = keep & (ctx.err == 0)
            if fold_spec is not None:
                _emit_fused_fold(outs, fold_spec, row, names, fin, bcur)
            if rowidx is None:
                outs["#err"] = ctx.err
                outs["#keep"] = fin
            else:
                outs["#err"] = full_err.at[rowidx].set(ctx.err, mode="drop")
                outs["#keep"] = jnp.zeros(b, dtype=bool).at[rowidx].set(
                    fin, mode="drop")
                outs["#rowidx"] = rowidx
                outs["#overflow"] = overflow
                if "#foldok" in outs:
                    outs["#foldok"] = jnp.zeros(b, dtype=bool).at[
                        rowidx].set(outs["#foldok"], mode="drop")
            return outs

        return fn


def _fusion_barrier(ctx: EmitCtx, row: CV, keep):
    """Cap XLA fusion scope at operator boundaries.

    Without this, XLA-CPU's producer fusion pulls an entire multi-operator
    string pipeline into ONE kLoop fusion whose per-element evaluation
    recomputes [B, W]-shaped intermediates per output element — measured 24s
    instead of ~1s for the Zillow extractPrice stage. The barrier is a
    runtime no-op; it only tells the fusion pass to materialize each
    operator's outputs (the reference analog: each LLVM pipeline stage writes
    its row before the next reads it).

    TPU's fusion pass doesn't exhibit the kLoop recompute pathology, so the
    barriers default to CPU-only (see jaxcfg.fusion_barriers_enabled)."""
    from ..compiler.values import cv_arrays, cv_rebuild
    from ..runtime.jaxcfg import fusion_barriers_enabled, lax

    if not fusion_barriers_enabled():
        return row, keep

    leaves: list = []
    cv_arrays(row, leaves)
    n_row = len(leaves)
    leaves.extend((keep, ctx.err, ctx.active))
    out = lax.optimization_barrier(tuple(leaves))
    it = iter(out[:n_row])
    row2 = cv_rebuild(row, it)
    keep2, ctx.err, ctx.active = out[n_row], out[n_row + 1], out[n_row + 2]
    return row2, keep2


_COMPACT_MARGIN = 1.15   # multiplicative headroom over the sample estimate
_COMPACT_Z = 5.0         # + this many binomial standard errors (see pad())
_COMPACT_GATHER = 0.5    # gather cost in per-op-pass units


def _emit_fused_fold(outs: dict, spec, row: CV, names, fin, bcur) -> None:
    """Evaluate the recognized aggregate fold exprs against the stage's
    OUTPUT row under a fresh error context and emit identity-seeded scalar
    partials ('#fold{i}') plus the per-row ok mask ('#foldok'). Rows whose
    fold expr errs fold on the host afterwards; a NotCompilable expr simply
    omits the outputs (the aggregate stage then runs its own pass)."""
    import dataclasses

    from ..parallel.collectives import reduce_identity

    try:
        fctx = EmitCtx(bcur, fin)
        em = Emitter(fctx, spec.globals)
        rrow = row
        if rrow.elts is not None and names:
            rrow = dataclasses.replace(rrow, names=tuple(names))
        frame = Frame(em, {spec.row_param: rrow})
        datas = []
        for expr in spec.exprs:
            cv = frame.eval(expr)
            cv = frame._require_numeric(cv, "aggregate expr")
            datas.append(cv.data)
        ok = fin & (fctx.err == 0)
        for fi, (d, red) in enumerate(zip(datas, spec.reducers)):
            ident = reduce_identity(red, d.dtype.kind == "f")
            m = jnp.where(ok, d, ident)
            outs[f"#fold{fi}"] = (m.sum() if red == "sum"
                                  else m.min() if red == "min" else m.max())
        outs["#foldok"] = ok
    except NotCompilable:
        for k in list(outs):
            if k.startswith("#fold"):
                del outs[k]


def _compaction_plan(ops) -> dict[int, float]:
    """Choose WHERE to insert selection-vector compactions.

    Returns op.id -> estimated live fraction (relative to the stage input
    sample) for the chosen filters. Selection is a small exhaustive search
    over filter subsets with a unit-cost-per-op model: each operator costs
    its current batch fraction, each compaction costs a gather
    (_COMPACT_GATHER) at the pre-compaction fraction. A greedy first-filter
    compaction can block a much better later one (measured on zillow: the
    72.8% bedrooms filter starved the 53.3% type filter), hence the global
    search. Estimates come from the same operator sampling that drives type
    speculation (reference: TraceVisitor branch counts feed its cost
    decisions the same way)."""
    try:
        base_op = next((op.parents[0] for op in ops if op.parents), None)
        if base_op is None:
            return {}
        base = len(base_op.cached_sample())
        if base < 32:
            return {}
        import math

        def pad(f: float) -> float:
            # upper confidence bound on the live fraction: the fixed
            # multiplicative margin alone is <1 sigma of binomial sampling
            # noise at small fractions (q6's 1.8% live rate), so add
            # _COMPACT_Z standard errors. The variance uses a Wilson-style
            # smoothed fraction so an observed 0 still gets real headroom
            # (raw sqrt(f(1-f)) vanishes at f=0, exactly where a small
            # sample most understates the true rate).
            fs = (f * base + _COMPACT_Z ** 2 / 2) / (base + _COMPACT_Z ** 2)
            return min(1.0, f * _COMPACT_MARGIN
                       + _COMPACT_Z * math.sqrt(fs * (1.0 - fs) / base))

        fracs = {}   # position in ops -> cumulative live fraction after it
        for k, op in enumerate(ops):
            if isinstance(op, L.FilterOperator):
                fracs[k] = pad(len(op.cached_sample()) / base)
        # candidates must leave >=2 real compute ops downstream
        cand = [k for k in fracs
                if sum(1 for o in ops[k + 1:]
                       if not isinstance(o, L.SelectColumnsOperator)) >= 2]
        cand = cand[:10]
        if not cand:
            return {}

        def cost(subset) -> float:
            factor, total = 1.0, 0.0
            for k, op in enumerate(ops):
                total += factor
                if k in subset:
                    # bucketed batch after compacting here (~6% pad waste);
                    # fracs[] already carry the confidence-bound margin
                    new = min(factor, fracs[k] * 1.06 + 0.01)
                    if new < factor:
                        total += _COMPACT_GATHER * factor
                        factor = new
            return total

        best, best_cost = (), cost(())
        import itertools as _it

        for r in (1, 2, 3):
            for subset in _it.combinations(cand, r):
                c = cost(set(subset))
                if c < best_cost - 1e-9:
                    best, best_cost = subset, c
        return {ops[k].id: fracs[k] for k in best}
    except Exception:
        return {}


def _compact_rows(ctx: EmitCtx, row: CV, keep, rowidx, full_err, overflow,
                  b2: int, full_b: int):
    """Gather live rows (keep & no error) to the front of a [b2] batch.

    Maintains: `rowidx` [b2] original input positions (ascending; sentinel
    full_b in dead slots), `full_err` [full_b] error codes for rows that
    left the batch (their dual-mode routing must survive compaction), and
    `overflow` (live count exceeded b2 — results are unusable and the host
    re-runs the partition without compaction)."""
    from ..compiler.values import cv_arrays, cv_rebuild

    bcur = keep.shape[0]
    cur_orig = rowidx if rowidx is not None \
        else jnp.arange(bcur, dtype=jnp.int32)
    if full_err is None:
        full_err = ctx.err
    else:
        full_err = full_err.at[cur_orig].set(ctx.err, mode="drop")
    live = keep & (ctx.err == 0)
    idx = jnp.nonzero(live, size=b2, fill_value=bcur)[0].astype(jnp.int32)
    count = jnp.sum(live.astype(jnp.int32))
    ovf = count > b2
    overflow = ovf if overflow is None else (overflow | ovf)
    valid = jnp.arange(b2, dtype=jnp.int32) < count
    safe = jnp.minimum(idx, bcur - 1)
    new_rowidx = jnp.where(valid, jnp.take(cur_orig, safe, axis=0),
                           jnp.int32(full_b))
    leaves: list = []
    cv_arrays(row, leaves)
    gathered = [jnp.take(a, safe, axis=0) for a in leaves]
    row2 = cv_rebuild(row, iter(gathered))
    ctx.b = b2
    ctx.err = jnp.zeros(b2, dtype=jnp.int32)
    ctx.active = valid
    return row2, valid, new_rowidx, full_err, overflow


def runtime_output_columns(input_schema: T.RowType,
                           ops: list[L.LogicalOperator]):
    """Replay the name flow of _emit_op over the RUNTIME input schema (which
    may be projection-pruned), without tracing. Mirrors _emit_op's names
    handling exactly."""
    from ..runtime.columns import user_columns

    names = user_columns(input_schema)
    for op in ops:
        if isinstance(op, (L.ResolveOperator, L.IgnoreOperator,
                           L.TakeOperator)):
            continue
        if isinstance(op, L.MapOperator):
            out_cols = op.columns()
            names = tuple(out_cols) if out_cols else None
        elif isinstance(op, L.WithColumnOperator):
            if names is None:
                return None
            if op.column not in names:
                names = tuple(names) + (op.column,)
        elif isinstance(op, L.SelectColumnsOperator):
            names = tuple(op.schema().columns)
        elif isinstance(op, L.RenameColumnOperator):
            if names is not None and isinstance(op.old, str) and \
                    op.old in names:
                names = tuple(op.new if c == op.old else c for c in names)
            else:
                names = op.columns()
        elif isinstance(op, L.DecodeOperator):
            names = user_columns(op.declared)
        # MapColumn keeps names
    return names


def _emit_op(ctx: EmitCtx, op: L.LogicalOperator, row: CV, keep,
             names: Optional[tuple], general: bool = False,
             speculate: bool = False):
    prof = None
    if speculate and not general:
        # the GENERAL tier must never speculate: it is where cold-arm rows
        # land, so pruning there would bounce them straight to the
        # interpreter
        bp = getattr(op, "branch_profile", None)
        if bp is not None:
            try:
                prof = bp()
            except Exception:
                prof = None
    em = Emitter(ctx, getattr(op, "udf", None).globals
                 if getattr(op, "udf", None) else {},
                 branch_profile=prof)
    frame = Frame(em, {})
    if isinstance(op, L.MapOperator):
        res = em.eval_udf(op.udf, [row])
        out_cols = op.columns()
        if res.elts is not None and out_cols and len(out_cols) == len(res.elts):
            res = tuple_cv(res.elts, names=out_cols, valid=res.valid)
            return res, keep, out_cols
        return res, keep, None
    if isinstance(op, L.FilterOperator):
        pred = em.eval_udf(op.udf, [row])
        tr = frame.truthy(pred)
        keep = keep & tr
        ctx.active = ctx.active & tr   # errors past a filter never fire
        return row, keep, names
    if isinstance(op, L.WithColumnOperator):
        if row.elts is None or names is None:
            raise NotCompilable("withColumn on unnamed row")
        val = em.eval_udf(op.udf, [row])
        elts = list(row.elts)
        nm = list(names)
        if op.column in nm:
            elts[nm.index(op.column)] = val
        else:
            elts.append(val)
            nm.append(op.column)
        return tuple_cv(elts, names=nm), keep, tuple(nm)
    if isinstance(op, L.MapColumnOperator):
        if row.elts is None or names is None:
            raise NotCompilable("mapColumn on unnamed row")
        ci = list(names).index(op.column)
        val = em.eval_udf(op.udf, [row.elts[ci]])
        elts = list(row.elts)
        elts[ci] = val
        return tuple_cv(elts, names=names), keep, names
    if isinstance(op, L.SelectColumnsOperator):
        if row.elts is None:
            raise NotCompilable("selectColumns on unnamed row")
        # resolve against the RUNTIME row names (projection pruning may have
        # shifted positions relative to the sampled schema)
        idx = []
        for c in op.selected:
            if isinstance(c, int):
                idx.append(c if c >= 0 else len(row.elts) + c)
            else:
                if names is None or c not in names:
                    raise NotCompilable(f"select: column {c!r} missing")
                idx.append(list(names).index(c))
        nm = tuple(op.schema().columns)
        return tuple_cv([row.elts[i] for i in idx], names=nm), keep, nm
    if isinstance(op, L.RenameColumnOperator):
        nm = tuple(op.schema().columns)
        if row.elts is not None:
            return tuple_cv(row.elts, names=nm, valid=row.valid), keep, nm
        return row, keep, nm
    if isinstance(op, L.DecodeOperator):
        return _emit_decode(ctx, frame, op, row, keep, general=general)
    raise NotCompilable(f"operator {type(op).__name__} not fusable")


def _emit_decode(ctx: EmitCtx, frame, op, row: CV, keep,
                 general: bool = False):
    """Vectorized normal-case cell decode (reference:
    CSVParseRowGenerator.cc codegen'd parse; here: parse kernels + err codes).
    Parse failures raise BADPARSE_STRING_INPUT; unexpected nulls NULLERROR —
    both re-run on the interpreter's general-case path."""
    from ..core.errors import ExceptionCode
    from ..ops import strings as S
    from ..runtime.columns import user_columns

    cells = row.elts if row.elts is not None else (row,)
    decl = op.declared
    if general and op.general is not None:
        decl = op.general
    elts = []
    for cv, t in zip(cells, decl.types):
        base = t.without_option() if t.is_optional() else t
        opt = t.is_optional()
        sb, sl = cv.sbytes, cv.slen
        missing = ~cv.valid if cv.valid is not None else \
            jnp.zeros(ctx.b, dtype=bool)
        is_null = missing
        for nv in op.null_values:
            is_null = is_null | S.equals(
                sb, sl, *S.broadcast_const(nv, ctx.b))
        if base is T.STR:
            if opt:
                elts.append(CV(t=T.option(T.STR), sbytes=sb, slen=sl,
                               valid=~is_null))
            else:
                frame.raise_where(is_null, ExceptionCode.NULLERROR)
                elts.append(CV(t=T.STR, sbytes=sb, slen=sl))
            continue
        if base is T.NULL:
            from ..compiler.values import null_cv

            # a non-null cell in an all-null speculated column violates the
            # normal case: send it to the interpreter's general-case path
            frame.raise_where(~is_null, ExceptionCode.NORMALCASEVIOLATION)
            elts.append(null_cv())
            continue
        if base is T.I64:
            # a cell outside i64 range violates the i64-typed column either
            # way at decode: both flags mean "not this schema" here
            val, bad, route = S.parse_i64(sb, sl)
            bad = bad | route
            out = CV(t=T.I64, data=val)
        elif base is T.F64:
            val, bad, route = S.parse_f64(sb, sl)
            bad = bad | route
            out = CV(t=T.F64, data=val)
        elif base is T.BOOL:
            low_b, low_l = S.lower(*S.strip(sb, sl))
            is_true = S.equals(low_b, low_l, *S.broadcast_const("true", ctx.b))
            is_false = S.equals(low_b, low_l,
                                *S.broadcast_const("false", ctx.b))
            bad = ~(is_true | is_false)
            out = CV(t=T.BOOL, data=is_true)
        else:
            raise NotCompilable(f"decode to {t}")
        if opt:
            frame.raise_where(bad & ~is_null,
                              ExceptionCode.BADPARSE_STRING_INPUT)
            out = CV(t=T.option(base), data=out.data, valid=~is_null)
        else:
            frame.raise_where(is_null, ExceptionCode.NULLERROR)
            frame.raise_where(bad & ~is_null,
                              ExceptionCode.BADPARSE_STRING_INPUT)
        elts.append(out)
    nm = user_columns(decl)
    if len(elts) == 1 and nm is None:
        return elts[0], keep, None
    return tuple_cv(elts, names=nm), keep, nm


class AggregateStage:
    """Pipeline-breaker stage wrapping one aggregation operator (reference:
    physical/AggregateStage.cc + LocalBackend executeAggregateStage)."""

    def __init__(self, op: L.LogicalOperator):
        self.op = op
        self.limit = -1
        self.output_schema = op.schema()
        self.output_columns = op.columns()


class JoinStage:
    """Pipeline-breaker stage wrapping a join: the build side is planned as
    its own sub-plan (reference: PhysicalPlan.cc:145-178 — build side becomes
    stage N-1 with HASHTABLE output; probe fuses into the next stage)."""

    def __init__(self, op):
        self.op = op
        self.limit = -1
        self.output_schema = op.schema()
        self.output_columns = op.columns()


def plan_stages(sink: L.LogicalOperator, options=None):
    """Walk the DAG sink→source splitting at pipeline breakers (reference:
    PhysicalPlan.cc:60-238 splitIntoAndPlanStages). Wrapped in a `plan`
    span (runtime/tracing) so planning cost shows up on the job timeline
    next to compile and execute."""
    from ..runtime import tracing as TR

    with TR.span("plan", "plan") as _sp:
        stages = _plan_stages_impl(sink, options)
        if _sp is not TR.NOOP:
            _sp.set("n_stages", len(stages))
            _sp.set("kinds", [type(s).__name__ for s in stages])
    return stages


def _plan_stages_impl(sink: L.LogicalOperator, options=None):
    chain: list[L.LogicalOperator] = []
    limit = -1
    node = sink
    # operators that materialize (cache) act as sources: stop the walk there
    while node.parents and not getattr(node, "acts_as_source", False):
        if isinstance(node, L.TakeOperator):
            limit = node.limit
        else:
            chain.append(node)
        node = node.parent
    source = node
    chain.reverse()

    # filter pushdown THROUGH joins (reference: emitPartialFilters pushes
    # key-side predicates across join boundaries) — on the extracted chain,
    # before it's cut into stages; the user's DAG is never mutated
    if options is None or options.get_bool(
            "tuplex.optimizer.filterPushdown", True):
        from .optimizer import push_filters_through_joins

        chain = push_filters_through_joins(chain)

    stages: list = []
    cur: list[L.LogicalOperator] = []
    cur_source: Optional[L.LogicalOperator] = source
    cur_input_op: Optional[L.LogicalOperator] = source
    for op in chain:
        if op.is_breaker():
            if cur or cur_source is not None:
                stages.append(TransformStage(cur_source, cur,
                                             input_op=cur_input_op))
            from .joins import JoinOperator

            if isinstance(op, JoinOperator):
                stages.append(JoinStage(op))
            else:
                stages.append(AggregateStage(op))
            cur = []
            cur_source = None
            cur_input_op = op
        else:
            cur.append(op)
    if cur or cur_source is not None or not stages:
        stages.append(TransformStage(cur_source, cur, limit,
                                     input_op=cur_input_op))
    elif stages:
        stages[-1].limit = limit
    # filter pushdown within each stage (reference: optimizeFilters;
    # dropped rows stop raising downstream exceptions — same semantics
    # change the reference's tuplex.optimizer.filterPushdown makes)
    if options is None or options.get_bool(
            "tuplex.optimizer.filterPushdown", True):
        from .optimizer import filter_pushdown, split_filter_conjunctions

        for st in stages:
            if isinstance(st, TransformStage):
                # conjunction breakdown first so each clause pushes down
                # independently (reference: FilterBreakdownVisitor.cc +
                # LogicalPlan.cc emitPartialFilters)
                if options is None or options.get_bool(
                        "tuplex.optimizer.filterBreakdown", True):
                    st.ops = split_filter_conjunctions(st.ops)
                st.ops = filter_pushdown(st.ops)
    # selectivity-ordered filter runs (off by default, like the reference's
    # tuplex.optimizer.operatorReordering)
    if options is not None and options.get_bool(
            "tuplex.optimizer.operatorReordering", False):
        from .optimizer import reorder_filters

        for st in stages:
            if isinstance(st, TransformStage):
                st.ops = reorder_filters(st.ops)
    # projection pushdown into file sources (reference: csv.selectionPushdown)
    for i, st in enumerate(stages):
        if isinstance(st, TransformStage):
            out_req = None
            nxt = stages[i + 1] if i + 1 < len(stages) else None
            if isinstance(nxt, AggregateStage):
                # the aggregate declares which stage-output columns it
                # reads (keys + UDF row subscripts): dead columns stop
                # being parsed/decoded/staged (tpch q1: tax, shipdate)
                from .optimizer import agg_required_columns

                out_req = agg_required_columns(nxt.op)
            _apply_projection(st, out_req)
    # sample-driven branch speculation (reference: normal-case dead-branch
    # removal, RemoveDeadBranchesVisitor.cc; on by default there too).
    # Applied BEFORE segmentation so the compile probes see the same
    # speculation state the execution will.
    if options is not None and not options.get_bool(
            "tuplex.optimizer.speculateBranches", True):
        for st in stages:
            if isinstance(st, TransformStage):
                st.speculate_branches = False
    # segment each transform stage so one non-compilable UDF doesn't sink
    # the whole fused pipeline to the interpreter
    out: list = []
    for st in stages:
        if isinstance(st, TransformStage):
            for seg in segment_stage(st):
                # pre-submission jaxpr vetting (compiler/graphlint):
                # wedge-severity findings pre-degrade HERE, hazard
                # scores and the static memory bound steer the split
                rep = _vet_stage(seg, options)
                out.extend(_split_oversize(seg, options, report=rep))
        else:
            out.append(st)
    # fuse pattern-fold aggregates into the preceding transform stage's
    # device fn: identity-seeded partials come back with the stage outputs,
    # so the whole plan is ONE device pass instead of two (the reference
    # likewise sinks rows straight into per-task aggregates inside the
    # compiled pipeline — PipelineBuilder.h aggregate:398-401)
    from . import aggregates as A

    for i in range(len(out) - 1):
        st, nxt = out[i], out[i + 1]
        if (isinstance(st, TransformStage) and not st.force_interpret
                and st.limit < 0 and isinstance(nxt, AggregateStage)
                and type(nxt.op) is A.AggregateOperator
                and A.recognize_fold(nxt.op.aggregate_udf) is not None):
            st.fold_op = nxt.op
    return out


def consumer_kind(stages: list, si: int):
    """Who consumes stage `si`'s output: False (terminal / interpreter
    consumer) or the consumer kind "stage"/"join"/"agg" — the value
    execute_any's `intermediate` parameter takes. Shared by the driver
    loop (api/dataset.py) and the ahead-of-time compile planner
    (exec/local.py precompile_plan) so the two can never disagree on the
    packed-vs-handoff build variant."""
    nxt = stages[si + 1] if si + 1 < len(stages) else None
    if nxt is None or getattr(nxt, "force_interpret", False):
        return False
    if isinstance(nxt, AggregateStage):
        return "agg"
    if isinstance(nxt, JoinStage):
        return "join"
    if isinstance(nxt, TransformStage):
        return "stage"
    return False


def _apply_projection(stage: TransformStage, output_required=None) -> None:
    """Prune unread columns at the Arrow read: unread columns are never
    parsed, decoded, or staged to HBM."""
    from ..io.csvsource import CSVSourceOperator
    from .optimizer import required_source_columns

    src = stage.source
    if not isinstance(src, CSVSourceOperator):
        return
    req = required_source_columns(tuple(src.stat.columns), stage.ops,
                                  output_required)
    if req is None or len(req) >= len(src.stat.columns):
        return
    stage.source_projection = list(req)
    # prune the fused decode + the stage input schema to the projection;
    # integer selections resolve to NAMES first (positions shift when
    # columns are pruned)
    new_ops = []
    for op in stage.ops:
        if isinstance(op, L.DecodeOperator) and op.parent is src:
            keep_idx = [src.stat.columns.index(c) for c in req]
            declared = T.row_of(req, [op.declared.types[i] for i in keep_idx])
            general = None
            if op.general is not None:
                general = T.row_of(req,
                                   [op.general.types[i] for i in keep_idx])
            pruned = L.DecodeOperator(src, declared, op.null_values,
                                      general=general)
            new_ops.append(pruned)
        elif isinstance(op, L.SelectColumnsOperator) and \
                any(isinstance(c, int) for c in op.selected):
            full_cols = op.parent.schema().columns
            names = [full_cols[c] if isinstance(c, int) else c
                     for c in op.selected]
            new_ops.append(L.SelectColumnsOperator(op.parent, names))
        else:
            new_ops.append(op)
    stage.input_schema = T.row_of(req, [T.option(T.STR)] * len(req))
    # RE-LINK the chain through the pruned decode (shallow copies with
    # cleared schema caches): ops still point at the unpruned DAG, and
    # consumers key off stage.output_schema/output_columns — a stale
    # unpruned schema would misalign the aggregate's key indices for
    # zero-row fallback partitions (review r4). Op ids survive the copy,
    # so metrics/history attribution is unchanged.
    import copy as _copy

    relinked = []
    prev: L.LogicalOperator = src
    for op in new_ops:
        if op.parents and op.parent is not prev:
            op = _copy.copy(op)
            op.parents = [prev]
            op._schema_cache = None
        relinked.append(op)
        prev = op
    stage.ops = relinked
    try:
        last = relinked[-1] if relinked else src
        stage.output_schema = last.schema()
        stage.output_columns = last.columns()
    except Exception:
        pass    # schema inference unchanged on failure (pre-existing state)


# compile-probe verdict memo — LRU-bounded like the plan/logical.py memos
# (grow-then-.clear() dropped every warm probe verdict at the cap)
from ..utils.lru import LruDict

_op_compiles_cache: LruDict = LruDict(4096)
import itertools as _it
_uid_counter = _it.count()


def op_compiles(op: L.LogicalOperator, input_schema: T.RowType,
                speculate: bool = True) -> bool:
    """Abstract-trace ONE operator against its input schema (tiny shapes,
    jax.eval_shape: no device work) — False if the emitter rejects it.
    Cached per (op, schema, speculation state): operators are immutable
    once planned, but the probe's verdict can depend on the branch profile
    (a pruned cold arm may hide a non-compilable construct), so the key
    carries the same profile signature the jit cache does."""
    if isinstance(op, (L.ResolveOperator, L.IgnoreOperator, L.TakeOperator)):
        return True
    from ..compiler import analyzer as _az

    rep = _az.op_analysis(op)
    if rep is not None and rep.must_fallback_now(speculate):
        # statically untraceable: route to the interpreter pipeline at PLAN
        # time — the emitter is never invoked, not even as a probe
        _az.STATS["plan_fallback_ops"] += 1
        return False
    ck = (_op_identity(op), input_schema.name,
          _branch_profile_sig(op) if speculate else None)
    hit = _op_compiles_cache.get(ck)
    if hit is not None:
        return hit
    result = _op_compiles_uncached(op, input_schema, speculate)
    _op_compiles_cache[ck] = result
    return result


def _branch_profile_sig(op) -> str:
    """Stable signature of an operator's sample branch observations (empty
    when the op has none). Feeds every cache whose value depends on the
    speculated kernel: stage.key() and the compile-probe cache."""
    bp = getattr(op, "branch_profile", None)
    if bp is None:
        return ""
    try:
        prof = bp()
    except Exception:
        return ""
    return repr(sorted(prof.items())) if prof else ""


def _op_identity(op: L.LogicalOperator) -> str:
    """Content identity of an operator, hashed — shared by the jit cache key
    and the compile-probe cache so the two can never disagree. Captured
    globals hash by repr; value-unfaithful reprs are why trace failures at
    EXECUTION time also fall back to the interpreter (exec/local.py)."""
    h = hashlib.sha256()
    h.update(type(op).__name__.encode())
    for udf_attr in ("udf", "combine_udf", "aggregate_udf"):
        udf = getattr(op, udf_attr, None)
        if udf is None:
            continue
        h.update(udf_attr.encode())
        h.update(udf.source.encode())
        for k in sorted(udf.globals):
            h.update(f"{k}={udf.globals[k]!r}".encode())
        if not udf.source:
            # a per-function uid (NOT id(): addresses get reused after GC)
            try:
                uid = udf.func.__dict__.setdefault(
                    "__tpx_uid__", f"u{next(_uid_counter)}")
            except (AttributeError, TypeError):
                uid = f"anon{id(udf.func)}"
            h.update(str(uid).encode())
    for attr in ("column", "selected", "old", "new", "null_values",
                 "left_column", "right_column", "how", "prefixes",
                 "suffixes", "initial", "key_columns", "limit"):
        if hasattr(op, attr):
            h.update(f"{attr}={getattr(op, attr)!r};".encode())
    if hasattr(op, "declared"):
        h.update(op.declared.name.encode())
    if getattr(op, "general", None) is not None:
        h.update(op.general.name.encode())
    return h.hexdigest()[:20]


def abstract_batch_arrays(input_schema: T.RowType):
    """Abstract 8-row DeviceBatch arrays for an input schema, or None when
    a column type has no columnar layout (the stage can't compile). Shared
    by the compile probe and the codeStats jaxpr counter."""
    from ..runtime.columns import flatten_type
    from ..runtime.jaxcfg import jax
    import numpy as np

    arrays: dict = {"#rowvalid": jax.ShapeDtypeStruct((8,), np.bool_)}
    for ci, ct in enumerate(input_schema.types):
        for path, lt in flatten_type(ct, str(ci)):
            base = lt.without_option() if lt.is_optional() else lt
            opt = lt.is_optional()
            if path.endswith("#opt"):
                arrays[path] = jax.ShapeDtypeStruct((8,), np.bool_)
                continue
            if base is T.STR:
                arrays[path + "#bytes"] = jax.ShapeDtypeStruct((8, 8), np.uint8)
                arrays[path + "#len"] = jax.ShapeDtypeStruct((8,), np.int32)
            elif base in (T.BOOL,):
                arrays[path] = jax.ShapeDtypeStruct((8,), np.bool_)
            elif base is T.I64:
                arrays[path] = jax.ShapeDtypeStruct((8,), np.int64)
            elif base is T.F64:
                arrays[path] = jax.ShapeDtypeStruct((8,), np.float64)
            elif base in (T.NULL, T.EMPTYTUPLE):
                pass
            else:
                return None
            if opt and not path.endswith("#opt"):
                arrays[path + "#valid"] = jax.ShapeDtypeStruct((8,), np.bool_)
    return arrays


def stage_fingerprint(stage: TransformStage,
                      input_schema: Optional[T.RowType] = None):
    """Content address of the stage's fast-path executable over an abstract
    8-row batch (exec/compilequeue fingerprint: canonical jaxpr + hoisted
    const values + avals + platform). Stages that differ only in logical
    identity — flights' isomorphic join-probe segments, equal re-planned
    pipelines — share a fingerprint and hence ONE compiled executable.
    None when the stage has no compilable device fn. NOTE: shape-specific
    (8-row probe shapes); equal fingerprints here imply the runtime
    executables dedup too, since runtime shapes derive from the same
    inputs."""
    try:
        schema = input_schema if input_schema is not None \
            else stage.input_schema
        arrays = abstract_batch_arrays(schema)
        if arrays is None or stage.force_interpret:
            return None
        fn = stage.build_device_fn(schema)
        from ..exec.compilequeue import fingerprint_fn

        return fingerprint_fn(fn, (arrays,))
    except Exception:
        return None


def _op_compiles_uncached(op: L.LogicalOperator,
                          input_schema: T.RowType,
                          speculate: bool = True) -> bool:
    from ..runtime.jaxcfg import jax

    arrays = abstract_batch_arrays(input_schema)
    if arrays is None:
        return False

    probe = TransformStage(None, [op], input_schema=input_schema,
                           input_op=op)
    # input_op=op is wrong for schema purposes; build fn against the given
    # input schema directly
    probe.input_schema = input_schema
    probe.speculate_branches = speculate
    fn = probe.build_device_fn()
    try:
        jax.eval_shape(fn, arrays)
        return True
    except NotCompilable:
        return False
    except Exception:
        # any other trace failure: treat as non-compilable (interpreter is
        # always correct)
        return False


def _vet_stage(stage: TransformStage, options) -> object:
    """Plan-time jaxpr vetting (compiler/graphlint): trace the stage at
    the probe shapes, attach the GraphReport, and PRE-DEGRADE statically
    known compile-wedges to the interpreter before the compile plane
    ever sees them. The flights airport build side is the load-bearing
    case: its jaxpr matches the ``wide-str-compaction`` rule (round-17
    bisection — see compiler/graphlint), so instead of burning a 300 s
    deadline + SIGKILL + tier restart, the stage plans straight onto the
    tier it would have ended up on anyway. The veto is recorded as a
    content-addressed ``.hazard`` marker (stage-fingerprint keyed) so
    lint/explain/compilestats — and any later process planning the same
    stage — can see WHY without re-tracing. Returns the report (None
    when the gate is off or the stage isn't traceable)."""
    from ..compiler import graphlint as GL

    if not GL.enabled() or stage.force_interpret or not stage.ops:
        return None
    if not _vet_relevant(stage, options):
        return None
    # memo key: the jit-cache key (op identities + schema + speculation
    # state) — cheap to compute, and by the same argument as the jit
    # cache it determines the traced jaxpr (backend is fixed per
    # process, jaxcfg)
    mk = None
    try:
        mk = stage.key()
    except Exception:
        pass
    if mk is not None:
        hit, report = GL.vet_memo_get(mk)
        if hit:
            stage.graph_report = report
            if report is not None and report.wedge:
                _apply_wedge_degrade(stage, report)
            return report
    from ..runtime import tracing as TR

    with TR.span("plan:graphlint", "plan") as _sp:
        report = GL.analyze_stage(stage)
        if _sp is not TR.NOOP and report is not None:
            _sp.set("eqns", report.n_eqns) \
               .set("hazard", round(min(report.hazard_score, 1e9), 2)) \
               .set("wedge", bool(report.wedge))
    stage.graph_report = report
    if mk is not None:
        GL.vet_memo_put(mk, report)
    if report is None or not report.wedge:
        return report
    _apply_wedge_degrade(stage, report)
    return report


#: probe-trace admission for _vet_stage: below ALL of these a stage can
#: neither wedge nor want construct-steered splitting nor threaten the
#: memory budget, so the ~300 ms trace is skipped outright
_VET_MIN_OPS = 16              # split steering only matters on big fusions
_VET_TIGHT_BUDGET = 32 << 20   # static peak check only bites tiny budgets


def _vet_relevant(stage: TransformStage, options) -> bool:
    """Is the probe trace worth its cost for this stage? Plan-time
    vetting pays a full ``make_jaxpr`` per stage; for stages that cannot
    plausibly wedge (fewer string columns on BOTH schema edges than the
    rule's floor), cannot want a construct-steered split (too few ops),
    and cannot threaten a tight executor budget, skip it. The compile
    plane still vets the real traced jaxpr at submission, so the hard
    no-wedge-submits guarantee does not depend on this heuristic."""
    from ..compiler import graphlint as GL

    if len(stage.ops) >= _VET_MIN_OPS:
        return True
    if options is not None and options.get_size(
            "tuplex.executorMemory", 1 << 30) < _VET_TIGHT_BUDGET:
        return True
    need = GL.WEDGE_MIN_STR_BUFS
    return (_schema_has_str_cols(stage.input_schema, need)
            or _schema_has_str_cols(stage.output_schema, need))


def _schema_has_str_cols(schema, need: int) -> bool:
    """>= `need` string leaves in a RowType (the wedge's row-buffer axis,
    counted without tracing)."""
    from ..runtime.columns import flatten_type

    n = 0
    for ci, ct in enumerate(getattr(schema, "types", ()) or ()):
        for path, lt in flatten_type(ct, str(ci)):
            if path.endswith("#opt"):
                continue
            base = lt.without_option() if lt.is_optional() else lt
            if base is T.STR:
                n += 1
                if n >= need:
                    return True
    return False


def _apply_wedge_degrade(stage: TransformStage, report) -> None:
    """Pre-degrade a statically known compile-wedge to the interpreter
    and record why (stats, content-addressed ``.hazard`` marker, log).
    The marker address is the compile-plane fingerprint — expensive (it
    traces), but only ever paid for actual wedges."""
    from ..exec import compilequeue as CQ
    from ..utils.logging import get_logger

    rule = next(f.rule for f in report.findings if f.severity == "wedge")
    stage.force_interpret = True
    stage.hazard_rule = rule
    detail = "; ".join(f.line() for f in report.findings
                       if f.severity == "wedge")
    with CQ._LOCK:
        CQ.STATS["hazards_found"] += 1
        CQ.STATS["hazards_avoided"] += 1
    try:
        fp = stage_fingerprint_prevet(stage)
        if fp is not None:
            CQ.write_marker(CQ._artifact_path(fp), "hazard",
                            reason=detail, fp=fp, rule=rule,
                            plane="plan")
    except Exception:   # pragma: no cover - provenance is best-effort
        pass
    get_logger("plan").warning(
        "graphlint: stage %s pre-degraded to the interpreter (%s)",
        ",".join(type(o).__name__ for o in stage.ops), detail)


def stage_fingerprint_prevet(stage: TransformStage):
    """stage_fingerprint ignoring a vet-applied force_interpret pin (the
    `.hazard` marker must land at the address the compile plane WOULD
    have used)."""
    pinned = stage.force_interpret
    try:
        stage.force_interpret = False
        return stage_fingerprint(stage)
    finally:
        stage.force_interpret = pinned


def _split_oversize(stage: TransformStage, options,
                    report=None) -> list:
    """Split a very large fused stage into balanced sub-stages on
    accelerator backends. Remote TPU compiles scale superlinearly with
    graph size (the 43-operator flights stage took >20 min in one
    tpu_compile_helper call vs ~2-3 min for zillow's 13); two half-size
    executables compile far faster and the intermediate rides the
    device-resident handoff. CPU keeps maximal fusion (local XLA compiles
    are cheap and stage boundaries cost real memcpys there).

    The split point is MEASURED, not hardcoded (plan/splittuner.py): the
    per-platform compile-seconds-vs-op-count curve (fed by every actual
    compile) is balanced against the observed per-boundary dispatch tax,
    under the ``tuplex.tpu.compileBudgetS`` ceiling; a stage whose finest
    split still blows the budget degrades to a host-CPU compile with
    device transfer. An explicit ``tuplex.tpu.maxStageOps`` (>0) overrides
    the tuner; =0 disables splitting entirely."""
    max_ops = 0
    if options is not None:
        max_ops = options.get_int("tuplex.tpu.maxStageOps", -1)
    n = len(stage.ops)
    dec = None
    if report is None:
        report = getattr(stage, "graph_report", None)
    if max_ops < 0:       # auto: ask the tuner
        from ..runtime.jaxcfg import jax

        from . import splittuner as ST

        on_cpu = jax.default_backend() == "cpu"
        budget = options.get_float(
            "tuplex.tpu.compileBudgetS", 480.0) if options is not None \
            else 480.0
        # a hazard score past the veto line re-plans with graphlint's
        # per-op construct costs: the budget becomes the threshold PER
        # SEGMENT, and chunk boundaries balance hazard cost, so the
        # split isolates the hazardous span instead of balancing op
        # counts (the compile plane would otherwise veto the whole
        # stage, satellite: "split around the hazardous eqn span")
        hazard_budget = None
        if report is not None and not report.wedge and n > 1:
            from ..compiler import graphlint as GL

            threshold = GL.hazard_threshold()
            if threshold > 0 and report.hazard_score > threshold:
                hazard_budget = threshold
        # CPU prefers fusion (boundaries are real memcpys, compiles are
        # usually cheap) and splits ONLY when the predicted compile blows
        # the budget — flights' 43-op mega-fusion ran >20 min at >120 GB
        # on XLA:CPU, the same superlinear pathology as the tunnel.
        # Accelerators cost-minimize across the whole curve.
        from ..runtime import tracing as TR

        with TR.span("plan:split-tune", "plan") as _sp:
            if hazard_budget is not None:
                dec = ST.plan_split(n, hazard_budget, ST.model_for(),
                                    prefer_fusion=on_cpu,
                                    op_costs=report.op_costs())
            else:
                dec = ST.plan_split(n, budget, ST.model_for(),
                                    prefer_fusion=on_cpu)
            if _sp is not TR.NOOP:
                # the tuner's verdict rides the span so a trace shows WHY
                # a plan split (or degraded) without digging through logs
                _sp.set("n_ops", n).set("k", dec.k) \
                   .set("degrade", bool(dec.degrade)) \
                   .set("predicted_compile_s",
                        round(float(dec.predicted_compile_s or 0.0), 3))
        stage.split_decision = dec
        stage.predicted_compile_s = dec.predicted_compile_s
        if dec.k > 1 or dec.degrade:
            ST.log_decision(dec)
        if dec.degrade and not on_cpu:
            # budget-degraded stages compile on the HOST CPU, where
            # fusion is cheap and every extra boundary costs a real
            # device transfer — so keep the stage fused rather than
            # applying the accelerator split, and predict off the CPU
            # curve
            stage.cpu_compile = True
            stage.predicted_compile_s = ST.model_for("cpu").predict(n)
            max_ops = 0
        else:
            # on CPU a degrade verdict has nowhere cheaper to go — take
            # the least-bad split and proceed
            max_ops = dec.per if dec.k > 1 else 0
    # static peak-memory vetting (compiler/graphlint): a stage whose
    # intermediates STATICALLY exceed the MemoryManager budget at the
    # runtime batch size must not reach the device — it would OOM-spill
    # (or hard-fail) after compiling. Splitting shrinks the live set
    # proportionally to the op share; a single op that alone blows the
    # budget degrades to the interpreter, which streams rows instead of
    # materializing columnar intermediates.
    if report is not None and options is not None \
            and not stage.force_interpret and report.peak_bytes > 0:
        mem_budget = options.get_size("tuplex.executorMemory", 1 << 30)
        psize = options.get_size("tuplex.partitionSize", 4 << 20)
        est_rows = psize // max(report.input_row_bytes, 1) \
            if report.input_row_bytes > 0 else report.traced_rows
        peak = report.peak_bytes_at(est_rows)
        if mem_budget > 0 and peak > mem_budget:
            from ..compiler import graphlint as GL
            from ..utils.logging import get_logger

            fit = (n * mem_budget) // peak
            if fit >= 1 and n > 1:
                max_ops = int(fit) if max_ops <= 0 \
                    else min(max_ops, int(fit))
                remedy = f"split to <={max_ops} ops/segment"
            else:
                stage.force_interpret = True
                remedy = "degraded to the interpreter"
            report.findings.append(GL.Finding(
                "static-peak-memory", "warn",
                f"static intermediate peak ~{peak >> 20} MiB at "
                f"~{est_rows} rows/batch exceeds executor memory "
                f"{mem_budget >> 20} MiB — {remedy}"))
            get_logger("plan").warning(
                "graphlint: %s", report.findings[-1].message)
    if not max_ops or n <= max_ops or stage.force_interpret:
        return [stage]
    import math

    k = math.ceil(n / max_ops)
    per = math.ceil(n / k)
    # chunk boundaries must not separate an op from its trailing
    # Resolve/Ignore guards. A hazard-mode split decision carries COST-
    # balanced cut points (splittuner boundaries) — honored as long as
    # nothing tightened the op cap after the decision was made.
    cuts = list(dec.boundaries) if (dec is not None and dec.boundaries
                                    and max_ops == dec.per) else None
    chunks: list[list] = [[]]
    for i, op in enumerate(stage.ops):
        if cuts is not None:
            split_here = bool(cuts) and i >= cuts[0]
        else:
            split_here = len(chunks[-1]) >= per
        if split_here and not isinstance(op, (L.ResolveOperator,
                                              L.IgnoreOperator)):
            chunks.append([])
            if cuts:
                cuts.pop(0)
        chunks[-1].append(op)
    schema = stage.input_schema
    segments: list[TransformStage] = []
    for j, ops_run in enumerate(chunks):
        if j == 0:
            seg = TransformStage(
                stage.source, ops_run,
                input_schema=schema,
                input_op=None if stage.source is not None else ops_run[0])
            if hasattr(stage, "source_projection"):
                seg.source_projection = stage.source_projection
        else:
            seg = TransformStage(None, ops_run, input_schema=schema,
                                 input_op=ops_run[0])
        seg.speculate_branches = stage.speculate_branches
        seg.cpu_compile = stage.cpu_compile
        if dec is not None:
            from . import splittuner as ST

            seg.split_decision = dec
            seg.predicted_compile_s = ST.model_for().predict(len(ops_run))
        for op in ops_run:
            if not isinstance(op, (L.ResolveOperator, L.IgnoreOperator)):
                schema = op.schema()
        segments.append(seg)
    segments[-1].limit = stage.limit
    return segments


def segment_stage(stage: TransformStage) -> list:
    """Split a fused stage at non-compilable operators: maximal compilable
    runs stay fused on device; runs of bad operators become interpreter
    segments. Resolvers/ignores ride with the run of the op they guard."""
    if not stage.ops:
        return [stage]
    flags: list = []          # True=compilable, False=not, None=passthrough
    schemas_before: list[T.RowType] = []
    schema = stage.input_schema
    for op in stage.ops:
        schemas_before.append(schema)
        if isinstance(op, (L.ResolveOperator, L.IgnoreOperator)):
            flags.append(None)
        else:
            flags.append(op_compiles(op, schema,
                                     speculate=stage.speculate_branches))
            schema = op.schema()
    if all(f is not False for f in flags):
        return [stage]

    runs: list[list] = []     # [start_idx, [ops], bad]
    for i, (op, ok) in enumerate(zip(stage.ops, flags)):
        if ok is None and runs:
            runs[-1][1].append(op)
            continue
        bad = ok is False
        if runs and runs[-1][2] == bad:
            runs[-1][1].append(op)
        else:
            runs.append([i, [op], bad])

    segments: list[TransformStage] = []
    for j, (start, ops_run, bad) in enumerate(runs):
        if j == 0:
            # inherit the (possibly projection-pruned) input schema and the
            # source projection — rebuilding from source.schema() would undo
            # the pushdown and misalign positional decode
            seg = TransformStage(
                stage.source, ops_run,
                input_schema=stage.input_schema,
                input_op=None if stage.source is not None else ops_run[0])
            if hasattr(stage, "source_projection"):
                seg.source_projection = stage.source_projection
        else:
            seg = TransformStage(None, ops_run,
                                 input_schema=schemas_before[start],
                                 input_op=ops_run[0])
        seg.force_interpret = bad
        if bad:
            from ..compiler.analyzer import op_analysis

            reasons = []
            for op in ops_run:
                rep = op_analysis(op)
                f = rep.routing_finding(stage.speculate_branches) \
                    if rep is not None else None
                if f is not None:
                    reasons.append(f"{rep.name}: {f.reason} ({rep.loc(f)})")
            if reasons:
                seg.route_reason = "plan-time fallback — " + \
                    "; ".join(reasons)
        seg.speculate_branches = stage.speculate_branches
        segments.append(seg)
    segments[-1].limit = stage.limit
    return segments
