"""Physical planning: stage splitting + fused stage functions.

Re-designs the reference's physical layer (reference:
core/src/physical/PhysicalPlan.cc:60-238 — split DAG into stages at pipeline
breakers; StageBuilder.cc — fuse the stage's operators into one compiled
function). Here a TransformStage compiles to ONE jax function over a staged
column batch: every fused operator contributes ops to the same trace, so XLA
sees the whole pipeline and fuses it into a handful of kernels (the TPU analog
of the reference's single LLVM row-loop).
"""

from __future__ import annotations

import hashlib
from typing import Callable, Optional

from ..compiler.emitter import EmitCtx, Emitter, Frame
from ..compiler.stagefn import input_row_cv, result_arrays
from ..compiler.values import CV, tuple_cv
from ..core import typesys as T
from ..core.errors import NotCompilable
from ..runtime.jaxcfg import jnp
from . import logical as L


class TransformStage:
    """A fused chain of row operators over one input source.

    `ops` excludes the source; Resolve/Ignore operators ride along for the
    host resolve path but emit nothing on device (reference: slow-path-only
    resolvers, StageBuilder.cc generateResolveCodePath).
    """

    def __init__(self, source: L.LogicalOperator, ops: list[L.LogicalOperator],
                 limit: int = -1):
        self.source = source
        self.ops = ops
        self.limit = limit
        self.input_schema = source.schema()
        self.output_schema = ops[-1].schema() if ops else source.schema()
        out_cols = (ops[-1] if ops else source).columns()
        self.output_columns = out_cols

    def key(self) -> str:
        """Cache key for the jit'd executable: operator chain + UDF sources +
        captured globals + input schema (specialization contract of the
        emitter)."""
        h = hashlib.sha256()
        h.update(self.input_schema.name.encode())
        for op in self.ops:
            h.update(type(op).__name__.encode())
            udf = getattr(op, "udf", None)
            if udf is not None:
                h.update(udf.source.encode())
                for k in sorted(udf.globals):
                    h.update(f"{k}={udf.globals[k]!r}".encode())
            for attr in ("column", "selected", "old", "new"):
                if hasattr(op, attr):
                    h.update(repr(getattr(op, attr)).encode())
        return h.hexdigest()[:16]

    # ------------------------------------------------------------------
    def build_device_fn(self) -> Callable:
        """The fused fast-path function: staged arrays -> output arrays +
        '#err' + '#keep'. Raises NotCompilable if any fused UDF can't compile
        (the backend then interprets every row)."""
        schema = self.input_schema
        ops = [op for op in self.ops
               if not isinstance(op, (L.ResolveOperator, L.IgnoreOperator,
                                      L.TakeOperator))]
        out_schema = self.output_schema

        def fn(arrays: dict):
            b = arrays["#rowvalid"].shape[0]
            ctx = EmitCtx(b, arrays["#rowvalid"])
            keep = arrays["#rowvalid"]
            row = input_row_cv(arrays, schema)
            from ..runtime.columns import user_columns

            names = user_columns(schema)
            for op in ops:
                row, keep, names = _emit_op(ctx, op, row, keep, names)
            outs, out_t = result_arrays(row, b)
            outs = dict(outs)
            outs["#err"] = ctx.err
            outs["#keep"] = keep & (ctx.err == 0)
            return outs

        return fn


def _emit_op(ctx: EmitCtx, op: L.LogicalOperator, row: CV, keep,
             names: Optional[tuple]):
    em = Emitter(ctx, getattr(op, "udf", None).globals
                 if getattr(op, "udf", None) else {})
    frame = Frame(em, {})
    if isinstance(op, L.MapOperator):
        res = em.eval_udf(op.udf, [row])
        out_cols = op.columns()
        if res.elts is not None and out_cols and len(out_cols) == len(res.elts):
            res = tuple_cv(res.elts, names=out_cols, valid=res.valid)
            return res, keep, out_cols
        return res, keep, None
    if isinstance(op, L.FilterOperator):
        pred = em.eval_udf(op.udf, [row])
        tr = frame.truthy(pred)
        keep = keep & tr
        ctx.active = ctx.active & tr   # errors past a filter never fire
        return row, keep, names
    if isinstance(op, L.WithColumnOperator):
        if row.elts is None or names is None:
            raise NotCompilable("withColumn on unnamed row")
        val = em.eval_udf(op.udf, [row])
        elts = list(row.elts)
        nm = list(names)
        if op.column in nm:
            elts[nm.index(op.column)] = val
        else:
            elts.append(val)
            nm.append(op.column)
        return tuple_cv(elts, names=nm), keep, tuple(nm)
    if isinstance(op, L.MapColumnOperator):
        if row.elts is None or names is None:
            raise NotCompilable("mapColumn on unnamed row")
        ci = list(names).index(op.column)
        val = em.eval_udf(op.udf, [row.elts[ci]])
        elts = list(row.elts)
        elts[ci] = val
        return tuple_cv(elts, names=names), keep, names
    if isinstance(op, L.SelectColumnsOperator):
        if row.elts is None:
            raise NotCompilable("selectColumns on unnamed row")
        idx = op._resolve_indices()
        nm = tuple(op.schema().columns)
        return tuple_cv([row.elts[i] for i in idx], names=nm), keep, nm
    if isinstance(op, L.RenameColumnOperator):
        nm = tuple(op.schema().columns)
        if row.elts is not None:
            return tuple_cv(row.elts, names=nm, valid=row.valid), keep, nm
        return row, keep, nm
    raise NotCompilable(f"operator {type(op).__name__} not fusable")


def plan_stages(sink: L.LogicalOperator) -> list[TransformStage]:
    """Walk the DAG sink→source splitting at breakers (single linear chain
    until joins/aggregates land)."""
    chain: list[L.LogicalOperator] = []
    limit = -1
    node = sink
    while node.parents:
        if isinstance(node, L.TakeOperator):
            limit = node.limit
        else:
            chain.append(node)
        node = node.parent
    chain.reverse()
    return [TransformStage(node, chain, limit)]
