"""Materialized dataset caching.

Reference semantics (reference: core/include/logical/CacheOperator.h:73-83 +
dataset.py:346): cache() EAGERLY executes the upstream plan and keeps the
result partitions — normal-case columnar partitions and boxed
fallback/general rows stay separate (store_specialized), so later plans
reuse them without recompute (PhysicalPlan.cc:85-99).
"""

from __future__ import annotations

from typing import Optional

from ..core import typesys as T
from ..core.row import Row
from . import logical as L


class CacheOperator(L.LogicalOperator):
    acts_as_source = True  # plan walk stops here; partitions come from cache

    def __init__(self, parent: L.LogicalOperator, store_specialized: bool = True):
        super().__init__([parent])
        self.store_specialized = store_specialized
        self._partitions: Optional[list] = None
        self._schema: Optional[T.RowType] = None
        self._exceptions: list = []

    @property
    def deterministic(self) -> bool:
        """Plan-time purity verdict over the whole upstream chain
        (compiler/analyzer.py). False means the cached materialization PINS
        one nondeterministic outcome: re-running the same pipeline without
        the cache would produce different rows, and speculative re-execution
        of cached rows must not assume reproducibility."""
        from ..compiler.analyzer import chain_deterministic

        memo = getattr(self, "_det_memo", None)
        if memo is None:
            memo = self._det_memo = chain_deterministic(self.parent)
        return memo

    # -- materialization (eager, like the reference) -----------------------
    def materialize(self, context) -> None:
        if self._partitions is not None:
            return
        from ..api.dataset import _source_partitions
        from ..compiler import analyzer as _az
        from .physical import plan_stages

        if not self.deterministic:
            from ..utils.logging import get_logger

            get_logger("plan").info(
                "cache(): upstream chain is nondeterministic (random/time "
                "UDFs) — materialized partitions pin this run's outcome; "
                "cross-job sample/schema memoization is disabled for it")
        snap = _az.snapshot()
        stages = plan_stages(self.parent, context.options_store)
        d = _az.delta(snap)
        context.metrics.record_plan({
            "analyzer_ms": d["analyze_ms"],
            "plan_fallback_ops": d["plan_fallback_ops"]})
        partitions = None
        for stage in stages:
            if getattr(stage, "source", None) is not None:
                partitions = _source_partitions(context, stage)
            result = context.backend.execute_any(stage, partitions, context)
            partitions = result.partitions
            self._exceptions.extend(result.exceptions)
            context.metrics.record_stage(result.metrics)
        self._partitions = partitions or []
        if self._partitions:
            self._schema = self._partitions[0].schema
        else:
            self._schema = self.parent.schema()
        if not self.store_specialized:
            # un-specialize: box everything (general case only)
            from ..runtime import columns as C

            values = []
            for p in self._partitions:
                for r in p.iter_rows():
                    values.append(r.unwrap() if len(r.values) == 1
                                  else tuple(r.values))
            schema = self._schema
            self._partitions = [C.build_partition(values, schema)] \
                if values else []

    # -- source protocol ---------------------------------------------------
    def schema(self) -> T.RowType:
        if self._schema is not None:
            return self._schema
        return self.parent.schema()

    def columns(self):
        from ..runtime.columns import user_columns

        return user_columns(self.schema())

    def sample(self) -> list[Row]:
        if self._partitions:
            out = []
            for p in self._partitions[:1]:
                for i in range(min(p.num_rows, 256)):
                    out.append(p.decode_row(i))
            return out
        return self.parent.cached_sample()

    def load_partitions(self, context, projection=None) -> list:
        self.materialize(context)
        return list(self._partitions or [])
