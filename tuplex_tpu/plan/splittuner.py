"""Measured stage-split tuner: compile-cost curve vs boundary tax.

Replaces the hardcoded ``maxStageOps=20`` auto-split. That constant was a
workaround for superlinear remote-TPU compile times (the 43-op flights stage
took >20 min in one tunnel call vs ~2-3 min for zillow's 13), but it trades
compile seconds against a REAL per-boundary cost — every extra stage boundary
pays a dispatch + D2H/H2D round trip — and the right cut point is a property
of the platform, not a constant. SystemML's fusion-plan work (PAPERS:
arXiv:1801.00829) and FusionStitching (arXiv:1811.05213) both cost this
granularity tradeoff explicitly; this module does the same with numbers
measured on THIS machine:

  * every actual stage compile (exec/compilequeue.py) records
    (op count, seconds) into a per-platform JSON model persisted under the
    cache dir — the compile-seconds-vs-op-count curve is FIT (power law,
    log-log least squares) once enough distinct sizes accumulate, with
    platform defaults anchored on the observed zillow/flights compiles
    until then;
  * the first device dispatch of every boundary-fed stage (exec/local.py)
    records the measured per-boundary dispatch cost;
  * ``plan_split`` picks the segment count k minimizing
    predicted_compile(k) + (k-1) * boundary_cost, subject to the
    ``tuplex.tpu.compileBudgetS`` ceiling — and when even the finest split
    blows the budget, degrades the stage to a host-CPU compile with device
    transfer (the stage still runs, just without an accelerator kernel).

The decision (prediction + chosen split) is logged at plan time and recorded
on the stage for metrics/history/compilestats.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from dataclasses import dataclass
from typing import Optional

# default power-law curves t(n) = a + b * n^c, anchored on measured compiles:
# zillow's 13-op stage ~150 s and flights' 43-op stage >20 min over the TPU
# tunnel (c = ln(1270/150)/ln(43/13) ~= 1.8). CPU XLA is NOT flat either:
# zillow's 13-op stage compiles in ~40 s locally but flights' 43-op stage
# ran >20 min at >120 GB RSS before being killed (c >= ln(30)/ln(3.3) ~=
# 2.9 between those two anchors — the barrier-laden mega-fusions blow up
# XLA:CPU superlinearly), so the CPU default is steep too
_DEFAULT_CURVE = {"cpu": (0.3, 0.05, 2.5)}
_DEFAULT_CURVE_ACCEL = (20.0, 1.5, 1.8)
_DEFAULT_BOUNDARY = {"cpu": 0.005}
_DEFAULT_BOUNDARY_ACCEL = 0.35

_MAX_OBS = 256          # persisted observation window per platform


def _model_dir() -> str:
    d = os.environ.get("TUPLEX_COMPILE_MODEL_DIR", "")
    if not d:
        d = os.path.join(os.path.expanduser("~"), ".cache", "tuplex_tpu")
    try:
        os.makedirs(d, exist_ok=True)
    except OSError:
        return ""
    return d


class CompileModel:
    """Per-platform compile-time model: raw (op count, seconds) observations
    plus per-boundary dispatch samples, persisted as JSON; predictions come
    from a power-law fit when >=3 distinct op counts are on record, else
    from the platform default curve."""

    def __init__(self, platform: str, path: Optional[str] = None):
        self.platform = platform
        d = _model_dir()
        self.path = path if path is not None else (
            os.path.join(d, f"compile_model_{platform}.json") if d else "")
        self.obs: list[list] = []        # [n_ops, seconds]
        # census-tagged observations [families dict, seconds] recorded by
        # the compile queue when graphlint is on: the raw material for the
        # per-family compile-cost terms (family_weights) that ride
        # ALONGSIDE the op-count power law in predict()
        self.fam_obs: list[list] = []
        self.boundary: list[float] = []
        # measured warm per-dispatch DEVICE seconds (runtime/devprof:
        # launch→ready, compile excluded) — the first real device-cost
        # feature in the split decision: an extra boundary re-dispatches
        # the downstream segment, so its measured device occupancy joins
        # the host-side boundary tax below
        self.device: list[float] = []
        # n_ops -> best-known LOWER BOUND seconds for compiles that have
        # not (yet) finished: a watchdog in the compile queue refreshes
        # this while a compile runs, so a compile that is killed /
        # wedges forever still teaches the model — without this, the
        # catastrophic compiles are exactly the ones the observation set
        # never contains (survivorship bias), and the fit extrapolated
        # from small finished compiles keeps predicting they are fine
        self.censored: dict[int, float] = {}
        self._fit: Optional[tuple] = None
        self._fam_fit: Optional[tuple] = None
        self._lock = threading.Lock()
        self._load()

    # -- persistence ----------------------------------------------------
    def _load(self) -> None:
        if not self.path or not os.path.exists(self.path):
            return
        try:
            with open(self.path) as fp:
                d = json.load(fp)
            self.obs = [o for o in d.get("obs", [])
                        if isinstance(o, list) and len(o) == 2][-_MAX_OBS:]
            self.fam_obs = [o for o in d.get("fam_obs", [])
                            if isinstance(o, list) and len(o) == 2
                            and isinstance(o[0], dict)][-_MAX_OBS:]
            self.boundary = [float(b) for b in
                             d.get("boundary", [])][-_MAX_OBS:]
            self.device = [float(b) for b in
                           d.get("device", [])][-_MAX_OBS:]
            self.censored = {int(k): float(v) for k, v in
                             d.get("censored", {}).items()}
        except Exception:   # pragma: no cover - corrupt model: start fresh
            self.obs, self.boundary, self.censored = [], [], {}
            self.device, self.fam_obs = [], []
        self._fit = None
        self._fam_fit = None

    def _save(self) -> None:
        if not self.path:
            return
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as fp:
                json.dump({"platform": self.platform, "updated": time.time(),
                           "obs": self.obs[-_MAX_OBS:],
                           "fam_obs": self.fam_obs[-_MAX_OBS:],
                           "boundary": self.boundary[-_MAX_OBS:],
                           "device": self.device[-_MAX_OBS:],
                           "censored": {str(k): v for k, v in
                                        self.censored.items()}}, fp)
            os.replace(tmp, self.path)
        except OSError:   # pragma: no cover - model persistence best-effort
            pass

    # -- recording ------------------------------------------------------
    def record_compile(self, n_ops: int, seconds: float,
                       families: Optional[dict] = None) -> None:
        if n_ops <= 0 or seconds <= 0:
            return
        with self._lock:
            self.obs.append([int(n_ops), float(seconds)])
            self.obs = self.obs[-_MAX_OBS:]
            if families:
                self.fam_obs.append([
                    {str(k): int(v) for k, v in families.items() if v},
                    float(seconds)])
                self.fam_obs = self.fam_obs[-_MAX_OBS:]
                self._fam_fit = None
            self._fit = None
            self._save()

    def record_running(self, n_ops: int, seconds_so_far: float) -> None:
        """Censored observation: a compile of `n_ops` has been running
        for `seconds_so_far` and is not done. Keeps the best lower bound
        per size; survives the process being killed mid-compile."""
        if n_ops <= 0 or seconds_so_far <= 0:
            return
        with self._lock:
            if seconds_so_far > self.censored.get(int(n_ops), 0.0):
                self.censored[int(n_ops)] = float(seconds_so_far)
                self._fit = None
                self._save()

    def record_boundary(self, seconds: float) -> None:
        if seconds <= 0:
            return
        with self._lock:
            self.boundary.append(float(seconds))
            self.boundary = self.boundary[-_MAX_OBS:]
            self._save()

    def record_device_dispatch(self, seconds: float) -> None:
        """Measured warm device seconds for one stage dispatch (devprof
        feeds the per-stage warm MEDIAN once per stage per process, so
        one chatty stage can't flood the window)."""
        if seconds <= 0:
            return
        with self._lock:
            self.device.append(float(seconds))
            self.device = self.device[-_MAX_OBS:]
            self._save()

    # -- prediction -----------------------------------------------------
    def _default_curve(self) -> tuple:
        return _DEFAULT_CURVE.get(self.platform, _DEFAULT_CURVE_ACCEL)

    def curve(self) -> tuple[tuple, bool]:
        """((a, b, c), fitted?) for t(n) = a + b * n^c. The fit is a
        2-parameter log-log least squares over per-size medians (the fixed
        term a is dropped once real data exists — it is inside the
        measurements), with censored lower-bound points (compiles that
        never finished) included as regular observations; the exponent
        clamps to [0.8, 3.0] so a couple of noisy points can't produce an
        absurd extrapolation."""
        with self._lock:
            if self._fit is not None:
                return self._fit
            by_n: dict[int, list[float]] = {}
            for n, s in self.obs:
                by_n.setdefault(int(n), []).append(float(s))
            max_done = max(by_n, default=0)
            for n, s in self.censored.items():
                # censored lower bounds join the fit only ABOVE the
                # finished-compile range: that is where survivorship bias
                # lives (big fused stages that never finish). A wedge at
                # a SMALL op count (XLA choking on one pathological fn
                # shape, not on size) must not bend the whole curve —
                # the per-fingerprint deadline marker handles those
                # (exec/compilequeue CompileTimeout negative cache).
                if int(n) > max_done and s > max(by_n.get(int(n), [0.0])):
                    by_n.setdefault(int(n), []).append(float(s))
            if len(by_n) >= 3:
                xs, ys = [], []
                for n, ss in by_n.items():
                    ss = sorted(ss)
                    med = ss[len(ss) // 2]
                    xs.append(math.log(max(n, 1)))
                    ys.append(math.log(max(med, 1e-4)))
                k = len(xs)
                mx, my = sum(xs) / k, sum(ys) / k
                den = sum((x - mx) ** 2 for x in xs)
                if den > 1e-9:
                    c = sum((x - mx) * (y - my)
                            for x, y in zip(xs, ys)) / den
                    c = min(3.0, max(0.8, c))
                    b = math.exp(my - c * mx)
                    self._fit = ((0.0, b, c), True)
                    return self._fit
            self._fit = (self._default_curve(), False)
            return self._fit

    def _max_observed_n(self) -> int:
        n = max((int(o[0]) for o in self.obs), default=0)
        return max(n, max(self.censored, default=0))

    def predict(self, n_ops: int) -> float:
        """Predicted compile seconds for a fused stage of `n_ops`
        operators. Beyond 1.5x the largest size ever observed the
        prediction never drops below the platform DEFAULT curve: a fit
        over small finished compiles must not extrapolate a regime change
        away (XLA's blowup on mega-fusions starts where the observations
        stop, precisely because those compiles don't finish)."""
        n_ops = max(int(n_ops), 1)
        (a, b, c), fitted = self.curve()
        pred = a + b * n_ops ** c
        if fitted and n_ops > 1.5 * max(self._max_observed_n(), 1):
            da, db, dc = self._default_curve()
            pred = max(pred, da + db * n_ops ** dc)
        # hard floor at censored lower bounds (compile time is monotone in
        # op count): a least-squares fit may pass BELOW a lower-bound
        # point. Same above-the-finished-range scoping as the fit.
        with self._lock:
            max_done = max((int(o[0]) for o in self.obs), default=0)
            for cn, cs in self.censored.items():
                if cn > max_done and n_ops >= cn:
                    pred = max(pred, cs)
        return pred

    # -- per-family construct terms (graphlint census) ------------------
    def family_weights(self) -> tuple[dict, bool]:
        """(per-family compile-seconds weights, fitted?). Fitted by ridge
        least squares over census-tagged compile observations (each one a
        primitive-family count vector from compiler/graphlint paired with
        the measured compile seconds) once >=6 are on record; before
        that, the graphlint seed weights calibrated offline against the
        bundled-pipeline corpus. Weights clamp non-negative — a family
        can't make a compile FASTER, and a noisy fit must not let e.g.
        scatters subsidize elementwise ops."""
        from ..compiler import graphlint as GL

        with self._lock:
            if self._fam_fit is not None:
                return self._fam_fit
            obs = list(self.fam_obs)
        fams = sorted({f for fam, _ in obs for f in fam})
        if len(obs) >= 6 and fams:
            try:
                import numpy as np

                A = np.array([[float(fam.get(f, 0)) for f in fams]
                              for fam, _ in obs])
                y = np.array([float(s) for _, s in obs])
                lam = 1e-3 * max(float((A * A).sum()), 1.0) / A.shape[1]
                w = np.linalg.solve(A.T @ A + lam * np.eye(len(fams)),
                                    A.T @ y)
                weights = dict(GL.FAMILY_WEIGHTS)
                for f, wf in zip(fams, w):
                    weights[f] = max(float(wf), 0.0)
                with self._lock:
                    self._fam_fit = (weights, True)
                return self._fam_fit
            except Exception:   # pragma: no cover - singular/odd census
                pass
        with self._lock:
            self._fam_fit = (dict(GL.FAMILY_WEIGHTS), False)
            return self._fam_fit

    def census_cost(self, families: dict) -> float:
        """Predicted compile seconds from the construct census alone:
        sum of per-family weights times counts. Rides ALONGSIDE the
        op-count power law in plan_split — two scatter-heavy ops can cost
        what twenty elementwise ops do, which op count can't see."""
        w, _ = self.family_weights()
        return sum(w.get(f, 0.0) * float(c) for f, c in families.items())

    def boundary_cost(self) -> float:
        """Measured per-boundary dispatch+transfer tax (median), or the
        platform default before any boundary has been observed."""
        with self._lock:
            if self.boundary:
                b = sorted(self.boundary)
                return b[len(b) // 2]
        return _DEFAULT_BOUNDARY.get(self.platform, _DEFAULT_BOUNDARY_ACCEL)

    def device_dispatch_cost(self) -> float:
        """The FIXED device-side cost of one extra dispatch, estimated
        as the smallest measured warm dispatch (runtime/devprof feeds
        per-stage warm medians); 0.0 before any measurement exists.
        Minimum, not median: a stage's occupancy is mostly compute that
        SPLITS with the stage — only the fixed part (launch, output
        round-trip, lost-fusion floor) is paid per extra boundary, and
        the cheapest observed dispatch is the best available proxy for
        it (an upper bound that tightens as small dispatches are
        observed)."""
        with self._lock:
            if self.device:
                return min(self.device)
        return 0.0


_MODELS: dict[str, CompileModel] = {}
_MODELS_LOCK = threading.Lock()


def model_for(platform: Optional[str] = None) -> CompileModel:
    if platform is None:
        from ..runtime.jaxcfg import jax

        platform = jax.default_backend()
    with _MODELS_LOCK:
        m = _MODELS.get(platform)
        if m is None:
            m = _MODELS[platform] = CompileModel(platform)
        return m


def reset_models() -> None:
    """Drop the singleton cache (tests repoint TUPLEX_COMPILE_MODEL_DIR)."""
    with _MODELS_LOCK:
        _MODELS.clear()


# ---------------------------------------------------------------------------
# the split decision
# ---------------------------------------------------------------------------

@dataclass
class SplitDecision:
    n_ops: int
    k: int                  # number of segments
    per: int                # max ops per segment
    predicted_compile_s: float   # summed over segments (serial; the compile
                                 # pool overlaps them, so wall is lower)
    boundary_s: float       # added per-boundary tax, (k-1) * unit cost
    budget_s: float         # tuplex.tpu.compileBudgetS (0 = unbounded)
    degrade: bool           # even the finest split blows the budget:
                            # compile on host CPU with device transfer
    fitted: bool            # curve came from measured points, not defaults
    reason: str = ""
    # op-index cut points (exclusive prefix lengths) when hazard costs
    # placed the boundaries; None = equal-size chunking by `per`
    boundaries: Optional[list] = None

    def describe(self) -> str:
        shape = (f"{self.n_ops} ops -> {self.k} segment(s) of <="
                 f"{self.per}")
        pred = (f"predicted compile {self.predicted_compile_s:.1f}s"
                f" ({'measured curve' if self.fitted else 'default curve'})"
                f", boundary tax {self.boundary_s:.2f}s")
        bud = f"budget {self.budget_s:.0f}s" if self.budget_s > 0 \
            else "no budget"
        tail = " — DEGRADED to host-CPU compile" if self.degrade else ""
        why = f" [{self.reason}]" if self.reason and not self.degrade else ""
        return f"stage-split tuner: {shape}; {pred}; {bud}{tail}{why}"


def _chunk_sizes(n: int, k: int) -> list[int]:
    per = math.ceil(n / k)
    sizes, left = [], n
    while left > 0:
        sizes.append(min(per, left))
        left -= per
    return sizes


def _weighted_chunks(costs: list, k: int) -> list:
    """Cut `costs` (per-op hazard costs) into <=k contiguous chunks with
    balanced COST (not count): the cut after op j lands where the cost
    prefix crosses the next 1/k-th of the total. Returns a list of
    exclusive cut indices (len k-1); every chunk keeps >=1 op."""
    n = len(costs)
    k = min(k, n)
    if k <= 1:
        return []
    total = sum(costs) or float(n)
    cuts, acc = [], 0.0
    for j, c in enumerate(costs):
        acc += c
        done = len(cuts)
        if done >= k - 1:
            break
        ops_left = n - (j + 1)
        chunks_left = k - done - 1
        if acc >= total * (done + 1) / k or ops_left <= chunks_left:
            cuts.append(j + 1)
    return cuts


def _cost_chunks(costs: list, k: int) -> list:
    """[(size, cost_sum)] for the k cost-balanced chunks of `costs`."""
    cuts = _weighted_chunks(costs, k)
    out, lo = [], 0
    for hi in cuts + [len(costs)]:
        out.append((hi - lo, sum(costs[lo:hi])))
        lo = hi
    return out


def plan_split(n_ops: int, budget_s: float,
               model: Optional[CompileModel] = None,
               max_segments: int = 32,
               prefer_fusion: bool = False,
               op_costs: Optional[list] = None) -> SplitDecision:
    """Pick the segment count for an `n_ops` fused stage.

    Minimizes predicted_compile + boundary tax over k; a positive
    `budget_s` is a ceiling on the predicted compile total — among the k
    that fit the budget the cheapest overall wins. With
    ``prefer_fusion=True`` (the CPU policy) the SMALLEST k that fits the
    budget wins instead: stage boundaries cost real memcpys there and the
    compile is a one-time cost the AOT artifact store amortizes away, so
    fusion is kept unless the predicted compile itself is pathological
    (flights' 43-op stage: >20 min / >120 GB on XLA:CPU). When nothing
    fits, the decision carries ``degrade=True`` with the cheapest split's
    numbers (what the accelerator WOULD cost): the physical planner then
    keeps the stage fused and pins its compile to the host CPU instead of
    the accelerator (_split_oversize).

    `op_costs` (compiler/graphlint: per-op construct-weighted compile
    seconds) rides ALONGSIDE the op-count curve: each candidate segment
    is predicted at max(power_law(size), hazard cost of its ops), and the
    chunk boundaries balance hazard COST rather than op count — two
    scatter-compaction ops can out-cost twenty elementwise ops, which op
    count alone can't see. When the hazard term (not the op-count curve)
    changes the chosen split, the decision says so (reason="hazard...")
    and carries the cost-balanced cut points in `boundaries`."""
    model = model or model_for()
    n_ops = max(int(n_ops), 1)
    if op_costs is not None and len(op_costs) != n_ops:
        # spread a mismatched cost vector evenly (e.g. census from a
        # traced fn whose op list was re-segmented since)
        tot = sum(op_costs)
        op_costs = [tot / n_ops] * n_ops
    # per-boundary unit tax: the host-side dispatch+transfer sample plus
    # the MEASURED device occupancy of one extra dispatch (devprof's warm
    # launch→ready median; 0.0 until a profiled run exists)
    bcost = model.boundary_cost() + model.device_dispatch_cost()
    (_, _, _), fitted = model.curve()

    def candidates(costs):
        cs = []
        for k in range(1, min(n_ops, max_segments) + 1):
            if costs is None:
                chunks = [(s, 0.0) for s in _chunk_sizes(n_ops, k)]
            else:
                chunks = _cost_chunks(costs, k)
            segs = [max(model.predict(s), c) for s, c in chunks]
            bnd = (len(chunks) - 1) * bcost
            cs.append((k, max(s for s, _ in chunks), sum(segs), bnd,
                       max(segs)))
        return cs

    def choose(cands, per_segment):
        # op-count mode: the budget caps the summed serial compile (the
        # historical contract). Hazard mode: construct cost is CONSERVED
        # by splitting (the scatters don't go away), so a total-sum cap
        # could never be met by any k — what splitting buys is smaller
        # compile UNITS, so the budget caps the worst single segment.
        def fits(c):
            return budget_s <= 0 or \
                (c[4] if per_segment else c[2]) <= budget_s
        in_budget = [c for c in cands if fits(c)]
        if in_budget:
            key = (lambda c: c[0]) if prefer_fusion \
                else (lambda c: c[2] + c[3])
            return min(in_budget, key=key), False
        return min(cands, key=lambda c: c[2]), True

    hazard = op_costs is not None
    (k, per, comp, bnd, _worst), over = choose(candidates(op_costs), hazard)
    reason = ""
    if over:
        reason = (f"finest split still predicts {comp:.0f}s compile "
                  f"> budget {budget_s:.0f}s")
    boundaries = None
    if hazard:
        (k0, _, _, _, _), over0 = choose(candidates(None), False)
        if k != k0 or over != over0:
            reason = (
                f"hazard: construct-weighted compile cost picked "
                f"{'degrade' if over else f'k={k}'} (op-count curve alone "
                f"picked {'degrade' if over0 else f'k={k0}'})")
        if k > 1:
            boundaries = _weighted_chunks(op_costs, k)
    return SplitDecision(n_ops, k, per, comp, bnd, budget_s,
                         degrade=over, fitted=fitted, reason=reason,
                         boundaries=boundaries)


def log_decision(dec: SplitDecision) -> None:
    from ..utils.logging import get_logger

    log = get_logger("plan")
    (log.warning if dec.degrade else log.info)("%s", dec.describe())
