"""`python -m tuplex_tpu compilestats <script.py>` — plan-time compile
forecast.

Runs the pipeline script with every DataSet ACTION stubbed out (collect/
take/show/tocsv/... capture the plan and return empty), plans each captured
action, and prints per stage: fused op count, jaxpr equation count, the
split tuner's predicted compile seconds (plan/splittuner.py — the measured
per-platform curve), and which stages would share one executable under the
content-addressed compile cache (exec/compilequeue.py fingerprints).

Unlike `lint` (purely syntactic, never imports the script), compilestats
MUST import the script to build its operator graph — sources are sniffed
and samples traced, but no stage executes and nothing compiles.
"""

from __future__ import annotations

import sys
from typing import Optional


def _capture_plans(script: str) -> list:
    """Import/run the script with actions stubbed; returns captured
    (action, sink_op, options_store) triples in call order."""
    import runpy

    from ..api.dataset import DataSet

    captured: list = []
    saved = {name: getattr(DataSet, name)
             for name in ("_execute", "_execute_partitions",
                          "tocsv", "toorc", "totuplex")}

    def fake_execute(self, limit: int):
        captured.append(("collect" if limit < 0 else f"take({limit})",
                         self._op, self._context.options_store))
        return []

    def fake_partitions(self, limit: int, output_sink=None):
        captured.append(("write", self._op, self._context.options_store))
        self._t_job = 0.0
        return []

    def fake_sink(self, path, *a, **kw):
        # capture WITHOUT creating an (empty) output file on disk
        captured.append((f"write({path!r})", self._op,
                         self._context.options_store))

    DataSet._execute = fake_execute
    DataSet._execute_partitions = fake_partitions
    DataSet.tocsv = DataSet.toorc = DataSet.totuplex = fake_sink
    try:
        runpy.run_path(script, run_name="__main__")
    finally:
        for name, fn in saved.items():
            setattr(DataSet, name, fn)
    return captured


def _stage_rows(stages, model) -> tuple[list, dict]:
    """Per-stage stat rows + fingerprint groups for one plan."""
    from ..plan.physical import TransformStage, stage_fingerprint
    from .planviz import stage_eqn_count

    rows = []
    by_fp: dict[str, list[int]] = {}
    for i, st in enumerate(stages):
        kind = type(st).__name__
        if not isinstance(st, TransformStage):
            rows.append({"i": i, "kind": kind, "n_ops": None})
            continue
        n_ops = len(st.ops)
        row = {"i": i, "kind": kind, "n_ops": n_ops,
               "key": st.key(),
               "interpreter": bool(st.force_interpret),
               "cpu_compile": bool(getattr(st, "cpu_compile", False))}
        if not st.force_interpret:
            row["eqns"] = stage_eqn_count(st)
            pred = getattr(st, "predicted_compile_s", None)
            row["predicted_s"] = float(pred) if pred is not None \
                else model.predict(n_ops)
            fp = stage_fingerprint(st)
            if fp is not None:
                row["fp"] = fp
                by_fp.setdefault(fp, []).append(i)
        dec = getattr(st, "split_decision", None)
        if dec is not None:
            row["split"] = dec.describe()
        rep = getattr(st, "graph_report", None)
        if rep is not None:
            row["hazard_score"] = float(min(rep.hazard_score, 1e9))
            row["findings"] = [f.line() for f in rep.findings]
            row["worst"] = rep.worst_severity()
        if getattr(st, "hazard_rule", None):
            row["hazard_rule"] = st.hazard_rule
        rows.append(row)
    return rows, {fp: ix for fp, ix in by_fp.items() if len(ix) > 1}


def _cost_line(entry: Optional[dict]) -> Optional[str]:
    """One human line from a devprof stage-index entry (runtime/devprof):
    the measured device-plane record a PREVIOUS run of this stage left in
    the AOT cache dir — ``stage.key()`` is content-derived, so planning
    the same script again computes the same key. Explicit about the two
    nothing-to-show cases instead of printing blanks."""
    from ..runtime import devprof

    if entry is None:
        return None     # never ran: the caller prints nothing extra
    ana = entry.get("analysis")
    if ana is None:
        return ("device analysis UNAVAILABLE (backend returned nothing; "
                "measured device "
                f"{entry.get('device_s_per_dispatch', 0.0) * 1e3:.1f} "
                "ms/dispatch)")
    cost = devprof.StageCost.from_dict(ana)
    bits = [devprof.fmt_flops(cost.flops),
            f"{devprof.fmt_bytes(cost.bytes_accessed)} accessed",
            f"peak {devprof.fmt_bytes(cost.peak_bytes)}"]
    ds = entry.get("device_s_per_dispatch")
    if ds:
        bits.append(f"device {ds * 1e3:.1f} ms/dispatch")
    rf = entry.get("roofline_frac")
    if rf:
        bits.append(f"roofline {rf * 100:.1f}%")
    if cost.partial:
        bits.append("(partial analysis)")
    return "measured cost: " + ", ".join(bits)


def lint_jaxprs(script: str, stream=None) -> tuple[int, int]:
    """`lint`'s jaxpr findings section: import the script with actions
    stubbed (same harness as compilestats — no stage executes, nothing
    compiles), plan each action, and print every graphlint finding the
    planner attached while vetting the stages. Returns
    ``(n_findings, n_wedge)`` so `lint --strict` can fail on
    wedge-severity jaxpr findings."""
    import sys as _sys

    from ..plan.physical import TransformStage, plan_stages

    stream = stream if stream is not None else _sys.stdout

    def emit(line=""):
        print(line, file=stream)

    from ..plan.physical import JoinStage

    captured = _capture_plans(script)
    n_findings = n_wedge = 0
    emitted_header = False
    for pi, (action, sink, options) in enumerate(captured):
        try:
            stages = plan_stages(sink, options)
        except Exception as e:
            emit(f"jaxpr findings: planning {action} failed: "
                 f"{type(e).__name__}: {e}")
            continue
        # join build sides plan lazily at execution time; vet them here
        # too (the flights airport wedge lives on one)
        labelled = [(str(i), st) for i, st in enumerate(stages)]
        for i, st in enumerate(stages):
            if isinstance(st, JoinStage):
                try:
                    labelled += [(f"{i}.build[{j}]", bs) for j, bs in
                                 enumerate(plan_stages(st.op.right,
                                                       options))]
                except Exception:
                    pass
        for i, st in labelled:
            if not isinstance(st, TransformStage):
                continue
            rep = getattr(st, "graph_report", None)
            if rep is None or not rep.findings:
                continue
            if not emitted_header:
                emit()
                emit("jaxpr findings (compiler/graphlint, post-trace "
                     "pre-compile):")
                emitted_header = True
            ops = ",".join(type(o).__name__ for o in st.ops)
            emit(f"  plan {pi + 1} ({action}) stage {i} [{ops}] — "
                 f"hazard score {min(rep.hazard_score, 1e9):.1f}s")
            for f in rep.findings:
                emit(f"    {f.line()}")
                n_findings += 1
                if f.severity == "wedge":
                    n_wedge += 1
            if getattr(st, "hazard_rule", None):
                emit(f"    -> pre-degraded to the interpreter "
                     f"(rule {st.hazard_rule})")
    if emitted_header:
        emit()
        emit(f"jaxpr findings: {n_findings} finding(s), "
             f"{n_wedge} wedge-severity")
    return n_findings, n_wedge


def main(script: str, platform: Optional[str] = None) -> int:
    from ..plan.physical import plan_stages
    from ..plan.splittuner import model_for
    from ..runtime import devprof

    try:
        captured = _capture_plans(script)
    except SystemExit as e:
        if e.code not in (0, None):
            print(f"compilestats: script exited with {e.code}",
                  file=sys.stderr)
            return 2
        captured = []
    if not captured:
        print("compilestats: the script ran no DataSet action "
              "(collect/take/show/tocsv/...)", file=sys.stderr)
        return 1

    model = model_for(platform)
    (_, _, curve_c), fitted = model.curve()
    dev_cost = model.device_dispatch_cost()
    print(f"compile model: platform={model.platform} "
          f"{'measured curve' if fitted else 'default curve'} "
          f"(exponent {curve_c:.2f}), "
          f"boundary cost {model.boundary_cost() * 1e3:.1f} ms"
          + (f", device dispatch {dev_cost * 1e3:.1f} ms (measured)"
             if dev_cost > 0 else ""))
    cost_index = devprof.load_stage_index()
    rc = 0
    for pi, (action, sink, options) in enumerate(captured):
        print(f"\nplan {pi + 1} ({action}):")
        try:
            stages = plan_stages(sink, options)
        except Exception as e:
            print(f"  planning failed: {type(e).__name__}: {e}")
            rc = 1
            continue
        rows, dedup = _stage_rows(stages, model)
        total = 0.0
        for row in rows:
            head = f"  stage {row['i']} [{row['kind']}]"
            if row["n_ops"] is None:
                print(f"{head}: pipeline breaker")
                continue
            bits = [f"{row['n_ops']} ops"]
            if row.get("eqns") is not None:
                bits.append(f"{row['eqns']} jaxpr eqns")
            if row.get("interpreter"):
                bits.append("interpreter segment (no compile)")
            elif row.get("cpu_compile"):
                bits.append("host-CPU compile (budget degrade)")
            if row.get("predicted_s") is not None \
                    and not row.get("interpreter"):
                bits.append(f"predicted compile {row['predicted_s']:.1f}s")
                total += row["predicted_s"]
            print(f"{head}: {', '.join(bits)}")
            if row.get("split"):
                print(f"    {row['split']}")
            if row.get("hazard_rule"):
                print(f"    HAZARD: pre-degraded to the interpreter "
                      f"(rule {row['hazard_rule']})")
            elif row.get("hazard_score") is not None:
                hline = (f"    hazard score "
                         f"{row['hazard_score']:.1f}s")
                n_find = len(row.get("findings") or ())
                if n_find:
                    hline += f", {n_find} jaxpr finding(s)"
                print(hline)
            if not row.get("interpreter"):
                cl = _cost_line(cost_index.get(row.get("key", "")))
                if cl:
                    print(f"    {cl}")
        saved = 0.0
        by_i = {r["i"]: r for r in rows}
        for fp, ix in dedup.items():
            dupes = ix[1:]
            saved += sum(r["predicted_s"] for r in rows
                         if r["i"] in dupes and r.get("predicted_s"))
            print(f"  dedup: stages {ix} share one executable "
                  f"(fingerprint {fp[:12]}…)")
            # the shared executable's measured device-plane cost (any
            # member's index entry — they dedup to one compile)
            gl = next((cl for i2 in ix
                       if (cl := _cost_line(cost_index.get(
                           by_i.get(i2, {}).get("key", ""))))), None)
            if gl:
                print(f"    group {gl}")
            else:
                print("    group cost: no record yet (stages never ran "
                      "with devprof on)")
        budget = options.get_float("tuplex.tpu.compileBudgetS", 480.0)
        line = (f"  predicted compile total: {total:.1f}s serial"
                + (f", {total - saved:.1f}s after dedup" if saved else ""))
        if budget > 0:
            line += (f"; budget {budget:.0f}s -> "
                     + ("fits" if total - saved <= budget else "OVER"))
        print(line)
    return rc
