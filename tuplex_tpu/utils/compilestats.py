"""`python -m tuplex_tpu compilestats <script.py>` — plan-time compile
forecast.

Runs the pipeline script with every DataSet ACTION stubbed out (collect/
take/show/tocsv/... capture the plan and return empty), plans each captured
action, and prints per stage: fused op count, jaxpr equation count, the
split tuner's predicted compile seconds (plan/splittuner.py — the measured
per-platform curve), and which stages would share one executable under the
content-addressed compile cache (exec/compilequeue.py fingerprints).

Unlike `lint` (purely syntactic, never imports the script), compilestats
MUST import the script to build its operator graph — sources are sniffed
and samples traced, but no stage executes and nothing compiles.
"""

from __future__ import annotations

import sys
from typing import Optional


def _capture_plans(script: str) -> list:
    """Import/run the script with actions stubbed; returns captured
    (action, sink_op, options_store) triples in call order."""
    import runpy

    from ..api.dataset import DataSet

    captured: list = []
    saved = {name: getattr(DataSet, name)
             for name in ("_execute", "_execute_partitions",
                          "tocsv", "toorc", "totuplex")}

    def fake_execute(self, limit: int):
        captured.append(("collect" if limit < 0 else f"take({limit})",
                         self._op, self._context.options_store))
        return []

    def fake_partitions(self, limit: int, output_sink=None):
        captured.append(("write", self._op, self._context.options_store))
        self._t_job = 0.0
        return []

    def fake_sink(self, path, *a, **kw):
        # capture WITHOUT creating an (empty) output file on disk
        captured.append((f"write({path!r})", self._op,
                         self._context.options_store))

    DataSet._execute = fake_execute
    DataSet._execute_partitions = fake_partitions
    DataSet.tocsv = DataSet.toorc = DataSet.totuplex = fake_sink
    try:
        runpy.run_path(script, run_name="__main__")
    finally:
        for name, fn in saved.items():
            setattr(DataSet, name, fn)
    return captured


def _stage_rows(stages, model) -> tuple[list, dict]:
    """Per-stage stat rows + fingerprint groups for one plan."""
    from ..plan.physical import TransformStage, stage_fingerprint
    from .planviz import stage_eqn_count

    rows = []
    by_fp: dict[str, list[int]] = {}
    for i, st in enumerate(stages):
        kind = type(st).__name__
        if not isinstance(st, TransformStage):
            rows.append({"i": i, "kind": kind, "n_ops": None})
            continue
        n_ops = len(st.ops)
        row = {"i": i, "kind": kind, "n_ops": n_ops,
               "key": st.key(),
               "interpreter": bool(st.force_interpret),
               "cpu_compile": bool(getattr(st, "cpu_compile", False))}
        if not st.force_interpret:
            row["eqns"] = stage_eqn_count(st)
            pred = getattr(st, "predicted_compile_s", None)
            row["predicted_s"] = float(pred) if pred is not None \
                else model.predict(n_ops)
            fp = stage_fingerprint(st)
            if fp is not None:
                row["fp"] = fp
                by_fp.setdefault(fp, []).append(i)
        dec = getattr(st, "split_decision", None)
        if dec is not None:
            row["split"] = dec.describe()
        rows.append(row)
    return rows, {fp: ix for fp, ix in by_fp.items() if len(ix) > 1}


def _cost_line(entry: Optional[dict]) -> Optional[str]:
    """One human line from a devprof stage-index entry (runtime/devprof):
    the measured device-plane record a PREVIOUS run of this stage left in
    the AOT cache dir — ``stage.key()`` is content-derived, so planning
    the same script again computes the same key. Explicit about the two
    nothing-to-show cases instead of printing blanks."""
    from ..runtime import devprof

    if entry is None:
        return None     # never ran: the caller prints nothing extra
    ana = entry.get("analysis")
    if ana is None:
        return ("device analysis UNAVAILABLE (backend returned nothing; "
                "measured device "
                f"{entry.get('device_s_per_dispatch', 0.0) * 1e3:.1f} "
                "ms/dispatch)")
    cost = devprof.StageCost.from_dict(ana)
    bits = [devprof.fmt_flops(cost.flops),
            f"{devprof.fmt_bytes(cost.bytes_accessed)} accessed",
            f"peak {devprof.fmt_bytes(cost.peak_bytes)}"]
    ds = entry.get("device_s_per_dispatch")
    if ds:
        bits.append(f"device {ds * 1e3:.1f} ms/dispatch")
    rf = entry.get("roofline_frac")
    if rf:
        bits.append(f"roofline {rf * 100:.1f}%")
    if cost.partial:
        bits.append("(partial analysis)")
    return "measured cost: " + ", ".join(bits)


def main(script: str, platform: Optional[str] = None) -> int:
    from ..plan.physical import plan_stages
    from ..plan.splittuner import model_for
    from ..runtime import devprof

    try:
        captured = _capture_plans(script)
    except SystemExit as e:
        if e.code not in (0, None):
            print(f"compilestats: script exited with {e.code}",
                  file=sys.stderr)
            return 2
        captured = []
    if not captured:
        print("compilestats: the script ran no DataSet action "
              "(collect/take/show/tocsv/...)", file=sys.stderr)
        return 1

    model = model_for(platform)
    (_, _, curve_c), fitted = model.curve()
    dev_cost = model.device_dispatch_cost()
    print(f"compile model: platform={model.platform} "
          f"{'measured curve' if fitted else 'default curve'} "
          f"(exponent {curve_c:.2f}), "
          f"boundary cost {model.boundary_cost() * 1e3:.1f} ms"
          + (f", device dispatch {dev_cost * 1e3:.1f} ms (measured)"
             if dev_cost > 0 else ""))
    cost_index = devprof.load_stage_index()
    rc = 0
    for pi, (action, sink, options) in enumerate(captured):
        print(f"\nplan {pi + 1} ({action}):")
        try:
            stages = plan_stages(sink, options)
        except Exception as e:
            print(f"  planning failed: {type(e).__name__}: {e}")
            rc = 1
            continue
        rows, dedup = _stage_rows(stages, model)
        total = 0.0
        for row in rows:
            head = f"  stage {row['i']} [{row['kind']}]"
            if row["n_ops"] is None:
                print(f"{head}: pipeline breaker")
                continue
            bits = [f"{row['n_ops']} ops"]
            if row.get("eqns") is not None:
                bits.append(f"{row['eqns']} jaxpr eqns")
            if row.get("interpreter"):
                bits.append("interpreter segment (no compile)")
            elif row.get("cpu_compile"):
                bits.append("host-CPU compile (budget degrade)")
            if row.get("predicted_s") is not None \
                    and not row.get("interpreter"):
                bits.append(f"predicted compile {row['predicted_s']:.1f}s")
                total += row["predicted_s"]
            print(f"{head}: {', '.join(bits)}")
            if row.get("split"):
                print(f"    {row['split']}")
            if not row.get("interpreter"):
                cl = _cost_line(cost_index.get(row.get("key", "")))
                if cl:
                    print(f"    {cl}")
        saved = 0.0
        by_i = {r["i"]: r for r in rows}
        for fp, ix in dedup.items():
            dupes = ix[1:]
            saved += sum(r["predicted_s"] for r in rows
                         if r["i"] in dupes and r.get("predicted_s"))
            print(f"  dedup: stages {ix} share one executable "
                  f"(fingerprint {fp[:12]}…)")
            # the shared executable's measured device-plane cost (any
            # member's index entry — they dedup to one compile)
            gl = next((cl for i2 in ix
                       if (cl := _cost_line(cost_index.get(
                           by_i.get(i2, {}).get("key", ""))))), None)
            if gl:
                print(f"    group {gl}")
            else:
                print("    group cost: no record yet (stages never ran "
                      "with devprof on)")
        budget = options.get_float("tuplex.tpu.compileBudgetS", 480.0)
        line = (f"  predicted compile total: {total:.1f}s serial"
                + (f", {total - saved:.1f}s after dedup" if saved else ""))
        if budget > 0:
            line += (f"; budget {budget:.0f}s -> "
                     + ("fits" if total - saved <= budget else "OVER"))
        print(line)
    return rc
