"""Bounded LRU dict for plan-time memo tables.

Several cross-job memos (sample rows, inferred schemas, branch profiles,
UDF analysis reports, compile-probe verdicts) used the same eviction
anti-pattern: grow to a cap, then ``.clear()`` wholesale — one insert past
the cap dropped EVERY warm entry, so a steady-state workload re-ran its
whole sample/analysis corpus every few hundred plans. ``LruDict`` keeps
the hot set: reads refresh recency, inserts evict only the single oldest
entry (reference analog: the JITCompiler executable cache is an LRU for
exactly this reason, JitCache in exec/local.py)."""

from __future__ import annotations

from collections import OrderedDict

_MISSING = object()


class LruDict:
    """Minimal LRU mapping. Not thread-safe by itself; the plan-time memos
    it backs are only touched under the GIL from planning code."""

    __slots__ = ("_store", "capacity")

    def __init__(self, capacity: int = 256):
        if capacity <= 0:
            raise ValueError("LruDict capacity must be positive")
        self._store: OrderedDict = OrderedDict()
        self.capacity = capacity

    def get(self, key, default=None):
        v = self._store.get(key, _MISSING)
        if v is _MISSING:
            return default
        self._store.move_to_end(key)
        return v

    def __getitem__(self, key):
        v = self._store[key]
        self._store.move_to_end(key)
        return v

    def __setitem__(self, key, value) -> None:
        self._store[key] = value
        self._store.move_to_end(key)
        while len(self._store) > self.capacity:
            self._store.popitem(last=False)

    def __contains__(self, key) -> bool:
        return key in self._store

    def __len__(self) -> int:
        return len(self._store)

    def pop(self, key, default=None):
        return self._store.pop(key, default)

    def clear(self) -> None:
        self._store.clear()

    def keys(self):
        return self._store.keys()
