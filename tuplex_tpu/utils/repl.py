"""REPL/notebook detection + UDF traceback cleanup (reference:
python/tuplex/repl/ shell detection, utils/tracebacks.py — strip framework
frames so a failing UDF shows the USER's code, not the engine's)."""

from __future__ import annotations

import os
import sys
import traceback


def in_google_colab() -> bool:
    return "google.colab" in sys.modules


def in_jupyter_notebook() -> bool:
    try:
        shell = get_ipython().__class__.__name__  # type: ignore[name-defined]
        return shell == "ZMQInteractiveShell"
    except NameError:
        return False


def in_interactive_shell() -> bool:
    """True in any REPL: plain `python`, IPython terminal, or a notebook."""
    if hasattr(sys, "ps1"):
        return True
    try:
        get_ipython()  # type: ignore[name-defined]
        return True
    except NameError:
        return False


_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def clean_udf_traceback(exc: BaseException) -> str:
    """Format an exception with framework-internal frames removed, so the
    trace reads from the user's UDF down (reference: tracebacks.py)."""
    frames = traceback.extract_tb(exc.__traceback__)
    kept = [f for f in frames
            if not os.path.abspath(f.filename).startswith(_PKG_DIR + os.sep)
            and not f.filename.startswith("<tpx-")]   # generated pipeline
    if not kept:          # error raised wholly inside the framework
        kept = frames
    lines = ["Traceback (most recent call last):\n"]
    lines += traceback.format_list(kept)
    lines += traceback.format_exception_only(type(exc), exc)
    return "".join(lines)
