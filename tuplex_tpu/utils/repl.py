"""REPL/notebook detection + UDF traceback cleanup (reference:
python/tuplex/repl/ shell detection, utils/tracebacks.py — strip framework
frames so a failing UDF shows the USER's code, not the engine's)."""

from __future__ import annotations

import os
import sys
import traceback


def in_google_colab() -> bool:
    return "google.colab" in sys.modules


def in_jupyter_notebook() -> bool:
    try:
        shell = get_ipython().__class__.__name__  # type: ignore[name-defined]
        return shell == "ZMQInteractiveShell"
    except NameError:
        return False


def in_interactive_shell() -> bool:
    """True in any REPL: plain `python`, IPython terminal, or a notebook."""
    if hasattr(sys, "ps1"):
        return True
    try:
        get_ipython()  # type: ignore[name-defined]
        return True
    except NameError:
        return False


_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def clean_udf_traceback(exc: BaseException) -> str:
    """Format an exception with framework-internal frames removed, so the
    trace reads from the user's UDF down (reference: tracebacks.py)."""
    frames = traceback.extract_tb(exc.__traceback__)
    kept = [f for f in frames
            if not os.path.abspath(f.filename).startswith(_PKG_DIR + os.sep)
            and not f.filename.startswith("<tpx-")]   # generated pipeline
    if not kept:          # error raised wholly inside the framework
        kept = frames
    lines = ["Traceback (most recent call last):\n"]
    lines += traceback.format_list(kept)
    lines += traceback.format_exception_only(type(exc), exc)
    return "".join(lines)


# ---------------------------------------------------------------------------
# interactive shell + completion (reference: python/tuplex/utils/
# interactive_shell.py + jedi_completer.py — theirs builds on
# prompt_toolkit; this redesign uses stdlib readline + code so the shell
# works in minimal environments, with jedi supplying the completions)
# ---------------------------------------------------------------------------

class JediCompleter:
    """readline-style completer over a live namespace via jedi.

    Usage: readline.set_completer(JediCompleter(lambda: ns).complete)
    """

    def __init__(self, get_locals):
        self._get_locals = get_locals
        self._matches: list[str] = []

    def _jedi_completions(self, line: str):
        """jedi completion objects for the buffer, or None if jedi is
        missing."""
        try:
            from jedi import Interpreter, settings
        except ImportError:
            return None
        prev = settings.case_insensitive_completion
        settings.case_insensitive_completion = False
        try:
            interp = Interpreter(line, [self._get_locals()])
            comps = (interp.complete() if hasattr(interp, "complete")
                     else interp.completions())
            return [c for c in comps
                    if not c.name_with_symbols.startswith("_")]
        except Exception:
            return []
        finally:
            settings.case_insensitive_completion = prev

    def _complete_line(self, line: str) -> list[str]:
        comps = self._jedi_completions(line)
        if comps is None:
            return self._stdlib_complete(line.split()[-1] if line.split()
                                         else line)
        return [c.name_with_symbols for c in comps]

    def _stdlib_complete(self, token: str) -> list[str]:
        """rlcompleter fallback when jedi isn't installed. `token` is the
        readline word under the cursor (already delimiter-split)."""
        import rlcompleter

        comp = rlcompleter.Completer(self._get_locals())
        out, i = [], 0
        while True:
            m = comp.complete(token, i)
            if m is None:
                break
            out.append(m)
            i += 1
        return out

    def complete(self, text: str, state: int):
        """readline entry point. `text` is the delimiter-split word under
        the cursor (for `c.cs<TAB>` readline passes 'cs' — '.' is a
        delimiter); each candidate must be a replacement for that word, so
        jedi's remaining-suffix (`c.complete`) is appended to `text`."""
        import readline

        if state == 0:
            buf = readline.get_line_buffer()[:readline.get_endidx()]
            comps = self._jedi_completions(buf)
            if comps is None:
                self._matches = self._stdlib_complete(text)
            else:
                self._matches = [text + c.complete for c in comps]
        return self._matches[state] if state < len(self._matches) else None


def interactive_shell(banner: str | None = None):
    """Start a REPL with a ready `Context` and jedi tab-completion
    (reference: interactive_shell.py TuplexShell)."""
    import code

    import tuplex_tpu

    ns: dict = {"Context": tuplex_tpu.Context, "tuplex": tuplex_tpu}
    try:
        import readline

        readline.set_completer(JediCompleter(lambda: ns).complete)
        readline.parse_and_bind("tab: complete")
    except ImportError:
        pass
    if banner is None:
        banner = ("tuplex_tpu interactive shell — `c = Context()` to begin; "
                  "tab completes")
    code.interact(banner=banner, local=ns)
