"""Logging with per-subsystem handlers + python-logging redirect.

Reference: utils/src/Logger.cc (spdlog, per-subsystem MessageHandlers) and
tuplex.redirectToPythonLogging (context.py:190-200, PythonCommon.cc).
"""

from __future__ import annotations

import logging
import sys
import time
from typing import Optional

_ROOT = "tuplex_tpu"
_configured = False


def get_logger(subsystem: str = "") -> logging.Logger:
    global _configured
    name = f"{_ROOT}.{subsystem}" if subsystem else _ROOT
    logger = logging.getLogger(name)
    if not _configured:
        root = logging.getLogger(_ROOT)
        if not root.handlers:
            h = logging.StreamHandler(sys.stderr)
            h.setFormatter(logging.Formatter(
                "[%(asctime)s] [%(name)s] [%(levelname)s] %(message)s",
                datefmt="%H:%M:%S"))
            root.addHandler(h)
            root.setLevel(logging.WARNING)
        _configured = True
    return logger


def redirect_to_python_logging(enable: bool = True) -> None:
    """With redirect on, messages propagate to the user's root logger
    unchanged (reference: tuplex.redirectToPythonLogging)."""
    root = logging.getLogger(_ROOT)
    root.propagate = bool(enable)
    for h in list(root.handlers):
        if enable:
            root.removeHandler(h)


def set_level(level: str) -> None:
    logging.getLogger(_ROOT).setLevel(level.upper())


class Timer:
    """Scope timer (reference: utils Timer.h)."""

    def __init__(self):
        self.start = time.perf_counter()

    def elapsed(self) -> float:
        return time.perf_counter() - self.start
