"""Plan visualization + codegen stats.

Reference: core/src/Context.cc:171 visualizeOperationGraph (GraphVizBuilder
→ PDF behind the GENERATE_PDFS cmake option) and
codegen/include/InstructionCountPass.h (per-stage generated-instruction
counts behind tuplex.optimizer.codeStats). The TPU redesign emits graphviz
DOT text directly (no graphviz binary needed to inspect it) and counts
jaxpr equations instead of LLVM instructions.
"""

from __future__ import annotations

from typing import Optional


def _op_label(op) -> str:
    name = type(op).__name__.replace("Operator", "")
    bits = [name]
    col = getattr(op, "column", None)
    if col:
        bits.append(repr(col))
    udf = getattr(op, "udf", None)
    if udf is not None and udf.source:
        src = udf.source.replace('"', "'")
        if len(src) > 40:
            src = src[:37] + "..."
        bits.append(src)
    return "\\n".join(bits)


def plan_to_dot(sink) -> str:
    """Operator DAG as graphviz DOT text (render with `dot -Tpdf` if
    graphviz is installed; the text itself is the artifact)."""
    lines = ["digraph plan {", "  rankdir=BT;",
             '  node [shape=box, fontname="monospace", fontsize=10];']
    seen: set[int] = set()

    def walk(op):
        if op.id in seen:
            return
        seen.add(op.id)
        lines.append(f'  n{op.id} [label="#{op.id} {_op_label(op)}"];')
        for p in op.parents:
            walk(p)
            lines.append(f"  n{p.id} -> n{op.id};")

    walk(sink)
    lines.append("}")
    return "\n".join(lines)


def explain(sink, options=None, lint: bool = False) -> str:
    """Human-readable physical plan: stages, fused operators, and (when
    tuplex.optimizer.codeStats is on) per-stage jaxpr equation counts —
    the reference logs the same shape at LocalBackend.cc:932-949.
    `lint=True` appends each stage's UDF static-analysis reports and
    possible row error codes (compiler/analyzer.py)."""
    from ..plan.physical import plan_stages

    stages = plan_stages(sink, options)
    out = []
    code_stats = options is not None and options.get_bool(
        "tuplex.optimizer.codeStats", False)
    for i, st in enumerate(stages):
        kind = type(st).__name__
        ops = getattr(st, "ops", [])
        head = f"Stage {i} [{kind}]"
        if getattr(st, "force_interpret", False):
            head += " (interpreter segment)"
        out.append(head)
        reason = getattr(st, "route_reason", "")
        if reason:
            out.append(f"  routed: {reason}")
        src = getattr(st, "source", None)
        if src is not None:
            out.append(f"  source: {type(src).__name__.replace('Operator', '')}")
        for op in ops:
            out.append(f"  - #{op.id} {_op_label(op).replace(chr(92)+'n', ' ')}")
        if code_stats and hasattr(st, "build_device_fn"):
            n = stage_eqn_count(st)
            if n is not None:
                out.append(f"  codegen: {n} jaxpr equations (fast path)")
        # static-vetting verdict (compiler/graphlint): the planner leaves
        # its GraphReport on every vetted stage — surface the hazard
        # score and any named findings, plus the pre-degrade decision
        rep = getattr(st, "graph_report", None)
        if rep is not None:
            out.append(f"  hazard score: "
                       f"{min(rep.hazard_score, 1e9):.1f}s predicted "
                       f"compile")
            for f in rep.findings:
                out.append(f"  jaxpr: {f.line()}")
        rule = getattr(st, "hazard_rule", None)
        if rule:
            out.append(f"  pre-degraded to the interpreter "
                       f"(graphlint rule {rule})")
        if lint and hasattr(st, "udf_reports"):
            reports = st.udf_reports()
            if reports:
                out.append("  lint:")
                for op, attr, rep in reports:
                    lines = rep.format(indent="    ")
                    if attr != "udf":
                        lines[0] = f"{lines[0]} [{attr}]"
                    out.extend(lines)
            dead = getattr(st, "dead_resolver_findings", None)
            if dead is not None:
                for rop, gop, reason in dead():
                    out.append(f"  lint: #{rop.id} {reason} "
                               f"(guards #{gop.id})")
            codes = st.possible_exception_codes()
            if codes:
                out.append("  possible row error codes: "
                           + ", ".join(c.name for c in codes))
            sug = getattr(st, "resolver_suggestions", None)
            if sug is not None:
                for s in sug():
                    out.append(f"  suggestion: {s}")
            rp = getattr(st, "resolve_plan", None)
            if rp is not None:
                # the plan-time tier verdict (plan/physical.ResolvePlan):
                # which resolve machinery this stage can ever need
                out.append(f"  resolve tier: {rp().tier}")
    return "\n".join(out)


def stage_eqn_count(stage) -> Optional[int]:
    """Total jaxpr equations of the stage's fast-path fn over an abstract
    8-row batch (InstructionCountPass analog — a size proxy, not a cost)."""
    try:
        from ..plan.physical import abstract_batch_arrays
        from ..runtime.jaxcfg import jax

        arrays = abstract_batch_arrays(stage.input_schema)
        if arrays is None:
            return None
        fn = stage.build_device_fn()
        jaxpr = jax.make_jaxpr(fn)(arrays)
        count = 0

        def walk(jx):
            nonlocal count
            for eq in jx.eqns:
                count += 1
                for p in eq.params.values():
                    if hasattr(p, "jaxpr"):
                        walk(p.jaxpr)

        walk(jaxpr.jaxpr)
        return count
    except Exception:
        return None
