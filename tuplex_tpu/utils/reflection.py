"""UDF source extraction + closure capture.

Re-designs the reference's reflection/source-vault machinery
(reference: python/tuplex/utils/reflection.py:156 get_function_code,
source_vault.py:129, globs.py) without the vault indirection: we parse the
defining source with `inspect` + `ast`, slice out the exact lambda when several
share a line, and capture referenced globals/closure cells.

The compiled path only needs the AST + captured constants; the interpreter
fallback calls the live function object directly, so (unlike the reference) we
never need to re-materialize code from source.
"""

from __future__ import annotations

import ast
import inspect
import textwrap
import types
from typing import Any, Callable


class UDFSource:
    __slots__ = ("func", "source", "tree", "globals", "name")

    def __init__(self, func: Callable, source: str, tree: ast.AST,
                 globs: dict[str, Any], name: str):
        self.func = func
        self.source = source          # normalized source ("lambda x: ..." / "def f...")
        self.tree = tree              # ast.Lambda or ast.FunctionDef
        self.globals = globs          # captured globals + closure cells (by name)
        self.name = name

    @property
    def params(self) -> list[str]:
        args = self.tree.args  # type: ignore[attr-defined]
        return [a.arg for a in args.args]


def _code_fingerprint(code: types.CodeType) -> tuple:
    """Location-independent fingerprint of a code object (bytecode + const
    structure), so identical-looking lambdas at different columns differ only
    if their bodies differ."""
    consts = tuple(
        _code_fingerprint(c) if isinstance(c, types.CodeType) else c
        for c in code.co_consts
    )
    return (code.co_code, consts, code.co_names, code.co_varnames[: code.co_argcount])


def _find_lambda_node(tree: ast.AST, func: types.FunctionType) -> ast.Lambda | None:
    """Pick the lambda node matching `func` when a line holds several, by
    compiling each candidate and comparing bytecode fingerprints (reference:
    source_vault disambiguates via code-object comparison,
    python/tuplex/utils/source_vault.py:129)."""
    lambdas = [n for n in ast.walk(tree) if isinstance(n, ast.Lambda)]
    if not lambdas:
        return None
    if len(lambdas) == 1:
        return lambdas[0]
    want = _code_fingerprint(func.__code__)
    matched: list[ast.Lambda] = []
    for n in lambdas:
        try:
            expr = ast.Expression(body=n)
            ast.fix_missing_locations(expr)
            compiled = compile(expr, "<udf>", "eval")
            lam_code = next(
                c for c in compiled.co_consts if isinstance(c, types.CodeType)
            )
            if _code_fingerprint(lam_code) == want:
                matched.append(n)
        except (SyntaxError, ValueError, StopIteration):
            continue
    if matched:
        return matched[0]  # identical fingerprints => identical behavior
    # last resort: argument-name match, then position order
    want_args = func.__code__.co_varnames[: func.__code__.co_argcount]
    pool = [
        n for n in lambdas if tuple(a.arg for a in n.args.args) == tuple(want_args)
    ] or lambdas
    pool.sort(key=lambda n: (n.lineno, n.col_offset))
    return pool[0]


def get_udf_source(func: Callable) -> UDFSource:
    """Extract normalized source + AST + captured globals for a UDF."""
    if not callable(func):
        raise TypeError(f"UDF must be callable, got {type(func)}")
    if not isinstance(func, types.FunctionType):
        # builtins / callables: no source — interpreter-only UDF
        return UDFSource(func, "", ast.Lambda(
            args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                               kw_defaults=[], defaults=[]),
            body=ast.Constant(value=None)), {}, getattr(func, "__name__", "<callable>"))

    try:
        raw = inspect.getsource(func)
    except (OSError, TypeError):
        raw = ""

    tree_node: ast.AST | None = None
    source = raw
    if raw:
        dedented = textwrap.dedent(raw)
        try:
            mod = ast.parse(dedented)
        except SyntaxError:
            # e.g. source slice starts mid-expression: `.map(lambda x: x)` —
            # retry after trimming to the first `lambda`/`def`
            for kw in ("lambda", "def "):
                idx = dedented.find(kw)
                if idx >= 0:
                    frag = dedented[idx:].rstrip()
                    while frag:
                        try:
                            mod = ast.parse(frag)
                            break
                        except SyntaxError:
                            frag = frag[:-1]
                    else:
                        mod = None
                    if mod is not None:
                        break
            else:
                mod = None
        if mod is not None:
            if func.__name__ == "<lambda>":
                tree_node = _find_lambda_node(mod, func)
                if tree_node is not None:
                    source = ast.unparse(tree_node)
            else:
                for n in ast.walk(mod):
                    if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) and \
                            n.name == func.__name__:
                        tree_node = n
                        source = ast.unparse(n)
                        break

    globs = capture_globals(func)
    if tree_node is None:
        # no retrievable source (stdin/REPL without history): interpreter-only
        # UDF, but keep real param names so schema hinting still works
        source = ""
        tree_node = _dummy(func.__code__.co_varnames[: func.__code__.co_argcount])
    return UDFSource(func, source, tree_node, globs, func.__name__)


def _dummy(params: tuple[str, ...] = ()) -> ast.Lambda:
    return ast.Lambda(
        args=ast.arguments(posonlyargs=[],
                           args=[ast.arg(arg=p) for p in params],
                           kwonlyargs=[], kw_defaults=[], defaults=[]),
        body=ast.Constant(value=None))


def capture_globals(func: types.FunctionType) -> dict[str, Any]:
    """Names the function references resolved from its globals and closure
    (reference: python/tuplex/utils/globs.py)."""
    out: dict[str, Any] = {}

    def walk_names(code: types.CodeType) -> set[str]:
        names = set(code.co_names)
        for c in code.co_consts:
            if isinstance(c, types.CodeType):  # nested lambdas/comprehensions
                names |= walk_names(c)
        return names

    g = func.__globals__
    for name in walk_names(func.__code__):
        if name in g:
            out[name] = g[name]
    if func.__closure__:
        for name, cell in zip(func.__code__.co_freevars, func.__closure__):
            try:
                out[name] = cell.cell_contents
            except ValueError:
                pass
    return out
