"""UDF source extraction + closure capture.

Re-designs the reference's reflection/source-vault machinery
(reference: python/tuplex/utils/reflection.py:156 get_function_code,
source_vault.py:129, globs.py) without the vault indirection: we parse the
defining source with `inspect` + `ast`, slice out the exact lambda when several
share a line, and capture referenced globals/closure cells.

The compiled path only needs the AST + captured constants; the interpreter
fallback calls the live function object directly, so (unlike the reference) we
never need to re-materialize code from source.
"""

from __future__ import annotations

import ast
import inspect
import textwrap
import types
from typing import Any, Callable


class UDFSource:
    __slots__ = ("func", "source", "tree", "globals", "name")

    def __init__(self, func: Callable, source: str, tree: ast.AST,
                 globs: dict[str, Any], name: str):
        self.func = func
        self.source = source          # normalized source ("lambda x: ..." / "def f...")
        self.tree = tree              # ast.Lambda or ast.FunctionDef
        self.globals = globs          # captured globals + closure cells (by name)
        self.name = name

    @property
    def params(self) -> list[str]:
        args = self.tree.args  # type: ignore[attr-defined]
        return [a.arg for a in args.args]


def _strip_qualname_consts(consts: tuple) -> tuple:
    """Mask the qualname string const that (on py<=3.10) follows each nested
    code-object const: it encodes the DEFINING scope ('outer.<locals>.
    <lambda>...'), so a candidate compiled in isolation can never match a
    live lambda that nests another lambda inside a function."""
    import sys

    if sys.version_info >= (3, 11):   # qualname lives on the code object
        return consts
    out = []
    prev_code = False
    for c in consts:
        if prev_code and isinstance(c, str):
            out.append("<qualname>")
            prev_code = False
            continue
        prev_code = isinstance(c, types.CodeType)
        out.append(c)
    return tuple(out)


def _code_fingerprint(code: types.CodeType) -> tuple:
    """Location-independent fingerprint of a code object (bytecode + const
    structure), so identical-looking lambdas at different columns differ only
    if their bodies differ."""
    consts = tuple(
        _code_fingerprint(c) if isinstance(c, types.CodeType) else c
        for c in _strip_qualname_consts(code.co_consts)
    )
    return (code.co_code, consts, code.co_names, code.co_varnames[: code.co_argcount])


def _loose_fingerprint(code: types.CodeType) -> tuple:
    """Location/closure-insensitive signature: referenced names + constant
    pool. Detects truncated extraction without false-failing closure lambdas
    (which compile with LOAD_GLOBAL instead of LOAD_DEREF in isolation)."""
    names: set = set(code.co_names) | set(code.co_freevars)
    consts: set = set()

    def walk(c: types.CodeType):
        for k in _strip_qualname_consts(c.co_consts):
            if isinstance(k, types.CodeType):
                names.update(k.co_names)
                names.update(k.co_freevars)
                walk(k)
            elif isinstance(k, (int, float, str, bytes, bool)) or k is None:
                consts.add(k)

    walk(code)
    return (code.co_varnames[: code.co_argcount], frozenset(names),
            frozenset(consts))


def _extract_lambda(func: types.FunctionType) -> ast.Lambda | None:
    """Locate the lambda's full source by scanning file lines from its first
    line, extending until a parse yields a lambda whose fingerprint matches
    the live code object. Returns None when no trustworthy source exists —
    the UDF then runs interpreter-only, which is always correct."""
    try:
        lines, lnum = inspect.findsource(func)
    except (OSError, TypeError):
        return None
    want_exact = _code_fingerprint(func.__code__)
    want_loose = _loose_fingerprint(func.__code__)
    loose_hits: dict[str, ast.Lambda] = {}  # unparse -> node
    max_end = min(lnum + 40, len(lines))
    for end in range(lnum + 1, max_end + 1):
        frag = textwrap.dedent(
            _cut_comments("".join(lines[lnum:end]))).strip()
        if not frag:
            continue
        base_frags = [frag]
        li = frag.find("lambda")
        if li > 0:
            # fragment starts mid-expression (".filter(lambda ...)"): anchor
            # at the lambda keyword; wrong cuts are fingerprint-rejected
            base_frags.append(frag[li:])
        candidates = []
        for bf in base_frags:
            candidates.append(bf)
            candidates.append("(" + bf + ")")
            # trailing unbalanced closers from the enclosing call
            t = bf
            for _ in range(6):
                t = t.rstrip().rstrip(",")
                if t and t[-1] in ")]}":
                    t = t[:-1]
                else:
                    break
                candidates.append(t)
                candidates.append("(" + t + ")")
        for cand in candidates:
            try:
                mod = ast.parse(cand)
            except SyntaxError:
                continue
            for n in ast.walk(mod):
                if not isinstance(n, ast.Lambda):
                    continue
                fp = _node_fingerprint(n, _code_fingerprint)
                if fp is None:
                    continue
                if fp == want_exact:
                    return n
                if _node_fingerprint(n, _loose_fingerprint) == want_loose:
                    loose_hits.setdefault(ast.unparse(n), n)
    if len(loose_hits) == 1:
        return next(iter(loose_hits.values()))
    # zero or AMBIGUOUS loose matches (e.g. two closure lambdas sharing a
    # name/const set): no trustworthy source -> interpreter-only
    return None


def _cut_comments(text: str) -> str:
    """Remove `# ...` comments with full quote awareness (incl. triple-quoted
    strings spanning lines) — comments after a lambda otherwise swallow the
    paren-balancing candidates."""
    out = []
    quote: str | None = None   # "'", '"', "\'\'\'", '\"\"\"'
    i = 0
    n = len(text)
    while i < n:
        ch = text[i]
        if quote:
            if ch == "\\" and len(quote) == 1:
                out.append(text[i:i + 2])
                i += 2
                continue
            if text.startswith(quote, i):
                out.append(quote)
                i += len(quote)
                quote = None
                continue
            out.append(ch)
            i += 1
            continue
        if ch in "\"'":
            quote = text[i:i + 3] if text.startswith(ch * 3, i) else ch
            out.append(quote)
            i += len(quote)
            continue
        if ch == "#":
            while i < n and text[i] != "\n":
                i += 1
            continue
        out.append(ch)
        i += 1
    return "".join(out)


def _node_fingerprint(node: ast.Lambda, fp_fn) -> tuple | None:
    """Compile a candidate lambda node and fingerprint its code object."""
    try:
        expr = ast.Expression(body=node)
        ast.fix_missing_locations(expr)
        compiled = compile(expr, "<udf>", "eval")
        lam = next(c for c in compiled.co_consts
                   if isinstance(c, types.CodeType))
        return fp_fn(lam)
    except (SyntaxError, ValueError, StopIteration):
        return None


def get_udf_source(func: Callable) -> UDFSource:
    """Extract normalized source + AST + captured globals for a UDF."""
    if not callable(func):
        raise TypeError(f"UDF must be callable, got {type(func)}")
    if not isinstance(func, types.FunctionType):
        # builtins / callables: no source — interpreter-only UDF
        return UDFSource(func, "", ast.Lambda(
            args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                               kw_defaults=[], defaults=[]),
            body=ast.Constant(value=None)), {}, getattr(func, "__name__", "<callable>"))

    # the expensive extraction (file scan + fingerprint matching) depends
    # only on the code object, which python compiles ONCE per source
    # location — rebuilding the same pipeline re-creates function objects
    # but reuses code objects (reference analog: source_vault dedupes via
    # code-object hash; measured 0.35s/flights-build without this)
    code = func.__code__
    if code in _source_memo:
        source = _source_memo[code]
        tree_node = _reparse(source) if source else None
        if tree_node is None:
            source = ""
            tree_node = _dummy(code.co_varnames[: code.co_argcount])
        return UDFSource(func, source, tree_node, capture_globals(func),
                         func.__name__)

    tree_node: ast.AST | None = None
    source = ""
    if func.__name__ == "<lambda>":
        # inspect.getsource truncates multi-line lambdas to their first line;
        # read the file ourselves and extend until the bytecode fingerprint
        # matches the live function (reference analog: source_vault's
        # code-object comparison)
        tree_node = _extract_lambda(func)
        if tree_node is not None:
            source = ast.unparse(tree_node)
    else:
        try:
            raw = inspect.getsource(func)
        except (OSError, TypeError):
            raw = ""
        if raw:
            try:
                mod = ast.parse(textwrap.dedent(raw))
            except SyntaxError:
                mod = None
            if mod is not None:
                for n in ast.walk(mod):
                    if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                            and n.name == func.__name__:
                        tree_node = n
                        source = ast.unparse(n)
                        break

    globs = capture_globals(func)
    if tree_node is None:
        # no retrievable source (stdin/REPL without history): interpreter-only
        # UDF, but keep real param names so schema hinting still works
        source = ""
        tree_node = _dummy(func.__code__.co_varnames[: func.__code__.co_argcount])
    _source_memo[code] = source
    return UDFSource(func, source, tree_node, globs, func.__name__)


# code object -> normalized source ("" = no source). LRU-bounded: the old
# grow-then-.clear() pattern dropped every warm entry at the cap (utils/lru)
from .lru import LruDict

_source_memo: LruDict = LruDict(4096)


def _reparse(source: str) -> ast.AST | None:
    """Rebuild the AST node from memoized source (a fresh tree per UDFSource
    so downstream annotation can never alias across instances)."""
    try:
        mod = ast.parse(source)
    except SyntaxError:
        return None
    for n in ast.walk(mod):
        if isinstance(n, (ast.Lambda, ast.FunctionDef, ast.AsyncFunctionDef)):
            return n
    return None


def _dummy(params: tuple[str, ...] = ()) -> ast.Lambda:
    return ast.Lambda(
        args=ast.arguments(posonlyargs=[],
                           args=[ast.arg(arg=p) for p in params],
                           kwonlyargs=[], kw_defaults=[], defaults=[]),
        body=ast.Constant(value=None))


def capture_globals(func: types.FunctionType) -> dict[str, Any]:
    """Names the function references resolved from its globals and closure
    (reference: python/tuplex/utils/globs.py)."""
    out: dict[str, Any] = {}

    def walk_names(code: types.CodeType) -> set[str]:
        names = set(code.co_names)
        for c in code.co_consts:
            if isinstance(c, types.CodeType):  # nested lambdas/comprehensions
                names |= walk_names(c)
        return names

    g = func.__globals__
    for name in walk_names(func.__code__):
        if name in g:
            out[name] = g[name]
    if func.__closure__:
        for name, cell in zip(func.__code__.co_freevars, func.__closure__):
            try:
                out[name] = cell.cell_contents
            except ValueError:
                pass
    return out


def udf_from_source(source: str, name: str, globs: dict[str, Any]):
    """Rebuild a UDF callable from its normalized source + captured globals
    (worker side of the serverless fan-out — the reference ships LLVM
    bitcode in its InvocationRequest, Lambda.proto:40-88; we ship source and
    re-derive everything through the same reflection path). Seeds the source
    memo so get_udf_source() on the rebuilt function round-trips without a
    file behind it."""
    ns = dict(globs)
    if not source:
        raise ValueError(f"UDF {name!r} has no retrievable source")
    if source.startswith("lambda"):
        func = eval(compile(source, "<tuplex-udf>", "eval"), ns)
    else:
        exec(compile(source, "<tuplex-udf>", "exec"), ns)
        func = ns[name]
    _source_memo[func.__code__] = source
    return func
