"""`python -m tuplex_tpu excstats` — exception-plane readout from the
job history.

Renders the terminal ``excprof`` events (history/recorder embeds the
runtime/excprof readout at every job's end; the job service writes one
per-tenant row per serve job) as text: per-stage x code counts against
the plan-time expected inventory, resolve-tier mix, the drift score vs
the baseline with the respecialize recommendation, and the sampled
deviant rows — the same data the dashboard drift panel draws, for a
terminal. Reads ``<logDir>/tuplex_history.jsonl``; nothing executes.
"""

from __future__ import annotations


def main(log_dir: str = ".", job: str | None = None) -> int:
    from ..history.recorder import _load_jobs

    jobs = _load_jobs(log_dir)      # FileNotFoundError -> caller prints
    n_shown = 0
    for job_id, events in jobs.items():
        if job is not None and not str(job_id).startswith(job):
            continue
        exev = next((e for e in reversed(events)
                     if e.get("event") == "excprof"), None)
        if exev is None:
            continue
        n_shown += 1
        _print_job(job_id, events, exev)
    if n_shown == 0:
        which = f" matching {job!r}" if job else ""
        print(f"excstats: no exception-plane events{which} in "
              f"{log_dir or '.'}/tuplex_history.jsonl — run a job with "
              f"tuplex.tpu.excprof on (the default; TUPLEX_EXCPROF=0 "
              f"disables)")
    return 0


def _print_job(job_id: str, events: list, exev: dict) -> None:
    done = next((e for e in events if e.get("event") == "job_done"), {})
    tenant = exev.get("tenant")
    head = f"job {job_id}"
    if tenant:
        head += f" (tenant {tenant})"
    if done.get("wall_s") is not None:
        head += f" — {done.get('rows', '?')} rows, {done['wall_s']}s"
    print(head)
    # both event shapes: the single-job recorder nests the global
    # readout under 'drift'; the serve row IS a flat scope_report
    drift = exev.get("drift") or exev
    score = float(drift.get("drift_score", 0.0) or 0.0)
    flag = "  RESPECIALIZE RECOMMENDED" \
        if drift.get("respecialize_recommended") else ""
    print(f"  drift {score:.2f}{flag} · exc rate "
          f"{float(drift.get('exception_rate', 0.0) or 0.0) * 100:.2f}% "
          f"· {int(drift.get('windows', 0) or 0)} window(s)")
    mix = drift.get("tier_mix") or {}
    if any(mix.values()):
        print("  tier mix: " + ", ".join(
            f"{k} {v * 100:.1f}%" for k, v in sorted(mix.items()) if v))
    for key, s in sorted((exev.get("stages") or {}).items()):
        unexpected = int(s.get("unexpected", 0))
        uflag = f"  unexpected={unexpected} !" if unexpected else ""
        print(f"  stage {str(key)[:16]}  rows {s.get('rows', 0)}  "
              f"rate {float(s.get('rate', 0.0)) * 100:.2f}%"
              f"  fallback {s.get('fallback', 0)}{uflag}")
        codes = s.get("codes") or {}
        if codes:
            print("      observed: " + ", ".join(
                f"{c}:{n}" for c, n in sorted(codes.items())))
        base = s.get("baseline") or {}
        if base:
            exp = ", ".join(base.get("codes") or []) or "none"
            pruned = "  [cold arm pruned]" if base.get("pruned") else ""
            print(f"      expected: {exp} -> {base.get('tier', '?')}"
                  f"{pruned}")
        tiers = s.get("tiers") or {}
        if tiers:
            print("      tiers: " + ", ".join(
                f"{t}:{n}" for t, n in sorted(tiers.items())))
    for key, by_code in sorted((exev.get("samples") or {}).items()):
        for code, caps in sorted(by_code.items()):
            for r in caps:
                print(f"      sample {code} @ {str(key)[:16]}: {r}")
