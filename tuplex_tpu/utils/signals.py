"""Graceful SIGINT handling during job execution.

Reference: core/include/Signals.h:28-43 — SIGINT is captured during a job,
checked between tasks (check_and_forward_signals), and cancels the work
queue cleanly instead of killing the process mid-partition.
"""

from __future__ import annotations

import signal
import threading
from contextlib import contextmanager


class JobInterrupted(KeyboardInterrupt):
    pass


class _State:
    def __init__(self):
        self.requested = False


_state = _State()


@contextmanager
def capture_sigint():
    """Within the scope, SIGINT sets a flag instead of raising immediately;
    callers poll check_interrupted() at partition boundaries. Only installs
    from the main thread (signal API restriction); elsewhere it's a no-op."""
    _state.requested = False
    if threading.current_thread() is not threading.main_thread():
        yield _state
        return
    prev = signal.getsignal(signal.SIGINT)

    def handler(signum, frame):
        _state.requested = True

    try:
        signal.signal(signal.SIGINT, handler)
    except ValueError:
        yield _state
        return
    try:
        yield _state
    finally:
        signal.signal(signal.SIGINT, prev)


def check_interrupted() -> None:
    if _state.requested:
        _state.requested = False
        raise JobInterrupted("job cancelled by SIGINT")
