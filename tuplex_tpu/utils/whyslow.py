"""`python -m tuplex_tpu whyslow` — latency-budget readout from the job
history.

Renders the terminal ``critpath`` events (serve/service stamps one per
job from runtime/critpath's span-timeline sweep) as text: the exclusive
bucket vector with shares of wall, the tenant's EWMA baseline with
per-bucket deltas, the slow-job blame verdict, the SLO met/missed line,
and the critical-path segment strip — the same record the dashboard
budget panel and the ``serve:slow-job`` instant read, so the three
surfaces must agree. Reads ``<logDir>/tuplex_history.jsonl``; nothing
executes.
"""

from __future__ import annotations

# canonical bucket order (critpath.BUCKETS) — kept inline so the readout
# works from a bare history file without importing the runtime plane
_ORDER = ("admission_wait", "queue_wait", "compile_trace", "compile_lower",
          "compile_xla", "h2d", "device", "resolve_general",
          "resolve_interpreter", "d2h", "merge", "scheduler_other",
          "unattributed")

_GLOSS = {
    "admission_wait": "queued before the scheduler admitted the job",
    "queue_wait": "requeued between stage turns (DRR slot contention)",
    "compile_trace": "jax trace of the stage fn",
    "compile_lower": "StableHLO lowering",
    "compile_xla": "XLA backend compile (inline, not pool-overlapped)",
    "h2d": "host->device transfer",
    "device": "device execution of compiled stages",
    "resolve_general": "compiled general-case resolve pass",
    "resolve_interpreter": "interpreter-tier row-at-a-time resolve",
    "d2h": "device->host fetch",
    "merge": "partition merge on host",
    "scheduler_other": "scheduler bookkeeping / unclassified spans",
    "unattributed": "wall time no span or wait accounts for",
}


def main(log_dir: str = ".", job: str | None = None) -> int:
    from ..history.recorder import _load_jobs

    jobs = _load_jobs(log_dir)      # FileNotFoundError -> caller prints
    n_shown = 0
    for job_id, events in jobs.items():
        if job is not None and not str(job_id).startswith(job):
            continue
        cpev = next((e for e in reversed(events)
                     if e.get("event") == "critpath"), None)
        if cpev is None or not cpev.get("buckets"):
            continue
        n_shown += 1
        _print_job(job_id, cpev)
    if n_shown == 0:
        which = f" matching {job!r}" if job else ""
        print(f"whyslow: no latency-budget events{which} in "
              f"{log_dir or '.'}/tuplex_history.jsonl — run a serve job "
              f"with tuplex.tpu.critpath on (the default; "
              f"TUPLEX_CRITPATH=0 disables) and tuplex.tpu.trace for "
              f"full coverage")
    return 0


def _print_job(job_id: str, ev: dict) -> None:
    wall = float(ev.get("wall_s") or 0.0)
    tenant = ev.get("tenant")
    head = f"job {job_id}"
    if tenant:
        head += f" (tenant {tenant})"
    head += (f" — wall {wall * 1e3:.1f}ms, dominant "
             f"{ev.get('dominant', '?')}, coverage "
             f"{float(ev.get('coverage_frac') or 0.0) * 100:.1f}%")
    if ev.get("degraded"):
        head += "  [degraded trace]"
    print(head)
    if ev.get("slow"):
        print(f"  SLOW: blame {ev.get('blame', '?')} "
              f"(+{float(ev.get('delta_s') or 0.0) * 1e3:.1f}ms over the "
              f"tenant baseline)")
    if float(ev.get("slo_ms") or 0.0) > 0:
        ok = ev.get("slo_ok")
        state = "met" if ok else ("MISSED" if ok is not None else "?")
        print(f"  SLO {float(ev['slo_ms']):.0f}ms: {state}")
    buckets = ev.get("buckets") or {}
    base = ev.get("baseline") or {}
    order = [b for b in _ORDER if b in buckets] + \
            [b for b in buckets if b not in _ORDER]
    print(f"  {'bucket':<20} {'ms':>9} {'share':>7} {'base ms':>9} "
          f"{'Δ ms':>8}")
    for b in order:
        v = float(buckets.get(b) or 0.0)
        bl = base.get(b)
        if v <= 0 and not bl:
            continue
        mark = " *" if b == ev.get("dominant") else \
            (" !" if ev.get("slow") and b == ev.get("blame") else "")
        share = f"{v / wall * 100:.1f}%" if wall > 0 else "—"
        bs = f"{float(bl) * 1e3:.1f}" if bl is not None else "—"
        d = f"{(v - float(bl)) * 1e3:+.1f}" if bl is not None else "—"
        print(f"  {b:<20} {v * 1e3:>9.1f} {share:>7} {bs:>9} {d:>8}"
              f"{mark}")
    path = ev.get("path") or []
    if path:
        print(f"  critical path ({len(path)} segment(s)):")
        for p in path[:24]:
            print(f"    {float(p[0]) / 1e3:>9.1f}ms  "
                  f"{float(p[1]) / 1e3:>8.1f}ms  {p[2]:<20} {p[3]}")
        if len(path) > 24:
            print(f"    … {len(path) - 24} more")


def glossary() -> None:
    """Print the bucket glossary (the README's table, for the terminal)."""
    for b in _ORDER:
        print(f"  {b:<20} {_GLOSS[b]}")
