"""Public Context — the engine entry point.

API parity with the reference's Python Context (reference:
python/tuplex/context.py:50 — options merge, parallelize/csv/text entry
points; core/include/Context.h:43). There is no binding layer: planning and
execution are Python-driven, the hot path is XLA-compiled.
"""

from __future__ import annotations

from typing import Any, Mapping, Optional, Sequence

from ..core import typesys as T
from ..core.errors import TuplexException
from ..core.options import ContextOptions
from ..exec.local import LocalBackend
from ..plan import logical as L
from ..runtime import columns as C
from .metrics import Metrics


class Context:
    def __init__(self, conf: Mapping[str, Any] | str | None = None, **kwargs):
        self.options_store = ContextOptions(conf if not isinstance(conf, str)
                                            else None, **kwargs)
        if isinstance(conf, str):
            self.options_store.update(conf)
        # sample-free specialization gate (compiler/typeinfer.py): like
        # tracing, the flag is process-wide — planning code paths have no
        # Context handle at schema-inference depth. TUPLEX_STATIC_TYPES
        # env (checked inside typeinfer.enabled) overrides either way.
        from ..compiler import typeinfer as _ti

        _ti.set_enabled(self.options_store.get_bool(
            "tuplex.tpu.staticTypes", True))
        if self.options_store.get_bool("tuplex.tpu.trace", False):
            # span tracing is process-wide (spans cross backend/compile-
            # pool threads); the option turns it on, never off — another
            # live Context (or TUPLEX_TRACE=1) may also depend on it
            from ..runtime import tracing

            tracing.enable(True)
        # device-plane cost attribution (runtime/devprof): same process-
        # wide on-only semantics as tracing/telemetry; TUPLEX_DEVPROF=0
        # is the env kill switch that wins over everything
        from ..runtime import devprof as _dp

        _dp.apply_options(self.options_store)
        # exception-plane observability (runtime/excprof): per-code
        # fallback attribution + drift detection; TUPLEX_EXCPROF=0 is
        # the env kill switch that wins over everything
        from ..runtime import excprof as _ex

        _ex.apply_options(self.options_store)
        # jaxpr-plane static vetting (compiler/graphlint): pre-submission
        # compile-hazard analysis; TUPLEX_GRAPHLINT=0 is the env kill
        # switch that wins over everything
        from ..compiler import graphlint as _gl

        _gl.apply_options(self.options_store)
        self.backend = self._make_backend()
        self.metrics = Metrics()
        from ..history import JobRecorder

        webui = self.options_store.get_bool("tuplex.webui", False)
        self.recorder = JobRecorder(
            self.options_store.get_str("tuplex.logDir", "."),
            enabled=webui or
            self.options_store.get_bool("tuplex.webui.enable"),
            exception_display_limit=self.options_store.get_int(
                "tuplex.webui.exceptionDisplayLimit", 5))
        self._webui_server = None
        self._webui_url = None
        if webui:
            # live dashboard autostart (reference: ensure_webui spawning
            # mongod + gunicorn; here one stdlib http thread)
            from ..history.recorder import start_server

            try:
                self._webui_server, self._webui_url = start_server(
                    self.options_store.get_str("tuplex.logDir", "."),
                    port=self.options_store.get_int("tuplex.webui.port", 0))
            except OSError as e:
                from ..utils.logging import get_logger

                get_logger("webui").warning("webui autostart failed: %s", e)
                self._webui_url = ""   # uiWebURL: nothing is serving
        if self.options_store.get_bool("tuplex.redirectToPythonLogging"):
            from ..utils.logging import redirect_to_python_logging

            redirect_to_python_logging(True)

    def _make_backend(self):
        name = self.options_store.get_str("tuplex.backend", "local")
        if name in ("local", "tpu"):
            return LocalBackend(self.options_store)
        if name == "multihost":
            from ..exec.multihost import MultiHostBackend

            return MultiHostBackend(self.options_store)
        if name in ("serverless", "lambda"):
            from ..exec.serverless import ServerlessBackend

            return ServerlessBackend(self.options_store)
        raise TuplexException(f"unknown backend {name!r}")

    # ------------------------------------------------------------------
    def parallelize(self, value_list: Sequence[Any],
                    columns: Optional[Sequence[str]] = None,
                    schema: Optional[T.RowType] = None,
                    auto_unpack: bool = True) -> "DataSet":
        """Create a DataSet from python values (reference: context.py:246
        parallelize → PythonContext.cc:823-919 fast transfer + fallback
        partitions for non-conforming rows). `auto_unpack=False` keeps dict
        rows as boxed dictionary values instead of spreading them into
        named columns."""
        from .dataset import DataSet

        data = list(value_list)
        if not data:
            raise TuplexException("parallelize: empty input")
        max_rows = self.options_store.get_int(
            "tuplex.sample.maxDetectionRows", 1000)
        threshold = self.options_store.get_float(
            "tuplex.normalcaseThreshold", 0.9)
        if schema is None:
            schema = _infer_row_schema(
                data[:max_rows], columns, threshold,
                auto_unpack=auto_unpack)
        elif columns:
            schema = T.row_of(columns, schema.types)

        if auto_unpack and C.user_columns(schema) and \
                any(isinstance(v, dict) for v in data[:8]):
            # dict rows were auto-unpacked into named columns: convert values
            # (rows missing keys stay boxed and go to the fallback path)
            keys = list(schema.columns)
            data = [
                tuple(d[k] for k in keys)
                if isinstance(d, dict) and set(d.keys()) == set(keys) else d
                for d in data
            ]

        op = L.ParallelizeOperator(data, schema, sample_size=max_rows)
        return DataSet(self, op)

    def csv(self, pattern: str, columns=None, header=None, delimiter=None,
            quotechar: Optional[str] = None, null_values=None,
            type_hints=None) -> "DataSet":
        from ..io.csvsource import make_csv_operator
        from .dataset import DataSet

        op = make_csv_operator(self.options_store, pattern, columns=columns,
                               header=header, delimiter=delimiter,
                               quotechar=quotechar, type_hints=type_hints,
                               null_values=null_values)
        return DataSet(self, op)

    def text(self, pattern: str, null_values=None) -> "DataSet":
        """One row per line; lines equal to a null value load as None
        (reference: context.py text → FileInputOperator text mode)."""
        from ..io.csvsource import make_text_operator
        from .dataset import DataSet

        return DataSet(self, make_text_operator(self.options_store, pattern,
                                                null_values=null_values))

    def orc(self, pattern: str, columns=None) -> "DataSet":
        from ..io.orcsource import make_orc_operator
        from .dataset import DataSet

        return DataSet(self, make_orc_operator(self.options_store, pattern,
                                               columns=columns))

    def tuplexfile(self, path: str) -> "DataSet":
        """Read a dataset written by DataSet.totuplex — the engine's native
        binary partition format; columnar leaves reload without sniffing or
        decoding (reference: FileFormat::OUTFMT_TUPLEX)."""
        from ..io.tuplexfmt import make_tuplex_operator
        from .dataset import DataSet

        return DataSet(self, make_tuplex_operator(self.options_store, path))

    def options(self, nested: bool = False) -> dict:
        flat = self.options_store.as_dict()
        if not nested:
            return flat
        out: dict = {}
        for k, v in flat.items():
            cur = out
            ks = k.split(".")
            for piece in ks[:-1]:
                nxt = cur.setdefault(piece, {})
                if not isinstance(nxt, dict):   # leaf-then-group collision
                    nxt = cur[piece] = {"": nxt}
                cur = nxt
            if isinstance(cur.get(ks[-1]), dict):
                cur[ks[-1]][""] = v             # group-then-leaf collision
            else:
                cur[ks[-1]] = v
        return out

    def optionsToYAML(self, file_path: str = "config.yaml") -> None:
        with open(file_path, "w") as fp:
            for k, v in sorted(self.options_store.as_dict().items()):
                fp.write(f"{k}: {v}\n")

    # filesystem helpers (reference: context.py ls/cp/rm via VFS)
    def ls(self, pattern: str) -> list[str]:
        from ..io.vfs import VirtualFileSystem

        return VirtualFileSystem.ls(pattern)

    def cp(self, pattern: str, target_uri: str) -> None:
        from ..io.vfs import VirtualFileSystem

        VirtualFileSystem.cp(pattern, target_uri)

    def rm(self, pattern: str) -> None:
        from ..io.vfs import VirtualFileSystem

        VirtualFileSystem.rm(pattern)

    # ------------------------------------------------------------------
    def job_service(self):
        """The lazily-created in-process JobService (serve/) sharing this
        context's options and warm device. One per Context; closed with
        it."""
        svc = getattr(self, "_job_service", None)
        if svc is None:
            from ..serve import JobService

            svc = self._job_service = JobService(
                self.options_store, recorder=self.recorder)
        return svc

    def submit(self, dataset, name: str = "job", tenant: str = "default",
               memory_budget=None, weight=None):
        """Submit a DataSet pipeline to the job service instead of running
        it inline: returns a JobHandle immediately; the service fair-shares
        stage dispatches across all submitted jobs on the warm device
        (serve/service.py). ``memory_budget`` (bytes or a "128MB" string)
        bounds the job's resident partitions — past it the job spills via
        the LRU evictor rather than pressuring other tenants."""
        from ..core.options import _size_to_bytes
        from ..serve import request_from_dataset

        budget = None if memory_budget is None \
            else _size_to_bytes(memory_budget)
        req = request_from_dataset(dataset, name=name, tenant=tenant,
                                   memory_budget=budget, weight=weight)
        return self.job_service().submit(req)

    def uiWebURL(self) -> str:
        if self._webui_url is not None:
            return self._webui_url   # "" when autostart failed: not serving
        host = self.options_store.get_str("tuplex.webui.url", "localhost")
        port = self.options_store.get_str("tuplex.webui.port", "5000")
        return f"http://{host}:{port}"

    def close(self) -> None:
        """Release context resources (the autostarted webui server's socket
        and thread; warm serverless workers). Safe to call repeatedly."""
        svc = getattr(self, "_job_service", None)
        if svc is not None:
            try:
                svc.close()
            except Exception:
                pass
            self._job_service = None
        be = getattr(self, "backend", None)
        if be is not None and hasattr(be, "close"):
            try:
                be.close()
            except Exception:
                pass
        if self._webui_server is not None:
            try:
                self._webui_server.shutdown()
                self._webui_server.server_close()
            except Exception:
                pass
            self._webui_server = None
            self._webui_url = ""   # nothing serving anymore

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def _infer_row_schema(sample: list, columns, threshold: float,
                      auto_unpack: bool = True) -> T.RowType:
    """Column-wise normal-case speculation (reference:
    PythonContext.cc:1023 inferType — majority type over the sample)."""
    dicts = auto_unpack and all(isinstance(v, dict) for v in sample)
    if dicts and sample:
        # auto-unpack string-keyed dicts into named columns (reference:
        # strDictParallelize, PythonContext.cc:617)
        keys = list(sample[0].keys())
        if all(list(d.keys()) == keys for d in sample) and \
                all(isinstance(k, str) for k in keys):
            types = [T.normal_case_type([d[k] for d in sample], threshold)[0]
                     for k in keys]
            return T.row_of(keys, types)
    tuples = [v for v in sample if isinstance(v, tuple)]
    if tuples and len(tuples) >= threshold * len(sample):
        k = len(tuples[0])
        if all(len(t) == k for t in tuples):
            types = []
            for ci in range(k):
                vals = [t[ci] for t in tuples]
                nc, _, _ = T.normal_case_type(vals, threshold)
                types.append(nc)
            names = list(columns) if columns else [f"_{i}" for i in range(k)]
            if len(names) != k:
                raise TuplexException(
                    f"{k} columns in data but {len(names)} names given")
            return T.row_of(names, types)
    nc, _, _ = T.normal_case_type(sample, threshold)
    names = list(columns) if columns else ["_0"]
    return T.row_of(names[:1], [nc])


class LambdaContext(Context):
    """Distributed-by-default Context (reference: python/tuplex/__init__.py
    exports LambdaContext preset to the serverless backend). Now that the
    serverless fan-out exists (`exec/serverless.py` — the AWSLambdaBackend
    analog) it is the honest default here too; pass
    ``tuplex.backend=multihost`` for SPMD-mesh distribution instead."""

    def __init__(self, conf=None, **kwargs):
        merged = dict(conf or {})
        merged.setdefault("tuplex.backend", "serverless")
        super().__init__(merged, **kwargs)
