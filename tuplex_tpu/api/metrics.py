"""Job metrics (reference: core/include/JobMetrics.h:23-70 — compile/sample
times, fast/slow path wall time, per-row ns; exposed via
python/tuplex/metrics.py and logged per stage at LocalBackend.cc:932-949)."""

from __future__ import annotations


class Metrics:
    #: when set (a callable returning a dict), ``as_dict()['counters']``
    #: uses it instead of the process-global registry — the job service
    #: installs each job's scoped family here so a tenant's metrics never
    #: embed other tenants' transfer accounting
    counters_source = None

    def __init__(self):
        self.stages: list[dict] = []
        self.plans: list[dict] = []

    def record_stage(self, m: dict) -> None:
        self.stages.append(dict(m))

    def record_plan(self, m: dict) -> None:
        """Planning-time record: static-analyzer wall time and the number
        of operators the analyzer routed to the interpreter at PLAN time
        (compiler/analyzer.py STATS delta for one plan_stages call)."""
        self.plans.append(dict(m))

    def analyzerTimeMs(self) -> float:
        """Total UDF static-analysis wall time (ms) across plans."""
        return sum(float(m.get("analyzer_ms", 0.0)) for m in self.plans)

    def planFallbackOps(self) -> int:
        """Operators routed to the interpreter by the PLAN-time analyzer
        verdict (the emitter was never invoked for them)."""
        return sum(int(m.get("plan_fallback_ops", 0)) for m in self.plans)

    def analyzerInferredOps(self) -> int:
        """Operators whose output type the abstract interpreter
        (compiler/typeinfer.py) decided EXACTLY from the UDF AST."""
        return sum(int(m.get("analyzer_inferred_ops", 0))
                   for m in self.plans)

    def sampleTracesSkipped(self) -> int:
        """CPython sample traces schema inference skipped because the
        static verdict was exact (sample-free specialization)."""
        return sum(int(m.get("sample_traces_skipped", 0))
                   for m in self.plans)

    # -- totals (JobMetrics getters) ----------------------------------------
    @property
    def totalExceptionCount(self) -> int:
        return sum(int(m.get("exception_rows", 0)) for m in self.stages)

    def fastPathWallTime(self) -> float:
        return sum(float(m.get("fast_path_s", 0.0)) for m in self.stages)

    def slowPathWallTime(self) -> float:
        return sum(float(m.get("slow_path_s", 0.0)) for m in self.stages)

    def generalPathWallTime(self) -> float:
        """Compiled general-case (resolve) tier wall time."""
        return sum(float(m.get("general_path_s", 0.0)) for m in self.stages)

    def totalWallTime(self) -> float:
        return sum(float(m.get("wall_s", 0.0)) for m in self.stages)

    def compileTime(self) -> float:
        """Total stage-executable compile seconds (JobMetrics.h
        get_compile_time analog). Attributed per stage by the compile
        queue: inline first-dispatch compiles AND ahead-of-time pool
        compiles both count; content-addressed cache hits (in-process
        dedup or cross-process AOT artifacts) cost zero here — a fully
        warm second run reports 0.0."""
        return sum(float(m.get("compile_s", 0.0)) for m in self.stages)

    def stageCompileCount(self) -> int:
        """Number of actual XLA compiles across stages (0 on a warm AOT
        cache — the cross-process reuse proof)."""
        return sum(int(m.get("stage_compiles", 0)) for m in self.stages)

    def totalRowsOut(self) -> int:
        return sum(int(m.get("rows_out", 0)) for m in self.stages)

    def deviceTime(self) -> float:
        """Total MEASURED device seconds across stages (runtime/devprof:
        launch→ready per dispatch, cold compile waits included in the
        cold split). 0.0 when attribution is off (TUPLEX_DEVPROF=0) or
        nothing dispatched to a compiled executable."""
        return sum(float(m.get("device_s", 0.0)) for m in self.stages)

    def hbmPeak(self) -> int:
        """Largest per-execution peak device-memory footprint of any
        stage executable (XLA memory_analysis: arguments + outputs +
        temps + generated code)."""
        return max((int(m.get("hbm_peak", 0)) for m in self.stages),
                   default=0)

    def rowsSeen(self) -> int:
        """Valid input rows the stages actually processed (the
        exception-rate denominator; runtime/excprof rides it onto the
        stage record — rows_out undercounts because filters drop rows)."""
        return sum(int(m.get("rows_seen", 0)) for m in self.stages)

    def exceptionRate(self) -> float:
        """Fraction of processed rows that left the compiled fast path
        with an exception code — INCLUDING rows a resolve tier later
        retired (that is the rate the drift detector watches; terminal
        unresolved rows stay separately visible as exception_rows).
        0.0 when excprof was off or nothing ran."""
        seen = errs = 0
        for m in self.stages:
            n = int(m.get("rows_seen", 0))
            seen += n
            errs += n * float(m.get("exception_rate", 0.0))
        return (errs / seen) if seen else 0.0

    def resolveTierMix(self) -> dict:
        """Which resolve tier the deviant rows finally landed on, as
        fractions: {'exact_exit': f, 'general': f, 'interpreter': f}.
        Summed across stages from the excprof per-tier retired counts."""
        tiers = {"exact_exit": 0, "general": 0, "interpreter": 0}
        for m in self.stages:
            tiers["exact_exit"] += int(m.get("resolve_exact_rows", 0))
            tiers["general"] += int(m.get("resolve_general_rows", 0))
            tiers["interpreter"] += int(m.get("resolve_interpreter_rows",
                                              0))
        total = sum(tiers.values())
        return {k: (v / total if total else 0.0) for k, v in tiers.items()}

    def d2hBytes(self) -> int:
        """Device->host transfer bytes attributed per stage (the boundary
        tunnel tax the varlen wire / handoff work is judged against)."""
        return sum(int(m.get("d2h_bytes", 0)) for m in self.stages)

    def h2dBytes(self) -> int:
        """Host->device upload bytes attributed per stage (packed dispatch
        buffers + per-leaf staging)."""
        return sum(int(m.get("h2d_bytes", 0)) for m in self.stages)

    def swapOutCount(self) -> int:
        return sum(int(m.get("swap_out", 0)) for m in self.stages)

    def swapInCount(self) -> int:
        return sum(int(m.get("swap_in", 0)) for m in self.stages)

    def swappedBytes(self) -> int:
        return sum(int(m.get("swapped_bytes", 0)) for m in self.stages)

    _STANDARD = ("wall_s", "fast_path_s", "general_path_s", "slow_path_s",
                 "rows_out", "exception_rows",
                 "swap_out", "swap_in", "swapped_bytes")

    # -- per-stage breakdown (JobMetrics.h ns/row discipline) ---------------
    def stage_breakdown(self) -> list[dict]:
        out = []
        for i, m in enumerate(self.stages):
            rows = int(m.get("rows_out", 0))
            wall = float(m.get("wall_s", 0.0))
            rec = {
                "stage": i,
                "wall_s": wall,
                "fast_path_s": float(m.get("fast_path_s", 0.0)),
                "general_path_s": float(m.get("general_path_s", 0.0)),
                "slow_path_s": float(m.get("slow_path_s", 0.0)),
                "rows_out": rows,
                "ns_per_row": (wall / rows * 1e9) if rows else 0.0,
                "exception_rows": int(m.get("exception_rows", 0)),
            }
            # backend-specific counters (compile_s, task_failures,
            # serverless_tasks, sink_rows...) survive into the breakdown;
            # never clobber derived fields, never admit bools
            for k, v in m.items():
                if k not in self._STANDARD and k not in rec \
                        and isinstance(v, (int, float)) \
                        and not isinstance(v, bool):
                    rec[k] = v
            out.append(rec)
        return out

    def as_dict(self) -> dict:
        from ..runtime import xferstats

        return {
            "stages": self.stage_breakdown(),
            "fast_path_s": self.fastPathWallTime(),
            "general_path_s": self.generalPathWallTime(),
            "slow_path_s": self.slowPathWallTime(),
            "wall_s": self.totalWallTime(),
            "device_s": self.deviceTime(),
            "hbm_peak": self.hbmPeak(),
            "compile_s": self.compileTime(),
            "stage_compiles": self.stageCompileCount(),
            "rows_out": self.totalRowsOut(),
            "exception_rows": self.totalExceptionCount,
            # exception-plane readouts (runtime/excprof): the observed
            # exception rate over rows actually processed, the resolve-
            # tier mix of the deviant rows (bench JSON flattens the dict
            # to resolve_tier_mix.* dotted keys), and the process-global
            # drift score vs the plan-time baseline
            "exception_rate": self.exceptionRate(),
            "resolve_tier_mix": self.resolveTierMix(),
            "drift_score": self._drift_score(),
            # latency-budget plane (runtime/critpath): the last job's
            # critical-path bucket vector swept from the tracing ring
            # (bench JSON flattens to latency_budget.* dotted keys);
            # empty when critpath or tracing is off
            "latency_budget": self.latencyBudget(),
            "analyzer_ms": self.analyzerTimeMs(),
            "plan_fallback_ops": self.planFallbackOps(),
            "analyzer_inferred_ops": self.analyzerInferredOps(),
            "sample_traces_skipped": self.sampleTracesSkipped(),
            "d2h_bytes": self.d2hBytes(),
            "h2d_bytes": self.h2dBytes(),
            # the tagged counter registry (runtime/xferstats): process-
            # cumulative by default; a job-service Metrics reports its
            # job's scoped family instead (counters_source)
            "counters": (self.counters_source()
                         if self.counters_source is not None
                         else xferstats.as_dict()),
        }

    @staticmethod
    def _drift_score() -> float:
        """Process-global exception-drift score (runtime/excprof EWMA vs
        the plan-time baseline); 0.0 when excprof is off or no window
        ever rolled."""
        try:
            from ..runtime import excprof

            return float(excprof.drift_score(None))
        except Exception:   # pragma: no cover - readout is best-effort
            return 0.0

    @staticmethod
    def latencyBudget() -> dict:
        """Critical-path bucket vector of the latest traced job
        (runtime/critpath sweeping the tracing ring): bucket -> seconds
        plus ``unattributed_frac``/``coverage_frac``/``dominant``. Empty
        dict when critpath is disabled (TUPLEX_CRITPATH=0), tracing
        never recorded a job span, or the sweep fails — the readout is
        best-effort and must never raise."""
        try:
            from ..runtime import critpath

            r = critpath.analyze_ring()
            if not r:
                return {}
            return {**{k: round(float(v), 6)
                       for k, v in r["buckets"].items()},
                    "unattributed_frac": round(
                        float(r["unattributed_frac"]), 4),
                    "coverage_frac": round(float(r["coverage_frac"]), 4),
                    "dominant": r["dominant"]}
        except Exception:   # pragma: no cover - readout is best-effort
            return {}

    def as_json(self) -> str:
        import json

        return json.dumps(self.as_dict())

    def export_prometheus(self) -> str:
        """Prometheus text exposition of the PROCESS-WIDE telemetry
        registry (runtime/telemetry): serve-path latency histograms,
        scheduler/memory gauges, the bridged tagged-counter families
        (runtime/xferstats) and compile-plane stats, plus the health
        state. The same text `python -m tuplex_tpu serve --metrics-port`
        serves at /metrics and the wire protocol drops as
        `<root>/metrics.prom` — this is the library entry point."""
        from ..runtime import telemetry

        return telemetry.render_prometheus()

    def export_trace(self, path: str) -> str:
        """Write the span timeline recorded so far (``tuplex.tpu.trace`` /
        TUPLEX_TRACE=1) as Chrome trace-event JSON — open in Perfetto
        (ui.perfetto.dev) or chrome://tracing. Raises RuntimeError when
        tracing never recorded anything (almost always: tracing was off)."""
        from ..runtime import tracing

        if not tracing.events():
            raise RuntimeError(
                "no spans recorded — enable tracing with tuplex.tpu.trace "
                "or TUPLEX_TRACE=1 before running the job")
        return tracing.export_chrome_trace(path)
