"""Job metrics (reference: core/include/JobMetrics.h:23-70 — compile/sample
times, fast/slow path wall time; exposed via python/tuplex/metrics.py)."""

from __future__ import annotations


class Metrics:
    def __init__(self):
        self.stages: list[dict] = []

    def record_stage(self, m: dict) -> None:
        self.stages.append(dict(m))

    @property
    def totalExceptionCount(self) -> int:
        return sum(int(m.get("exception_rows", 0)) for m in self.stages)

    def fastPathWallTime(self) -> float:
        return sum(float(m.get("fast_path_s", 0.0)) for m in self.stages)

    def slowPathWallTime(self) -> float:
        return sum(float(m.get("slow_path_s", 0.0)) for m in self.stages)

    def totalWallTime(self) -> float:
        return sum(float(m.get("wall_s", 0.0)) for m in self.stages)

    def as_dict(self) -> dict:
        return {
            "stages": list(self.stages),
            "fast_path_s": self.fastPathWallTime(),
            "slow_path_s": self.slowPathWallTime(),
            "wall_s": self.totalWallTime(),
        }

    def as_json(self) -> str:
        import json

        return json.dumps(self.as_dict())
