"""Public DataSet — lazy operator-graph builder.

Method-for-method parity with the reference's DataSet (reference:
python/tuplex/dataset.py — map:49, filter:83, collect:113, take:125, show:144,
resolve:162, withColumn:201, mapColumn:231, selectColumns:262,
renameColumn:293, ignore:319, cache:346, columns:365, types:375, join:384,
leftJoin:442, tocsv:500, aggregate:593, aggregateByKey:644, unique:36,
exception_counts:707). Every method returns a NEW DataSet over a new logical
operator; nothing executes until an action (collect/take/show/tocsv).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

from ..core import typesys as T
from ..core.errors import TuplexException
from ..plan import logical as L
from ..plan.physical import (AggregateStage, JoinStage, TransformStage,
                             plan_stages)


def _vfs_is_dir(path: str) -> bool:
    from ..io.vfs import VirtualFileSystem

    return VirtualFileSystem.is_dir_path(path)


class DataSet:
    def __init__(self, context, op: L.LogicalOperator):
        self._context = context
        self._op = op
        self._last_exceptions: list = []

    def _derive(self, op: L.LogicalOperator) -> "DataSet":
        return DataSet(self._context, op)

    # -- transformations ----------------------------------------------------
    def map(self, ftor: Callable) -> "DataSet":
        return self._derive(L.MapOperator(self._op, ftor))

    def filter(self, ftor: Callable) -> "DataSet":
        return self._derive(L.FilterOperator(self._op, ftor))

    def withColumn(self, column: str, ftor: Callable) -> "DataSet":
        return self._derive(L.WithColumnOperator(self._op, column, ftor))

    def mapColumn(self, column: str, ftor: Callable) -> "DataSet":
        return self._derive(L.MapColumnOperator(self._op, column, ftor))

    def selectColumns(self, columns: Sequence) -> "DataSet":
        if not isinstance(columns, (list, tuple)):
            columns = [columns]
        return self._derive(L.SelectColumnsOperator(self._op, columns))

    def renameColumn(self, key, newColumnName: str) -> "DataSet":
        return self._derive(
            L.RenameColumnOperator(self._op, key, newColumnName))

    def resolve(self, eclass: type, ftor: Callable) -> "DataSet":
        return self._derive(L.ResolveOperator(self._op, eclass, ftor))

    def ignore(self, eclass: type) -> "DataSet":
        return self._derive(L.IgnoreOperator(self._op, eclass))

    def unique(self) -> "DataSet":
        from ..plan.aggregates import UniqueOperator

        return self._derive(UniqueOperator(self._op))

    def aggregate(self, combine: Callable, aggregate: Callable,
                  initial_value: Any) -> "DataSet":
        from ..plan.aggregates import AggregateOperator

        return self._derive(
            AggregateOperator(self._op, combine, aggregate, initial_value))

    def aggregateByKey(self, combine: Callable, aggregate: Callable,
                       initial_value: Any,
                       key_columns: Sequence[str]) -> "DataSet":
        from ..plan.aggregates import AggregateByKeyOperator

        return self._derive(AggregateByKeyOperator(
            self._op, combine, aggregate, initial_value, key_columns))

    def join(self, dsRight: "DataSet", leftKeyColumn: str,
             rightKeyColumn: str, prefixes=None, suffixes=None) -> "DataSet":
        from ..plan.joins import JoinOperator

        return self._derive(JoinOperator(
            self._op, dsRight._op, leftKeyColumn, rightKeyColumn, "inner",
            prefixes, suffixes))

    def leftJoin(self, dsRight: "DataSet", leftKeyColumn: str,
                 rightKeyColumn: str, prefixes=None,
                 suffixes=None) -> "DataSet":
        from ..plan.joins import JoinOperator

        return self._derive(JoinOperator(
            self._op, dsRight._op, leftKeyColumn, rightKeyColumn, "left",
            prefixes, suffixes))

    def cache(self, store_specialized: bool = True) -> "DataSet":
        from ..plan.cacheop import CacheOperator

        op = CacheOperator(self._op, store_specialized)
        op.materialize(self._context)
        return self._derive(op)

    # -- metadata -----------------------------------------------------------
    @property
    def columns(self) -> Optional[list[str]]:
        cols = self._op.columns()
        return list(cols) if cols else None

    @property
    def types(self) -> list:
        return list(self._op.schema().types)

    @property
    def schema(self) -> T.RowType:
        return self._op.schema()

    # -- actions ------------------------------------------------------------
    def collect(self):
        return self._execute(limit=-1)

    def take(self, nrows: int = 5):
        return self._execute(limit=nrows)

    def show(self, nrows: int = -1) -> None:
        rows = self._execute(limit=nrows) if nrows >= 0 else self.collect()
        cols = self.columns
        if cols:
            print(" | ".join(cols))
            print("-" * (3 * len(cols) + sum(len(c) for c in cols)))
        for r in rows:
            if isinstance(r, tuple):
                print(" | ".join(repr(v) for v in r))
            else:
                print(repr(r))

    def explain(self, lint: bool = False) -> str:
        """Human-readable physical plan: stages + fused operators, with
        per-stage jaxpr codegen stats when tuplex.optimizer.codeStats is on
        (reference: LocalBackend.cc:932-949 stage logs +
        InstructionCountPass.h). `lint=True` appends the plan-time UDF
        static-analysis reports (compiler/analyzer.py): per-UDF fallback /
        exception-site / purity findings with source locations, and each
        stage's possible row error codes."""
        from ..utils.planviz import explain as _explain

        text = _explain(self._op, self._context.options_store, lint=lint)
        print(text)
        return text

    def to_dot(self) -> str:
        """Operator DAG as graphviz DOT text (reference:
        Context.cc:171 visualizeOperationGraph / GENERATE_PDFS)."""
        from ..utils.planviz import plan_to_dot

        return plan_to_dot(self._op)

    def tocsv(self, path: str, part_size: int = 0, num_rows: int = -1,
              num_parts: int = 0, part_name_generator=None,
              null_value=None, header=True, **kwargs) -> None:
        """Stream results to CSV from columnar buffers — normal-case rows
        never box into python tuples (reference: buildWithCSVRowWriter,
        PipelineBuilder.h:238; round 1 collected the whole dataset first).

        Signature parity with the reference (dataset.py:500-509):
        `num_parts` splits the output evenly across part files (last part
        smallest), `part_size` rotates parts on a byte budget,
        `part_name_generator(i)` names them, `num_rows` limits output,
        `null_value` renders None cells, `header` may be a bool or an
        explicit list of column names."""
        from ..io.csvsink import write_partitions_csv

        sink = None
        if getattr(self._context.backend, "supports_sink_pushdown", False) \
                and num_rows < 0 and num_parts == 0 and part_size == 0 \
                and part_name_generator is None and not kwargs \
                and _vfs_is_dir(path):
            # distributed output: each worker writes its own part file
            # straight from its columnar buffers (reference: Lambda tasks
            # writing S3 output.part-N, AWSLambdaBackend.cc:410-430)
            sink = {"format": "csv", "path": path.rstrip("/"),
                    "columns": self.columns, "null_value": null_value,
                    "header": header}
        partitions = self._execute_partitions(limit=-1,
                                      output_sink=sink)
        if sink is not None and not partitions and \
                getattr(self._context.backend, "_sink_pushed", False):
            self._finish_file_job(partitions, rows_override=self._context
                                  .metrics.as_dict().get("rows_out"))
            return
        write_partitions_csv(path, partitions, self.columns,
                             backend=self._context.backend,
                             part_size=part_size, num_rows=num_rows,
                             num_parts=num_parts,
                             part_name_generator=part_name_generator,
                             null_value=null_value, header=header,
                             **kwargs)
        self._finish_file_job(partitions)

    def toorc(self, path: str, part_size: int = 0, num_rows: int = -1,
              num_parts: int = 0, part_name_generator=None) -> None:
        """Write ORC with the same splitting controls as tocsv (reference:
        dataset.py:554 toorc signature)."""
        from ..io.orcsource import write_partitions_orc

        partitions = self._execute_partitions(limit=-1)
        write_partitions_orc(path, partitions, self.columns,
                             backend=self._context.backend,
                             part_size=part_size, num_rows=num_rows,
                             num_parts=num_parts,
                             part_name_generator=part_name_generator)
        self._finish_file_job(partitions)

    def totuplex(self, path: str) -> None:
        """Write the engine's native binary partition format (reference:
        FileFormat::OUTFMT_TUPLEX, LocalBackend.cc:1597) — reload with
        Context.tuplexfile(path), no sniffing or decode on the way back."""
        from ..io.tuplexfmt import write_partitions_tuplex

        partitions = self._execute_partitions(limit=-1)
        write_partitions_tuplex(path, partitions,
                                backend=self._context.backend)
        self._finish_file_job(partitions)

    def _finish_file_job(self, partitions, rows_override=None) -> None:
        import time as _time

        counts: dict[str, int] = {}
        for rec in self._last_exceptions:
            counts[rec.exc_name] = counts.get(rec.exc_name, 0) + 1
        rows = rows_override if rows_override is not None else \
            sum(p.num_rows for p in partitions)
        self._context.recorder.job_done(
            rows, _time.perf_counter() - self._t_job, counts)

    def exception_counts(self) -> dict[str, int]:
        """Counts of unresolved exceptions from the LAST action on this
        dataset chain (reference: dataset.py:707)."""
        counts: dict[str, int] = {}
        for rec in self._last_exceptions:
            counts[rec.exc_name] = counts.get(rec.exc_name, 0) + 1
        return counts

    # ------------------------------------------------------------------
    def _execute_partitions(self, limit: int,
                        output_sink=None) -> list:
        """Run the plan and return the OUTPUT PARTITIONS (columnar). The
        sinks (tocsv/toorc) stream from these without boxing."""
        import time as _time

        from ..utils.signals import capture_sigint, check_interrupted

        self._t_job = _time.perf_counter()
        from ..runtime import tracing as TR

        # the history slice starts HERE — before the job span opens — so
        # the job/plan/analyzer spans land in the per-job waterfall too
        _tmark = TR.now_us()
        _jsp = TR.span("job", "job")
        _jsp.__enter__()
        partitions = None
        all_exceptions = []
        prof_cm = None
        try:
            _jsp.set("action", "collect" if limit < 0 else f"take({limit})")
            prof_dir = self._context.options_store.get_str(
                "tuplex.tpu.profileDir", "")
            if prof_dir:
                # capture an XLA/TPU trace of the whole job (open with
                # tensorboard or xprof; VERDICT r1 asked for exactly this on
                # the chip). Best-effort: profiling must never fail a job.
                try:
                    import jax.profiler as _prof

                    prof_cm = _prof.trace(prof_dir)
                    prof_cm.__enter__()
                except Exception:
                    prof_cm = None
            sink = L.TakeOperator(self._op, limit) if limit >= 0 \
                else self._op
            from ..compiler import analyzer as _az

            azsnap = _az.snapshot()
            stages = plan_stages(sink, self._context.options_store)
            azd = _az.delta(azsnap)
            self._context.metrics.record_plan({
                "analyzer_ms": azd["analyze_ms"],
                "plan_fallback_ops": azd["plan_fallback_ops"],
                "analyzer_inferred_ops": azd["inferred_ops"],
                "sample_traces_skipped": azd["sample_traces_skipped"]})
            backend = self._context.backend
            recorder = self._context.recorder
            recorder.job_started(
                "collect" if limit < 0 else f"take({limit})",
                stages, trace_mark=_tmark)
            with capture_sigint():
                for si, stage in enumerate(stages):
                    check_interrupted()
                    if getattr(stage, "source", None) is not None:
                        # take(n): stream partitions lazily so the backend
                        # stops pulling source data once n rows survive
                        # (reference: range tasks, LocalBackend.cc:552-611;
                        # round 1 loaded the WHOLE source for take(5))
                        lazy = getattr(stage, "limit", -1) >= 0 and \
                            isinstance(stage, TransformStage)
                        partitions = _source_partitions(
                            self._context, stage, lazy=lazy)
                        if si == 0 and not lazy:
                            # ahead-of-time compile of the WHOLE plan on
                            # the pool: stage i+1's (predicted-spec)
                            # compile overlaps stage i's execution
                            # (exec/compilequeue; remote XLA compiles are
                            # minutes, not the reference's milliseconds)
                            pre = getattr(backend, "precompile_plan", None)
                            if pre is not None:
                                try:
                                    pre(stages, partitions)
                                except Exception:
                                    pass
                    # device handoff: tell the backend WHO consumes this
                    # stage's output ("stage"/"agg"/"join" — all three
                    # drain device views now; round 5 excluded joins and
                    # aggregates, which made q19/flights round-trip every
                    # boundary through the ~50 MB/s tunnel)
                    from ..plan.physical import consumer_kind

                    consumer = consumer_kind(stages, si)
                    kw = {}
                    if output_sink is not None and \
                            si == len(stages) - 1 and \
                            getattr(backend, "supports_sink_pushdown",
                                    False):
                        kw["sink"] = output_sink
                    recorder.stage_started(stage)
                    backend.progress_cb = recorder.task_progress
                    try:
                        result = backend.execute_any(
                            stage, partitions, self._context,
                            intermediate=consumer, **kw)
                    finally:
                        backend.progress_cb = None
                    partitions = result.partitions
                    all_exceptions.extend(result.exceptions)
                    self._context.metrics.record_stage(result.metrics)
                    recorder.stage_done(stage, result.metrics,
                                        result.exceptions)
        finally:
            import sys as _sys

            # pass the in-flight exception (if any) so a crashed job's
            # span carries the error attribute like every other span
            _jsp.__exit__(*_sys.exc_info())
            if prof_cm is not None:
                try:
                    prof_cm.__exit__(None, None, None)
                except Exception:
                    pass
            # multihost: every process dumps its own span stream next to
            # the history file; the driver merges the per-host lanes via
            # `python -m tuplex_tpu trace` (history.recorder reads the
            # tuplex_trace_host*.jsonl siblings). Lanes are keyed by the
            # jax process index (tracing.set_host), so streams never
            # collide in the merged timeline.
            if TR.enabled():
                try:
                    import jax as _jax

                    if _jax.process_count() > 1:
                        import os as _os

                        _ld = self._context.options_store.get_str(
                            "tuplex.logDir", ".")
                        TR.dump_jsonl(_os.path.join(
                            _ld,
                            f"tuplex_trace_host{_jax.process_index()}"
                            ".jsonl"))
                except Exception:
                    pass    # span dump must never fail the job
            # interrupted jobs must not leave stale per-action state
            self._last_exceptions = all_exceptions
        return partitions or []

    def _execute(self, limit: int):
        import time as _time

        from ..runtime.columns import partition_to_pylist

        partitions = self._execute_partitions(limit)
        out = []
        for p in partitions:
            self._context.backend.touch_partition(p)
            out.extend(partition_to_pylist(p))
        if limit >= 0:
            out = out[:limit]
        counts = {}
        for rec in self._last_exceptions:
            counts[rec.exc_name] = counts.get(rec.exc_name, 0) + 1
        self._context.recorder.job_done(
            len(out), _time.perf_counter() - self._t_job, counts)
        return out


def _source_partitions(context, stage, lazy: bool = False):
    """Materialize the stage source into columnar partitions.

    `lazy=True` returns a GENERATOR (no dataset-wide harmonization): used by
    take(n) so the backend can stop consuming once the limit is met. Lazy
    batches may have differing str widths — worst case a few extra jit
    retraces, which a take() of a handful of rows never hits."""
    from ..runtime import columns as C

    src = stage.source
    if isinstance(src, L.ParallelizeOperator):
        schema = src.schema()
        part_rows = _rows_per_partition(context, schema, len(src.data))

        def gen_parallel():
            for off in range(0, len(src.data), part_rows):
                chunk = src.data[off: off + part_rows]
                yield C.build_partition(chunk, schema, start_index=off)

        if lazy:
            return gen_parallel()
        return C.harmonize_partitions(list(gen_parallel()))
    if hasattr(src, "load_partitions"):
        import inspect

        proj = getattr(stage, "source_projection", None)
        sig = inspect.signature(src.load_partitions)
        kwargs = {"projection": proj} if "projection" in sig.parameters \
            else {}
        if lazy and hasattr(src, "iter_partitions"):
            return src.iter_partitions(context, **kwargs)
        parts = src.load_partitions(context, **kwargs)
        if lazy:
            return iter(parts)
        return C.harmonize_partitions(parts)
    raise TuplexException(f"unknown source {src!r}")


def _rows_per_partition(context, schema, total_rows: int) -> int:
    psize = context.options_store.get_size("tuplex.partitionSize", 32 << 20)
    # rough per-row cost: 8B per numeric leaf + 64B per str leaf
    from ..runtime import columns as C

    per_row = 0
    for ci, ct in enumerate(schema.types):
        for _, lt in C.flatten_type(ct, str(ci)):
            base = lt.without_option() if lt.is_optional() else lt
            per_row += 64 if base is T.STR else 8
    per_row = max(per_row, 8)
    return max(64, min(total_rows, psize // per_row))
