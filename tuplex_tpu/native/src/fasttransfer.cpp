// Fast python<->columnar transfer kernels (CPython C API).
//
// The native analog of the reference's PythonContext fast paths
// (reference: tuplex/python/src/PythonContext.cc:823-919 —
// fastI64Parallelize / fastMixedSimpleTypeTupleTransfer / strDictParallelize:
// typed bulk conversion of python lists into partition buffers, with
// non-conforming elements routed to fallback). Here each column of a
// parallelize()/join-output batch is encoded by one C loop instead of a
// per-row python loop; buffers are returned as python `bytes` that numpy
// wraps zero-copy via np.frombuffer.
//
// Exposed module: _tuplex_native
//   encode_i64(list)  -> (data_bytes,  valid_bytes, bad_index_list)
//   encode_f64(list)  -> (data_bytes,  valid_bytes, bad_index_list)
//   encode_bool(list) -> (data_bytes,  valid_bytes, bad_index_list)
//   encode_str(list)  -> (mat_bytes, lens_bytes, valid_bytes, width,
//                         bad_index_list)
//   decode_str(mat_bytes, lens_bytes, width, n) -> list[str]
//
// "bad" = element whose type doesn't conform (including bool where int is
// expected — python bool is an int subtype but the type lattice separates
// them); None is VALID (valid=0) since Option columns carry a validity mask.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

struct EncodedCommon {
  PyObject *valid_bytes = nullptr;
  PyObject *bad_list = nullptr;
};

static bool alloc_common(Py_ssize_t n, EncodedCommon &out) {
  out.valid_bytes = PyBytes_FromStringAndSize(nullptr, n);
  out.bad_list = PyList_New(0);
  return out.valid_bytes && out.bad_list;
}

static PyObject *encode_i64(PyObject *, PyObject *arg) {
  if (!PyList_Check(arg)) {
    PyErr_SetString(PyExc_TypeError, "expected list");
    return nullptr;
  }
  Py_ssize_t n = PyList_GET_SIZE(arg);
  PyObject *data = PyBytes_FromStringAndSize(nullptr, n * 8);
  EncodedCommon c;
  if (!data || !alloc_common(n, c)) return nullptr;
  int64_t *d = reinterpret_cast<int64_t *>(PyBytes_AS_STRING(data));
  char *v = PyBytes_AS_STRING(c.valid_bytes);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *o = PyList_GET_ITEM(arg, i);
    if (o == Py_None) {
      d[i] = 0;
      v[i] = 0;
      continue;
    }
    if (PyLong_Check(o) && !PyBool_Check(o)) {
      int overflow = 0;
      long long val = PyLong_AsLongLongAndOverflow(o, &overflow);
      if (!overflow) {
        d[i] = static_cast<int64_t>(val);
        v[i] = 1;
        continue;
      }
    }
    d[i] = 0;
    v[i] = 1;  // slot unusable; caller boxes the row
    PyObject *idx = PyLong_FromSsize_t(i);
    PyList_Append(c.bad_list, idx);
    Py_DECREF(idx);
  }
  return Py_BuildValue("(NNN)", data, c.valid_bytes, c.bad_list);
}

static PyObject *encode_f64(PyObject *, PyObject *arg) {
  if (!PyList_Check(arg)) {
    PyErr_SetString(PyExc_TypeError, "expected list");
    return nullptr;
  }
  Py_ssize_t n = PyList_GET_SIZE(arg);
  PyObject *data = PyBytes_FromStringAndSize(nullptr, n * 8);
  EncodedCommon c;
  if (!data || !alloc_common(n, c)) return nullptr;
  double *d = reinterpret_cast<double *>(PyBytes_AS_STRING(data));
  char *v = PyBytes_AS_STRING(c.valid_bytes);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *o = PyList_GET_ITEM(arg, i);
    if (o == Py_None) {
      d[i] = 0.0;
      v[i] = 0;
      continue;
    }
    if (PyFloat_Check(o)) {
      d[i] = PyFloat_AS_DOUBLE(o);
      v[i] = 1;
      continue;
    }
    d[i] = 0.0;
    v[i] = 1;
    PyObject *idx = PyLong_FromSsize_t(i);
    PyList_Append(c.bad_list, idx);
    Py_DECREF(idx);
  }
  return Py_BuildValue("(NNN)", data, c.valid_bytes, c.bad_list);
}

static PyObject *encode_bool(PyObject *, PyObject *arg) {
  if (!PyList_Check(arg)) {
    PyErr_SetString(PyExc_TypeError, "expected list");
    return nullptr;
  }
  Py_ssize_t n = PyList_GET_SIZE(arg);
  PyObject *data = PyBytes_FromStringAndSize(nullptr, n);
  EncodedCommon c;
  if (!data || !alloc_common(n, c)) return nullptr;
  char *d = PyBytes_AS_STRING(data);
  char *v = PyBytes_AS_STRING(c.valid_bytes);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *o = PyList_GET_ITEM(arg, i);
    if (o == Py_None) {
      d[i] = 0;
      v[i] = 0;
      continue;
    }
    if (PyBool_Check(o)) {
      d[i] = (o == Py_True) ? 1 : 0;
      v[i] = 1;
      continue;
    }
    d[i] = 0;
    v[i] = 1;
    PyObject *idx = PyLong_FromSsize_t(i);
    PyList_Append(c.bad_list, idx);
    Py_DECREF(idx);
  }
  return Py_BuildValue("(NNN)", data, c.valid_bytes, c.bad_list);
}

static PyObject *encode_str(PyObject *, PyObject *arg) {
  if (!PyList_Check(arg)) {
    PyErr_SetString(PyExc_TypeError, "expected list");
    return nullptr;
  }
  Py_ssize_t n = PyList_GET_SIZE(arg);
  // pass 1: utf8 views + max width
  std::vector<const char *> ptrs(static_cast<size_t>(n), nullptr);
  std::vector<Py_ssize_t> lens(static_cast<size_t>(n), 0);
  std::vector<Py_ssize_t> bad;
  Py_ssize_t w = 1;
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *o = PyList_GET_ITEM(arg, i);
    if (o == Py_None) continue;
    if (PyUnicode_Check(o)) {
      Py_ssize_t sz = 0;
      const char *u = PyUnicode_AsUTF8AndSize(o, &sz);
      if (u) {
        ptrs[static_cast<size_t>(i)] = u;
        lens[static_cast<size_t>(i)] = sz;
        if (sz > w) w = sz;
        continue;
      }
      PyErr_Clear();
    }
    bad.push_back(i);
  }
  PyObject *mat = PyBytes_FromStringAndSize(nullptr, n * w);
  PyObject *lens_b = PyBytes_FromStringAndSize(nullptr, n * 4);
  PyObject *valid_b = PyBytes_FromStringAndSize(nullptr, n);
  PyObject *bad_list = PyList_New(0);
  if (!mat || !lens_b || !valid_b || !bad_list) return nullptr;
  char *m = PyBytes_AS_STRING(mat);
  int32_t *lp = reinterpret_cast<int32_t *>(PyBytes_AS_STRING(lens_b));
  char *v = PyBytes_AS_STRING(valid_b);
  memset(m, 0, static_cast<size_t>(n * w));
  for (Py_ssize_t i = 0; i < n; i++) {
    const char *u = ptrs[static_cast<size_t>(i)];
    if (u) {
      memcpy(m + i * w, u, static_cast<size_t>(lens[static_cast<size_t>(i)]));
      lp[i] = static_cast<int32_t>(lens[static_cast<size_t>(i)]);
      v[i] = 1;
    } else {
      lp[i] = 0;
      v[i] = 0;
    }
  }
  for (Py_ssize_t i : bad) {
    v[i] = 1;  // not a None: row must be boxed by the caller
    PyObject *idx = PyLong_FromSsize_t(i);
    PyList_Append(bad_list, idx);
    Py_DECREF(idx);
  }
  return Py_BuildValue("(NNNnN)", mat, lens_b, valid_b, w, bad_list);
}

// Arrow large_string buffers -> zero-padded [n, w] byte matrix + clamped
// int32 lens + unclamped int64 lens. The hot half of CSV/ORC ingestion
// (python fallback: runtime/columns.py arrow_string_to_leaf's fancy-index
// gather builds an [n, w] index matrix first — this is one pass of memcpy).
static PyObject *offsets_to_matrix(PyObject *, PyObject *args) {
  Py_buffer data, offs;
  Py_ssize_t n, aoff, maxw;
  if (!PyArg_ParseTuple(args, "y*y*nnn", &data, &offs, &n, &aoff, &maxw))
    return nullptr;
  if (maxw < 0) maxw = 0;  // python fallback: w = min(max_len, maxw) >= 0
  if (offs.len < static_cast<Py_ssize_t>((aoff + n + 1) * 8) ||
      n < 0 || aoff < 0) {
    PyBuffer_Release(&data);
    PyBuffer_Release(&offs);
    PyErr_SetString(PyExc_ValueError, "offsets buffer too small");
    return nullptr;
  }
  const int64_t *off = reinterpret_cast<const int64_t *>(offs.buf) + aoff;
  int64_t wmax = 1;
  for (Py_ssize_t i = 0; i < n; i++) {
    int64_t li = off[i + 1] - off[i];
    if (li > wmax) wmax = li;
  }
  Py_ssize_t w = static_cast<Py_ssize_t>(wmax < maxw ? wmax : maxw);
  PyObject *mat = PyBytes_FromStringAndSize(nullptr, n * w);
  PyObject *lens_b = PyBytes_FromStringAndSize(nullptr, n * 4);
  PyObject *full_b = PyBytes_FromStringAndSize(nullptr, n * 8);
  if (!mat || !lens_b || !full_b) {
    PyBuffer_Release(&data);
    PyBuffer_Release(&offs);
    Py_XDECREF(mat);
    Py_XDECREF(lens_b);
    Py_XDECREF(full_b);
    return nullptr;
  }
  char *m = PyBytes_AS_STRING(mat);
  int32_t *lp = reinterpret_cast<int32_t *>(PyBytes_AS_STRING(lens_b));
  int64_t *fp = reinterpret_cast<int64_t *>(PyBytes_AS_STRING(full_b));
  const char *src = reinterpret_cast<const char *>(data.buf);
  bool ok = true;
  Py_BEGIN_ALLOW_THREADS;
  memset(m, 0, static_cast<size_t>(n * w));
  for (Py_ssize_t i = 0; i < n; i++) {
    int64_t start = off[i];
    int64_t li = off[i + 1] - start;
    if (start < 0 || li < 0 || start + li > data.len) {
      ok = false;
      break;
    }
    int64_t c = li < w ? li : w;
    memcpy(m + i * w, src + start, static_cast<size_t>(c));
    lp[i] = static_cast<int32_t>(c);
    fp[i] = li;
  }
  Py_END_ALLOW_THREADS;
  PyBuffer_Release(&data);
  PyBuffer_Release(&offs);
  if (!ok) {
    Py_DECREF(mat);
    Py_DECREF(lens_b);
    Py_DECREF(full_b);
    PyErr_SetString(PyExc_ValueError, "offsets out of data bounds");
    return nullptr;
  }
  return Py_BuildValue("(NNNn)", mat, lens_b, full_b, w);
}

static PyObject *decode_str(PyObject *, PyObject *args) {
  PyObject *mat_obj, *lens_obj;
  Py_ssize_t w, n;
  if (!PyArg_ParseTuple(args, "SSnn", &mat_obj, &lens_obj, &w, &n))
    return nullptr;
  const char *m = PyBytes_AS_STRING(mat_obj);
  const int32_t *lp =
      reinterpret_cast<const int32_t *>(PyBytes_AS_STRING(lens_obj));
  if (PyBytes_GET_SIZE(mat_obj) < n * w ||
      PyBytes_GET_SIZE(lens_obj) < n * 4) {
    PyErr_SetString(PyExc_ValueError, "buffer too small");
    return nullptr;
  }
  PyObject *out = PyList_New(n);
  if (!out) return nullptr;
  for (Py_ssize_t i = 0; i < n; i++) {
    int32_t li = lp[i];
    if (li < 0) li = 0;
    if (li > w) li = static_cast<int32_t>(w);
    PyObject *s =
        PyUnicode_DecodeUTF8(m + i * w, li, "replace");
    if (!s) {
      Py_DECREF(out);
      return nullptr;
    }
    PyList_SET_ITEM(out, i, s);
  }
  return out;
}

// One-pass multi-column decode: typed column buffers -> list of row tuples
// (list of bare values for a single column). The resultSetToCPython analog
// (reference: tuplex/python/src/PythonDataSet.cc:1400-1442 dispatches to
// per-type bulk decoders) — avoids per-column python lists, Option-mask
// comprehensions, and the final zip().
//
// spec per column: (kind, data_buf, valid_buf|None[, lens_buf, width])
//   kind: 0=i64 1=f64 2=bool 3=str(bytes matrix + i32 lens + width)
static PyObject *decode_columns(PyObject *, PyObject *args) {
  PyObject *spec;
  Py_ssize_t n;
  if (!PyArg_ParseTuple(args, "On", &spec, &n)) return nullptr;
  if (!PyList_Check(spec)) {
    PyErr_SetString(PyExc_TypeError, "spec must be a list");
    return nullptr;
  }
  Py_ssize_t k = PyList_GET_SIZE(spec);
  struct Col {
    int kind = 0;
    Py_buffer data{}, valid{}, lens{};
    bool has_valid = false, has_lens = false;
    Py_ssize_t w = 0;
  };
  std::vector<Col> cols(static_cast<size_t>(k));
  bool arg_ok = true;
  for (Py_ssize_t c = 0; c < k && arg_ok; c++) {
    PyObject *t = PyList_GET_ITEM(spec, c);
    Col &col = cols[static_cast<size_t>(c)];
    PyObject *vb = Py_None;
    long kind = 0;
    if (!PyArg_ParseTuple(t, "ly*|Oy*n", &kind, &col.data, &vb, &col.lens,
                          &col.w)) {
      col = Col{};  // ParseTuple released any y* buffers it acquired
      arg_ok = false;
      break;
    }
    col.kind = static_cast<int>(kind);
    col.has_lens = col.lens.buf != nullptr;
    if (vb != Py_None) {
      if (PyObject_GetBuffer(vb, &col.valid, PyBUF_SIMPLE) < 0) {
        arg_ok = false;
        break;
      }
      col.has_valid = true;
    }
    // bounds: every row index must stay inside the provided buffers; a
    // negative width would make `need` vacuously small and let
    // buf + i*w index backwards, so reject it outright (w == 0 is a
    // legal degenerate: every row decodes to the empty string)
    if (col.kind == 3 && col.w < 0) {
      PyErr_SetString(PyExc_ValueError, "string column width must be >= 0");
      arg_ok = false;
      break;
    }
    Py_ssize_t need = col.kind == 3 ? n * col.w
                      : col.kind == 2 ? n
                                      : n * 8;
    if (col.data.len < need || (col.has_valid && col.valid.len < n) ||
        (col.kind == 3 && (!col.has_lens || col.lens.len < n * 4))) {
      PyErr_SetString(PyExc_ValueError, "column buffer too small");
      arg_ok = false;
      break;
    }
  }
  PyObject *out = arg_ok ? PyList_New(n) : nullptr;
  if (out) {
    bool single = (k == 1);
    for (Py_ssize_t i = 0; i < n && out; i++) {
      PyObject *row = single ? nullptr : PyTuple_New(k);
      if (!single && !row) {
        Py_CLEAR(out);
        break;
      }
      for (Py_ssize_t c = 0; c < k; c++) {
        Col &col = cols[static_cast<size_t>(c)];
        PyObject *v = nullptr;
        if (col.has_valid &&
            !reinterpret_cast<const char *>(col.valid.buf)[i]) {
          v = Py_None;
          Py_INCREF(v);
        } else {
          switch (col.kind) {
            case 0:
              v = PyLong_FromLongLong(
                  reinterpret_cast<const int64_t *>(col.data.buf)[i]);
              break;
            case 1:
              v = PyFloat_FromDouble(
                  reinterpret_cast<const double *>(col.data.buf)[i]);
              break;
            case 2:
              v = PyBool_FromLong(
                  reinterpret_cast<const char *>(col.data.buf)[i]);
              break;
            case 3: {
              int32_t li = reinterpret_cast<const int32_t *>(col.lens.buf)[i];
              if (li < 0) li = 0;
              if (li > col.w) li = static_cast<int32_t>(col.w);
              v = PyUnicode_DecodeUTF8(
                  reinterpret_cast<const char *>(col.data.buf) + i * col.w,
                  li, "replace");
              break;
            }
            default:
              PyErr_SetString(PyExc_ValueError, "bad column kind");
          }
        }
        if (!v) {
          Py_XDECREF(row);
          Py_CLEAR(out);
          break;
        }
        if (single) {
          PyList_SET_ITEM(out, i, v);
        } else {
          PyTuple_SET_ITEM(row, c, v);
        }
      }
      if (out && !single) PyList_SET_ITEM(out, i, row);
    }
  }
  for (auto &col : cols) {
    if (col.data.buf) PyBuffer_Release(&col.data);
    if (col.has_valid) PyBuffer_Release(&col.valid);
    if (col.has_lens) PyBuffer_Release(&col.lens);
  }
  return out;
}

// One-pass mixed-tuple encode: list of k-tuples -> per-column typed buffers
// (the fastMixedSimpleTypeTupleTransfer analog, reference:
// tuplex/python/src/PythonContext.cc:860). kinds: same codes as
// decode_columns. Returns (cols, bad_list) where cols is a list of
//   i64/f64: (data_bytes, valid_bytes)   bool: (data_bytes, valid_bytes)
//   str:     (mat_bytes, lens_bytes, valid_bytes, width)
// bad rows (wrong arity / non-conforming field type / i64 overflow) have
// every column slot zeroed+valid and appear in bad_list for boxing.
static PyObject *encode_rows(PyObject *, PyObject *args) {
  PyObject *rows, *kinds_obj;
  if (!PyArg_ParseTuple(args, "OO", &rows, &kinds_obj)) return nullptr;
  if (!PyList_Check(rows) || !PyList_Check(kinds_obj)) {
    PyErr_SetString(PyExc_TypeError, "expected (list, list)");
    return nullptr;
  }
  Py_ssize_t n = PyList_GET_SIZE(rows);
  Py_ssize_t k = PyList_GET_SIZE(kinds_obj);
  std::vector<int> kinds(static_cast<size_t>(k));
  for (Py_ssize_t c = 0; c < k; c++) {
    long v = PyLong_AsLong(PyList_GET_ITEM(kinds_obj, c));
    if (v < 0 || v > 3) {
      PyErr_SetString(PyExc_ValueError, "bad kind");
      return nullptr;
    }
    kinds[static_cast<size_t>(c)] = static_cast<int>(v);
  }
  // str columns need a width pass first
  std::vector<Py_ssize_t> widths(static_cast<size_t>(k), 0);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *row = PyList_GET_ITEM(rows, i);
    // exact tuple only (matches the python path's `type(v) is tuple`):
    // namedtuple rows must box so collect() returns them unchanged
    if (!PyTuple_CheckExact(row) || PyTuple_GET_SIZE(row) != k) continue;
    for (Py_ssize_t c = 0; c < k; c++) {
      if (kinds[static_cast<size_t>(c)] != 3) continue;
      PyObject *o = PyTuple_GET_ITEM(row, c);
      if (PyUnicode_Check(o)) {
        Py_ssize_t sz = 0;
        if (PyUnicode_AsUTF8AndSize(o, &sz)) {
          if (sz > widths[static_cast<size_t>(c)])
            widths[static_cast<size_t>(c)] = sz;
        } else {
          PyErr_Clear();
        }
      }
    }
  }
  struct OutCol {
    PyObject *data = nullptr, *valid = nullptr, *lens = nullptr;
    char *d = nullptr, *v = nullptr;
    int32_t *lp = nullptr;
    Py_ssize_t w = 1;
  };
  std::vector<OutCol> out(static_cast<size_t>(k));
  bool alloc_ok = true;
  for (Py_ssize_t c = 0; c < k && alloc_ok; c++) {
    OutCol &oc = out[static_cast<size_t>(c)];
    int kind = kinds[static_cast<size_t>(c)];
    Py_ssize_t esz = kind == 2 ? 1 : 8;
    if (kind == 3) {
      oc.w = widths[static_cast<size_t>(c)] > 0
                 ? widths[static_cast<size_t>(c)]
                 : 1;
      oc.data = PyBytes_FromStringAndSize(nullptr, n * oc.w);
      oc.lens = PyBytes_FromStringAndSize(nullptr, n * 4);
      if (!oc.data || !oc.lens) {
        alloc_ok = false;
        break;
      }
      oc.lp = reinterpret_cast<int32_t *>(PyBytes_AS_STRING(oc.lens));
      memset(PyBytes_AS_STRING(oc.data), 0, static_cast<size_t>(n * oc.w));
    } else {
      oc.data = PyBytes_FromStringAndSize(nullptr, n * esz);
      if (!oc.data) {
        alloc_ok = false;
        break;
      }
      memset(PyBytes_AS_STRING(oc.data), 0, static_cast<size_t>(n * esz));
    }
    oc.valid = PyBytes_FromStringAndSize(nullptr, n);
    if (!oc.valid) {
      alloc_ok = false;
      break;
    }
    oc.d = PyBytes_AS_STRING(oc.data);
    oc.v = PyBytes_AS_STRING(oc.valid);
  }
  PyObject *bad_list = alloc_ok ? PyList_New(0) : nullptr;
  if (!bad_list) {
    for (auto &oc : out) {
      Py_XDECREF(oc.data);
      Py_XDECREF(oc.valid);
      Py_XDECREF(oc.lens);
    }
    return nullptr;
  }
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *row = PyList_GET_ITEM(rows, i);
    bool bad = !PyTuple_CheckExact(row) || PyTuple_GET_SIZE(row) != k;
    for (Py_ssize_t c = 0; c < k && !bad; c++) {
      OutCol &oc = out[static_cast<size_t>(c)];
      PyObject *o = PyTuple_GET_ITEM(row, c);
      oc.v[i] = 1;
      if (o == Py_None) {
        oc.v[i] = 0;  // Option slot; schema-validity is the caller's check
        if (oc.lp) oc.lp[i] = 0;
        continue;
      }
      switch (kinds[static_cast<size_t>(c)]) {
        case 0: {
          if (!PyLong_Check(o) || PyBool_Check(o)) {
            bad = true;
            break;
          }
          int overflow = 0;
          long long val = PyLong_AsLongLongAndOverflow(o, &overflow);
          if (overflow) {
            bad = true;
            break;
          }
          reinterpret_cast<int64_t *>(oc.d)[i] = val;
          break;
        }
        case 1:
          if (!PyFloat_Check(o)) {
            bad = true;
            break;
          }
          reinterpret_cast<double *>(oc.d)[i] = PyFloat_AS_DOUBLE(o);
          break;
        case 2:
          if (!PyBool_Check(o)) {
            bad = true;
            break;
          }
          oc.d[i] = (o == Py_True) ? 1 : 0;
          break;
        case 3: {
          if (!PyUnicode_Check(o)) {
            bad = true;
            break;
          }
          Py_ssize_t sz = 0;
          const char *u = PyUnicode_AsUTF8AndSize(o, &sz);
          // sz > w can only happen if pass 1's AsUTF8 failed transiently
          // for this object — never write past the row slot
          if (!u || sz > oc.w) {
            PyErr_Clear();
            bad = true;
            break;
          }
          memcpy(oc.d + i * oc.w, u, static_cast<size_t>(sz));
          oc.lp[i] = static_cast<int32_t>(sz);
          break;
        }
      }
    }
    if (bad) {
      for (Py_ssize_t c = 0; c < k; c++) {
        OutCol &oc = out[static_cast<size_t>(c)];
        oc.v[i] = 1;  // slot unusable; caller boxes the row
        if (oc.lp) oc.lp[i] = 0;
      }
      PyObject *idx = PyLong_FromSsize_t(i);
      PyList_Append(bad_list, idx);
      Py_DECREF(idx);
    }
  }
  PyObject *cols_out = PyList_New(k);
  if (!cols_out) {
    for (auto &oc : out) {
      Py_XDECREF(oc.data);
      Py_XDECREF(oc.valid);
      Py_XDECREF(oc.lens);
    }
    Py_DECREF(bad_list);
    return nullptr;
  }
  for (Py_ssize_t c = 0; c < k; c++) {
    OutCol &oc = out[static_cast<size_t>(c)];
    PyObject *t =
        kinds[static_cast<size_t>(c)] == 3
            ? Py_BuildValue("(NNNn)", oc.data, oc.lens, oc.valid, oc.w)
            : Py_BuildValue("(NN)", oc.data, oc.valid);
    if (!t) {
      Py_DECREF(cols_out);
      Py_DECREF(bad_list);
      return nullptr;
    }
    PyList_SET_ITEM(cols_out, c, t);
  }
  return Py_BuildValue("(NN)", cols_out, bad_list);
}

static PyMethodDef Methods[] = {
    {"encode_i64", encode_i64, METH_O, "bulk encode int column"},
    {"encode_f64", encode_f64, METH_O, "bulk encode float column"},
    {"encode_bool", encode_bool, METH_O, "bulk encode bool column"},
    {"encode_str", encode_str, METH_O, "bulk encode str column"},
    {"offsets_to_matrix", offsets_to_matrix, METH_VARARGS,
     "arrow offsets+data -> padded byte matrix"},
    {"decode_str", decode_str, METH_VARARGS, "bulk decode str column"},
    {"decode_columns", decode_columns, METH_VARARGS,
     "typed column buffers -> list of row tuples"},
    {"encode_rows", encode_rows, METH_VARARGS,
     "list of tuples -> per-column typed buffers"},
    {nullptr, nullptr, 0, nullptr}};

static struct PyModuleDef Module = {PyModuleDef_HEAD_INIT, "_tuplex_native",
                                    "native host runtime kernels", -1,
                                    Methods};

}  // namespace

PyMODINIT_FUNC PyInit__tuplex_native(void) { return PyModule_Create(&Module); }
