// Fast python<->columnar transfer kernels (CPython C API).
//
// The native analog of the reference's PythonContext fast paths
// (reference: tuplex/python/src/PythonContext.cc:823-919 —
// fastI64Parallelize / fastMixedSimpleTypeTupleTransfer / strDictParallelize:
// typed bulk conversion of python lists into partition buffers, with
// non-conforming elements routed to fallback). Here each column of a
// parallelize()/join-output batch is encoded by one C loop instead of a
// per-row python loop; buffers are returned as python `bytes` that numpy
// wraps zero-copy via np.frombuffer.
//
// Exposed module: _tuplex_native
//   encode_i64(list)  -> (data_bytes,  valid_bytes, bad_index_list)
//   encode_f64(list)  -> (data_bytes,  valid_bytes, bad_index_list)
//   encode_bool(list) -> (data_bytes,  valid_bytes, bad_index_list)
//   encode_str(list)  -> (mat_bytes, lens_bytes, valid_bytes, width,
//                         bad_index_list)
//   decode_str(mat_bytes, lens_bytes, width, n) -> list[str]
//
// "bad" = element whose type doesn't conform (including bool where int is
// expected — python bool is an int subtype but the type lattice separates
// them); None is VALID (valid=0) since Option columns carry a validity mask.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

struct EncodedCommon {
  PyObject *valid_bytes = nullptr;
  PyObject *bad_list = nullptr;
};

static bool alloc_common(Py_ssize_t n, EncodedCommon &out) {
  out.valid_bytes = PyBytes_FromStringAndSize(nullptr, n);
  out.bad_list = PyList_New(0);
  return out.valid_bytes && out.bad_list;
}

static PyObject *encode_i64(PyObject *, PyObject *arg) {
  if (!PyList_Check(arg)) {
    PyErr_SetString(PyExc_TypeError, "expected list");
    return nullptr;
  }
  Py_ssize_t n = PyList_GET_SIZE(arg);
  PyObject *data = PyBytes_FromStringAndSize(nullptr, n * 8);
  EncodedCommon c;
  if (!data || !alloc_common(n, c)) return nullptr;
  int64_t *d = reinterpret_cast<int64_t *>(PyBytes_AS_STRING(data));
  char *v = PyBytes_AS_STRING(c.valid_bytes);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *o = PyList_GET_ITEM(arg, i);
    if (o == Py_None) {
      d[i] = 0;
      v[i] = 0;
      continue;
    }
    if (PyLong_Check(o) && !PyBool_Check(o)) {
      int overflow = 0;
      long long val = PyLong_AsLongLongAndOverflow(o, &overflow);
      if (!overflow) {
        d[i] = static_cast<int64_t>(val);
        v[i] = 1;
        continue;
      }
    }
    d[i] = 0;
    v[i] = 1;  // slot unusable; caller boxes the row
    PyObject *idx = PyLong_FromSsize_t(i);
    PyList_Append(c.bad_list, idx);
    Py_DECREF(idx);
  }
  return Py_BuildValue("(NNN)", data, c.valid_bytes, c.bad_list);
}

static PyObject *encode_f64(PyObject *, PyObject *arg) {
  if (!PyList_Check(arg)) {
    PyErr_SetString(PyExc_TypeError, "expected list");
    return nullptr;
  }
  Py_ssize_t n = PyList_GET_SIZE(arg);
  PyObject *data = PyBytes_FromStringAndSize(nullptr, n * 8);
  EncodedCommon c;
  if (!data || !alloc_common(n, c)) return nullptr;
  double *d = reinterpret_cast<double *>(PyBytes_AS_STRING(data));
  char *v = PyBytes_AS_STRING(c.valid_bytes);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *o = PyList_GET_ITEM(arg, i);
    if (o == Py_None) {
      d[i] = 0.0;
      v[i] = 0;
      continue;
    }
    if (PyFloat_Check(o)) {
      d[i] = PyFloat_AS_DOUBLE(o);
      v[i] = 1;
      continue;
    }
    d[i] = 0.0;
    v[i] = 1;
    PyObject *idx = PyLong_FromSsize_t(i);
    PyList_Append(c.bad_list, idx);
    Py_DECREF(idx);
  }
  return Py_BuildValue("(NNN)", data, c.valid_bytes, c.bad_list);
}

static PyObject *encode_bool(PyObject *, PyObject *arg) {
  if (!PyList_Check(arg)) {
    PyErr_SetString(PyExc_TypeError, "expected list");
    return nullptr;
  }
  Py_ssize_t n = PyList_GET_SIZE(arg);
  PyObject *data = PyBytes_FromStringAndSize(nullptr, n);
  EncodedCommon c;
  if (!data || !alloc_common(n, c)) return nullptr;
  char *d = PyBytes_AS_STRING(data);
  char *v = PyBytes_AS_STRING(c.valid_bytes);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *o = PyList_GET_ITEM(arg, i);
    if (o == Py_None) {
      d[i] = 0;
      v[i] = 0;
      continue;
    }
    if (PyBool_Check(o)) {
      d[i] = (o == Py_True) ? 1 : 0;
      v[i] = 1;
      continue;
    }
    d[i] = 0;
    v[i] = 1;
    PyObject *idx = PyLong_FromSsize_t(i);
    PyList_Append(c.bad_list, idx);
    Py_DECREF(idx);
  }
  return Py_BuildValue("(NNN)", data, c.valid_bytes, c.bad_list);
}

static PyObject *encode_str(PyObject *, PyObject *arg) {
  if (!PyList_Check(arg)) {
    PyErr_SetString(PyExc_TypeError, "expected list");
    return nullptr;
  }
  Py_ssize_t n = PyList_GET_SIZE(arg);
  // pass 1: utf8 views + max width
  std::vector<const char *> ptrs(static_cast<size_t>(n), nullptr);
  std::vector<Py_ssize_t> lens(static_cast<size_t>(n), 0);
  std::vector<Py_ssize_t> bad;
  Py_ssize_t w = 1;
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *o = PyList_GET_ITEM(arg, i);
    if (o == Py_None) continue;
    if (PyUnicode_Check(o)) {
      Py_ssize_t sz = 0;
      const char *u = PyUnicode_AsUTF8AndSize(o, &sz);
      if (u) {
        ptrs[static_cast<size_t>(i)] = u;
        lens[static_cast<size_t>(i)] = sz;
        if (sz > w) w = sz;
        continue;
      }
      PyErr_Clear();
    }
    bad.push_back(i);
  }
  PyObject *mat = PyBytes_FromStringAndSize(nullptr, n * w);
  PyObject *lens_b = PyBytes_FromStringAndSize(nullptr, n * 4);
  PyObject *valid_b = PyBytes_FromStringAndSize(nullptr, n);
  PyObject *bad_list = PyList_New(0);
  if (!mat || !lens_b || !valid_b || !bad_list) return nullptr;
  char *m = PyBytes_AS_STRING(mat);
  int32_t *lp = reinterpret_cast<int32_t *>(PyBytes_AS_STRING(lens_b));
  char *v = PyBytes_AS_STRING(valid_b);
  memset(m, 0, static_cast<size_t>(n * w));
  for (Py_ssize_t i = 0; i < n; i++) {
    const char *u = ptrs[static_cast<size_t>(i)];
    if (u) {
      memcpy(m + i * w, u, static_cast<size_t>(lens[static_cast<size_t>(i)]));
      lp[i] = static_cast<int32_t>(lens[static_cast<size_t>(i)]);
      v[i] = 1;
    } else {
      lp[i] = 0;
      v[i] = 0;
    }
  }
  for (Py_ssize_t i : bad) {
    v[i] = 1;  // not a None: row must be boxed by the caller
    PyObject *idx = PyLong_FromSsize_t(i);
    PyList_Append(bad_list, idx);
    Py_DECREF(idx);
  }
  return Py_BuildValue("(NNNnN)", mat, lens_b, valid_b, w, bad_list);
}

// Arrow large_string buffers -> zero-padded [n, w] byte matrix + clamped
// int32 lens + unclamped int64 lens. The hot half of CSV/ORC ingestion
// (python fallback: runtime/columns.py arrow_string_to_leaf's fancy-index
// gather builds an [n, w] index matrix first — this is one pass of memcpy).
static PyObject *offsets_to_matrix(PyObject *, PyObject *args) {
  Py_buffer data, offs;
  Py_ssize_t n, aoff, maxw;
  if (!PyArg_ParseTuple(args, "y*y*nnn", &data, &offs, &n, &aoff, &maxw))
    return nullptr;
  if (maxw < 0) maxw = 0;  // python fallback: w = min(max_len, maxw) >= 0
  if (offs.len < static_cast<Py_ssize_t>((aoff + n + 1) * 8) ||
      n < 0 || aoff < 0) {
    PyBuffer_Release(&data);
    PyBuffer_Release(&offs);
    PyErr_SetString(PyExc_ValueError, "offsets buffer too small");
    return nullptr;
  }
  const int64_t *off = reinterpret_cast<const int64_t *>(offs.buf) + aoff;
  int64_t wmax = 1;
  for (Py_ssize_t i = 0; i < n; i++) {
    int64_t li = off[i + 1] - off[i];
    if (li > wmax) wmax = li;
  }
  Py_ssize_t w = static_cast<Py_ssize_t>(wmax < maxw ? wmax : maxw);
  PyObject *mat = PyBytes_FromStringAndSize(nullptr, n * w);
  PyObject *lens_b = PyBytes_FromStringAndSize(nullptr, n * 4);
  PyObject *full_b = PyBytes_FromStringAndSize(nullptr, n * 8);
  if (!mat || !lens_b || !full_b) {
    PyBuffer_Release(&data);
    PyBuffer_Release(&offs);
    Py_XDECREF(mat);
    Py_XDECREF(lens_b);
    Py_XDECREF(full_b);
    return nullptr;
  }
  char *m = PyBytes_AS_STRING(mat);
  int32_t *lp = reinterpret_cast<int32_t *>(PyBytes_AS_STRING(lens_b));
  int64_t *fp = reinterpret_cast<int64_t *>(PyBytes_AS_STRING(full_b));
  const char *src = reinterpret_cast<const char *>(data.buf);
  bool ok = true;
  Py_BEGIN_ALLOW_THREADS;
  memset(m, 0, static_cast<size_t>(n * w));
  for (Py_ssize_t i = 0; i < n; i++) {
    int64_t start = off[i];
    int64_t li = off[i + 1] - start;
    if (start < 0 || li < 0 || start + li > data.len) {
      ok = false;
      break;
    }
    int64_t c = li < w ? li : w;
    memcpy(m + i * w, src + start, static_cast<size_t>(c));
    lp[i] = static_cast<int32_t>(c);
    fp[i] = li;
  }
  Py_END_ALLOW_THREADS;
  PyBuffer_Release(&data);
  PyBuffer_Release(&offs);
  if (!ok) {
    Py_DECREF(mat);
    Py_DECREF(lens_b);
    Py_DECREF(full_b);
    PyErr_SetString(PyExc_ValueError, "offsets out of data bounds");
    return nullptr;
  }
  return Py_BuildValue("(NNNn)", mat, lens_b, full_b, w);
}

static PyObject *decode_str(PyObject *, PyObject *args) {
  PyObject *mat_obj, *lens_obj;
  Py_ssize_t w, n;
  if (!PyArg_ParseTuple(args, "SSnn", &mat_obj, &lens_obj, &w, &n))
    return nullptr;
  const char *m = PyBytes_AS_STRING(mat_obj);
  const int32_t *lp =
      reinterpret_cast<const int32_t *>(PyBytes_AS_STRING(lens_obj));
  if (PyBytes_GET_SIZE(mat_obj) < n * w ||
      PyBytes_GET_SIZE(lens_obj) < n * 4) {
    PyErr_SetString(PyExc_ValueError, "buffer too small");
    return nullptr;
  }
  PyObject *out = PyList_New(n);
  if (!out) return nullptr;
  for (Py_ssize_t i = 0; i < n; i++) {
    int32_t li = lp[i];
    if (li < 0) li = 0;
    if (li > w) li = static_cast<int32_t>(w);
    PyObject *s =
        PyUnicode_DecodeUTF8(m + i * w, li, "replace");
    if (!s) {
      Py_DECREF(out);
      return nullptr;
    }
    PyList_SET_ITEM(out, i, s);
  }
  return out;
}

static PyMethodDef Methods[] = {
    {"encode_i64", encode_i64, METH_O, "bulk encode int column"},
    {"encode_f64", encode_f64, METH_O, "bulk encode float column"},
    {"encode_bool", encode_bool, METH_O, "bulk encode bool column"},
    {"encode_str", encode_str, METH_O, "bulk encode str column"},
    {"offsets_to_matrix", offsets_to_matrix, METH_VARARGS,
     "arrow offsets+data -> padded byte matrix"},
    {"decode_str", decode_str, METH_VARARGS, "bulk decode str column"},
    {nullptr, nullptr, 0, nullptr}};

static struct PyModuleDef Module = {PyModuleDef_HEAD_INIT, "_tuplex_native",
                                    "native host runtime kernels", -1,
                                    Methods};

}  // namespace

PyMODINIT_FUNC PyInit__tuplex_native(void) { return PyModule_Create(&Module); }
