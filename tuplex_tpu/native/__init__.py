"""Native host-runtime kernels: lazy-compiled CPython extension.

The reference's native layer (reference: tuplex/runtime + the pybind'd fast
transfer of PythonContext.cc) becomes a small C++ extension compiled on
first use with the system toolchain and cached next to the source; every
entry point has a pure-python fallback so the framework works without a
compiler.
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import sysconfig
from typing import Optional

_mod = None
_tried = False


def _build_dir() -> str:
    d = os.path.join(os.path.dirname(__file__), "_build")
    os.makedirs(d, exist_ok=True)
    return d


def _compile() -> Optional[str]:
    src = os.path.join(os.path.dirname(__file__), "src", "fasttransfer.cpp")
    with open(src, "rb") as fp:
        tag = hashlib.sha256(fp.read()).hexdigest()[:12]
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    out = os.path.join(_build_dir(), f"_tuplex_native_{tag}{suffix}")
    if os.path.exists(out):
        return out
    include = sysconfig.get_paths()["include"]
    cxx = os.environ.get("CXX", "g++")
    tmp = out + f".tmp{os.getpid()}"
    cmd = [cxx, "-O2", "-shared", "-fPIC", "-std=c++17",
           f"-I{include}", src, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, out)  # atomic: killed/concurrent builds can't leave
        return out            # a truncated .so at the cached path
    except (subprocess.CalledProcessError, FileNotFoundError,
            subprocess.TimeoutExpired):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


def get() -> Optional[object]:
    """The compiled module, or None when unavailable (python fallback)."""
    global _mod, _tried
    if _mod is not None or _tried:
        return _mod
    _tried = True
    if os.environ.get("TUPLEX_TPU_NO_NATIVE"):
        return None
    path = _compile()
    if path is None:
        return None
    import importlib.util

    spec = importlib.util.spec_from_file_location("_tuplex_native", path)
    if spec is None or spec.loader is None:
        return None
    try:
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _mod = mod
    except Exception:
        _mod = None
    return _mod
