"""Serverless-style fan-out backend (reference: AWSLambdaBackend,
core/src/ee/aws/AWSLambdaBackend.cc:254-506 + awslambda/src/lambda_main.cc).

The reference ships each stage as a protobuf InvocationRequest (LLVM
bitcode + symbols + S3 input/output URIs) to AWS Lambda workers, uploads
memory inputs to an S3 scratch dir, invokes up to aws.maxConcurrency
lambdas, polls responses, and downloads output parts. This backend is the
same architecture with TPU-native substitutions:

- invocation   = a detached WORKER PROCESS (`python -m tuplex_tpu.exec.
  worker`) — the process boundary stands in for the cloud boundary; on a
  real pod each worker owns its own chip/host (set
  ``tuplex.aws.workerPlatform`` accordingly).
- bitcode      = the stage SPEC: normalized UDF sources + captured globals
  (utils/reflection) + schemas + source recipe. Workers re-derive the
  jitted XLA executable through the ordinary emitter — the persistent
  compile cache dedupes compilation across workers.
- S3 parts     = directories of native-format partitions
  (io/tuplexfmt npz parts + manifest) under ``tuplex.aws.scratchDir``.
- file splits  = multi-file sources are split BY FILE across tasks and
  read inside the worker (AWSLambdaBackend.cc:410-430 input_uris); memory
  / intermediate inputs are staged to scratch first (:306-330).

Failure path: a task that dies, times out, or writes no valid response is
retried ``tuplex.aws.retryCount`` times and finally re-run in-process on
the driver (degrade, never wedge); every attempt lands in the backend
failure log. Aggregate/join/limit stages run on the driver, like the
reference's driver-side resolve/merge tier (AWSLambdaBackend.cc:468-506).
"""

from __future__ import annotations

import os
import pickle
import subprocess
import sys
import time
import types
from typing import Any, Optional

from ..core.errors import TuplexException
from ..plan import logical as L
from ..utils.logging import get_logger
from ..utils.reflection import UDFSource, get_udf_source
from .local import LocalBackend, StageResult

log = get_logger("tuplex_tpu.serverless")



from ..io.vfs import join_uri as _djoin  # noqa: E402

class NotShippable(Exception):
    """Stage/UDF cannot be serialized for remote execution (no source, an
    unpicklable captured global, an unknown operator...). The driver falls
    back to in-process execution — never a user-visible failure."""


# ---------------------------------------------------------------------------
# UDF + operator spec (de)serialization
# ---------------------------------------------------------------------------

def _pack_value(v: Any, owner: UDFSource, seen: frozenset):
    """One captured global -> a picklable tagged cell. `seen` carries the
    code objects of enclosing UDFs so helper-function cycles terminate."""
    if isinstance(v, types.ModuleType):
        return ("mod", v.__name__)
    if isinstance(v, types.FunctionType):
        if getattr(owner.func, "__code__", None) is v.__code__ \
                and owner.source.startswith("def"):
            # a recursive def references itself by name; the worker-side
            # exec re-binds that name in the rebuilt function's own
            # namespace, so nothing needs to travel
            return ("selfref",)
        if v.__code__ in seen:
            raise NotShippable(f"mutually recursive helper {v!r}")
        us = get_udf_source(v)
        if us.source:
            return ("udf", _udf_spec(us, seen | {v.__code__}))
        raise NotShippable(f"global function {v!r} has no source")
    try:
        return ("pkl", pickle.dumps(v))
    except Exception as e:
        raise NotShippable(f"global {v!r} not picklable: {e}") from None


def _unpack_value(cell):
    tag = cell[0]
    if tag == "selfref":
        return None   # dropped: the exec'd def binds its own name
    if tag == "mod":
        import importlib

        return importlib.import_module(cell[1])
    if tag == "udf":
        return _rebuild_udf(cell[1])
    return pickle.loads(cell[1])


def _udf_spec(us: UDFSource, seen: frozenset = frozenset()) -> dict:
    if not us.source:
        raise NotShippable(f"UDF {us.name!r} has no retrievable source")
    code = getattr(us.func, "__code__", None)
    if code is not None:
        seen = seen | {code}
    return {"src": us.source, "name": us.name,
            "globals": {k: _pack_value(v, us, seen)
                        for k, v in us.globals.items()}}


def _rebuild_udf(spec: dict):
    from ..utils.reflection import udf_from_source

    globs = {k: _unpack_value(c) for k, c in spec["globals"].items()
             if c[0] != "selfref"}
    return udf_from_source(spec["src"], spec["name"], globs)


def _op_spec(op: L.LogicalOperator) -> tuple:
    """Operator -> ctor recipe. Only data + UDF sources travel; the worker
    reconstructs real operator objects against its own chain."""
    from ..io.csvsource import CSVSourceOperator  # noqa: F401 (isinstance)

    if isinstance(op, L.MapOperator):
        return ("map", _udf_spec(op.udf))
    if isinstance(op, L.FilterOperator):
        return ("filter", _udf_spec(op.udf))
    if isinstance(op, L.WithColumnOperator):
        return ("withcol", op.column, _udf_spec(op.udf))
    if isinstance(op, L.MapColumnOperator):
        return ("mapcol", op.column, _udf_spec(op.udf))
    if isinstance(op, L.SelectColumnsOperator):
        return ("select", list(op.selected))
    if isinstance(op, L.RenameColumnOperator):
        return ("rename", op.old, op.new)
    if isinstance(op, L.ResolveOperator):
        return ("resolve", pickle.dumps(op.exc_class), _udf_spec(op.udf))
    if isinstance(op, L.IgnoreOperator):
        return ("ignore", pickle.dumps(op.exc_class))
    if isinstance(op, L.TakeOperator):
        return ("take", op.limit)
    if isinstance(op, L.DecodeOperator):
        return ("decode",
                pickle.dumps((op.declared, op.null_values, op.general)))
    raise NotShippable(f"operator {type(op).__name__} not shippable")


def _op_rebuild(spec: tuple, parent: L.LogicalOperator) -> L.LogicalOperator:
    kind = spec[0]
    if kind == "map":
        return L.MapOperator(parent, _rebuild_udf(spec[1]))
    if kind == "filter":
        return L.FilterOperator(parent, _rebuild_udf(spec[1]))
    if kind == "withcol":
        return L.WithColumnOperator(parent, spec[1], _rebuild_udf(spec[2]))
    if kind == "mapcol":
        return L.MapColumnOperator(parent, spec[1], _rebuild_udf(spec[2]))
    if kind == "select":
        return L.SelectColumnsOperator(parent, spec[1])
    if kind == "rename":
        return L.RenameColumnOperator(parent, spec[1], spec[2])
    if kind == "resolve":
        return L.ResolveOperator(parent, pickle.loads(spec[1]),
                                 _rebuild_udf(spec[2]))
    if kind == "ignore":
        return L.IgnoreOperator(parent, pickle.loads(spec[1]))
    if kind == "take":
        return L.TakeOperator(parent, spec[1])
    if kind == "decode":
        declared, nulls, general = pickle.loads(spec[1])
        return L.DecodeOperator(parent, declared, nulls, general)
    raise TuplexException(f"unknown op spec {kind!r}")


class _SpecInput(L.LogicalOperator):
    """Worker-side stand-in for the upstream chain of a staged-input task:
    fixed schema, sample shipped from the driver (may be empty — planning
    already happened there; the sample only feeds worker-side cost
    heuristics like compaction sizing)."""

    def __init__(self, schema, columns, sample_rows):
        super().__init__([])
        self._schema = schema
        self._columns = columns
        self._sample = sample_rows

    def schema(self):
        return self._schema

    def columns(self):
        return self._columns

    def sample(self):
        from ..core.row import Row

        return [Row(list(v), self._columns) for v in self._sample]


def serialize_stage(stage) -> dict:
    """TransformStage -> picklable spec (the InvocationRequest 'code' half;
    reference: TransformStage::to_protobuf, physical/TransformStage.h:76)."""
    spec: dict[str, Any] = {
        "ops": [_op_spec(op) for op in stage.ops],
        "schemas": pickle.dumps(
            [op.schema() for op in stage.ops]),
        "input_schema": pickle.dumps(stage.input_schema),
        "input_columns": _input_columns(stage),
        "limit": stage.limit,
        "force_interpret": stage.force_interpret,
        "source_projection": getattr(stage, "source_projection", None),
        "sample": _input_sample(stage),
    }
    src = stage.source
    if src is None or isinstance(src, L.ParallelizeOperator):
        # memory input: the driver stages partitions to scratch (reference:
        # upload to S3 scratch, AWSLambdaBackend.cc:306-330); the worker
        # sees only the staged parts
        spec["source"] = None
    elif type(src).__name__ == "CSVSourceOperator":
        spec["source"] = ("csv", src.pattern, pickle.dumps(src.stat))
    elif type(src).__name__ == "ORCSourceOperator":
        spec["source"] = ("orc", src.pattern, src.user_cols)
    elif type(src).__name__ == "TuplexFileSourceOperator":
        # directory source: the driver already has the partitions loaded;
        # ship them through the staged-parts path like memory inputs
        spec["source"] = None
    else:
        raise NotShippable(f"source {type(src).__name__} not shippable")
    return spec


def _input_columns(stage):
    src_like = stage.source
    if src_like is None and stage.ops:
        src_like = stage.ops[0].parent if stage.ops[0].parents else None
    if src_like is not None:
        try:
            return src_like.columns()
        except Exception:
            pass
    return stage.input_schema.columns


def _input_sample(stage, cap: int = 256):
    """Up to `cap` input rows (as value tuples) for worker-side cost
    heuristics. Best-effort: an empty sample only disables compaction."""
    src_like = stage.source
    if src_like is None and stage.ops and stage.ops[0].parents:
        src_like = stage.ops[0].parent
    if src_like is None:
        return []
    try:
        rows = src_like.cached_sample()[:cap]
        return pickle.dumps([tuple(r.values) for r in rows])
    except Exception:
        return []


def rebuild_stage(spec: dict, options, files: Optional[list] = None):
    """Spec -> executable TransformStage (worker side). `files` is this
    task's file-split subset for file sources."""
    from ..plan.physical import TransformStage

    input_schema = pickle.loads(spec["input_schema"])
    sample = pickle.loads(spec["sample"]) if spec["sample"] else []
    source = None
    sspec = spec["source"]
    if files is None:
        # staged-parts task: input partitions arrive via the scratch dir
        # regardless of what the original source was
        sspec = None
    if sspec is None:
        root: L.LogicalOperator = _SpecInput(
            input_schema, spec["input_columns"], sample)
    elif sspec[0] == "csv":
        from ..io.csvsource import CSVSourceOperator

        source = CSVSourceOperator(options, sspec[1],
                                   pickle.loads(sspec[2]), list(files or []))
        root = source
    elif sspec[0] == "orc":
        from ..io.orcsource import ORCSourceOperator

        source = ORCSourceOperator(options, sspec[1], list(files or []),
                                   sspec[2])
        root = source
    else:
        raise TuplexException(f"unknown source spec {sspec!r}")

    ops: list[L.LogicalOperator] = []
    parent = root
    schemas = pickle.loads(spec["schemas"])
    for i, (ospec, schema) in enumerate(zip(spec["ops"], schemas)):
        op = _op_rebuild(ospec, parent)
        # authoritative schemas travel with the spec: workers must never
        # re-speculate (different file subsets could sniff differently)
        op._schema_cache = schema          # UDFOperator slot
        op._schema = schema                # structural-op convention
        # DETERMINISTIC stage-local ids: the emitter bakes `code |
        # op_id << 8` literals into the kernel lattice, so ids from the
        # session-global counter would give every rebuilt job a unique
        # jaxpr and defeat the content-addressed executable dedup the
        # job service depends on (N isomorphic tenants ~ 1 compile set).
        # Ids only need to be unique WITHIN the stage: resolver matching
        # and the python pipeline are positional, and nothing maps ids
        # globally back to operators on the rebuild side.
        op.id = i + 1
        ops.append(op)
        parent = op

    stage = TransformStage(source, ops, limit=spec["limit"],
                           input_schema=input_schema,
                           input_op=None if source is not None else root)
    stage.force_interpret = spec["force_interpret"]
    if spec["source_projection"] is not None:
        stage.source_projection = spec["source_projection"]
    return stage


# ---------------------------------------------------------------------------
# driver-side backend
# ---------------------------------------------------------------------------

class _WarmWorker:
    """A long-lived `--serve` worker process. busy: None = idle, task id
    while processing, -1 = condemned (killed / wedged). `logf` is the
    driver-side handle of the worker's log file — kept so close() can
    release the fd (the child holds its own descriptor)."""

    __slots__ = ("proc", "busy", "resp_path", "logf")

    def __init__(self, proc, logf=None):
        self.proc = proc
        self.busy = None
        self.resp_path = ""
        self.logf = logf

    def close_log(self) -> None:
        if self.logf is not None:
            try:
                self.logf.close()
            except OSError:
                pass
            self.logf = None


class ServerlessBackend(LocalBackend):
    """Fan a TransformStage out over detached worker processes with
    object-store-style part staging. Aggregates, joins, fused folds, and
    limited (take) stages run on the driver via LocalBackend."""

    # tocsv() to a directory ships the sink INTO the workers: each task
    # writes its own part file from columnar buffers (reference: Lambda
    # tasks writing S3 output.part-N, AWSLambdaBackend.cc:410-430)
    supports_sink_pushdown = True

    def __init__(self, options):
        super().__init__(options)
        # counts WORKERS, not local cores (reference: concurrent Lambda
        # invocations) — on a real deployment each worker owns its own
        # host/chip, so do not clamp to the driver's cpu_count
        self.max_conc = max(1, options.get_int(
            "tuplex.aws.maxConcurrency", 100))
        self.retries = options.get_int("tuplex.aws.retryCount", 2)
        self.timeout_s = options.get_int("tuplex.aws.requestTimeout", 600)
        scratch = options.get_str("tuplex.aws.scratchDir", "") or \
            os.path.join(options.get_str("tuplex.scratchDir",
                                         "/tmp/tuplex_tpu"), "serverless")
        self.scratch = scratch
        # remote scratch (s3://...): the DATA plane (staged in-parts, task
        # out-parts) rides the object store; the CONTROL plane (request
        # pickles, worker logs, responses) stays host-local — the analog
        # of the Invoke API payload vs S3 in the reference
        # (AWSLambdaBackend.cc:306-330 + :410-430)
        from ..io.vfs import is_remote_uri

        self.scratch_remote = is_remote_uri(scratch)
        self.control_root = os.path.join(
            options.get_str("tuplex.scratchDir", "/tmp/tuplex_tpu"),
            "serverless-ctl") if self.scratch_remote else scratch
        # warm worker pool (reference: Lambda container reuse — the
        # measured cold path costs ~15 s/task in interpreter+jax import and
        # stage re-trace; a warm worker amortizes both across tasks and
        # across jobs). Workers persist on the backend until close().
        self.reuse = options.get_bool("tuplex.aws.reuseWorkers", True)
        self._pool: list = []

    def close(self) -> None:
        """Shut down warm workers (EXIT handshake, then terminate)."""
        for w in self._pool:
            try:
                if w.proc.poll() is None:
                    w.proc.stdin.write("EXIT\n")
                    w.proc.stdin.flush()
            except OSError:
                pass
        for w in self._pool:
            try:
                w.proc.wait(timeout=2)
            except Exception:
                try:
                    w.proc.kill()
                except OSError:
                    pass
            # one leaked driver-side fd per warm worker otherwise
            # (ADVICE r5); the child's own descriptor died with it
            w.close_log()
        self._pool = []

    def __del__(self):  # pragma: no cover - interpreter teardown
        try:
            self.close()
        except Exception:
            pass

    # -- dispatch ----------------------------------------------------------
    def execute_any(self, stage, partitions, context,
                    intermediate: bool = False,
                    sink: Optional[dict] = None) -> StageResult:
        from ..plan.physical import TransformStage

        self._sink_pushed = False
        fan_out = (isinstance(stage, TransformStage)
                   and stage.fold_op is None
                   and stage.limit < 0
                   and not self.interpret_only)
        if fan_out:
            try:
                spec = serialize_stage(stage)
            except NotShippable as e:
                log.info("stage not shippable (%s); running on driver", e)
            except Exception as e:   # serialization must never kill a job
                log.warning("stage spec serialization failed (%s: %s); "
                            "running on driver", type(e).__name__, e)
            else:
                return self._execute_fanout(stage, spec, partitions,
                                            context, sink=sink)
        # device views never survive the process boundary
        return super().execute_any(stage, partitions, context,
                                   intermediate=False)

    # -- task planning -----------------------------------------------------
    def _plan_tasks(self, stage, spec, partitions, run_dir):
        """Returns a list of task dicts ({'files': [...]} or
        {'indir': path}). File sources with >1 file split BY FILE (workers
        read their own input); everything else stages partitions to
        scratch."""
        from ..io.tuplexfmt import write_partitions_tuplex

        src = stage.source
        files = list(getattr(src, "files", []) or []) if src is not None \
            else []
        if src is not None and len(files) > 1 and spec["source"] is not None \
                and spec["source"][0] in ("csv", "orc"):
            n_tasks = min(self.max_conc, len(files))
            per = -(-len(files) // n_tasks)
            return [{"files": files[i: i + per]}
                    for i in range(0, len(files), per)]
        # memory / intermediate / single-file input: stage partitions
        parts = list(partitions or [])
        if not parts:
            return []
        n_tasks = min(self.max_conc, len(parts))
        per = -(-len(parts) // n_tasks)
        tasks = []
        for t, i in enumerate(range(0, len(parts), per)):
            indir = _djoin(run_dir, f"in-{t:04d}")
            write_partitions_tuplex(indir, parts[i: i + per], backend=self)
            tasks.append({"indir": indir})
        return tasks

    # -- fan-out core ------------------------------------------------------
    def _execute_fanout(self, stage, spec, partitions, context,
                        sink: Optional[dict] = None) -> StageResult:
        import uuid

        from ..utils.signals import check_interrupted

        t0 = time.perf_counter()
        fl_snap = len(self.failure_log)
        runid = uuid.uuid4().hex[:12]
        run_dir = os.path.join(self.control_root, runid)
        data_dir = _djoin(self.scratch, runid) if self.scratch_remote \
            else run_dir
        os.makedirs(run_dir, exist_ok=True)
        tasks = self._plan_tasks(stage, spec, partitions, data_dir)
        if not tasks:
            return StageResult([], [], {"serverless_tasks": 0})
        if sink is not None:
            _sweep_stale_parts(sink, len(tasks))
        req_base = {"stage": spec, "options": self.options.to_dict(),
                    "sink": sink}
        procs: dict[int, tuple[subprocess.Popen, float, int]] = {}
        done: dict[int, Optional[str]] = {}   # task -> outdir (None = local)
        pending = list(range(len(tasks)))
        attempts = {t: 0 for t in pending}
        recorder = getattr(context, "recorder", None)
        ev_offsets: dict[int, int] = {}
        try:
            while pending or procs:
                check_interrupted()
                while pending and len(procs) < self.max_conc:
                    t = pending[0]
                    if self.reuse:
                        w = self._acquire_worker()
                        if w is None:
                            break       # every warm worker busy
                        pending.pop(0)
                        self._send_task(w, run_dir, data_dir, t,
                                        tasks[t], req_base)
                        procs[t] = (w, time.perf_counter(), attempts[t])
                    else:
                        pending.pop(0)
                        procs[t] = (self._launch(run_dir, data_dir, t,
                                                 tasks[t], req_base),
                                    time.perf_counter(), attempts[t])
                self._reap(procs, done, pending, attempts, tasks, run_dir,
                           data_dir, recorder=recorder,
                           ev_offsets=ev_offsets)
                # only RUNNING tasks can grow their events file; completed
                # tasks drain once inside _reap at the transition
                self._pump_task_events(run_dir, ev_offsets, recorder,
                                       list(procs))
                if procs:
                    time.sleep(0.02)
        finally:
            for p, _, _ in procs.values():
                try:
                    (p.proc if isinstance(p, _WarmWorker) else p).kill()
                except OSError:
                    pass
        result = self._collect(stage, tasks, done, context, run_dir, t0,
                               fl_snap, sink=sink)
        if sink is not None:
            self._sink_pushed = True
        if all(d is not None for d in done.values()):
            # clean scratch only for fully-healthy runs; failed runs keep
            # their request/worker.log for post-mortem (reference keeps the
            # S3 scratch parts for the same reason)
            import shutil

            shutil.rmtree(run_dir, ignore_errors=True)
            if self.scratch_remote:
                from ..io.vfs import VirtualFileSystem as VFS

                try:
                    # PREFIX listing ("dir/"), not a glob: '*' does not
                    # cross '/' in the object-store backends, so a glob
                    # would miss every nested key (review r4)
                    for uri in VFS.ls(data_dir.rstrip("/") + "/"):
                        VFS.rm(uri)
                except Exception:
                    pass    # best-effort (reference leaves S3 scratch too)
        return result

    def _write_request(self, run_dir: str, data_dir: str, task: int,
                       tspec: dict, req_base: dict) -> str:
        task_dir = os.path.join(run_dir, f"task-{task:04d}")
        os.makedirs(task_dir, exist_ok=True)
        # a retry must not see the failed attempt's response as completion
        try:
            os.remove(os.path.join(task_dir, "response.pkl"))
        except OSError:
            pass
        req = dict(req_base)
        req["task"] = task
        req["files"] = tspec.get("files")
        req["indir"] = tspec.get("indir")
        req["outdir"] = _djoin(_djoin(data_dir, f"task-{task:04d}"), "out")
        req_path = os.path.join(task_dir, "request.pkl")
        with open(req_path, "wb") as fp:
            pickle.dump(req, fp)
        return req_path

    def _worker_env(self) -> dict:
        env = dict(os.environ)
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_root + os.pathsep + \
            env.get("PYTHONPATH", "")
        env["TUPLEX_WORKER_PLATFORM"] = self.options.get_str(
            "tuplex.aws.workerPlatform", "cpu")
        return env

    def _launch(self, run_dir: str, data_dir: str, task: int, tspec: dict,
                req_base: dict) -> subprocess.Popen:
        req_path = self._write_request(run_dir, data_dir, task, tspec,
                                       req_base)
        task_dir = os.path.dirname(req_path)
        with open(os.path.join(task_dir, "worker.log"), "wb") as logf:
            return subprocess.Popen(
                [sys.executable, "-m", "tuplex_tpu.exec.worker", req_path],
                stdout=logf, stderr=subprocess.STDOUT,
                env=self._worker_env())

    # -- warm pool (reference: Lambda container reuse) ---------------------
    def _spawn_warm(self) -> "_WarmWorker":
        wid = len(self._pool)
        logdir = os.path.join(self.control_root, "workers")
        os.makedirs(logdir, exist_ok=True)
        logf = open(os.path.join(logdir, f"worker-{wid}.log"), "ab")
        try:
            proc = subprocess.Popen(
                [sys.executable, "-m", "tuplex_tpu.exec.worker", "--serve"],
                stdin=subprocess.PIPE, stdout=logf,
                stderr=subprocess.STDOUT, env=self._worker_env(), text=True)
        except Exception:
            logf.close()
            raise
        return _WarmWorker(proc, logf)

    def _acquire_worker(self):
        """An idle live warm worker, spawning up to max_conc; None if all
        are busy."""
        for w in self._pool:
            if w.proc.poll() is not None:
                w.close_log()       # dead worker: release the driver-side fd
        self._pool = [w for w in self._pool if w.proc.poll() is None]
        for w in self._pool:
            if w.busy is None:
                return w
        if len(self._pool) < self.max_conc:
            w = self._spawn_warm()
            self._pool.append(w)
            return w
        return None

    def _send_task(self, w: "_WarmWorker", run_dir: str, data_dir: str,
                   task: int, tspec: dict, req_base: dict) -> None:
        req_path = self._write_request(run_dir, data_dir, task, tspec,
                                       req_base)
        w.busy = task
        w.resp_path = os.path.join(os.path.dirname(req_path),
                                   "response.pkl")
        try:
            w.proc.stdin.write(req_path + "\n")
            w.proc.stdin.flush()
        except OSError:
            pass    # dead worker: _reap sees proc.poll() and retries

    def _reap(self, procs, done, pending, attempts, tasks, run_dir,
              data_dir, recorder=None, ev_offsets=None):
        now = time.perf_counter()
        for t in list(procs):
            p, started, att = procs[t]
            warm = isinstance(p, _WarmWorker)
            proc = p.proc if warm else p
            resp = os.path.join(run_dir, f"task-{t:04d}", "response.pkl")
            rc = proc.poll()
            # warm workers signal completion by the atomic response write
            # (the process stays alive); cold workers by exiting
            completed = os.path.exists(resp) if warm else rc is not None
            if not completed and rc is None:
                if now - started > self.timeout_s:
                    proc.kill()   # a warm worker dies with its stuck task
                    rc = -9
                else:
                    continue
            del procs[t]
            if warm:
                p.busy = None if (completed and rc is None) else -1
            # drain the worker's remaining events exactly once, at the
            # transition — its file cannot grow after the task completes
            if ev_offsets is not None:
                self._pump_task_events(run_dir, ev_offsets, recorder, [t])
            outdir = _djoin(_djoin(data_dir, f"task-{t:04d}"), "out")
            resp_ok = False
            if os.path.exists(resp):
                try:
                    with open(resp, "rb") as fp:
                        resp_ok = bool(pickle.load(fp).get("ok", True))
                except Exception:
                    resp_ok = False
            if resp_ok and (rc == 0 or (warm and rc is None)):
                done[t] = outdir
                continue
            tail = self._log_tail(run_dir, t)
            self.failure_log.append({
                "stage": "serverless", "task": t, "attempt": att,
                "rc": rc, "error": tail})
            if att + 1 <= self.retries:
                log.warning("task %d failed (rc=%s); retry %d/%d",
                            t, rc, att + 1, self.retries)
                attempts[t] = att + 1
                pending.append(t)
                if recorder is not None and getattr(recorder, "enabled",
                                                    False):
                    recorder.worker_task_event(
                        t, {"event": "retry", "rc": rc,
                            "attempt": att + 1})
            else:
                log.warning("task %d failed after %d attempts; running "
                            "on the driver", t, att + 1)
                done[t] = None   # degrade: in-process fallback
                if recorder is not None and getattr(recorder, "enabled",
                                                    False):
                    # terminal event: the archival dashboard must not show
                    # a finished job's task as perpetually running
                    recorder.worker_task_event(
                        t, {"event": "fallback", "rc": rc,
                            "attempt": att + 1})

    @staticmethod
    def _pump_task_events(run_dir: str, offsets: dict, recorder,
                          tasks) -> None:
        """Stream NEW lines of each task's events.jsonl into the history
        recorder (per-task live updates while the fan-out runs — reference:
        HistoryServerConnector.cc:102-198; thserver/rest.py task routes).
        Offsets persist across polls so each event forwards exactly once."""
        if recorder is None or not getattr(recorder, "enabled", False):
            return
        import json

        for t in tasks:
            path = os.path.join(run_dir, f"task-{t:04d}", "events.jsonl")
            try:
                with open(path, "rb") as fp:
                    base = offsets.get(t, 0)
                    fp.seek(base)
                    chunk = fp.read()
            except OSError:
                continue
            # consume only complete lines; a torn tail re-reads next poll
            last_nl = chunk.rfind(b"\n")
            if last_nl < 0:
                continue
            offsets[t] = base + last_nl + 1
            for line in chunk[:last_nl].splitlines():
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                recorder.worker_task_event(t, rec)

    @staticmethod
    def _log_tail(run_dir: str, task: int, n: int = 800) -> str:
        try:
            with open(os.path.join(run_dir, f"task-{task:04d}",
                                   "worker.log"), "rb") as fp:
                fp.seek(0, 2)
                fp.seek(max(0, fp.tell() - n))
                return fp.read().decode("utf-8", "replace")
        except OSError:
            return ""

    # -- result collection -------------------------------------------------
    def _collect(self, stage, tasks, done, context, run_dir, t0,
                 fl_snap, sink: Optional[dict] = None) -> StageResult:
        from ..runtime import columns as C

        out_parts: list = []
        exceptions: list = []
        metrics: dict[str, Any] = {"serverless_tasks": len(tasks),
                                   "serverless_retries":
                                       len(self.failure_log) - fl_snap}
        offset = 0
        for t in range(len(tasks)):
            outdir = done.get(t)
            if outdir is None:
                res = self._run_task_local(stage, tasks[t], context,
                                           sink=sink, task=t)
            else:
                res = self._load_response(run_dir, t, outdir, context,
                                          skip_parts=sink is not None)
            for part in res.partitions:
                part.start_index = offset
                offset += part.num_rows
                self.mm.register(part)
                out_parts.append(part)
            exceptions.extend(res.exceptions)
            for k, v in res.metrics.items():
                if isinstance(v, (int, float)):
                    metrics[k] = metrics.get(k, 0) + v
            offset += res.metrics.get("sink_rows", 0) \
                if isinstance(res.metrics.get("sink_rows"), int) else 0
        metrics["wall_s"] = time.perf_counter() - t0
        metrics["rows_out"] = offset
        return StageResult(C.harmonize_partitions(out_parts), exceptions,
                           metrics)

    def _load_response(self, run_dir, t, outdir, context,
                       skip_parts: bool = False) -> StageResult:
        from ..io.tuplexfmt import TuplexFileSourceOperator

        with open(os.path.join(run_dir, f"task-{t:04d}", "response.pkl"),
                  "rb") as fp:
            resp = pickle.load(fp)
        for entry in resp.get("failure_log", []):
            self.failure_log.append(dict(entry, task=t))
        if skip_parts or not resp.get("rows"):
            m = dict(resp.get("metrics", {}))
            if skip_parts:
                m["sink_rows"] = resp.get("rows", 0)
            return StageResult([], resp.get("exceptions", []), m)
        src = TuplexFileSourceOperator(self.options, outdir)
        parts = src.load_partitions(context)
        return StageResult(parts, resp.get("exceptions", []),
                           resp.get("metrics", {}))

    def _run_task_local(self, stage, tspec, context,
                        sink: Optional[dict] = None,
                        task: int = 0) -> StageResult:
        """Degraded path: run one failed task's share in-process."""
        from ..api.dataset import _source_partitions
        from ..io.tuplexfmt import TuplexFileSourceOperator

        if tspec.get("files") is not None:
            sub = _clone_stage_for_files(stage, tspec["files"])
            parts = _source_partitions(context, sub, lazy=False)
            res = LocalBackend.execute(self, sub, parts)
        else:
            src = TuplexFileSourceOperator(self.options, tspec["indir"])
            res = LocalBackend.execute(self, stage,
                                       src.load_partitions(context))
        if sink is not None:
            write_sink_part(sink, task, res.partitions, backend=self)
            m = dict(res.metrics)
            m["sink_rows"] = sum(p.num_rows for p in res.partitions)
            return StageResult([], res.exceptions, m)
        return res


def _clone_stage_for_files(stage, files):
    """Shallow stage clone whose source reads only `files` (driver-side
    degrade path for a failed file-split task)."""
    import copy

    sub = copy.copy(stage)
    sub.source = copy.copy(stage.source)
    sub.source.files = list(files)
    return sub


def _sweep_stale_parts(sink: dict, n_tasks: int) -> None:
    """A previous run with MORE tasks leaves higher-numbered part files;
    mixing them into this run's directory would silently append old rows
    (task count varies with maxConcurrency/partitioning)."""
    import glob

    from ..io.vfs import VirtualFileSystem

    if VirtualFileSystem._scheme(sink["path"]) != "file":
        return   # remote stores: writers overwrite; sweeping needs listing
    root = VirtualFileSystem._strip(sink["path"].rstrip("/"))
    for f in glob.glob(os.path.join(root, "part*.csv")):
        base = os.path.basename(f)[4:-4]
        try:
            if int(base) >= n_tasks:
                os.unlink(f)
        except (ValueError, OSError):
            pass


def write_sink_part(sink: dict, task: int, partitions, backend=None) -> None:
    """One task's output as its own part file, written straight from
    columnar buffers (reference: per-invocation S3 output parts)."""
    if sink["format"] != "csv":
        raise TuplexException(f"unknown sink format {sink['format']!r}")
    from ..io.csvsink import write_partitions_csv

    path = sink["path"].rstrip("/") + f"/part{task:05d}.csv"
    write_partitions_csv(path, list(partitions), sink.get("columns"),
                         backend=backend,
                         null_value=sink.get("null_value"),
                         header=sink.get("header", True))
