"""Join stage execution: broadcast build side + vectorized probe.

Reference model (reference: PhysicalPlan.cc:145-178 + LocalBackend.cc:213
executeHashJoinStage + HybridHashTable.h:46-60): the build side is fully
materialized into a hash table, broadcast to every task; the probe side
streams. Keys that can't live in the native table go to a python-dict backup
(hybrid). Here:

  * build: factorize build-side keys into sorted signatures (np.unique — C
    speed) + group offsets (CSR layout)
  * probe: per-partition vectorized signature match via np.searchsorted,
    match expansion via np.repeat, row materialization via leaf gathers
  * boxed fallback rows on either side probe/build through a python dict —
    the HybridHashTable semantics
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from ..core import typesys as T
from ..core.row import Row
from ..runtime import columns as C
from .local import ExceptionRecord, StageResult


def _key_signatures(part: C.Partition, ci: int) -> Optional[np.ndarray]:
    """[N] object array of bytes signatures for the key column, None if the
    column isn't vectorizable. None-valued keys get signature b'' + marker."""
    pieces = []
    for path, lt in C.flatten_type(part.schema.types[ci], str(ci)):
        leaf = part.leaves.get(path)
        if isinstance(leaf, C.NumericLeaf):
            pieces.append(np.ascontiguousarray(
                leaf.data.reshape(part.num_rows, -1)).view(np.uint8).reshape(
                    part.num_rows, -1))
            if leaf.valid is not None:
                pieces.append(leaf.valid.reshape(-1, 1).view(np.uint8))
        elif isinstance(leaf, C.StrLeaf):
            pieces.append(leaf.bytes)
            pieces.append(leaf.lengths.astype("<i4").view(np.uint8).reshape(
                part.num_rows, -1))
            if leaf.valid is not None:
                pieces.append(leaf.valid.reshape(-1, 1).view(np.uint8))
        elif isinstance(leaf, C.NullLeaf):
            pieces.append(np.zeros((part.num_rows, 1), np.uint8))
        else:
            return None
    if not pieces:
        return None
    mat = np.ascontiguousarray(np.concatenate(pieces, axis=1))
    return mat


class JoinExecutor:
    def __init__(self, backend):
        self.backend = backend

    def execute(self, stage, left_partitions: list[C.Partition], context):
        from ..plan.physical import plan_stages

        op = stage.op
        t0 = time.perf_counter()
        # --- build side: execute the right sub-plan (stage N-1) ------------
        from ..api.dataset import _source_partitions

        right_stages = plan_stages(op.right, context.options_store)
        rparts: Optional[list] = None
        excs: list[ExceptionRecord] = []
        for rs in right_stages:
            if rparts is None and getattr(rs, "source", None) is not None:
                rparts = _source_partitions(context, rs)
            res = self.backend.execute_any(rs, rparts, context)
            rparts = res.partitions
            excs.extend(res.exceptions)

        build = self._build_table(op, rparts or [])
        out_parts = []
        for part in left_partitions:
            self.backend.mm.touch(part)
            outp = self._probe_partition(op, part, rparts or [], build, excs)
            self.backend.mm.register(outp)
            out_parts.append(outp)
        m = {"wall_s": time.perf_counter() - t0,
             "rows_out": sum(p.num_rows for p in out_parts),
             "exception_rows": len(excs)}
        return StageResult(out_parts, excs, m)

    # ------------------------------------------------------------------
    def _build_table(self, op, rparts: list[C.Partition]) -> dict:
        """Hash table over the build side — rebuilt per execution (stale
        caches across actions would probe against old data)."""
        build: dict = {}
        for rp in rparts:
            self.backend.mm.touch(rp)
            rk = rp.schema.columns.index(op.right_column)
            single = len(rp.schema.columns) == 1
            for vals in C.partition_to_pylist(rp):
                row_vals = (vals,) if single else vals
                try:
                    if not isinstance(row_vals, tuple) or \
                            rk >= len(row_vals):
                        continue
                    build.setdefault(row_vals[rk], []).append(row_vals)
                except TypeError:
                    pass  # unhashable build key: unreachable by probe
        return build

    def _probe_partition(self, op, lpart: C.Partition,
                         rparts: list[C.Partition], build: dict,
                         excs: list) -> C.Partition:
        """Probe one left partition against the build table.

        Round-1 implementation materializes matches row-wise through decode
        (correct, host-bound); the vectorized leaf-gather fast path comes
        with the device join."""
        ls = lpart.schema
        lk = ls.columns.index(op.left_column)
        rs_cols_n = len(rparts[0].schema.columns) if rparts else \
            len(op.right.schema().columns)
        rkk = (rparts[0].schema.columns.index(op.right_column) if rparts
               else op.right.schema().columns.index(op.right_column))
        values = []
        single = len(ls.columns) == 1
        empty_right = (None,) * (rs_cols_n - 1)
        for vals in C.partition_to_pylist(lpart):
            row_vals = (vals,) if single else vals
            try:
                key = row_vals[lk]
                lvals = [v for i, v in enumerate(row_vals) if i != lk]
                matches = build.get(key, []) if _hashable(key) else []
            except Exception as e:
                excs.append(ExceptionRecord(op.id, type(e).__name__, vals))
                continue
            if matches:
                for m in matches:
                    rvals = [v for i, v in enumerate(m) if i != rkk]
                    values.append(tuple(lvals + [key] + rvals))
            elif op.how == "left":
                values.append(tuple(lvals) + (key,) + empty_right)
        schema = op.schema()
        if not values:
            return C.Partition(schema=schema, num_rows=0, leaves={},
                               start_index=lpart.start_index)
        return C.build_partition(values, schema,
                                 start_index=lpart.start_index)


def _hashable(v) -> bool:
    try:
        hash(v)
        return True
    except TypeError:
        return False
