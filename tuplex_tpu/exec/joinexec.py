"""Join stage execution: broadcast build side + vectorized probe.

Reference model (reference: PhysicalPlan.cc:145-178 + LocalBackend.cc:213
executeHashJoinStage + HybridHashTable.h:46-60): the build side is fully
materialized into a hash table, broadcast to every task; the probe side
streams. Keys that can't live in the native table go to a python-dict backup
(hybrid). Here:

  * build: factorize build-side keys into sorted signatures (np.unique — C
    speed) + group offsets (CSR layout)
  * probe: per-partition vectorized signature match via np.searchsorted,
    match expansion via np.repeat, row materialization via leaf gathers
  * boxed fallback rows on either side probe/build through a python dict —
    the HybridHashTable semantics
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from ..core import typesys as T
from ..core.row import Row
from ..runtime import columns as C
from .local import ExceptionRecord, StageResult


def _key_signatures(part: C.Partition, ci: int) -> Optional[np.ndarray]:
    """[N, W] canonical byte-signature matrix for the key column, None if the
    column isn't signature-comparable (see C.key_signature_matrix for the
    canonicalization contract — byte equality must imply python equality)."""
    return C.key_signature_matrix(part, [ci], reject_nan=True)


class JoinExecutor:
    def __init__(self, backend):
        self.backend = backend

    def execute(self, stage, left_partitions: list[C.Partition], context):
        from ..plan.physical import plan_stages

        op = stage.op
        t0 = time.perf_counter()
        # --- build side: execute the right sub-plan (stage N-1) ------------
        from ..api.dataset import _source_partitions

        right_stages = plan_stages(op.right, context.options_store)
        rparts: Optional[list] = None
        excs: list[ExceptionRecord] = []
        for rs in right_stages:
            if rparts is None and getattr(rs, "source", None) is not None:
                rparts = _source_partitions(context, rs)
            res = self.backend.execute_any(rs, rparts, context)
            rparts = res.partitions
            excs.extend(res.exceptions)

        # one path for ALL partitions so every output shares one schema
        vec = _VectorBuild.try_build(op, rparts or [], self.backend)
        if vec is not None and not all(
                vec.can_probe(part) for part in left_partitions):
            vec = None
        build = None
        out_parts = []
        for part in left_partitions:
            self.backend.mm.touch(part)
            if vec is not None:
                outp = vec.probe(part)
                assert outp is not None
            else:
                if build is None:
                    build = self._build_table(op, rparts or [])
                outp = self._probe_partition(op, part, rparts or [], build,
                                             excs)
            self.backend.mm.register(outp)
            out_parts.append(outp)
        m = {"wall_s": time.perf_counter() - t0,
             "rows_out": sum(p.num_rows for p in out_parts),
             "exception_rows": len(excs)}
        return StageResult(out_parts, excs, m)

    # ------------------------------------------------------------------
    def _build_table(self, op, rparts: list[C.Partition]) -> dict:
        """Hash table over the build side — rebuilt per execution (stale
        caches across actions would probe against old data)."""
        build: dict = {}
        for rp in rparts:
            self.backend.mm.touch(rp)
            rk = rp.schema.columns.index(op.right_column)
            single = len(rp.schema.columns) == 1
            for vals in C.partition_to_pylist(rp):
                row_vals = (vals,) if single else vals
                try:
                    if not isinstance(row_vals, tuple) or \
                            rk >= len(row_vals):
                        continue
                    build.setdefault(row_vals[rk], []).append(row_vals)
                except TypeError:
                    pass  # unhashable build key: unreachable by probe
        return build

    def _probe_partition(self, op, lpart: C.Partition,
                         rparts: list[C.Partition], build: dict,
                         excs: list) -> C.Partition:
        """Probe one left partition against the build table.

        Round-1 implementation materializes matches row-wise through decode
        (correct, host-bound); the vectorized leaf-gather fast path comes
        with the device join."""
        ls = lpart.schema
        lk = ls.columns.index(op.left_column)
        rs_cols_n = len(rparts[0].schema.columns) if rparts else \
            len(op.right.schema().columns)
        rkk = (rparts[0].schema.columns.index(op.right_column) if rparts
               else op.right.schema().columns.index(op.right_column))
        values = []
        single = len(ls.columns) == 1
        empty_right = (None,) * (rs_cols_n - 1)
        for vals in C.partition_to_pylist(lpart):
            row_vals = (vals,) if single else vals
            try:
                key = row_vals[lk]
                lvals = [v for i, v in enumerate(row_vals) if i != lk]
                matches = build.get(key, []) if _hashable(key) else []
            except Exception as e:
                excs.append(ExceptionRecord(op.id, type(e).__name__, vals))
                continue
            if matches:
                for m in matches:
                    rvals = [v for i, v in enumerate(m) if i != rkk]
                    values.append(tuple(lvals + [key] + rvals))
            elif op.how == "left":
                values.append(tuple(lvals) + (key,) + empty_right)
        schema = op.schema()
        if not values:
            return C.Partition(schema=schema, num_rows=0, leaves={},
                               start_index=lpart.start_index)
        return C.build_partition(values, schema,
                                 start_index=lpart.start_index)


def _hashable(v) -> bool:
    try:
        hash(v)
        return True
    except TypeError:
        return False


def _concat_leaves(parts: list[C.Partition]) -> Optional[C.Partition]:
    """Concatenate partitions (same schema) into one; None if any leaf kind
    can't concatenate."""
    if not parts:
        return None
    C.harmonize_partitions(parts)
    schema = parts[0].schema
    paths = set(parts[0].leaves)
    if any(set(p.leaves) != paths for p in parts):
        return None
    leaves: dict[str, C.Leaf] = {}
    n = sum(p.num_rows for p in parts)
    for path in paths:
        ls = [p.leaves[path] for p in parts]
        if all(isinstance(l, C.NumericLeaf) for l in ls):
            data = np.concatenate([l.data for l in ls])
            valid = None
            if any(l.valid is not None for l in ls):
                valid = np.concatenate(
                    [l.valid if l.valid is not None
                     else np.ones(len(l), np.bool_) for l in ls])
            leaves[path] = C.NumericLeaf(data, valid)
        elif all(isinstance(l, C.StrLeaf) for l in ls):
            leaves[path] = C.StrLeaf(
                np.concatenate([l.bytes for l in ls]),
                np.concatenate([l.lengths for l in ls]),
                np.concatenate([l.valid if l.valid is not None
                                else np.ones(len(l), np.bool_)
                                for l in ls])
                if any(l.valid is not None for l in ls) else None)
        elif all(isinstance(l, C.NullLeaf) for l in ls):
            leaves[path] = C.NullLeaf(n)
        else:
            return None
    return C.Partition(schema=schema, num_rows=n, leaves=leaves)


def _gather_leaves(part: C.Partition, idx: np.ndarray, valid_rows=None
                   ) -> Optional[dict]:
    """Leaf dict gathered at idx; rows where valid_rows is False become
    invalid slots (left-join None fill)."""
    out: dict[str, C.Leaf] = {}
    m = len(idx)
    for path, leaf in part.leaves.items():
        if isinstance(leaf, C.NumericLeaf):
            data = leaf.data[idx] if m else leaf.data[:0]
            valid = leaf.valid[idx] if leaf.valid is not None and m else (
                leaf.valid[:0] if leaf.valid is not None else None)
            if valid_rows is not None:
                v = valid if valid is not None else np.ones(m, np.bool_)
                valid = v & valid_rows
                data = np.where(valid_rows, data, 0)
            out[path] = C.NumericLeaf(data, valid)
        elif isinstance(leaf, C.StrLeaf):
            b = leaf.bytes[idx] if m else leaf.bytes[:0]
            ln = leaf.lengths[idx] if m else leaf.lengths[:0]
            valid = leaf.valid[idx] if leaf.valid is not None and m else (
                leaf.valid[:0] if leaf.valid is not None else None)
            if valid_rows is not None:
                v = valid if valid is not None else np.ones(m, np.bool_)
                valid = v & valid_rows
            out[path] = C.StrLeaf(b, ln, valid)
        elif isinstance(leaf, C.NullLeaf):
            out[path] = C.NullLeaf(m)
        else:
            return None
    return out


class _VectorBuild:
    """Vectorized broadcast-join build: unique build keys + CSR row groups.

    The fast path of the reference's per-task hashtable probe
    (LocalBackend.cc:213 + HashJoinStage), done with np.unique over key
    signatures and numpy gathers — no per-row python on the hot path.
    Applies when both sides are fully normal-case; anything boxed falls back
    to the row-wise hybrid path.
    """

    @classmethod
    def try_build(cls, op, rparts: list[C.Partition], backend):
        if not rparts:
            return None
        if any(p.fallback for p in rparts):
            return None
        for p in rparts:
            backend.mm.touch(p)
        big = _concat_leaves(rparts)
        if big is None or big.num_rows == 0:
            return None  # empty build: row-wise path handles it
        rk = big.schema.columns.index(op.right_column)
        sig = _key_signatures(big, rk)
        if sig is None:
            return None
        view = np.ascontiguousarray(sig).view(
            [("v", np.void, sig.shape[1])]).ravel()
        uniq, inverse = np.unique(view, return_inverse=True)
        order = np.argsort(inverse, kind="stable")
        counts = np.bincount(inverse, minlength=len(uniq))
        offsets = np.concatenate([[0], np.cumsum(counts)])
        self = cls()
        self.op = op
        self.big = big
        self.rk = rk
        self.uniq_view = uniq
        self.order = order
        self.counts = counts
        self.offsets = offsets
        self.key_width = sig.shape[1]
        return self

    def can_probe(self, lpart: C.Partition) -> bool:
        """Cheap qualification; ALL partitions must pass or the whole join
        uses the row-wise path (mixed paths would mix output schemas)."""
        op = self.op
        if lpart.fallback or op.left_column not in lpart.schema.columns:
            return False
        lk = lpart.schema.columns.index(op.left_column)
        lt = lpart.schema.types[lk]
        rt = self.big.schema.types[self.rk]
        if lt.name != rt.name:
            return False  # e.g. i64 vs f64 keys: byte equality would diverge
        sig = _key_signatures(lpart, lk)
        # width mismatch (str keys of different bucket W): fallback rather
        # than padding — harmonize only covers one dataset's partitions
        return sig is not None and sig.shape[1] == self.key_width

    def probe(self, lpart: C.Partition) -> Optional[C.Partition]:
        op = self.op
        ls = lpart.schema
        lk = ls.columns.index(op.left_column)
        sig = _key_signatures(lpart, lk)
        if sig is None or sig.shape[1] != self.key_width:
            return None
        return self._probe_sig(lpart, sig)

    def _probe_sig(self, lpart: C.Partition, sig: np.ndarray
                   ) -> Optional[C.Partition]:
        op = self.op
        ls = lpart.schema
        lk = ls.columns.index(op.left_column)
        n = lpart.num_rows
        view = np.ascontiguousarray(sig).view(
            [("v", np.void, sig.shape[1])]).ravel()
        pos = np.searchsorted(self.uniq_view, view)
        pos_c = np.clip(pos, 0, len(self.uniq_view) - 1)
        matched = (pos < len(self.uniq_view)) & \
            (self.uniq_view[pos_c] == view)
        cnt = np.where(matched, self.counts[pos_c], 0)
        if op.how == "left":
            out_per_row = np.maximum(cnt, 1)
        else:
            out_per_row = cnt
        m = int(out_per_row.sum())
        left_idx = np.repeat(np.arange(n), out_per_row)
        # build-row index per output row: offsets[code] + intra-group rank
        row_starts = np.concatenate([[0], np.cumsum(out_per_row)])[:-1]
        intra = np.arange(m) - np.repeat(row_starts, out_per_row)
        code = self.offsets[np.repeat(pos_c, out_per_row)]
        has_match = np.repeat(matched, out_per_row)
        build_rows = np.where(
            has_match, self.order[np.clip(code + intra, 0,
                                          max(len(self.order) - 1, 0))], 0)
        # gather left (minus key), key, right (minus key)
        lgather = _gather_leaves(lpart, left_idx)
        rgather = _gather_leaves(self.big, build_rows,
                                 valid_rows=has_match
                                 if op.how == "left" else None)
        if lgather is None or rgather is None:
            return None
        rs = self.big.schema
        out_cols: list[str] = []
        out_types: list = []
        leaves: dict[str, C.Leaf] = {}

        def put(col_t, src_leaves, src_ci, make_opt=False):
            ci_out = len(out_types)
            t = col_t
            if make_opt:
                t = T.option(t)
            out_types.append(t)
            for path, leaf in src_leaves.items():
                if path == str(src_ci) or path.startswith(f"{src_ci}.") or \
                        path.startswith(f"{src_ci}#"):
                    # make_opt leaves already carry validity: _gather_leaves
                    # was called with valid_rows=has_match for left joins
                    newp = str(ci_out) + path[len(str(src_ci)):]
                    leaves[newp] = leaf

        for i, (c, t) in enumerate(zip(ls.columns, ls.types)):
            if i == lk:
                continue
            out_cols.append(op._decorate(c, 0))
            put(t, lgather, i)
        out_cols.append(op.left_column)
        put(ls.types[lk], lgather, lk)
        for i, (c, t) in enumerate(zip(rs.columns, rs.types)):
            if i == self.rk:
                continue
            out_cols.append(op._decorate(c, 1))
            put(t, rgather, i, make_opt=(op.how == "left"))
        schema = T.row_of(out_cols, out_types)
        return C.Partition(schema=schema, num_rows=m, leaves=leaves,
                           start_index=lpart.start_index)
