"""Join stage execution: broadcast build side + vectorized probe.

Reference model (reference: PhysicalPlan.cc:145-178 + LocalBackend.cc:213
executeHashJoinStage + HybridHashTable.h:46-60): the build side is fully
materialized into a hash table, broadcast to every task; the probe side
streams. Keys that can't live in the native table go to a python-dict backup
(hybrid). Here:

  * build: factorize build-side keys into sorted signatures (np.unique — C
    speed) + group offsets (CSR layout)
  * probe: per-partition vectorized signature match via np.searchsorted,
    match expansion via np.repeat, row materialization via leaf gathers
  * boxed fallback rows on either side probe/build through a python dict —
    the HybridHashTable semantics
"""

from __future__ import annotations

import time
from typing import Any, Optional

import numpy as np

from ..core import typesys as T
from ..core.row import Row
from ..runtime import columns as C
from .local import ExceptionRecord, StageResult


def _key_signatures(part: C.Partition, ci: int) -> Optional[np.ndarray]:
    """[N, W] canonical byte-signature matrix for the key column, None if the
    column isn't signature-comparable (see C.key_signature_matrix for the
    canonicalization contract — byte equality must imply python equality)."""
    return C.key_signature_matrix(part, [ci], reject_nan=True)


class JoinExecutor:
    def __init__(self, backend):
        self.backend = backend

    def execute(self, stage, left_partitions: list[C.Partition], context,
                intermediate=False):
        from ..runtime import tracing as TR

        with TR.span("join:execute", "exec") as _sp:
            res = self._execute_impl(stage, left_partitions, context,
                                     intermediate=intermediate)
            if _sp is not TR.NOOP:
                _sp.set("rows_out", res.metrics.get("rows_out", 0))
        return res

    def _execute_impl(self, stage, left_partitions: list[C.Partition],
                      context, intermediate=False):
        from ..plan.physical import plan_stages
        from ..runtime import tracing as TR

        op = stage.op
        t0 = time.perf_counter()
        # --- build side: execute the right sub-plan (stage N-1) ------------
        from ..api.dataset import _source_partitions

        with TR.span("join:build-side", "exec"):
            right_stages = plan_stages(op.right, context.options_store)
            rparts: Optional[list] = None
            excs: list[ExceptionRecord] = []
            for rs in right_stages:
                if rparts is None and \
                        getattr(rs, "source", None) is not None:
                    rparts = _source_partitions(context, rs)
                res = self.backend.execute_any(rs, rparts, context)
                rparts = res.partitions
                excs.extend(res.exceptions)

        # one path for ALL partitions so every output shares one schema;
        # device probe when a mesh/accelerator is present (or forced)
        vec = None
        if self._device_join_enabled():
            vec = _DeviceProbe.try_build(op, rparts or [], self.backend)
            if vec is not None:
                # device-resident OUTPUT: when a later stage consumes this
                # join, the match-expansion gathers stay on device and the
                # host leaves go lazy (jaxcfg gate per consumer kind)
                from ..runtime.jaxcfg import (device_handoff_budget_bytes,
                                              device_handoff_enabled)

                vec.dev_out = bool(intermediate) and device_handoff_enabled(
                    intermediate if isinstance(intermediate, str)
                    else "stage")
                if vec.dev_out:
                    vec._handoff_left = device_handoff_budget_bytes()
        if vec is None:
            vec = _VectorBuild.try_build(op, rparts or [], self.backend)
        if vec is not None and not all(
                vec.can_probe(part) for part in left_partitions):
            vec = None
        build = None
        out_parts = []
        for part in left_partitions:
            self.backend.mm.touch(part)
            with TR.span("join:probe", "exec") as _psp:
                _psp.set("rows", part.num_rows) \
                    .set("path", "device" if vec is not None else "host")
                if vec is not None:
                    outp = vec.probe(part, excs)
                    assert outp is not None
                else:
                    if build is None:
                        with TR.span("join:build-table", "exec"):
                            build = self._build_table(op, rparts or [])
                    outp = self._probe_partition(op, part, rparts or [],
                                                 build, excs)
            self.backend.mm.register(outp)
            out_parts.append(outp)
        from . import compilequeue as _cq

        cs, cn = _cq.consume_tag("join")
        m = {"wall_s": time.perf_counter() - t0,
             "rows_out": sum(p.num_rows for p in out_parts),
             "exception_rows": len(excs),
             "compile_s": cs, "stage_compiles": cn}
        return StageResult(out_parts, excs, m)

    # ------------------------------------------------------------------
    def _device_join_enabled(self) -> bool:
        """Device probe policy: 'auto' uses the device when the backend has
        a mesh or the platform is a real accelerator; CPU-local defaults to
        the host numpy probe (np.searchsorted is already C-speed there)."""
        mode = self.backend.options.get_str("tuplex.tpu.deviceJoin", "auto")
        if mode in ("true", "1", "yes"):
            return True
        if mode in ("false", "0", "no"):
            return False
        if getattr(self.backend, "mesh", None) is not None:
            return True
        try:
            from ..runtime.jaxcfg import jax

            return jax.devices()[0].platform != "cpu"
        except Exception:
            return False

    # ------------------------------------------------------------------
    def _build_table(self, op, rparts: list[C.Partition]) -> dict:
        """Hash table over the build side — rebuilt per execution (stale
        caches across actions would probe against old data)."""
        for rp in rparts:
            self.backend.mm.touch(rp)
        return _build_pydict(op, rparts)

    def _probe_partition(self, op, lpart: C.Partition,
                         rparts: list[C.Partition], build: dict,
                         excs: list) -> C.Partition:
        """Probe one left partition against the build table.

        Round-1 implementation materializes matches row-wise through decode
        (correct, host-bound); the vectorized leaf-gather fast path comes
        with the device join."""
        ls = lpart.schema
        lk = ls.columns.index(op.left_column)
        rs_cols_n = len(rparts[0].schema.columns) if rparts else \
            len(op.right.schema().columns)
        rkk = (rparts[0].schema.columns.index(op.right_column) if rparts
               else op.right.schema().columns.index(op.right_column))
        values = []
        single = len(ls.columns) == 1
        empty_right = (None,) * (rs_cols_n - 1)
        for vals in C.partition_to_pylist(lpart):
            row_vals = (vals,) if single else vals
            try:
                key = row_vals[lk]
                lvals = [v for i, v in enumerate(row_vals) if i != lk]
                matches = build.get(key, []) if _hashable(key) else []
            except Exception as e:
                excs.append(ExceptionRecord(op.id, type(e).__name__, vals))
                continue
            if matches:
                for m in matches:
                    rvals = [v for i, v in enumerate(m) if i != rkk]
                    values.append(tuple(lvals + [key] + rvals))
            elif op.how == "left":
                values.append(tuple(lvals) + (key,) + empty_right)
        schema = op.schema()
        if not values:
            return C.Partition(schema=schema, num_rows=0, leaves={},
                               start_index=lpart.start_index)
        return C.build_partition(values, schema,
                                 start_index=lpart.start_index)


def _build_pydict(op, rparts: list[C.Partition]) -> dict:
    """python-dict build table over ALL rows (normal + boxed) — the backup
    side of the hybrid table and the row-wise path's table."""
    build: dict = {}
    for rp in rparts:
        rk = rp.schema.columns.index(op.right_column)
        single = len(rp.schema.columns) == 1
        for vals in C.partition_to_pylist(rp):
            row_vals = (vals,) if single else vals
            try:
                if not isinstance(row_vals, tuple) or rk >= len(row_vals):
                    continue
                build.setdefault(row_vals[rk], []).append(row_vals)
            except TypeError:
                pass  # unhashable build key: unreachable by probe
    return build


def _hashable(v) -> bool:
    try:
        hash(v)
        return True
    except TypeError:
        return False


def _concat_leaves(parts: list[C.Partition]) -> Optional[C.Partition]:
    """Concatenate partitions (same schema) into one; None if any leaf kind
    can't concatenate."""
    if not parts:
        return None
    C.harmonize_partitions(parts)
    schema = parts[0].schema
    paths = set(parts[0].leaves)
    if any(set(p.leaves) != paths for p in parts):
        return None
    leaves: dict[str, C.Leaf] = {}
    n = sum(p.num_rows for p in parts)
    for path in paths:
        ls = [p.leaves[path] for p in parts]
        if all(isinstance(l, C.NumericLeaf) for l in ls):
            data = np.concatenate([l.data for l in ls])
            valid = None
            if any(l.valid is not None for l in ls):
                valid = np.concatenate(
                    [l.valid if l.valid is not None
                     else np.ones(len(l), np.bool_) for l in ls])
            leaves[path] = C.NumericLeaf(data, valid)
        elif all(isinstance(l, C.StrLeaf) for l in ls):
            leaves[path] = C.StrLeaf(
                np.concatenate([l.bytes for l in ls]),
                np.concatenate([l.lengths for l in ls]),
                np.concatenate([l.valid if l.valid is not None
                                else np.ones(len(l), np.bool_)
                                for l in ls])
                if any(l.valid is not None for l in ls) else None)
        elif all(isinstance(l, C.NullLeaf) for l in ls):
            leaves[path] = C.NullLeaf(n)
        else:
            return None
    return C.Partition(schema=schema, num_rows=n, leaves=leaves)


def _gather_leaves(part: C.Partition, idx: np.ndarray, valid_rows=None
                   ) -> Optional[dict]:
    """Leaf dict gathered at idx; rows where valid_rows is False become
    invalid slots (left-join None fill)."""
    out: dict[str, C.Leaf] = {}
    m = len(idx)
    for path, leaf in part.leaves.items():
        if isinstance(leaf, C.NumericLeaf):
            data = leaf.data[idx] if m else leaf.data[:0]
            valid = leaf.valid[idx] if leaf.valid is not None and m else (
                leaf.valid[:0] if leaf.valid is not None else None)
            if valid_rows is not None:
                v = valid if valid is not None else np.ones(m, np.bool_)
                valid = v & valid_rows
                data = np.where(valid_rows, data, 0)
            out[path] = C.NumericLeaf(data, valid)
        elif isinstance(leaf, C.StrLeaf):
            b = leaf.bytes[idx] if m else leaf.bytes[:0]
            ln = leaf.lengths[idx] if m else leaf.lengths[:0]
            valid = leaf.valid[idx] if leaf.valid is not None and m else (
                leaf.valid[:0] if leaf.valid is not None else None)
            if valid_rows is not None:
                v = valid if valid is not None else np.ones(m, np.bool_)
                valid = v & valid_rows
            out[path] = C.StrLeaf(b, ln, valid)
        elif isinstance(leaf, C.NullLeaf):
            out[path] = C.NullLeaf(m)
        else:
            return None
    return out


class _VectorBuild:
    """Vectorized broadcast-join build: unique build keys + CSR row groups,
    with HYBRID handling of boxed rows (reference: HybridHashTable.h:46-60 —
    compiled keys in the native table, incompatible rows in a python backup).

    Normal-case rows on both sides match via canonical byte signatures
    (np.unique + searchsorted — no per-row python on the hot path). Boxed
    probe rows python-probe the full dict; boxed BUILD rows with conforming
    keys get signatures so normal probe rows still find them (their output
    rows box through the partition fallback slots). Cross-type boxed build
    keys reject the vectorized path entirely — python `==` semantics there
    need the row-wise dict."""

    @classmethod
    def try_build(cls, op, rparts: list[C.Partition], backend):
        if not rparts:
            return None
        for p in rparts:
            backend.mm.touch(p)
        big = _concat_leaves(rparts)
        if big is None or big.num_rows == 0:
            return None  # empty build: row-wise path handles it
        rk = big.schema.columns.index(op.right_column)
        rt = big.schema.types[rk]
        n_cols = len(big.schema.columns)
        # boxed build rows -> backup side
        boxed_rows: list[tuple] = []
        normal_mask_all = np.ones(big.num_rows, np.bool_)
        off = 0
        for rp in rparts:
            single = len(rp.schema.columns) == 1
            for i, v in rp.fallback.items():
                row_vals = (v,) if single and not (
                    isinstance(v, tuple) and len(v) == n_cols) else v
                if not isinstance(row_vals, tuple) or \
                        len(row_vals) != n_cols:
                    return None      # arity-weird boxed rows: row-wise path
                if not T.python_value_conforms(row_vals[rk], rt):
                    return None      # cross-type key: python == semantics
                boxed_rows.append(tuple(row_vals))
                normal_mask_all[off + i] = False
            off += rp.num_rows
        normal_idx = np.nonzero(normal_mask_all)[0]
        if len(normal_idx) == 0:
            return None   # all-boxed build: nothing to sign; row-wise path
        sig = _key_signatures(big, rk)
        if sig is None:
            return None
        sub = np.ascontiguousarray(sig[normal_idx])
        view = sub.view([("v", np.void, sig.shape[1])]).ravel()
        uniq, inverse = np.unique(view, return_inverse=True)
        order = np.argsort(inverse, kind="stable")
        counts = np.bincount(inverse, minlength=len(uniq))
        offsets = np.concatenate([[0], np.cumsum(counts)])
        self = cls()
        self.op = op
        self.big = big
        self.rk = rk
        self.rparts = rparts
        self.uniq_view = uniq
        self.order = normal_idx[order]        # global big-row indices
        self.counts = counts
        self.offsets = offsets
        self.key_width = sig.shape[1]
        self.boxed_rows = boxed_rows
        self.boxed_sigs = None
        self._pydict: Optional[dict] = None
        if boxed_rows and not self._encode_boxed_sigs(rt):
            return None              # can't sign boxed keys: stay exact
        return self

    def _encode_boxed_sigs(self, rt) -> bool:
        """Signatures for boxed build keys in the SAME byte layout as the
        normal-case key column (width-padded); keys too long for the layout
        are unreachable by normal probe rows and sign as all-0xFF sentinels
        (never equal to a canonical signature's zero padding)."""
        kschema = T.row_of(["k"], [rt])
        kpart = C.build_partition([r[self.rk] for r in self.boxed_rows],
                                  kschema)
        if kpart.fallback:
            return False
        too_long = np.zeros(kpart.num_rows, np.bool_)
        for path, leaf in kpart.leaves.items():
            if isinstance(leaf, C.StrLeaf):
                big_path = str(self.rk) + path[1:]
                big_leaf = self.big.leaves.get(big_path)
                if not isinstance(big_leaf, C.StrLeaf):
                    return False
                w = big_leaf.width
                too_long |= leaf.lengths > w
                if leaf.width < w:
                    leaf.bytes = C.pad_to(leaf.bytes, w, axis=1)
                elif leaf.width > w:
                    leaf.bytes = np.ascontiguousarray(leaf.bytes[:, :w])
        sigs = C.key_signature_matrix(kpart, [0], reject_nan=True)
        if sigs is None or sigs.shape[1] != self.key_width:
            return False
        sigs = np.where(too_long[:, None], np.uint8(0xFF), sigs)
        self.boxed_sigs = sigs
        return True

    def _full_pydict(self) -> dict:
        if self._pydict is None:
            self._pydict = _build_pydict(self.op, self.rparts)
        return self._pydict

    def can_probe(self, lpart: C.Partition) -> bool:
        """Cheap qualification; ALL partitions must pass or the whole join
        uses the row-wise path (mixed paths would mix output schemas)."""
        op = self.op
        if op.left_column not in lpart.schema.columns:
            return False
        lk = lpart.schema.columns.index(op.left_column)
        lt = lpart.schema.types[lk]
        rt = self.big.schema.types[self.rk]
        if lt.name != rt.name:
            return False  # e.g. i64 vs f64 keys: byte equality would diverge
        sig = _key_signatures(lpart, lk)
        # width mismatch (str keys of different bucket W): fallback rather
        # than padding — harmonize only covers one dataset's partitions
        return sig is not None and sig.shape[1] == self.key_width

    def probe(self, lpart: C.Partition, excs: list
              ) -> Optional[C.Partition]:
        op = self.op
        ls = lpart.schema
        lk = ls.columns.index(op.left_column)
        sig = _key_signatures(lpart, lk)
        if sig is None or sig.shape[1] != self.key_width:
            return None
        return self._probe_sig(lpart, sig, excs)

    def _match_positions(self, sig: np.ndarray):
        """(pos_clipped [N], matched [N]) — lower-bound probe into the sorted
        unique build signatures. Host numpy; _DeviceProbe overrides with the
        on-device binary search."""
        view = np.ascontiguousarray(sig).view(
            [("v", np.void, sig.shape[1])]).ravel()
        pos = np.searchsorted(self.uniq_view, view)
        pos_c = np.clip(pos, 0, len(self.uniq_view) - 1)
        matched = (pos < len(self.uniq_view)) & \
            (self.uniq_view[pos_c] == view)
        return pos_c, matched

    def _gather(self, part: C.Partition, idx: np.ndarray, valid_rows=None
                ) -> Optional[dict]:
        """Leaf gather for the match expansion; _DeviceProbe overrides with
        jitted device gathers."""
        return _gather_leaves(part, idx, valid_rows)

    def _output_layout(self, ls: T.RowType):
        """(out_cols, out_types, entries) where entries[i] = (side,
        src_ci, make_opt) maps output column i to its source column —
        the single definition of the join's output column order, shared
        by the host and device assemblies."""
        op = self.op
        rs = self.big.schema
        lk = ls.columns.index(op.left_column)
        out_cols: list[str] = []
        out_types: list = []
        entries: list[tuple[str, int, bool]] = []
        for i, (c, t) in enumerate(zip(ls.columns, ls.types)):
            if i == lk:
                continue
            out_cols.append(op._decorate(c, 0))
            out_types.append(t)
            entries.append(("l", i, False))
        out_cols.append(op.left_column)
        out_types.append(ls.types[lk])
        entries.append(("l", lk, False))
        for i, (c, t) in enumerate(zip(rs.columns, rs.types)):
            if i == self.rk:
                continue
            out_cols.append(op._decorate(c, 1))
            mo = op.how == "left"
            out_types.append(T.option(t) if mo else t)
            entries.append(("r", i, mo))
        return out_cols, out_types, entries

    def _probe_sig(self, lpart: C.Partition, sig: np.ndarray, excs: list
                   ) -> Optional[C.Partition]:
        plan = self._probe_plan(lpart, sig, excs)
        return self._assemble_host(lpart, plan)

    def _probe_plan(self, lpart: C.Partition, sig: np.ndarray,
                    excs: list) -> dict:
        """Host-side match planning shared by the host and device
        assemblies: per-row match counts, boxed-row splices, output slot
        layout, and the flat (left_idx, build_rows, has_match) gather
        program for the vectorized portion."""
        op = self.op
        ls = lpart.schema
        lk = ls.columns.index(op.left_column)
        n = lpart.num_rows
        fb = lpart.fallback
        is_fb = np.zeros(n, np.bool_)
        if fb:
            is_fb[list(fb.keys())] = True
        pos_c, matched = self._match_positions(sig)
        matched = matched & ~is_fb   # boxed slots carry placeholder bytes
        cnt = np.where(matched, self.counts[pos_c], 0).astype(np.int64)

        # boxed-build matches for normal probe rows, and python probes for
        # boxed probe rows — each lands as a boxed OUTPUT row in its slot
        extra_rows: dict[int, list] = {}
        bcnt = np.zeros(n, np.int64)
        ncols_r = len(self.big.schema.columns)
        if self.boxed_sigs is not None and len(self.boxed_sigs):
            # loop over the (small) boxed side: a broadcast [N, B, W] compare
            # would transiently allocate N*B*W bytes on large probes
            cand = np.zeros((n, len(self.boxed_sigs)), np.bool_)
            for bi in range(len(self.boxed_sigs)):
                cand[:, bi] = (sig == self.boxed_sigs[bi][None, :]).all(-1)
            cand &= ~is_fb[:, None]
            rows_with_b = np.nonzero(cand.any(1))[0]
            for i, row in zip(rows_with_b.tolist(),
                              C.decode_rows(lpart, rows_with_b)):
                row_vals = tuple(row.values)
                key = row_vals[lk]
                lvals = [x for j, x in enumerate(row_vals) if j != lk]
                outs = []
                for bi in np.nonzero(cand[i])[0].tolist():
                    mrow = self.boxed_rows[bi]
                    rvals = [x for j, x in enumerate(mrow) if j != self.rk]
                    outs.append(tuple(lvals + [key] + rvals))
                extra_rows[i] = outs
            bcnt[rows_with_b] = cand[rows_with_b].sum(1)
        if fb:
            pydict = self._full_pydict()
            for i, v in fb.items():
                row_vals = v if isinstance(v, tuple) else (v,)
                try:
                    key = row_vals[lk]
                    lvals = [x for j, x in enumerate(row_vals) if j != lk]
                    matches = pydict.get(key, []) if _hashable(key) else []
                except Exception as e:
                    excs.append(ExceptionRecord(op.id, type(e).__name__, v))
                    continue
                outs = []
                for mrow in matches:
                    rvals = [x for j, x in enumerate(mrow) if j != self.rk]
                    outs.append(tuple(lvals + [key] + rvals))
                if not outs and op.how == "left":
                    outs.append(tuple(lvals) + (key,) +
                                (None,) * (ncols_r - 1))
                if outs:
                    extra_rows[i] = outs
                bcnt[i] = len(outs)

        total = cnt + bcnt
        filler = np.zeros(n, np.bool_)
        if op.how == "left":
            filler = (total == 0) & ~is_fb
        out_per_row = np.where(filler, 1, total)
        m = int(out_per_row.sum())
        starts = np.concatenate([[0], np.cumsum(out_per_row)])[:-1]

        # ---- vectorized portion: signature matches (+ left-join fillers) --
        vec_take = np.where(filler, 1, cnt)
        m_vec = int(vec_take.sum())
        left_idx = np.repeat(np.arange(n), vec_take)
        row_starts = np.concatenate([[0], np.cumsum(vec_take)])[:-1]
        intra = np.arange(m_vec) - np.repeat(row_starts, vec_take)
        code = self.offsets[np.repeat(pos_c, vec_take)]
        has_match = np.repeat(matched, vec_take)
        build_rows = np.where(
            has_match, self.order[np.clip(code + intra, 0,
                                          max(len(self.order) - 1, 0))], 0)
        # output slot of each vectorized row: row start + intra-group rank
        vec_slots = np.repeat(starts, vec_take) + intra
        return {"lk": lk, "is_fb": is_fb, "cnt": cnt,
                "extra_rows": extra_rows, "starts": starts, "m": m,
                "m_vec": m_vec, "left_idx": left_idx,
                "build_rows": build_rows, "has_match": has_match,
                "vec_slots": vec_slots}

    def _assemble_host(self, lpart: C.Partition, plan: dict
                       ) -> Optional[C.Partition]:
        """Materialize the join output on host from the gather program."""
        op = self.op
        ls = lpart.schema
        left_idx = plan["left_idx"]
        build_rows = plan["build_rows"]
        has_match = plan["has_match"]
        m_vec = plan["m_vec"]
        m = plan["m"]
        extra_rows = plan["extra_rows"]
        # gather left (minus key), key, right (minus key)
        lgather = self._gather(lpart, left_idx)
        rgather = self._gather(self.big, build_rows,
                               valid_rows=has_match
                               if op.how == "left" else None)
        if lgather is None or rgather is None:
            return None
        out_cols, out_types, entries = self._output_layout(ls)
        leaves: dict[str, C.Leaf] = {}
        for ci_out, (side, src_ci, _mo) in enumerate(entries):
            src_leaves = lgather if side == "l" else rgather
            for path, leaf in src_leaves.items():
                if path == str(src_ci) or path.startswith(f"{src_ci}.") or \
                        path.startswith(f"{src_ci}#"):
                    # make_opt leaves already carry validity: _gather_leaves
                    # was called with valid_rows=has_match for left joins
                    newp = str(ci_out) + path[len(str(src_ci)):]
                    leaves[newp] = leaf
        schema = T.row_of(out_cols, out_types)
        vec_part = C.Partition(schema=schema, num_rows=m_vec, leaves=leaves,
                               start_index=lpart.start_index)
        if not extra_rows:
            return vec_part
        # ---- splice boxed outputs into their slots ------------------------
        starts, cnt, is_fb = plan["starts"], plan["cnt"], plan["is_fb"]
        vec_slots = plan["vec_slots"]
        outp = C.gather_partition(vec_part, vec_slots,
                                  np.arange(m_vec, dtype=np.int64), m)
        outp.start_index = lpart.start_index
        mask = np.zeros(m, np.bool_)
        mask[vec_slots] = True
        fallback_out: dict[int, Any] = {}
        for i, outs in extra_rows.items():
            base = int(starts[i]) + (int(cnt[i]) if not is_fb[i] else 0)
            for j, t in enumerate(outs):
                fallback_out[base + j] = t
        outp.normal_mask = mask
        outp.fallback = fallback_out
        return outp


# ===========================================================================
# device-side probe + gather (SURVEY §2.10.4: device-sharded broadcast join)
# ===========================================================================

def _pack_sig_words(sig: np.ndarray) -> np.ndarray:
    """[N, W] uint8 canonical signatures -> [N, nw] uint64 words whose
    word-sequence lexicographic order equals the byte lexicographic order
    (big-endian packing), so the device can binary-search them."""
    n, w = sig.shape
    nw = max(1, -(-w // 8))
    if w < nw * 8:
        sig = np.concatenate(
            [sig, np.zeros((n, nw * 8 - w), np.uint8)], axis=1)
    return np.ascontiguousarray(sig).view(">u8").astype(np.uint64)


def _build_probe_fn(u: int, nw: int, mesh=None):
    """Jittable lower-bound binary search of [B, nw] probe words in the
    sorted [u, nw] build words. On a mesh the probe rows shard over the data
    axis while the build side replicates on every device — the broadcast
    hash join of the reference (PhysicalPlan.cc:145-178: no shuffle, build
    side fully materialized everywhere)."""
    from ..runtime.jaxcfg import jax, jnp

    steps = max(1, u).bit_length() + 1

    # direct rank probe: lower_bound[n] = |{j : build[j] <lex probe[n]}| as
    # one fused comparison/reduction pass — no per-step row gathers. The
    # binary search's build_words[mid] gathers run on the TPU scalar core
    # (the profiled zillow-stage gathers cost ~49ms each at this batch
    # size); the [B, u, nw] comparison streams through the VPU instead.
    # Falls back to the log-step search when the broadcast build side is
    # large enough that the B x u compare matrix would out-cost it.
    direct = u * max(1, nw) <= (1 << 15)
    # the loop-carried [chunk, u] less/prefix_eq intermediates are bounded
    # by chunking the probe batch: an unchunked 1M-row bucket against
    # u=32768 would carry multi-GB booleans per dispatch if XLA doesn't
    # fuse the chain into the reductions (ADVICE r5) — cap chunk*u*nw
    _DIRECT_CHUNK_ELEMS = 1 << 22

    def _lower_bound_direct_one(words, build_words):
        bw = build_words[None, :, :]          # [1, u, nw]
        pw = words[:, None, :]                # [chunk, 1, nw]
        lt = bw < pw
        eq = bw == pw
        b = words.shape[0]
        less = jnp.zeros((b, u), dtype=bool)
        prefix_eq = jnp.ones((b, u), dtype=bool)
        for k in range(nw):                   # nw is tiny (key bytes / 8)
            less = less | (prefix_eq & lt[..., k])
            prefix_eq = prefix_eq & eq[..., k]
        pos = less.sum(axis=1, dtype=jnp.int32)
        matched = prefix_eq.any(axis=1)       # some build row fully equal
        return (jnp.clip(pos, 0, max(u - 1, 0)).astype(jnp.int64),
                matched)

    def lower_bound_direct(words, build_words):
        b = words.shape[0]
        chunk = max(1, _DIRECT_CHUNK_ELEMS // max(1, u * max(1, nw)))
        if b <= chunk:
            return _lower_bound_direct_one(words, build_words)
        nchunks = -(-b // chunk)
        pad = nchunks * chunk - b
        wpad = jnp.pad(words, ((0, pad), (0, 0))) if pad else words
        pos, matched = jax.lax.map(
            lambda w: _lower_bound_direct_one(w, build_words),
            wpad.reshape(nchunks, chunk, wpad.shape[1]))
        return pos.reshape(-1)[:b], matched.reshape(-1)[:b]

    def lower_bound_search(words, build_words):
        b = words.shape[0]
        lo = jnp.zeros(b, jnp.int32)
        hi = jnp.full(b, u, jnp.int32)
        for _ in range(steps):
            done = lo >= hi
            mid = (lo + hi) // 2
            mw = build_words[jnp.clip(mid, 0, max(u - 1, 0))]   # [b, nw]
            diff = mw != words
            anyd = jnp.any(diff, axis=1)
            first = jnp.argmax(diff, axis=1)
            aw = jnp.take_along_axis(mw, first[:, None], 1)[:, 0]
            bw = jnp.take_along_axis(words, first[:, None], 1)[:, 0]
            less = anyd & (aw < bw)
            lo = jnp.where(~done & less, mid + 1, lo)
            hi = jnp.where(~done & ~less, mid, hi)
        pos = jnp.clip(lo, 0, max(u - 1, 0))
        cand = build_words[pos]
        matched = (lo < u) & jnp.all(cand == words, axis=1)
        return pos.astype(jnp.int64), matched

    lower_bound = lower_bound_direct if direct else lower_bound_search

    if mesh is None:
        # content-addressed compile (exec/compilequeue): flights' probe
        # stages are isomorphic up to the build table — which is an
        # ARGUMENT here, so equal (u, nw) probes share one executable
        # in-process and reuse the serialized artifact across processes
        from .compilequeue import aot_jit

        return aot_jit(lower_bound, tag="join")
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import DATA_AXIS
    from ..runtime.jaxcfg import shard_map_compat

    fn = shard_map_compat(lower_bound, mesh,
                          (P(DATA_AXIS), P()),
                          (P(DATA_AXIS), P(DATA_AXIS)))
    return jax.jit(fn)


def _leaf_flat_arrays(part: C.Partition, prefix: str) -> Optional[dict]:
    """Flatten a partition's leaves into a dict of arrays for the device
    gather; None if any leaf kind can't ride the device."""
    out: dict[str, np.ndarray] = {}
    for path, leaf in part.leaves.items():
        if isinstance(leaf, C.NumericLeaf):
            out[f"{prefix}{path}#d"] = leaf.data
            if leaf.valid is not None:
                out[f"{prefix}{path}#v"] = leaf.valid
        elif isinstance(leaf, C.StrLeaf):
            out[f"{prefix}{path}#b"] = leaf.bytes
            out[f"{prefix}{path}#l"] = leaf.lengths
            if leaf.valid is not None:
                out[f"{prefix}{path}#v"] = leaf.valid
        elif isinstance(leaf, C.NullLeaf):
            pass                      # rebuilt host-side from m
        else:
            return None
    return out


def _build_assemble_fn(pairs: tuple, left_join: bool):
    """Jittable join-output assembly: gathers every source leaf array at
    the match-expansion indices and emits OUTPUT-convention keys (path /
    path#bytes / path#len / path#valid) so the result doubles as the
    output partition's device view. pairs: (outkey, side, srckey|None,
    suffix) with suffix 'synth_v' synthesizing Option validity for left
    joins whose build side had none."""
    from ..runtime.jaxcfg import jax, jnp

    def fn(larr, rarr, lidx, ridx, hm):
        out = {}
        for outkey, side, srckey, suf in pairs:
            if suf == "synth_v":
                out[outkey] = hm
                continue
            src = larr if side == "l" else rarr
            idx = lidx if side == "l" else ridx
            g = src[srckey][idx]
            if side == "r" and left_join:
                if suf == "v":
                    g = g & hm
                elif suf == "d":
                    shape = (hm.shape[0],) + (1,) * (g.ndim - 1)
                    g = jnp.where(hm.reshape(shape), g, 0)
            out[outkey] = g
        return out

    from .compilequeue import aot_jit

    return aot_jit(fn, salt=f"assemble{int(left_join)}", tag="join")


def _build_gather_fn(lkeys: tuple, rkeys: tuple, left_join: bool):
    """Jittable match-expansion gather: output row i takes left row
    left_idx[i] and build row build_rows[i]; for left joins the unmatched
    rows' right side is invalidated on device."""
    from ..runtime.jaxcfg import jax, jnp

    def gather(left_arrays, build_arrays, left_idx, build_rows, has_match):
        out = {}
        for k in lkeys:
            out[k] = left_arrays[k][left_idx]
        for k in rkeys:
            g = build_arrays[k][build_rows]
            if left_join:
                if k.endswith("#v"):
                    g = g & has_match
                elif k.endswith("#d"):
                    shape = (has_match.shape[0],) + (1,) * (g.ndim - 1)
                    g = jnp.where(has_match.reshape(shape), g, 0)
            out[k] = g
        return out

    from .compilequeue import aot_jit

    return aot_jit(gather, salt=f"gather{int(left_join)}", tag="join")


class _DeviceProbe(_VectorBuild):
    """Broadcast join with the probe + gathers ON DEVICE (single chip or
    mesh). The build side stays host-factorized (np.unique — it is the small
    side by the reference's own cost model) and ships to the device once;
    probe partitions search it with a vectorized binary search and expand
    matches with device gathers. Reference: PipelineBuilder.h
    innerJoinDict/leftJoinDict fused probes; HashJoinStage.cc:473.

    With `dev_out` set (the join feeds a later stage and the handoff gate
    allows it), the match-expansion output stays ON DEVICE: the result
    partition carries a device view for the consumer and lazy host leaves
    that fetch only if some slow path needs them."""

    dev_out = False

    @classmethod
    def try_build(cls, op, rparts, backend):
        self = super().try_build(op, rparts, backend)
        if self is None:
            return None
        if _leaf_flat_arrays(self.big, "r.") is None:
            return None
        u = len(self.uniq_view)
        sig_bytes = self.uniq_view.view(np.uint8).reshape(u, -1)
        self._build_words = _pack_sig_words(sig_bytes)
        self._nw = self._build_words.shape[1]
        self._mesh = getattr(backend, "mesh", None)
        self.backend = backend
        self._rflat_dev = None
        return self

    # ------------------------------------------------------------------
    def _probe_sig(self, lpart: C.Partition, sig: np.ndarray, excs: list
                   ) -> Optional[C.Partition]:
        plan = self._probe_plan(lpart, sig, excs)
        if self.dev_out and self._mesh is None and not plan["extra_rows"]:
            outp = self._assemble_device(lpart, plan)
            if outp is not None:
                return outp
        return self._assemble_host(lpart, plan)

    def _assemble_device(self, lpart: C.Partition, plan: dict
                         ) -> Optional[C.Partition]:
        """Device-resident join output: one jitted gather writes the
        output-convention arrays; host leaves go lazy and the next stage
        consumes the attached view directly. Best-effort — None falls back
        to the host assembly (identical semantics)."""
        try:
            import jax

            from ..runtime import xferstats
            from ..runtime.jaxcfg import jnp

            op = self.op
            m = int(plan["m"])
            if m == 0 or plan["m_vec"] != m:
                return None
            out_cols, out_types, entries = self._output_layout(lpart.schema)
            rs = self.big.schema
            for side, src_ci, mo in entries:
                if not mo:
                    continue
                base = rs.types[src_ci]
                base = base.without_option() if base.is_optional() else base
                if isinstance(base, T.TupleType) or \
                        base in (T.NULL, T.EMPTYTUPLE):
                    return None   # nested Option synthesis: host path
            # PEEK the input view: every bail below must leave it intact
            # for the host assembly (a burnt view would force a full
            # lazy-leaf D2H — worse than no handoff at all)
            lflat = self._flat_device_arrays(lpart, "l.", consume=False)
            if lflat is None:
                return None
            if self._rflat_dev is None:
                rf = _leaf_flat_arrays(self.big, "r.")
                if rf is None:
                    return None
                # the device copy of the build side pins HBM for the
                # executor's lifetime: charge it against the handoff
                # budget once, up front
                rf_nb = sum(v.nbytes for v in rf.values())
                if rf_nb > getattr(self, "_handoff_left", 0):
                    return None
                self._handoff_left -= rf_nb
                self._rflat_dev = {k: jnp.asarray(v) for k, v in rf.items()}
            rflat = self._rflat_dev
            left = op.how == "left"

            def src_pairs(flat, side_tag, src_ci, ci_out):
                ps = []
                for k in flat:
                    core = k[2:]
                    srcpath, suf = core.rsplit("#", 1)
                    if not (srcpath == str(src_ci)
                            or srcpath.startswith(f"{src_ci}.")
                            or srcpath.startswith(f"{src_ci}#")):
                        continue
                    outpath = str(ci_out) + srcpath[len(str(src_ci)):]
                    outkey = {"d": outpath, "b": outpath + "#bytes",
                              "l": outpath + "#len",
                              "v": outpath + "#valid"}[suf]
                    ps.append((outkey, side_tag, k, suf))
                return ps

            pairs: list = []
            for ci_out, (side, src_ci, mo) in enumerate(entries):
                flat = lflat if side == "l" else rflat
                ps = src_pairs(flat, side, src_ci, ci_out)
                if mo and not any(ok == f"{ci_out}#valid"
                                  for ok, _, _, _ in ps):
                    ps.append((f"{ci_out}#valid", "r", None, "synth_v"))
                pairs.extend(ps)

            # structural check: the assembled keys must be exactly what a
            # host-materialized partition would stage (one executable for
            # handoff-fed and host-fed batches alike)
            leaf_types: dict = {}
            for ci, ct in enumerate(out_types):
                for pth, lt in C.flatten_type(ct, str(ci)):
                    leaf_types[pth] = lt
            expect: set = set()
            for pth, lt in leaf_types.items():
                expect.update(C.staged_keys_for_type(pth, lt))
            if expect != {ok for ok, _, _, _ in pairs}:
                return None

            b2 = C.bucket_size(m, self.backend.bucket_mode)
            est = b2
            for _, side_tag, sk, suf in pairs:
                if sk is None:
                    est += b2
                    continue
                a = (lflat if side_tag == "l" else rflat)[sk]
                est += (a.nbytes // max(1, int(a.shape[0]))) * b2
            if est * 2 > getattr(self, "_handoff_left", 0):
                return None
            self._handoff_left -= est * 2
            lpart.device_batch = None     # committed: release the one-shot

            lidx = np.zeros(b2, np.int64)
            lidx[:m] = plan["left_idx"]
            ridx = np.zeros(b2, np.int64)
            ridx[:m] = plan["build_rows"]
            hm = np.zeros(b2, np.bool_)
            hm[:m] = plan["has_match"]
            fkey = ("joinassemble", tuple(pairs), left)
            fn = self.backend.jit_cache.get_or_build(
                fkey, lambda: _build_assemble_fn(tuple(pairs), left))
            outs = fn(lflat, rflat, jnp.asarray(lidx), jnp.asarray(ridx),
                      jnp.asarray(hm))

            schema = T.row_of(out_cols, out_types)
            outp = C.Partition(schema=schema, num_rows=m, leaves={},
                               start_index=lpart.start_index)
            view = dict(outs)
            rv = np.zeros(b2, np.bool_)
            rv[:m] = True
            view["#rowvalid"] = jnp.asarray(rv)
            view["#seed"] = C.partition_seed(outp)

            def loader(pth):
                arrs = {}
                for k in C.result_keys_for_leaf(outs, pth):
                    h = np.asarray(jax.device_get(outs[k][:m]))
                    xferstats.note_d2h(h.nbytes)
                    arrs[k] = h
                return C.leaf_from_result_arrays(arrs, pth,
                                                 leaf_types[pth], m)

            ll = C.LazyLeaves(leaf_types.keys(), loader, tag="join")
            ll.nbytes_hint = est
            outp.leaves = ll
            outp.device_batch = C.DeviceBatch(arrays=view, n=m, b=b2,
                                              schema=schema)
            return outp
        except Exception:   # pragma: no cover - purely an optimization
            return None

    def _match_positions(self, sig: np.ndarray):
        import numpy as _np

        from ..parallel import mesh as _mesh

        u = len(self.uniq_view)
        words = _pack_sig_words(sig)
        n = words.shape[0]
        b = C.bucket_size(n)
        n_dev = len(self._mesh.devices.flat) if self._mesh is not None else 1
        b = -(-b // n_dev) * n_dev
        if b > n:
            words = _np.concatenate(
                [words, _np.zeros((b - n, self._nw), _np.uint64)])
        fn = self.backend.jit_cache.get_or_build(
            ("joinprobe", u, self._nw, id(self._mesh)),
            lambda: _build_probe_fn(u, self._nw, self._mesh))
        pos, matched = fn(words, self._build_words)
        pos = _mesh.materialize_np(pos)[:n]
        matched = _mesh.materialize_np(matched)[:n]
        return pos, matched

    def _flat_device_arrays(self, part: C.Partition, side: str,
                            consume: bool = True):
        """Flat '#d/#b/#l/#v' gather inputs, preferring a device-resident
        handoff view over host leaves (the view's arrays skip both the
        D2H of the producing stage and the H2D here). Falls back to the
        host leaf arrays — forcing lazy leaves if it must.

        consume=False peeks without releasing the one-shot view — callers
        that may still bail to the host path must not burn it (a consumed
        view would force a full lazy-leaf D2H on the fallback)."""
        dv = getattr(part, "device_batch", None)
        if dv is not None and dv.n == part.num_rows:
            if consume:
                part.device_batch = None      # one-shot, like stage_partition
            out = {}
            for k, v in dv.arrays.items():
                if k in ("#rowvalid", "#seed"):
                    continue
                if k.endswith("#bytes"):
                    out[f"{side}{k[:-6]}#b"] = v
                elif k.endswith("#len"):
                    out[f"{side}{k[:-4]}#l"] = v
                elif k.endswith("#valid"):
                    out[f"{side}{k[:-6]}#v"] = v
                else:
                    out[f"{side}{k}#d"] = v
            return out
        return _leaf_flat_arrays(part, side)

    def _gather(self, part: C.Partition, idx: np.ndarray, valid_rows=None
                ) -> Optional[dict]:
        import numpy as _np

        from ..parallel import mesh as _mesh

        m = len(idx)
        if m == 0:
            return _gather_leaves(part, idx, valid_rows)
        side = "r." if part is self.big else "l."
        arrays = self._flat_device_arrays(part, side)
        if arrays is None:
            return _gather_leaves(part, idx, valid_rows)
        mb = C.bucket_size(m)
        idx_p = _np.zeros(mb, _np.int64)
        idx_p[:m] = idx
        hm = _np.zeros(mb, _np.bool_)
        hm[:m] = valid_rows if valid_rows is not None else True
        keys = tuple(sorted(arrays))
        left_join = valid_rows is not None
        fn = self.backend.jit_cache.get_or_build(
            ("joingather", side, keys, left_join),
            lambda: _build_gather_fn(
                keys if side == "l." else (),
                keys if side == "r." else (), left_join))
        if side == "l.":
            outs = fn(arrays, {}, idx_p, idx_p, hm)
        else:
            outs = fn({}, arrays, idx_p, idx_p, hm)
        outs = {k: _mesh.materialize_np(v) for k, v in outs.items()}
        # rebuild leaves, sliced back to the true match count. Leaf
        # structure derives from the SCHEMA + array key set, never from
        # leaf instances — the partition's host leaves may be lazy
        # (device-backed) and must not be forced here
        gathered: dict[str, C.Leaf] = {}
        for ci, ct in enumerate(part.schema.types):
            for path, _lt in C.flatten_type(ct, str(ci)):
                if f"{side}{path}#b" in outs:
                    b_ = _np.asarray(outs[f"{side}{path}#b"])[:m]
                    ln = _np.asarray(outs[f"{side}{path}#l"])[:m]
                    valid = _np.asarray(outs[f"{side}{path}#v"])[:m] \
                        if f"{side}{path}#v" in outs else None
                    if left_join and valid is None:
                        valid = hm[:m].copy()
                    gathered[path] = C.StrLeaf(b_, ln, valid)
                elif f"{side}{path}#d" in outs:
                    data = _np.asarray(outs[f"{side}{path}#d"])[:m]
                    valid = _np.asarray(outs[f"{side}{path}#v"])[:m] \
                        if f"{side}{path}#v" in outs else None
                    if left_join and valid is None:
                        valid = hm[:m].copy()
                    gathered[path] = C.NumericLeaf(data, valid)
                else:
                    gathered[path] = C.NullLeaf(m)
        return gathered
