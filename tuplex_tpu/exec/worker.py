"""Serverless worker entry point (reference: awslambda/src/lambda_main.cc —
the Lambda-side handler that parses the InvocationRequest, JIT-compiles the
shipped stage, processes its input split, and writes output parts).

Two modes:

* ``python -m tuplex_tpu.exec.worker <request.pkl>`` — one task, then exit
  (the cold-start Lambda invocation).
* ``python -m tuplex_tpu.exec.worker --serve`` — WARM worker: read request
  paths line-by-line from stdin and process each in this long-lived process
  (reference: Lambda container reuse across invocations,
  AWSLambdaBackend.cc:254-430 relies on warm containers the same way).
  Completion is signalled by the atomic ``response.pkl`` write, never by
  process exit; a task exception produces ``{"ok": False}`` instead of
  killing the worker. The interpreter+jax import (~6 s) and every traced
  stage executable (keyed by content hash, TransformStage.key) amortize
  across tasks — measured 15 s/task cold vs sub-second warm on zillow.

The request carries the stage spec (UDF sources + schemas), this task's
input (either a file-split subset or a staged-partition directory), the
output directory, and the full option set. The worker rebuilds the stage,
executes it through the ordinary LocalBackend (fast path + general tier +
interpreter resolve — the full dual-mode ladder, unlike the reference
Lambda which defers the slow path to the driver), and writes native-format
output parts plus a pickled response (metrics, exceptions).

Platform: ``TUPLEX_WORKER_PLATFORM`` (set by the driver from
``tuplex.aws.workerPlatform``) picks the jax platform POST-import — on
machines where a TPU plugin force-registers itself, only a late
``jax.config.update`` wins over the environment.
"""

from __future__ import annotations

import os
import pickle
import sys


def _set_platform() -> None:
    plat = os.environ.get("TUPLEX_WORKER_PLATFORM", "")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)


def run_task(req_path: str, backends=None) -> dict:
    """Process one request pickle; returns the response dict (also written
    atomically to response.pkl next to the request). `backends` is a
    per-process cache {options-fingerprint: LocalBackend} — an
    LRU-bounded mapping (utils/lru.LruDict in serve()) so warm workers
    reuse traced stage executables across tasks AND across interleaved
    tenants with different option sets."""
    import json
    import time

    with open(req_path, "rb") as fp:
        req = pickle.load(fp)

    task_dir = os.path.dirname(os.path.abspath(req_path))
    t_start = time.time()

    def emit(kind: str, **fields) -> None:
        """Append a live task event for the driver's poll loop to stream
        into the history dashboard (reference: Lambda workers posting task
        status back, HistoryServerConnector.cc:102-198). Best-effort: an
        unwritable control dir must never fail the task."""
        try:
            with open(os.path.join(task_dir, "events.jsonl"), "a") as efp:
                efp.write(json.dumps(
                    {"event": kind, "pid": os.getpid(), **fields}) + "\n")
        except OSError:
            pass

    emit("started", task=req.get("task"),
         input=(req.get("files") or req.get("indir") or "memory"))

    from ..core.options import ContextOptions
    from ..exec.local import LocalBackend
    from ..io.tuplexfmt import (TuplexFileSourceOperator,
                                write_partitions_tuplex)
    from .serverless import rebuild_stage

    opts_dict = dict(req["options"])
    # workers are leaves: never recurse into another fan-out, never serve UI
    opts_dict["tuplex.backend"] = "local"
    opts_dict["tuplex.webui.enable"] = "false"
    fing = tuple(sorted(opts_dict.items()))
    backend = None if backends is None else backends.get(fing)
    if backend is None:
        options = ContextOptions(opts_dict)
        backend = LocalBackend(options)
        if backends is not None:
            # bounded LRU, NOT one-live-set: interleaved tenants with
            # different option fingerprints used to rebuild backends (and
            # lose every traced stage executable) on each alternation
            backends[fing] = backend
    options = backend.options
    fl_snap = len(backend.failure_log)

    stage = rebuild_stage(req["stage"], options, files=req.get("files"))

    class _Ctx:   # minimal context for source loading (duck-typed)
        options_store = options

        def __init__(self):
            self.backend = backend

    ctx = _Ctx()
    if req.get("indir"):
        src = TuplexFileSourceOperator(options, req["indir"])
        partitions = src.load_partitions(ctx)
    else:
        from ..api.dataset import _source_partitions

        partitions = _source_partitions(ctx, stage, lazy=False)

    result = backend.execute(stage, partitions)

    sink = req.get("sink")
    if sink is not None:
        # sink pushdown: this task's rows become its own part file written
        # straight from columnar buffers (reference: Lambda writing S3
        # output.part-N); no partitions travel back
        from .serverless import write_sink_part

        write_sink_part(sink, req["task"], result.partitions,
                        backend=backend)
    else:
        write_partitions_tuplex(req["outdir"], result.partitions,
                                backend=backend)
    resp = {"ok": True,
            "rows": sum(p.num_rows for p in result.partitions),
            "metrics": result.metrics,
            "exceptions": result.exceptions,
            "failure_log": list(backend.failure_log[fl_snap:])}
    emit("done", task=req.get("task"), rows=resp["rows"],
         exceptions=len(result.exceptions),
         wall_s=round(time.time() - t_start, 3))
    _write_response(req_path, resp)
    return resp


def _write_response(req_path: str, resp: dict) -> None:
    task_dir = os.path.dirname(os.path.abspath(req_path))
    tmp = os.path.join(task_dir, ".response.tmp")
    with open(tmp, "wb") as fp:
        pickle.dump(resp, fp)
    os.replace(tmp, os.path.join(task_dir, "response.pkl"))


def serve() -> int:
    """Warm-worker loop: one request path per stdin line; 'EXIT' quits.

    Completion AND liveness are signalled solely by the atomic
    response.pkl write — the driver redirects this process's stdout into
    its log file and never reads it, so the 'READY'/'OK' lines below are
    log breadcrumbs, not a protocol (ADVICE r5)."""
    _set_platform()
    from ..utils.lru import LruDict

    # one backend per option fingerprint, LRU-bounded: a multi-tenant
    # driver interleaving option sets keeps each tenant's warm backend
    # (and its traced executables) instead of thrashing on every task
    try:
        cap = max(1, int(os.environ.get("TUPLEX_WORKER_BACKENDS", "4")))
    except ValueError:
        cap = 4
    backends = LruDict(cap)
    print("READY", flush=True)
    for line in sys.stdin:
        req_path = line.strip()
        if not req_path:
            continue
        if req_path == "EXIT":
            break
        try:
            run_task(req_path, backends)
            print(f"OK {req_path}", flush=True)
        except Exception as e:  # task failure must not kill the worker
            try:
                _write_response(req_path, {
                    "ok": False,
                    "error": f"{type(e).__name__}: {e}"})
            except OSError:
                pass
            import traceback

            traceback.print_exc(file=sys.stderr)
            print(f"ERR {req_path}", flush=True)
    return 0


def main(argv: list[str]) -> int:
    if argv == ["--serve"]:
        return serve()
    if len(argv) != 1:
        print("usage: python -m tuplex_tpu.exec.worker "
              "(<request.pkl> | --serve)", file=sys.stderr)
        return 2
    _set_platform()
    run_task(argv[0])
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
