"""Serverless worker entry point (reference: awslambda/src/lambda_main.cc —
the Lambda-side handler that parses the InvocationRequest, JIT-compiles the
shipped stage, processes its input split, and writes output parts).

Run as ``python -m tuplex_tpu.exec.worker <request.pkl>``. The request
carries the stage spec (UDF sources + schemas), this task's input (either
a file-split subset or a staged-partition directory), the output directory,
and the full option set. The worker rebuilds the stage, executes it through
the ordinary LocalBackend (fast path + general tier + interpreter resolve —
the full dual-mode ladder, unlike the reference Lambda which defers the
slow path to the driver), and writes native-format output parts plus a
pickled response (metrics, exceptions).

Platform: ``TUPLEX_WORKER_PLATFORM`` (set by the driver from
``tuplex.aws.workerPlatform``) picks the jax platform POST-import — on
machines where a TPU plugin force-registers itself, only a late
``jax.config.update`` wins over the environment.
"""

from __future__ import annotations

import os
import pickle
import sys


def main(argv: list[str]) -> int:
    if len(argv) != 1:
        print("usage: python -m tuplex_tpu.exec.worker <request.pkl>",
              file=sys.stderr)
        return 2
    plat = os.environ.get("TUPLEX_WORKER_PLATFORM", "")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)

    with open(argv[0], "rb") as fp:
        req = pickle.load(fp)

    import json
    import time

    task_dir = os.path.dirname(os.path.abspath(argv[0]))
    t_start = time.time()

    def emit(kind: str, **fields) -> None:
        """Append a live task event for the driver's poll loop to stream
        into the history dashboard (reference: Lambda workers posting task
        status back, HistoryServerConnector.cc:102-198). Best-effort: an
        unwritable control dir must never fail the task."""
        try:
            with open(os.path.join(task_dir, "events.jsonl"), "a") as efp:
                efp.write(json.dumps(
                    {"event": kind, "pid": os.getpid(), **fields}) + "\n")
        except OSError:
            pass

    emit("started", task=req.get("task"),
         input=(req.get("files") or req.get("indir") or "memory"))

    from ..core.options import ContextOptions
    from ..exec.local import LocalBackend
    from ..io.tuplexfmt import (TuplexFileSourceOperator,
                                write_partitions_tuplex)
    from .serverless import rebuild_stage

    opts_dict = dict(req["options"])
    # workers are leaves: never recurse into another fan-out, never serve UI
    opts_dict["tuplex.backend"] = "local"
    opts_dict["tuplex.webui.enable"] = "false"
    options = ContextOptions(opts_dict)
    backend = LocalBackend(options)

    stage = rebuild_stage(req["stage"], options, files=req.get("files"))

    class _Ctx:   # minimal context for source loading (duck-typed)
        options_store = options

        def __init__(self):
            self.backend = backend

    ctx = _Ctx()
    if req.get("indir"):
        src = TuplexFileSourceOperator(options, req["indir"])
        partitions = src.load_partitions(ctx)
    else:
        from ..api.dataset import _source_partitions

        partitions = _source_partitions(ctx, stage, lazy=False)

    result = backend.execute(stage, partitions)

    sink = req.get("sink")
    if sink is not None:
        # sink pushdown: this task's rows become its own part file written
        # straight from columnar buffers (reference: Lambda writing S3
        # output.part-N); no partitions travel back
        from .serverless import write_sink_part

        write_sink_part(sink, req["task"], result.partitions,
                        backend=backend)
    else:
        write_partitions_tuplex(req["outdir"], result.partitions,
                                backend=backend)
    resp = {"ok": True,
            "rows": sum(p.num_rows for p in result.partitions),
            "metrics": result.metrics,
            "exceptions": result.exceptions,
            "failure_log": list(backend.failure_log)}
    emit("done", task=req.get("task"), rows=resp["rows"],
         exceptions=len(result.exceptions),
         wall_s=round(time.time() - t_start, 3))
    tmp = os.path.join(os.path.dirname(argv[0]), ".response.tmp")
    with open(tmp, "wb") as fp:
        pickle.dump(resp, fp)
    os.replace(tmp, os.path.join(os.path.dirname(argv[0]), "response.pkl"))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
