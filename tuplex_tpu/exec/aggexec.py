"""Aggregate stage execution: vectorized folds + segment reductions.

The device path exploits the associative-combine contract the reference
imposes on user aggregates (reference: AggregateFunctions.cc agg_combine_f
is required to be associative for thread-parallel aggregation;
LocalBackend.cc:2219 createFinalHashmap merges per-task tables). Here:

  per-partition: recognized fold exprs evaluate as whole columns on device
  (Emitter trace) and reduce via jnp.sum / segment_sum — per-device partials
  then combine on host (tiny), or via psum over a mesh (parallel backend).

Rows that error during expr evaluation (plus boxed fallback rows) fold on the
interpreter exactly like other dual-mode work.
"""

from __future__ import annotations

import time
from typing import Any, Optional

import numpy as np

from ..compiler.emitter import EmitCtx, Emitter, Frame
from ..core import typesys as T
from ..core.errors import NotCompilable
from ..core.row import Row
from ..plan import aggregates as A
from ..plan import logical as L
from ..runtime import columns as C
from .local import ExceptionRecord


def _aot(fn):
    """Content-addressed compile for the scan-fold executables (the agg
    analog of the stage fns' compilequeue route: identical fold structures
    across jobs/processes reuse one executable)."""
    from .compilequeue import aot_jit

    return aot_jit(fn, tag="agg")


from ..parallel.collectives import reduce_identity as _identity


def _combine_scalar(reducer: str, a, b):
    if reducer == "sum":
        return a + b
    if reducer == "min":
        return min(a, b)
    return max(a, b)


class AggregateExecutor:
    def __init__(self, backend):
        self.backend = backend

    # ==================================================================
    def execute(self, stage, partitions: list[C.Partition]):
        from ..runtime import tracing as TR
        from .local import StageResult

        op = stage.op
        t0 = time.perf_counter()
        with TR.span("agg:execute", "exec") as _sp:
            _sp.set("op", type(op).__name__)
            if isinstance(op, A.UniqueOperator):
                parts, excs = self._unique(op, partitions)
            elif isinstance(op, A.AggregateByKeyOperator):
                parts, excs = self._aggregate(op, partitions, by_key=True)
            elif isinstance(op, A.AggregateOperator):
                parts, excs = self._aggregate(op, partitions, by_key=False)
            else:
                raise NotCompilable(f"aggregate stage op {op!r}")
            rows_out = sum(p.num_rows for p in parts)
            _sp.set("rows_out", rows_out)
        from . import compilequeue as _cq

        cs, cn = _cq.consume_tag("agg")
        m = {"wall_s": time.perf_counter() - t0,
             "rows_out": rows_out,
             "exception_rows": len(excs),
             "compile_s": cs, "stage_compiles": cn}
        return StageResult(parts, excs, m)

    # ==================================================================
    def _unique(self, op, partitions):
        """Distinct rows, first-occurrence order. Vectorized per partition
        via structured-view np.unique; cross-partition merge via host set."""
        seen_sig: set = set()
        seen_val: set = set()
        out_rows: list = []
        for part in partitions:
            self.backend.mm.touch(part)
            sig = _row_signatures(part)
            for i in range(part.num_rows):
                s = sig[i] if sig is not None and i not in part.fallback \
                    else None
                if s is not None and s in seen_sig:
                    continue
                row = part.decode_row(i)
                try:
                    key = tuple(row.values)
                except TypeError:
                    out_rows.append(row)  # unhashable: keep (reference keeps
                    continue              # such rows in the backup dict)
                if s is not None:
                    seen_sig.add(s)
                if key in seen_val:
                    continue
                seen_val.add(key)
                out_rows.append(row)
        schema = op.schema()
        values = [r.unwrap() if len(schema.columns) == 1 else tuple(r.values)
                  for r in out_rows]
        if not values:
            return [], []
        return [C.build_partition(values, schema)], []

    # ==================================================================
    def _aggregate(self, op, partitions, by_key: bool):
        spec = A.recognize_fold(op.aggregate_udf)
        excs: list[ExceptionRecord] = []
        ps = partitions[0].schema if partitions else None

        if by_key:
            kidx = [ps.columns.index(c) for c in op.key_columns] if ps else []
            groups: dict = {}
            scan_k = None
            if spec is None and ps is not None and not getattr(
                    self.backend, "interpret_only", False):
                scan_k = A.ScanFold.try_build(op, ps)
            for part in partitions:
                self.backend.mm.touch(part)
                device_ok = spec is not None and self._device_fold_bykey(
                    op, spec, part, kidx, groups, excs)
                if not device_ok and scan_k is not None:
                    device_ok = self._scan_fold_bykey(op, scan_k, part, kidx,
                                                      groups, excs)
                if not device_ok:
                    self._python_fold(op, part, range(part.num_rows),
                                      groups, kidx, excs)
            out_schema = op.schema()
            values = []
            for k, acc in groups.items():
                accs = acc if isinstance(acc, tuple) else (acc,)
                values.append(tuple(k) + tuple(accs))
            if not values:
                return [], excs
            return [C.build_partition(values, out_schema)], excs

        # whole-dataset aggregate: pattern folds vectorize; everything else
        # tries the compiled sequential scan fold before per-row python
        scan = None
        if spec is None and ps is not None and not getattr(
                self.backend, "interpret_only", False):
            scan = A.ScanFold.try_build(op, ps)
        if scan is not None:
            return self._scan_aggregate(op, scan, partitions, excs)
        acc_holder = {"acc": op.initial, "started": False}

        def merge_partial(partial):
            # partial is a raw reduction (identity-seeded); merge via the
            # recognized reducers
            accs = list(acc_holder["acc"]) if isinstance(
                acc_holder["acc"], tuple) else [acc_holder["acc"]]
            parts_ = list(partial) if isinstance(partial, tuple) else [partial]
            merged = [_combine_scalar(r, a, p)
                      for r, a, p in zip(spec.reducers, accs, parts_)]
            acc_holder["acc"] = tuple(merged) if isinstance(
                acc_holder["acc"], tuple) else merged[0]

        groups2: dict = {(): op.initial}
        for part in partitions:
            self.backend.mm.touch(part)
            done = False
            if spec is not None:
                partial, bad_rows = self._device_fold(op, spec, part)
                if partial is not None:
                    merge_partial(partial)
                    self._python_fold(op, part, bad_rows, groups2, [], excs,
                                      into_key=())
                    done = True
            if not done:
                self._python_fold(op, part, range(part.num_rows), groups2,
                                  [], excs, into_key=())
        # fold the python-side accumulator into the device-side one via the
        # user combine (both are real agg values, reference: agg_combine_f)
        py_acc = groups2[()]
        if spec is not None:
            if py_acc != op.initial:
                acc_holder["acc"] = op.combine_udf.func(
                    acc_holder["acc"], py_acc)
            final = acc_holder["acc"]
        else:
            final = py_acc
        schema = op.schema()
        return [C.build_partition([final], schema)], excs

    # ------------------------------------------------------------------
    def _scan_aggregate(self, op, scan, partitions, excs):
        """Arbitrary aggregate UDF on device: lax.scan fold per partition
        with the accumulator CHAINED partition-to-partition (the initial
        value seeds exactly once, matching the interpreter tier); rows the
        scan flags bad fold onto the running value via the interpreter
        (reference: per-task agg_agg_f, AggregateFunctions.cc:16-178)."""
        import jax
        import numpy as np

        acc_val = op.initial

        def fold_py(part, indices):
            nonlocal acc_val
            g = {(): acc_val}
            self._python_fold(op, part, indices, g, [], excs, into_key=())
            acc_val = g[()]

        for part in partitions:
            self.backend.mm.touch(part)
            outs = None
            if part.n_normal() > 0:
                try:
                    fn = self.backend.jit_cache.get_or_build(
                        ("scanfold", op.id, part.schema.name),
                        lambda: _aot(scan.build_fn()))
                    batch = C.stage_partition(part, self.backend.bucket_mode)
                    acc_in = scan.encode_acc(acc_val)
                    outs = jax.device_get(fn(batch.arrays, acc_in))
                except Exception as e:
                    from ..utils.logging import get_logger

                    get_logger("exec").warning(
                        "scan fold failed (%s: %s); partition folds on the "
                        "interpreter", type(e).__name__, e)
            if outs is None:
                fold_py(part, range(part.num_rows))
                continue
            *acc_leaves, bads = outs
            acc_val = scan.decode_acc(acc_leaves)
            bad_idx = np.nonzero(np.asarray(bads)[:part.num_rows])[0]
            if len(bad_idx):
                fold_py(part, bad_idx.tolist())
        schema = op.schema()
        return [C.build_partition([acc_val], schema)], excs

    # ------------------------------------------------------------------
    def _scan_fold_bykey(self, op, scan, part, kidx, groups, excs) -> bool:
        """Arbitrary aggregateByKey UDF on device: segmented lax.scan fold —
        per-key accumulator slots seeded from the running `groups` table so
        cross-partition chaining (and the once-per-key initial) stays exact;
        rows the scan flags bad fold via the interpreter afterward."""
        import jax

        real = _real_mask(part)
        codes, uniq_rows = _factorize_keys(part, kidx, real)
        if codes is None or len(uniq_rows) == 0:
            return False
        n = part.num_rows
        nseg = len(uniq_rows)
        nseg_b = C.bucket_size(nseg)
        # key columns only: a device-resident (lazy) partition must not be
        # forced to host just to name its groups
        keys = C.decode_key_tuples(part, uniq_rows.tolist(), kidx)
        try:
            seg_init = A._scanfold_encode_segments(
                scan, [groups.get(k, op.initial) for k in keys], nseg_b)
        except Exception:
            return False   # an existing acc no longer conforms: python path
        try:
            fn = self.backend.jit_cache.get_or_build(
                ("scanfoldseg", op.id, part.schema.name),
                lambda: _aot(A._seg_build_fn(scan)))
            batch = C.stage_partition(part, self.backend.bucket_mode)
            b = batch.arrays["#rowvalid"].shape[0]
            codes_b = np.full(b, nseg_b, dtype=np.int32)
            codes_b[:n][real] = codes
            outs = jax.device_get(fn(batch.arrays, codes_b, seg_init))
        except Exception as e:
            from ..utils.logging import get_logger

            get_logger("exec").warning(
                "segmented scan fold failed (%s: %s); partition folds on "
                "the interpreter", type(e).__name__, e)
            return False
        *leaves, bads = outs
        bads_n = np.asarray(bads)[:n]
        # ghost-group guard (matches the mesh fold's counts check): a key
        # whose rows ALL errored must not emit an initial-only output row
        ok_codes = codes_b[:n][~bads_n]
        seg_ok = np.bincount(ok_codes, minlength=nseg_b + 1)
        vals = A._scanfold_decode_segments(scan, leaves, nseg)
        for si, k in enumerate(keys):
            if seg_ok[si] or k in groups:
                groups[k] = vals[si]
        bad_idx = np.nonzero(bads_n)[0].tolist()
        if bad_idx:
            self._python_fold(op, part, bad_idx, groups, kidx, excs)
        return True

    # ------------------------------------------------------------------
    def _python_fold(self, op, part, indices, groups, kidx, excs,
                     into_key: Optional[tuple] = None):
        for i in indices:
            row = part.decode_row(i)
            k = into_key if into_key is not None else \
                tuple(row.values[j] for j in kidx)
            acc = groups.get(k, op.initial)
            try:
                groups[k] = A._apply_agg(op.aggregate_udf, acc, row)
            except Exception as e:
                excs.append(ExceptionRecord(op.id, type(e).__name__,
                                            row.unwrap()))

    # ------------------------------------------------------------------
    def _device_fold(self, op, spec: A.FoldSpec, part: C.Partition):
        """(partial_tuple|scalar, bad_row_indices) or (None, _) if the
        partition can't run on device."""
        fp = getattr(part, "fold_partials", None)
        if fp is not None and fp[0] == op.id:
            # the transform stage already computed identity-seeded partials
            # inside its own device pass (plan_stages fused the fold) — no
            # second staging/dispatch needed
            partials, bad = fp[1], fp[2]
            out = tuple(partials) if not spec.scalar else partials[0]
            return out, list(bad)
        mesh = getattr(self.backend, "mesh", None)
        if mesh is not None:
            try:
                return self._device_fold_mesh(op, spec, part, mesh)
            except NotCompilable:
                return None, range(part.num_rows)
        try:
            vals, ok_mask, err = self._eval_exprs(op, spec, part)
        except NotCompilable:
            return None, range(part.num_rows)
        import jax.numpy as jnp

        partials = []
        for cv_data, reducer in zip(vals, spec.reducers):
            is_float = cv_data.dtype.kind == "f"
            ident = _identity(reducer, is_float)
            masked = jnp.where(ok_mask, cv_data, ident)
            if reducer == "sum":
                r = masked.sum()
            elif reducer == "min":
                r = masked.min()
            else:
                r = masked.max()
            partials.append(r.item())
        bad = np.nonzero(~np.asarray(ok_mask)[: part.num_rows] &
                         _real_mask(part))[0].tolist()
        bad += [i for i in part.fallback if i not in bad]
        out = tuple(partials) if not spec.scalar else partials[0]
        return out, sorted(set(bad))

    def _device_fold_mesh(self, op, spec: A.FoldSpec, part: C.Partition,
                          mesh):
        """Mesh-parallel fold: per-device shard reduction + psum over ICI
        (SURVEY §2.10: parallel aggregation via collectives)."""
        from ..parallel import collectives as CC
        from ..parallel import mesh as M

        if not part.leaves and part.fallback:
            raise NotCompilable("all-fallback partition")
        batch = C.stage_partition(part, self.backend.bucket_mode)
        arrays = M.pad_batch_for_mesh(batch.arrays, len(mesh.devices.flat))
        schema = part.schema
        eval_exprs = _make_eval_exprs(spec, schema)
        shapes = tuple(sorted((k, v.shape, str(v.dtype))
                              for k, v in arrays.items()))
        run = self.backend.jit_cache.get_or_build(
            ("meshfold", op.id, schema.name, shapes,
             self.backend.fn_cache_salt()),
            lambda: CC.sharded_fold_fn(eval_exprs, spec.reducers, mesh,
                                       arrays))
        outs = run(arrays)
        ok_np = M.materialize_np(outs[-1])[: part.num_rows] & _real_mask(part)
        partials = [o.item() for o in outs[:-1]]
        bad = np.nonzero(~ok_np & _real_mask(part))[0].tolist()
        bad += [i for i in part.fallback if i not in bad]
        out = tuple(partials) if not spec.scalar else partials[0]
        return out, sorted(set(bad))

    def _device_fold_bykey(self, op, spec, part, kidx, groups, excs) -> bool:
        mesh = getattr(self.backend, "mesh", None)
        if mesh is not None:
            try:
                return self._device_fold_bykey_mesh(op, spec, part, kidx,
                                                    groups, excs, mesh)
            except NotCompilable:
                return False
        try:
            vals, ok_mask, err = self._eval_exprs(op, spec, part)
        except NotCompilable:
            return False
        import jax.numpy as jnp
        import jax.ops

        n = part.num_rows
        ok_np = np.asarray(ok_mask)[:n] & _real_mask(part)
        codes, uniq_rows = _factorize_keys(part, kidx, ok_np)
        if codes is None:
            return False
        nseg = len(uniq_rows)
        b = np.asarray(ok_mask).shape[0]
        codes_b = np.full(b, nseg, dtype=np.int32)  # padding -> dropped seg
        codes_b[:n][ok_np] = codes
        seg_partials = []
        for cv_data, reducer in zip(vals, spec.reducers):
            is_float = cv_data.dtype.kind == "f"
            ident = _identity(reducer, is_float)
            masked = jnp.where(ok_mask, cv_data, ident)
            if reducer == "sum":
                r = jax.ops.segment_sum(masked, codes_b,
                                        num_segments=nseg + 1)
            elif reducer == "min":
                r = jax.ops.segment_min(masked, codes_b,
                                        num_segments=nseg + 1)
            else:
                r = jax.ops.segment_max(masked, codes_b,
                                        num_segments=nseg + 1)
            seg_partials.append(np.asarray(r)[:nseg])
        # merge per-key partials into the global dict (key columns only —
        # see decode_key_tuples: full decode would force lazy leaves)
        key_vals = C.decode_key_tuples(part, uniq_rows, kidx)
        for si, row_i in enumerate(uniq_rows):
            k = key_vals[si]
            acc = groups.get(k, op.initial)
            accs = list(acc) if isinstance(acc, tuple) else [acc]
            merged = []
            for j, reducer in enumerate(spec.reducers):
                v = seg_partials[j][si].item()
                merged.append(_combine_scalar(reducer, accs[j], v)
                              if reducer != "sum" else accs[j] + v)
            groups[k] = tuple(merged) if isinstance(acc, tuple) else merged[0]
        # bad rows -> interpreter
        bad = np.nonzero(~ok_np & _real_mask(part))[0].tolist()
        bad += [i for i in part.fallback if i not in bad]
        self._python_fold(op, part, sorted(set(bad)), groups, kidx, excs)
        return True

    def _device_fold_bykey_mesh(self, op, spec, part, kidx, groups, excs,
                                mesh) -> bool:
        """Grouped mesh aggregate: per-device segment reductions over the
        row shard, partial tables combined with psum/pmin/pmax over ICI
        (no shuffle — reference analog: per-task hashtables merged by
        createFinalHashmap, here merged on the interconnect)."""
        from ..parallel import collectives as CC
        from ..parallel import mesh as M

        if not part.leaves and part.fallback:
            raise NotCompilable("all-fallback partition")
        n = part.num_rows
        real = _real_mask(part)
        codes, uniq_rows = _factorize_keys(part, kidx, real)
        if codes is None:
            return False
        nseg = len(uniq_rows)
        batch = C.stage_partition(part, self.backend.bucket_mode)
        arrays = M.pad_batch_for_mesh(batch.arrays, len(mesh.devices.flat))
        b = arrays["#rowvalid"].shape[0]
        codes_b = np.full(b, nseg, dtype=np.int32)  # padding -> dropped seg
        codes_b[:n][real] = codes
        schema = part.schema
        eval_exprs = _make_eval_exprs(spec, schema)
        shapes = tuple(sorted((k, v.shape, str(v.dtype))
                              for k, v in arrays.items()))
        run = self.backend.jit_cache.get_or_build(
            ("meshseg", op.id, schema.name, nseg, shapes,
             self.backend.fn_cache_salt()),
            lambda: CC.sharded_segment_fold_fn(
                eval_exprs, spec.reducers, nseg, mesh, arrays))
        outs = run(arrays, codes_b)
        ok_np = M.materialize_np(outs[-1])[:n] & real
        counts = M.materialize_np(outs[-2])[:nseg]
        seg_partials = [np.asarray(o)[:nseg] for o in outs[:-2]]
        key_vals = C.decode_key_tuples(part, uniq_rows, kidx)
        for si, row_i in enumerate(uniq_rows):
            if counts[si] == 0:
                continue  # every row of this key failed: no ghost group —
                          # the interpreter fold below decides its fate
            k = key_vals[si]
            acc = groups.get(k, op.initial)
            accs = list(acc) if isinstance(acc, tuple) else [acc]
            merged = [_combine_scalar(reducer, accs[j],
                                      seg_partials[j][si].item())
                      for j, reducer in enumerate(spec.reducers)]
            groups[k] = tuple(merged) if isinstance(acc, tuple) else merged[0]
        bad = np.nonzero(~ok_np & real)[0].tolist()
        bad += [i for i in part.fallback if i not in bad]
        self._python_fold(op, part, sorted(set(bad)), groups, kidx, excs)
        return True

    # ------------------------------------------------------------------
    def _eval_exprs(self, op, spec: A.FoldSpec, part: C.Partition):
        """Evaluate fold exprs over the staged partition; returns
        (list of [B] arrays, ok_mask [B], err [B])."""
        from ..compiler.stagefn import input_row_cv
        import jax.numpy as jnp

        if not part.leaves and part.fallback:
            raise NotCompilable("all-fallback partition")
        batch = C.stage_partition(part, self.backend.bucket_mode)
        arrays = {k: jnp.asarray(v) for k, v in batch.arrays.items()}
        ctx = EmitCtx(batch.b, arrays["#rowvalid"])
        em = Emitter(ctx, spec.globals)
        row = input_row_cv(arrays, part.schema)
        frame = Frame(em, {spec.row_param: row})
        datas = []
        for expr in spec.exprs:
            cv = frame.eval(expr)
            cv = frame._require_numeric(cv, "aggregate expr")
            datas.append(cv.data)
        ok = arrays["#rowvalid"] & (ctx.err == 0)
        return datas, ok, ctx.err


def _make_eval_exprs(spec: A.FoldSpec, schema):
    """Emitter-traced fold expressions as a closure usable inside shard_map
    (shared by scalar and grouped mesh folds)."""
    from ..compiler.stagefn import input_row_cv

    def eval_exprs(arrs):
        ctx = EmitCtx(arrs["#rowvalid"].shape[0], arrs["#rowvalid"])
        em = Emitter(ctx, spec.globals)
        row = input_row_cv(arrs, schema)
        frame = Frame(em, {spec.row_param: row})
        datas = []
        for expr in spec.exprs:
            cv = frame.eval(expr)
            cv = frame._require_numeric(cv, "aggregate expr")
            datas.append(cv.data)
        ok = arrs["#rowvalid"] & (ctx.err == 0)
        return datas, ok

    return eval_exprs


def _real_mask(part: C.Partition) -> np.ndarray:
    m = np.ones(part.num_rows, dtype=np.bool_)
    if part.normal_mask is not None:
        m &= part.normal_mask
    return m


def _row_signatures(part: C.Partition) -> Optional[np.ndarray]:
    """[N] array of hashable per-row signatures (bytes), or None if the
    partition has non-vectorizable leaves. Invalid (None) slots are zeroed so
    every None has ONE canonical signature regardless of placeholder bytes."""
    pieces = []
    n = part.num_rows
    for path in sorted(part.leaves):
        leaf = part.leaves[path]
        if isinstance(leaf, C.NumericLeaf):
            data = leaf.data
            if leaf.valid is not None:
                data = np.where(leaf.valid, data, 0)
            pieces.append(np.ascontiguousarray(
                data.reshape(n, -1)).view(np.uint8).reshape(n, -1))
            if leaf.valid is not None:
                pieces.append(leaf.valid.reshape(-1, 1).view(np.uint8))
        elif isinstance(leaf, C.StrLeaf):
            b, ln = leaf.bytes, leaf.lengths
            if leaf.valid is not None:
                b = np.where(leaf.valid[:, None], b, 0)
                ln = np.where(leaf.valid, ln, 0)
            # zero padding past len (stage outputs may carry stale bytes)
            w = b.shape[1]
            b = np.where(np.arange(w)[None, :] < ln[:, None], b, 0)
            pieces.append(b)
            pieces.append(ln.astype("<i4").view(np.uint8).reshape(n, -1))
            if leaf.valid is not None:
                pieces.append(leaf.valid.reshape(-1, 1).view(np.uint8))
        elif isinstance(leaf, C.NullLeaf):
            continue
        else:
            return None
    if not pieces:
        return None
    mat = np.ascontiguousarray(np.concatenate(pieces, axis=1))
    return np.asarray([mat[i].tobytes() for i in range(n)], dtype=object)


def _factorize_keys(part: C.Partition, kidx: list[int], ok_mask: np.ndarray):
    """(codes[n_ok], unique_first_row_indices) — vectorized key factorization
    over the key columns' leaf bytes."""
    # canonical signatures: None slots zeroed, stale str padding zeroed —
    # raw leaf bytes would give the same python key distinct group codes
    # (same defect class as the joinexec Option-key bug)
    mat = C.key_signature_matrix(part, kidx, reject_nan=False)
    if mat is None:
        return None, None
    sub = mat[ok_mask]
    if len(sub) == 0:
        return np.zeros(0, np.int32), np.zeros(0, np.int64)
    inverse, first_idx = C.unique_rows(sub)
    ok_rows = np.nonzero(ok_mask)[0]
    return inverse, ok_rows[first_idx]
