"""Multi-device / multi-host backend.

The distributed seam of the reference is IBackend (reference:
core/include/ee/IBackend.h:29-45; AwsLambdaBackend.cc fans tasks out over
Lambda with S3 as the data plane). The TPU-native replacement: the SAME fused
stage functions run under jit over a `jax.sharding.Mesh` — rows sharded
across devices on the data axis, XLA inserting collectives only where a
stage contains reductions. Multi-host: initialize `jax.distributed` before
building the Context and every host runs the same program (SPMD); DCN
carries the collectives, the driver host owns planning and host-side IO.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..core.errors import NotCompilable
from ..parallel import mesh as M
from .local import LocalBackend


class MultiHostBackend(LocalBackend):
    """LocalBackend whose device dispatch row-shards every batch over a mesh.

    Usable single-process with N local devices (CI: 8 virtual CPU devices)
    and unchanged under multi-host jax.distributed initialization.
    """

    # selection-vector compaction computes a global nonzero() over the batch;
    # under shard_map that would need a cross-device exchange to stay
    # load-balanced, so the mesh path keeps full-length outputs
    supports_compaction = False
    # fused fold partials are scalar outputs the shard_map wrapper's
    # out_specs don't carry; the mesh fold path (psum over ICI) handles
    # aggregation instead
    supports_fused_fold = False

    def __init__(self, options):
        super().__init__(options)
        import jax

        shape = options.get_str("tuplex.tpu.meshShape", "auto")
        n = len(jax.devices()) if shape == "auto" else int(shape.split("x")[0])
        self.mesh = M.make_mesh(n)
        self.n_devices = n
        self._mesh_epoch = 0    # bumped on elastic shrink
        # span streams key their pid lane by the HOST (jax process index)
        # so per-host dumps merge into one driver timeline without
        # colliding; single-process runs keep the default OS pid
        if jax.process_count() > 1:
            from ..runtime import tracing

            tracing.set_host(jax.process_index())

    def fn_cache_salt(self) -> str:
        """Stage-fn cache keys must change when the mesh does — a cached fn
        closes over the mesh's device set, and a post-shrink fetch of a
        pre-shrink fn would dispatch onto the dead device forever."""
        return f"/mesh{self._mesh_epoch}x{self.n_devices}"

    def _surviving_devices(self) -> list:
        """Probe every mesh device with a tiny put+compute round trip; the
        survivors define the reduced mesh. (A wedged — as opposed to
        erroring — device is indistinguishable from a slow one without a
        deadline; the reference's Lambda analog has the same blind spot and
        bounds it with request timeouts.)"""
        import jax
        import numpy as np

        alive = []
        for d in self.mesh.devices.flat:
            try:
                x = jax.device_put(np.ones(8, dtype=np.float32), d)
                (x + 1).block_until_ready()
                alive.append(d)
            except Exception:
                continue
        return alive

    def _elastic_stage_fn(self, stage, skey, in_schema):
        """Elastic degrade ladder for a twice-failed mesh dispatch (lost
        device, wedged collective) — reference analog: AWSLambdaBackend
        re-invokes failed tasks at full remaining concurrency:

        1. REDUCED MESH: rebuild over the devices that still answer a
           probe and re-shard the same stage over them (padding adapts —
           any size >= 2 works, not just pow2). Later stages of the job
           ride the smaller mesh too.
        2. Single device, plain jit.
        3. (caller) interpreter.
        """
        import jax

        try:
            raw = stage.build_device_fn(
                in_schema, compaction=False,
                fused_fold=self.supports_fused_fold)
        except Exception:
            return None
        alive = self._surviving_devices()
        if jax.process_count() == 1 and 2 <= len(alive) < self.n_devices:
            try:
                new_mesh = M.make_mesh_of(alive)
                prev_mesh, prev_n = self.mesh, self.n_devices
                # _jit_stage_fn reads self.mesh/n_devices; commit only
                # after the fn builds (a failed build must not leave a
                # shrunk-but-unvalidated mesh or a false log entry)
                self.mesh, self.n_devices = new_mesh, len(alive)
                try:
                    fn = self.jit_cache.get_or_build(
                        ("elastic-mesh", skey, len(alive)),
                        lambda: self._jit_stage_fn(raw))
                except Exception:
                    self.mesh, self.n_devices = prev_mesh, prev_n
                    raise
                self._mesh_epoch += 1   # invalidate mesh-keyed fn caches
                self.failure_log.append({
                    "stage": skey[:16], "action": "elastic-mesh",
                    "devices": len(alive)})
                return fn
            except Exception:
                pass
        return self.jit_cache.get_or_build(
            ("elastic", skey), lambda: jax.jit(raw))

    def _jit_stage_fn(self, raw_fn, packed: bool = True, tag: str = "",
                      n_ops: int = 0):
        """Row-shard over ALL mesh devices (`packed`/`tag`/`n_ops` are
        accepted for interface parity and ignored: mesh staging is per-leaf
        sharded device_put, and sharded executables stay outside the AOT
        artifact store — serialized sharding layouts are not portable
        across mesh epochs). Non-pow2 meshes work too: the
        batch pads up to a multiple of the mesh size before dispatch (padded
        rows carry #rowvalid=False and the host slices outputs back to the
        partition's row count) — round 1 silently rounded 6 devices down to
        4 and kept a dead pow2 raise here."""
        inner = M.shard_stage_fn(raw_fn, self.mesh)
        n_dev = self.n_devices

        def padded_dispatch(arrays):
            return inner(M.pad_batch_for_mesh(arrays, n_dev))

        return padded_dispatch

    # -- host-sharded reads (each process staged ONLY its byte range) ------
    def execute(self, stage, partitions, intermediate: bool = False):
        import itertools

        it = iter(partitions or [])
        first = next(it, None)
        if first is not None and \
                getattr(first, "host_block", None) is not None:
            rest = list(it)
            assert not rest, "host-block sources produce one partition"
            from ..runtime import tracing as TR

            with TR.span("hostblock:execute", "exec") as _sp:
                res = self._execute_hostblock(stage, first)
                if _sp is not TR.NOOP:
                    _sp.set("key", stage.key()[:12])
                    _sp.set("rows_out", res.metrics.get("rows_out", 0))
            return res
        parts = [] if first is None else itertools.chain([first], it)
        return super().execute(stage, parts, intermediate=intermediate)

    def _execute_hostblock(self, stage, part):
        """Transform-stage execution over a host-sharded source: the global
        batch is [host0 block | host1 block | ...] (each block tail-padded
        to the same slot count), devices hold exactly the rows their host
        READ, outputs replicate, and rows needing the interpreter resolve
        on the host that owns their raw data with the boxed results
        exchanged over DCN (reference analog: workers read their own S3
        ranges and ship exception rows back, AWSLambdaBackend.cc:410-506;
        here the exchange is an allgather). The compiled general tier runs
        HOST-LOCALLY (plain jit over each host's own err rows) before the
        interpreter, same ladder as the local backend."""
        import time

        import jax

        from ..parallel.hostio import allgather_obj
        from ..runtime import columns as C
        from ..runtime import tracing as TR
        from .local import ExceptionRecord, StageResult

        t0 = time.perf_counter()
        hb = part.host_block
        pid, nproc, counts = hb["pid"], hb["nproc"], hb["counts"]
        total = sum(counts)
        metrics: dict = {"fast_path_s": 0.0, "slow_path_s": 0.0,
                         "general_path_s": 0.0, "compile_s": 0.0}
        if total == 0:
            return StageResult([], [], metrics)
        # per-host slot count: every block identical, divisible over each
        # process's local devices (q8 widths are multiples of 8; device
        # counts per host are too on real pods — round up to be safe)
        ldev = max(1, self.n_devices // nproc)
        quant = 8 * ldev
        bh = -(-max(max(counts), 1) // quant) * quant
        # GLOBAL shape agreement: string widths differ per host's data
        local_w = {p: C.bucket_size(max(leaf.width, 1), self.bucket_mode,
                                    minimum=8)
                   for p, leaf in part.leaves.items()
                   if isinstance(leaf, C.StrLeaf)}
        mask_list = None if part.normal_mask is None \
            else part.normal_mask.tolist()
        with TR.span("hostblock:shape-exchange", "exec"):
            meta = allgather_obj({"w": local_w, "mask": mask_list})
        fw = {p: max(m["w"].get(p, 8) for m in meta) for p in local_w}

        # ---- compiled fast path over the assembled global batch ----------
        skey = stage.key() + "/" + part.schema.name + "/hostblock" \
            + self.fn_cache_salt()
        out_arrays: dict = {}
        err = keep = None
        if not self.interpret_only and skey not in self._not_compilable:
            try:
                with TR.span("hostblock:fastpath", "exec") as _fsp:
                    _fsp.set("slots", bh * nproc)
                    fn = self.jit_cache.get_or_build(
                        ("stagefn", skey, bh),
                        lambda: M.hostblock_stage_fn(
                            stage.build_device_fn(
                                part.schema, compaction=False,
                                fused_fold=False),
                            self.mesh, bh))
                    batch = C.stage_partition(part, self.bucket_mode,
                                              force_b=bh, force_widths=fw)
                    # replicated scalars must be IDENTICAL across processes
                    # (device_put asserts it): the per-host seed derives
                    # from the host-local start_index — use the global
                    # block's
                    batch.arrays["#seed"] = C.partition_seed(
                        C.Partition(schema=part.schema, num_rows=0,
                                    start_index=0))
                    outs = fn(batch.arrays)
                    outs = {k: M.materialize_np(v) for k, v in outs.items()}
                    err = outs.pop("#err")
                    keep = outs.pop("#keep")
                    out_arrays = outs
            except NotCompilable:
                self._not_compilable.add(skey)
        metrics["fast_path_s"] = time.perf_counter() - t0

        # global slot validity: [h*bh, h*bh + counts[h]) minus each host's
        # boxed (normal_mask False) rows
        nslots = bh * nproc
        slot_normal = np.zeros(nslots, dtype=bool)
        for h in range(nproc):
            m = meta[h]["mask"]
            blk = slice(h * bh, h * bh + counts[h])
            slot_normal[blk] = True if m is None else np.asarray(m, bool)
        if err is not None:
            compiled_ok = slot_normal & keep[:nslots] & (err[:nslots] == 0)
            my_err = slot_normal & (err[:nslots] != 0)
        else:
            compiled_ok = np.zeros(nslots, dtype=bool)
            my_err = slot_normal.copy()
        # rows THIS host must interpret: its err slots + its boxed rows.
        # take(n): resolution work is bounded to slots before the point
        # where compiled rows alone satisfy the limit (the exchange below
        # still runs exactly once on every process — SPMD lockstep)
        cutoff = nslots
        if stage.limit >= 0:
            cum = np.cumsum(compiled_ok)
            hit = np.nonzero(cum >= stage.limit)[0]
            if hit.size:
                cutoff = int(hit[0]) + 1
        lo = pid * bh
        local_fb = [i for i in range(counts[pid])
                    if lo + i < cutoff and (
                        my_err[lo + i] or not (
                            part.normal_mask is None
                            or part.normal_mask[i]))]

        # ---- compiled general tier on the OWNING host --------------------
        # (same ladder as the local backend: supertype re-trace first,
        # interpreter only for rows the general tier neither resolved nor
        # FILTERED — its filter verdicts are final, like the local
        # backend's; each host runs over ITS OWN rows and the results ride
        # the same exchange). device_codes prunes rows whose fast-path
        # code is already an exact Python exception class.
        resolved_local: dict = {}
        fb_set = set(local_fb)
        if fb_set and not self.interpret_only \
                and stage.resolve_plan().use_general:
            from ..core.errors import unpack_device_codes

            dc = {}
            if err is not None:
                import numpy as _np

                codes = _np.asarray(err)[_np.asarray(local_fb) + lo]
                dc = dict(zip(local_fb, unpack_device_codes(codes)))
            t1 = time.perf_counter()
            try:
                with TR.span("resolve:general", "exec") as _gsp:
                    _gsp.set("rows", len(fb_set)).set("tier", "host-local")
                    self._general_case_pass(stage, part, fb_set,
                                            resolved_local, device_codes=dc,
                                            local_jit=True)
            except Exception as e:
                from ..utils.logging import get_logger

                get_logger("exec").warning(
                    "host-local general tier failed (%s: %s); rows stay "
                    "on the interpreter", type(e).__name__, e)
                resolved_local = {}
                fb_set = set(local_fb)
            metrics["general_path_s"] = time.perf_counter() - t1

        # ---- interpreter on the OWNING host + result exchange ------------
        t1 = time.perf_counter()
        payload = [(lo + i, "ok", row) for i, row in resolved_local.items()]
        local_fb = [i for i in local_fb
                    if i in fb_set and i not in resolved_local]
        if local_fb:
            with TR.span("resolve:interpreter", "exec") as _isp:
                _isp.set("rows", len(local_fb))
                pipeline = stage.python_pipeline(part.user_columns)
                for i, row in zip(local_fb, C.decode_rows(part, local_fb)):
                    status, pl = pipeline(row)
                    payload.append((lo + i, status, pl))
        resolved: dict = {}
        exc_by_slot: dict = {}
        with TR.span("hostblock:resolve-exchange", "exec") as _xsp:
            _xsp.set("sent", len(payload))
            for host_payload in allgather_obj(payload):
                for slot, status, pl in host_payload:
                    if status == "ok":
                        resolved[slot] = pl
                    elif status == "exc":
                        exc_by_slot[slot] = ExceptionRecord(
                            pl[0], pl[1], pl[2],
                            pl[3] if len(pl) > 3 else None)
        metrics["slow_path_s"] = time.perf_counter() - t1

        pseudo = C.Partition(schema=part.schema, num_rows=nslots,
                             leaves={}, start_index=0)
        outp = self._merge(stage, pseudo, compiled_ok, out_arrays, resolved)
        self.mm.register(outp)
        exceptions = [exc_by_slot[s] for s in sorted(exc_by_slot)]
        metrics["rows_out"] = outp.num_rows
        return StageResult([outp], exceptions, metrics)


def init_multihost(coordinator_address: Optional[str] = None,
                   num_processes: Optional[int] = None,
                   process_id: Optional[int] = None) -> None:
    """Initialize jax.distributed for multi-host execution (reference analog:
    AwsLambdaBackend bring-up; here DCN + the JAX runtime replace the
    Invoke/S3 control+data planes)."""
    import jax

    kwargs = {}
    if coordinator_address:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)
