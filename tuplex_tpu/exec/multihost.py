"""Multi-device / multi-host backend.

The distributed seam of the reference is IBackend (reference:
core/include/ee/IBackend.h:29-45; AwsLambdaBackend.cc fans tasks out over
Lambda with S3 as the data plane). The TPU-native replacement: the SAME fused
stage functions run under jit over a `jax.sharding.Mesh` — rows sharded
across devices on the data axis, XLA inserting collectives only where a
stage contains reductions. Multi-host: initialize `jax.distributed` before
building the Context and every host runs the same program (SPMD); DCN
carries the collectives, the driver host owns planning and host-side IO.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..parallel import mesh as M
from .local import LocalBackend


class MultiHostBackend(LocalBackend):
    """LocalBackend whose device dispatch row-shards every batch over a mesh.

    Usable single-process with N local devices (CI: 8 virtual CPU devices)
    and unchanged under multi-host jax.distributed initialization.
    """

    # selection-vector compaction computes a global nonzero() over the batch;
    # under shard_map that would need a cross-device exchange to stay
    # load-balanced, so the mesh path keeps full-length outputs
    supports_compaction = False
    # fused fold partials are scalar outputs the shard_map wrapper's
    # out_specs don't carry; the mesh fold path (psum over ICI) handles
    # aggregation instead
    supports_fused_fold = False

    def __init__(self, options):
        super().__init__(options)
        import jax

        shape = options.get_str("tuplex.tpu.meshShape", "auto")
        n = len(jax.devices()) if shape == "auto" else int(shape.split("x")[0])
        self.mesh = M.make_mesh(n)
        self.n_devices = n
        self._mesh_epoch = 0    # bumped on elastic shrink

    def fn_cache_salt(self) -> str:
        """Stage-fn cache keys must change when the mesh does — a cached fn
        closes over the mesh's device set, and a post-shrink fetch of a
        pre-shrink fn would dispatch onto the dead device forever."""
        return f"/mesh{self._mesh_epoch}x{self.n_devices}"

    def _surviving_devices(self) -> list:
        """Probe every mesh device with a tiny put+compute round trip; the
        survivors define the reduced mesh. (A wedged — as opposed to
        erroring — device is indistinguishable from a slow one without a
        deadline; the reference's Lambda analog has the same blind spot and
        bounds it with request timeouts.)"""
        import jax
        import numpy as np

        alive = []
        for d in self.mesh.devices.flat:
            try:
                x = jax.device_put(np.ones(8, dtype=np.float32), d)
                (x + 1).block_until_ready()
                alive.append(d)
            except Exception:
                continue
        return alive

    def _elastic_stage_fn(self, stage, skey, in_schema):
        """Elastic degrade ladder for a twice-failed mesh dispatch (lost
        device, wedged collective) — reference analog: AWSLambdaBackend
        re-invokes failed tasks at full remaining concurrency:

        1. REDUCED MESH: rebuild over the devices that still answer a
           probe and re-shard the same stage over them (padding adapts —
           any size >= 2 works, not just pow2). Later stages of the job
           ride the smaller mesh too.
        2. Single device, plain jit.
        3. (caller) interpreter.
        """
        import jax

        try:
            raw = stage.build_device_fn(
                in_schema, compaction=False,
                fused_fold=self.supports_fused_fold)
        except Exception:
            return None
        alive = self._surviving_devices()
        if jax.process_count() == 1 and 2 <= len(alive) < self.n_devices:
            try:
                new_mesh = M.make_mesh_of(alive)
                prev_mesh, prev_n = self.mesh, self.n_devices
                # _jit_stage_fn reads self.mesh/n_devices; commit only
                # after the fn builds (a failed build must not leave a
                # shrunk-but-unvalidated mesh or a false log entry)
                self.mesh, self.n_devices = new_mesh, len(alive)
                try:
                    fn = self.jit_cache.get_or_build(
                        ("elastic-mesh", skey, len(alive)),
                        lambda: self._jit_stage_fn(raw))
                except Exception:
                    self.mesh, self.n_devices = prev_mesh, prev_n
                    raise
                self._mesh_epoch += 1   # invalidate mesh-keyed fn caches
                self.failure_log.append({
                    "stage": skey[:16], "action": "elastic-mesh",
                    "devices": len(alive)})
                return fn
            except Exception:
                pass
        return self.jit_cache.get_or_build(
            ("elastic", skey), lambda: jax.jit(raw))

    def _jit_stage_fn(self, raw_fn):
        """Row-shard over ALL mesh devices. Non-pow2 meshes work too: the
        batch pads up to a multiple of the mesh size before dispatch (padded
        rows carry #rowvalid=False and the host slices outputs back to the
        partition's row count) — round 1 silently rounded 6 devices down to
        4 and kept a dead pow2 raise here."""
        inner = M.shard_stage_fn(raw_fn, self.mesh)
        n_dev = self.n_devices

        def padded_dispatch(arrays):
            return inner(M.pad_batch_for_mesh(arrays, n_dev))

        return padded_dispatch


def init_multihost(coordinator_address: Optional[str] = None,
                   num_processes: Optional[int] = None,
                   process_id: Optional[int] = None) -> None:
    """Initialize jax.distributed for multi-host execution (reference analog:
    AwsLambdaBackend bring-up; here DCN + the JAX runtime replace the
    Invoke/S3 control+data planes)."""
    import jax

    kwargs = {}
    if coordinator_address:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)
