"""Multi-device / multi-host backend.

The distributed seam of the reference is IBackend (reference:
core/include/ee/IBackend.h:29-45; AwsLambdaBackend.cc fans tasks out over
Lambda with S3 as the data plane). The TPU-native replacement: the SAME fused
stage functions run under jit over a `jax.sharding.Mesh` — rows sharded
across devices on the data axis, XLA inserting collectives only where a
stage contains reductions. Multi-host: initialize `jax.distributed` before
building the Context and every host runs the same program (SPMD); DCN
carries the collectives, the driver host owns planning and host-side IO.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..parallel import mesh as M
from .local import LocalBackend


class MultiHostBackend(LocalBackend):
    """LocalBackend whose device dispatch row-shards every batch over a mesh.

    Usable single-process with N local devices (CI: 8 virtual CPU devices)
    and unchanged under multi-host jax.distributed initialization.
    """

    # selection-vector compaction computes a global nonzero() over the batch;
    # under shard_map that would need a cross-device exchange to stay
    # load-balanced, so the mesh path keeps full-length outputs
    supports_compaction = False
    # fused fold partials are scalar outputs the shard_map wrapper's
    # out_specs don't carry; the mesh fold path (psum over ICI) handles
    # aggregation instead
    supports_fused_fold = False

    def __init__(self, options):
        super().__init__(options)
        import jax

        shape = options.get_str("tuplex.tpu.meshShape", "auto")
        n = len(jax.devices()) if shape == "auto" else int(shape.split("x")[0])
        self.mesh = M.make_mesh(n)
        self.n_devices = n

    def _elastic_stage_fn(self, stage, skey, in_schema):
        """Elastic degrade: the mesh dispatch failed twice (lost device,
        wedged collective) — keep the COMPILED path alive on one device
        instead of dropping all the way to the interpreter (reference
        analog: AWSLambdaBackend re-invoking failed tasks on new workers;
        SPMD can't shrink mid-job, so the graceful step down is
        single-device)."""
        import jax

        try:
            raw = stage.build_device_fn(
                in_schema, compaction=False,
                fused_fold=self.supports_fused_fold)
        except Exception:
            return None
        return self.jit_cache.get_or_build(
            ("elastic", skey), lambda: jax.jit(raw))

    def _jit_stage_fn(self, raw_fn):
        """Row-shard over ALL mesh devices. Non-pow2 meshes work too: the
        batch pads up to a multiple of the mesh size before dispatch (padded
        rows carry #rowvalid=False and the host slices outputs back to the
        partition's row count) — round 1 silently rounded 6 devices down to
        4 and kept a dead pow2 raise here."""
        inner = M.shard_stage_fn(raw_fn, self.mesh)
        n_dev = self.n_devices

        def padded_dispatch(arrays):
            return inner(M.pad_batch_for_mesh(arrays, n_dev))

        return padded_dispatch


def init_multihost(coordinator_address: Optional[str] = None,
                   num_processes: Optional[int] = None,
                   process_id: Optional[int] = None) -> None:
    """Initialize jax.distributed for multi-host execution (reference analog:
    AwsLambdaBackend bring-up; here DCN + the JAX runtime replace the
    Invoke/S3 control+data planes)."""
    import jax

    kwargs = {}
    if coordinator_address:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)
