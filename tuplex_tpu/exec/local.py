"""Local backend: dual-mode stage execution on one host.

Re-designs the reference's LocalBackend orchestration (reference:
core/src/ee/local/LocalBackend.cc:815-1253 executeTransformStage — JIT the
stage, run tasks per partition, route exception rows through the slow path,
merge in order :1254-1530 resolveViaSlowPath) for the TPU model:

  * the compiled fast path is ONE jax.jit executable per
    (stage-key, batch-spec) — cached like the reference's JITCompiler cache
  * rows whose device error code != 0 (or that were fallback slots already)
    re-run on the interpreter pipeline with resolvers (ResolveTask analog)
  * merge-in-order is positional: partitions preserve original row slots
"""

from __future__ import annotations

import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

from ..core import typesys as T
from ..core.errors import (ExceptionCode, NotCompilable, TuplexException,
                           code_for_exception, exception_class_for_code,
                           exception_name, unpack_device_code,
                           unpack_device_codes)
from ..core.row import Row
from ..plan import logical as L
from ..plan.physical import TransformStage
from .compilequeue import CompileTimeout
from ..runtime import columns as C
from ..runtime import devprof as DP
from ..runtime import excprof as EX
from ..runtime import faults
from ..runtime import tracing as TR
from ..runtime import xferstats
from ..runtime.packing import PackedOuts, PackedStageFn


def _get_outs(pending):
    """Materialize a stage result to host numpy: packed single-buffer
    fetch (runtime/packing.py) or plain per-leaf device_get."""
    import jax

    if isinstance(pending, PackedOuts):
        return pending.to_host()      # notes its own d2h bytes
    with TR.span("d2h:leaf-fetch", "xfer") as _sp:
        outs = jax.device_get(pending)
        try:
            vals = outs.values() if isinstance(outs, dict) else outs
            nb = sum(np.asarray(v).nbytes for v in vals)
            xferstats.note_d2h(nb, tag="leaf_fetch")
            _sp.set("bytes", nb)
        except Exception:   # pragma: no cover - accounting is best-effort
            pass
    return outs


def _cpu_device():
    """The host CPU device alongside an accelerator backend, or None."""
    import jax

    try:
        return jax.local_devices(backend="cpu")[0]
    except Exception:
        return None


class _CpuJit:
    """jit pinned to the host CPU backend: numpy args placed (and the
    executable compiled) on the CPU device regardless of the default
    accelerator — used for small resolve batches where the device
    round-trip tax exceeds the compute, and for compile-budget-degraded
    stages (plan/splittuner).

    Per-input-spec compilation routes through exec/compilequeue's
    ``compile_traced`` (traced/lowered/compiled INSIDE the cpu
    default_device pin), so these host compiles are counted into the
    stage's ``compile_s``/``stage_compiles``, content-address-cached and
    reused like any other stage executable — they used to bypass the
    queue entirely (ROADMAP item). The "/cpupin" salt keeps the
    fingerprints disjoint from accelerator compiles of the same jaxpr.
    Any AOT-machinery failure falls back to the plain pinned jit; trace
    errors (NotCompilable) propagate either way."""

    def __init__(self, fn, tag: str = "", n_ops: int = 0,
                 deadline: float = 0.0):
        import jax

        self._raw = fn
        self._tag = tag
        self._n_ops = n_ops
        self._deadline = deadline or 0.0
        self._fn = jax.jit(fn)
        self._by_spec: dict = {}

    def _queue_entry(self, args):
        """(compiled-or-None, spec key) via the compile queue; None routes
        the call to the plain pinned jit. Must run inside the cpu pin.
        With a deadline set, CompileTimeout PROPAGATES — the host-CPU
        compile is itself killable (the flights wedge IS an XLA:CPU
        compile), and swallowing it into the unbounded plain jit would
        reintroduce the exact hang the deadline exists to kill."""
        from . import compilequeue as CQ

        try:
            avals, key = CQ._args_avals(args)
        except Exception:
            return None, None
        if avals is None:
            return None, None
        if key in self._by_spec:
            return self._by_spec[key], key
        try:
            entry = CQ.compile_traced(self._raw, avals, salt="/cpupin",
                                      tag=self._tag, n_ops=self._n_ops,
                                      deadline_s=self._deadline)
        except CQ._AotUnsupported:
            entry = None
        except CQ.CompileHazard:
            # a static veto predicts the hang itself — the plain pinned
            # jit below is exactly the unbounded compile it forbids, so
            # it must propagate even with the deadline off
            raise
        except CQ.CompileTimeout:
            if self._deadline > 0:
                raise
            entry = None
        self._by_spec[key] = entry
        return entry, key

    def __call__(self, *args, **kwargs):
        import jax

        from ..ops.strings import mxu_gather_override

        # default_backend() still reports the accelerator inside this
        # context, so force the CPU kernel formulations for the trace
        with jax.default_device(_cpu_device()), mxu_gather_override(False):
            if not kwargs:
                entry, key = self._queue_entry(args)
                if entry is not None:
                    try:
                        return entry(*args)
                    except TypeError:
                        # call-convention mismatch (weak-type drift): pin
                        # this spec to the plain jit like AotJit does
                        self._by_spec[key] = None
                    except Exception as e:
                        from . import compilequeue as CQ

                        if not CQ.deserialize_defect(e):
                            raise
                        # unloadable serialized executable: recompile
                        # in-process via the plain pinned jit (AotJit's
                        # fallback, under the cpu pin); persist the
                        # verdict so cold runs skip the doomed load
                        CQ.note_deserialize_defect(entry)
                        self._by_spec[key] = None
            return self._fn(*args, **kwargs)


@dataclass
class ExceptionRecord:
    op_id: int
    exc_name: str
    row: Any
    trace: Any = None    # cleaned user-frame traceback (sampled rows only)

    def __repr__(self):
        return f"<{self.exc_name} at op#{self.op_id}: {self.row!r}>"


class _DispatchFailed:
    """Sentinel riding the dispatch window when the device call itself
    raised synchronously (wedged runtime, lost mesh) — the collect side
    re-raises it into the same retry -> elastic -> interpreter ladder as
    async failures surfacing at device_get."""

    def __init__(self, err: Exception):
        self.err = err


class _CompileTimedOut:
    """Sentinel riding the dispatch window when the stage executable's
    compile blew the deadline (killed child / negative-cache skip). NOT a
    task failure: per-partition retries can't help — the collect side
    restarts the WHOLE stage on one degraded tier (_TierRestart) so rows
    are never split across compiled/interpreted tiers mid-stage (the
    flights divergence, ROADMAP item b)."""

    def __init__(self, err: Exception):
        self.err = err


class _TierRestart(Exception):
    """Control flow: re-run the current stage from its first partition on
    `tier` ('cpu' = host-pinned compile, 'interpreter'). Raised by the
    windowed executor's collect side on a _CompileTimedOut sentinel and
    caught by _execute_windowed's tier loop — never escapes the stage."""

    def __init__(self, tier: str, cause: Exception):
        super().__init__(tier)
        self.tier = tier
        self.cause = cause


@dataclass
class StageResult:
    partitions: list[C.Partition]
    exceptions: list[ExceptionRecord] = field(default_factory=list)
    metrics: dict = field(default_factory=dict)


class JitCache:
    """LRU cache of compiled stage executables (reference analog: ORCv2
    LLJIT symbol cache, core/include/llvm13/JITCompiler_llvm13.h:30-72).

    Traced-shape bookkeeping lives WITH the cache entry and is dropped on
    eviction — round 1 bolted it on externally, so a rebuilt evicted stage
    claimed first_call=False and turned a trace failure into a hard raise."""

    def __init__(self, capacity: int = 128):
        self._store: OrderedDict = OrderedDict()
        self._traced: dict = {}           # key -> set of batch specs
        self.capacity = capacity
        self.hits = 0
        self.misses = 0

    def get_or_build(self, key, builder):
        if key in self._store:
            self.hits += 1
            self._store.move_to_end(key)
            return self._store[key]
        self.misses += 1
        fn = builder()
        self._store[key] = fn
        self._traced.pop(key, None)       # fresh executable: nothing traced
        if len(self._store) > self.capacity:
            old_key, _ = self._store.popitem(last=False)
            self._traced.pop(old_key, None)
        return fn

    def was_traced(self, key, spec) -> bool:
        return spec in self._traced.get(key, ())

    def note_traced(self, key, spec) -> None:
        self._traced.setdefault(key, set()).add(spec)


class LocalBackend:
    # selection-vector compaction is correct only where the plain dispatch/
    # collect path consumes '#rowidx' outputs; the mesh backend shards
    # batches across devices and keeps full-length outputs instead
    supports_compaction = True
    supports_fused_fold = True

    def __init__(self, options):
        self.options = options
        self.jit_cache = JitCache(options.get_int("tuplex.tpu.jitCacheSize", 128))
        self.interpret_only = options.get_bool("tuplex.tpu.interpretOnly")
        self.bucket_mode = options.get_str("tuplex.tpu.padBucketing", "q8")
        self._not_compilable: set[str] = set()
        # stages whose sample-estimated compaction bucket overflowed: re-run
        # and remember to build without compaction from then on
        self._compaction_off: set[str] = set()
        # stages whose per-boundary dispatch cost was already sampled into
        # the split-tuner model (one clean sample per stage)
        self._boundary_sampled: set[str] = set()
        from ..runtime.spill import MemoryManager

        self.mm = MemoryManager(
            options.get_size("tuplex.executorMemory", 1 << 30),
            options.get_str("tuplex.scratchDir", "/tmp/tuplex_tpu"))
        # task-level fault tolerance record (reference analog: the Lambda
        # backend's failure_log, AWSLambdaBackend.cc:410-474)
        self.failure_log: list[dict] = []
        # live per-partition progress hook (set by the driver around
        # execute_any; feeds history 'progress' events)
        self.progress_cb = None

    def fn_cache_salt(self) -> str:
        return ""   # mesh backends salt per mesh epoch (multihost.py)

    def touch_partition(self, part) -> None:
        self.mm.touch(part)

    def _jit_stage_fn(self, raw_fn, packed: bool = True, tag: str = "",
                      n_ops: int = 0):
        """Compile a stage fn for dispatch (overridden by MultiHostBackend
        to row-shard over a mesh). Input buffers are donated off-CPU: the
        staged batch is dead once the kernel reads it (consumers re-stage
        from host leaves or a one-shot handoff view), so XLA may reuse its
        HBM for the outputs (reference analog: partitions freed/recycled
        as tasks retire, Partition ref-counting).

        packed=False keeps per-leaf dict outputs — required where a
        consumer needs device-resident arrays (the intermediate-stage
        handoff, _attach_device_view).

        Per-spec compilation routes through exec/compilequeue: the
        content-addressed store dedups isomorphic stages in-process and
        reuses serialized executables across processes; `tag` attributes
        compile seconds to the owning stage (metrics 'compile_s') and
        `n_ops` feeds the stage-split tuner's measured curve."""
        from ..runtime.jaxcfg import donation_enabled
        from ..runtime.packing import PackedStageFn, packing_enabled
        from .compilequeue import aot_jit

        donate = donation_enabled() and self.options.get_bool(
            "tuplex.tpu.donateBuffers", True)
        deadline = self.options.get_float("tuplex.tpu.compileDeadlineS", 0.0)
        if packed and type(self) is LocalBackend and packing_enabled():
            # single-buffer transfers both ways (see runtime/packing.py);
            # mesh backends keep per-leaf staging (sharded device_put)
            return PackedStageFn(raw_fn, donate, tag=tag, n_ops=n_ops,
                                 deadline=deadline)
        return aot_jit(raw_fn, donate=donate, salt=self.fn_cache_salt(),
                       tag=tag, n_ops=n_ops, deadline=deadline)

    # ------------------------------------------------------------------
    def precompile_plan(self, stages, partitions) -> None:
        """Kick off ahead-of-time compilation of the whole plan on the
        compile pool (exec/compilequeue). Speculative and asynchronous:
        stage avals are PREDICTED by chaining abstract shape evaluation
        from the first source partition, so stage i+1 (and i+2, ...)
        compiles while stage i executes; a wrong prediction only wastes a
        background compile — dispatch always verifies by content address.
        The reference compiles a stage in the milliseconds before its first
        task (LocalBackend.cc:865); remote XLA compiles are minutes, so
        here the plan's compiles must all be in flight before stage 0's
        first batch lands."""
        from . import compilequeue as CQ

        if type(self) is not LocalBackend:
            return   # mesh/serverless dispatch builds different executables
        if self.interpret_only or not CQ.parallel_compile_enabled() \
                or not self.options.get_bool(
                    "tuplex.tpu.parallelCompile", True):
            return
        first = partitions[0] if isinstance(partitions, list) \
            and partitions else None
        if first is None:
            return
        CQ.pool().submit(self._precompile_driver, list(stages), first)

    def _precompile_driver(self, stages, first_part):
        """Walk the plan predicting each stage's dispatch avals and submit
        pool compiles. Returns the submitted futures (tests drive this
        synchronously). Prediction stops where shapes become
        data-dependent: pipeline breakers, filters/limits (output row
        count), compacted outputs, host-repacked wire layouts."""
        from ..compiler import stagefn as SF

        try:
            avals = SF.partition_avals(first_part, self.bucket_mode)
            schema = first_part.schema
        except Exception:
            return []
        return self._precompile_avals(stages, avals, schema)

    def _precompile_avals(self, stages, avals, schema):
        """The aval-driven half of :meth:`_precompile_driver`, callable
        without a live partition: the respecialization controller
        (serve/respec) stores each tenant's stage-0 dispatch avals and
        replays them here — inside a compilequeue ``background_lane()``
        — to compile a candidate stage set ahead of its canary with zero
        foreground partitions in hand."""
        from ..compiler import stagefn as SF
        from ..plan import logical as L
        from ..plan.physical import TransformStage, consumer_kind
        from ..runtime.jaxcfg import (device_handoff_enabled,
                                      donation_enabled, jax)
        from ..runtime.packing import packing_enabled
        from . import compilequeue as CQ

        futs: list = []
        donate = donation_enabled() and self.options.get_bool(
            "tuplex.tpu.donateBuffers", True)
        for si, stage in enumerate(stages):
            if avals is None or not isinstance(stage, TransformStage) \
                    or stage.force_interpret \
                    or getattr(stage, "cpu_compile", False):
                break
            skey = stage.key() + "/" + schema.name + self.fn_cache_salt()
            if skey in self._not_compilable:
                break
            use_comp = (self.supports_compaction
                        and self.options.get_bool(
                            "tuplex.tpu.filterCompaction", True)
                        and stage.key() not in self._compaction_off)
            consumer = consumer_kind(stages, si)
            packed = True
            if consumer:
                packed = not device_handoff_enabled(consumer)
            try:
                raw = stage.build_device_fn(
                    schema, compaction=use_comp,
                    fused_fold=self.supports_fused_fold)
                out = jax.eval_shape(raw, avals)
            except Exception:
                break
            deadline = self.options.get_float(
                "tuplex.tpu.compileDeadlineS", 0.0)
            if packed and type(self) is LocalBackend and packing_enabled():
                # packed-wire stage: the dispatched fn is the wire-layout
                # closure, not `raw` — predict its buffer spec from the
                # leaf avals (PackedStageFn.warm) so the packed executable
                # prewarms in the AOT cache instead of compiling at first
                # dispatch (ROADMAP compile-hardening item d)
                try:
                    pfn = PackedStageFn(raw, donate, tag=stage.key(),
                                        n_ops=len(stage.ops),
                                        deadline=deadline)
                    f = pfn.warm(avals)
                    if f is not None:
                        futs.append(f)
                except Exception:   # prewarm is speculative by contract
                    pass
            else:
                futs.append(CQ.submit_compile(
                    raw, (avals,), donate_argnums=(0,) if donate else (),
                    salt=self.fn_cache_salt(), tag=stage.key(),
                    n_ops=len(stage.ops), deadline_s=deadline))
            if stage.limit >= 0 or any(
                    isinstance(op, L.FilterOperator) for op in stage.ops):
                break        # output row count is data-dependent
            avals = SF.restage_avals(out, self.bucket_mode)
            nxt = stages[si + 1] if si + 1 < len(stages) else None
            if not isinstance(nxt, TransformStage):
                break
            schema = nxt.input_schema
        return futs

    # ------------------------------------------------------------------
    def execute_any(self, stage, partitions, context,
                    intermediate=False) -> StageResult:
        """Dispatch by stage kind (reference: LocalBackend.cc:145-180).
        `intermediate`: a later stage consumes this one's output (enables
        the device-resident handoff; terminal outputs only ever go to
        host). It is False or the CONSUMER KIND — "stage" / "join" /
        "agg" — so the handoff gate can be tuned per consumer
        (jaxcfg.device_handoff_enabled).

        Transfer attribution happens HERE, for every stage kind: the
        stage's xferstats delta (d2h/h2d bytes) lands on its metrics
        record, so join/aggregate transfers count the same as transform
        stages and `Metrics.d2hBytes()` agrees with the counter registry
        for work done inside stages."""
        from ..plan.physical import AggregateStage, JoinStage

        x_snap = xferstats.snapshot()
        if isinstance(stage, AggregateStage):
            from .aggexec import AggregateExecutor

            res = AggregateExecutor(self).execute(stage, partitions or [])
        elif isinstance(stage, JoinStage):
            from .joinexec import JoinExecutor

            res = JoinExecutor(self).execute(stage, partitions or [],
                                             context,
                                             intermediate=intermediate)
        else:
            res = self.execute(stage, partitions or [],
                               intermediate=intermediate)
        xd = xferstats.delta(x_snap)
        res.metrics["d2h_bytes"] = xd["d2h_bytes"]
        res.metrics["h2d_bytes"] = xd["h2d_bytes"]
        return res

    # ------------------------------------------------------------------
    def execute(self, stage: TransformStage,
                partitions, intermediate: bool = False) -> StageResult:
        """Span-wrapped stage entry: one `stage:execute` span per stage
        (runtime/tracing); transfer attribution happens in execute_any so
        every stage kind gets it; the windowed impl below does the
        dual-mode work."""
        with TR.span("stage:execute", "exec") as sp:
            if sp is not TR.NOOP:
                sp.set("kind", type(stage).__name__)
                sp.set("key", stage.key()[:12]).set("n_ops", len(stage.ops))
            res = self._execute_windowed(stage, partitions, intermediate)
            if sp is not TR.NOOP:
                sp.set("rows_out", res.metrics.get("rows_out", 0))
                for k in ("device_s", "flops", "hbm_peak",
                          "roofline_frac"):
                    v = res.metrics.get(k)
                    if v is not None:
                        sp.set(k, round(float(v), 6))
        return res

    def _execute_windowed(self, stage: TransformStage,
                          partitions,
                          intermediate: bool = False) -> StageResult:
        """Window-pipelined dual-mode execution (reference analog:
        Executor/WorkQueue task parallelism, Executor.h:45-109 +
        LocalBackend.cc:1531-1586). Device dispatch is ASYNC — while the
        device crunches partition i, the host stages partition i+1 and
        merges partition i-1; `partitions` may be a lazy iterator, so
        take(n) stops pulling source data once the limit is satisfied.

        This wrapper is the TIER loop: a stage whose executable compile
        blows the deadline (killed compile child, `.timeout` negative
        cache) is restarted FROM ITS FIRST PARTITION on one degraded
        tier — host-CPU compile where that's a distinct backend, else
        interpreter — because results already emitted on the compiled
        tier must not be merged with later rows from a different tier
        (the mixed compiled/interpreted divergence observed on flights,
        ROADMAP item b). Every pulled partition is recorded so the
        replay sees exactly the same input; the few duplicated dispatch
        seconds are the price of tier purity."""
        from itertools import chain

        parts_it = iter(partitions)
        first_part = next(parts_it, None)

        def parts_stream():
            if first_part is not None:
                yield first_part
            yield from parts_it

        prefetch = max(0, self.options.get_int(
            "tuplex.tpu.sourcePrefetch", 2))
        live = _prefetch_iter(parts_stream(), prefetch) if prefetch \
            else parts_stream()
        seen: list = []
        # replay retention costs O(input) partition references (spilled,
        # not resident, under memory pressure — but still disk): only pay
        # it where a CompileTimeout can actually happen. With the
        # deadline disabled (or interpret-only) the restart is
        # unreachable and streaming retention stays O(window).
        record_replay = not self.interpret_only and self.options.get_float(
            "tuplex.tpu.compileDeadlineS", 0.0) > 0

        def recording():
            for p in live:
                if record_replay:
                    seen.append(p)
                yield p

        rec = recording()
        tier = "device"
        restarts = 0
        while True:
            stream = chain(list(seen), rec) if restarts else rec
            try:
                res = self._run_stage_tier(stage, stream, first_part,
                                           intermediate, tier)
                res.metrics["tier_restarts"] = restarts
                return res
            except _TierRestart as tr:
                restarts += 1
                # the re-run re-records every partition the aborted tier
                # already processed: back out this execution's exception-
                # plane accounting so rows_seen/exception_rate and the
                # drift windows don't double-count (BEFORE any overlay
                # revert below — the discard must hit the key the aborted
                # execution recorded under)
                if EX.enabled():
                    EX.discard_stage(stage.key(), owner=id(self))
                from ..utils.logging import get_logger

                # re-specialization fallback rung (serve/respec): a stage
                # running under a promoted candidate overlay whose
                # compile blows the deadline falls back onto the RETAINED
                # INCUMBENT configuration first — same 'device' tier,
                # previous plan generation, restarted from partition 0 so
                # rows are never split across plan generations mid-stage
                # (the PR-8 tier-purity invariant, extended to
                # generations). The controller is told so it quarantines
                # the candidate and demotes the tenant for future jobs.
                rev = getattr(stage, "_respec_revert", None)
                if rev is not None:
                    for k, v in rev.items():
                        setattr(stage, k, v)
                    stage._respec_revert = None
                    for memo in ("_resolve_plan_memo",):
                        if hasattr(stage, memo):
                            try:
                                delattr(stage, memo)
                            except AttributeError:
                                pass
                    notify = getattr(stage, "_respec_notify", None)
                    if notify is not None:
                        try:
                            notify(tr.cause)
                        except Exception:   # controller is advisory here
                            pass
                    tier = "device"
                    get_logger("exec").warning(
                        "stage %s failed under its re-specialized "
                        "generation (%s); restarting the whole stage on "
                        "the retained incumbent (restart %d)",
                        stage.key()[:12], tr.cause, restarts)
                    continue
                # a degraded tier timing out again steps down once more;
                # the cap is belt-and-braces (the ladder is 3 rungs)
                tier = "interpreter" if restarts >= 3 else tr.tier
                get_logger("exec").warning(
                    "stage %s compile deadline (%s); restarting the "
                    "whole stage on the %s tier (restart %d)",
                    stage.key()[:12], tr.cause, tier, restarts)

    def _run_stage_tier(self, stage: TransformStage, stream, first_part,
                        intermediate, tier: str) -> StageResult:
        """One tier attempt of the windowed executor. `tier` is 'device'
        (normal: accelerator/packed compile), 'cpu' (host-pinned compile
        after a device-tier deadline) or 'interpreter' (no compiled fast
        path at all). Raises _TierRestart when a compile deadline means
        the stage must re-run one rung down."""
        from collections import deque

        from . import compilequeue as CQ

        t0 = time.perf_counter()
        mm_snap = self.mm.metrics_snapshot()
        fl_snap = len(self.failure_log)
        metrics: dict[str, Any] = {"fast_path_s": 0.0, "slow_path_s": 0.0,
                                   "general_path_s": 0.0, "compile_s": 0.0}
        if EX.enabled():
            # exception-plane baseline (runtime/excprof): snapshot the
            # plan-time code inventory + resolve-plan verdict BEFORE any
            # row executes — the drift detector compares live windows
            # against exactly this expectation
            EX.capture_baseline(stage)
        device_fn = None
        in_schema = first_part.schema if first_part is not None else None
        skey = stage.key() + "/" + (in_schema.name if in_schema else "") \
            + self.fn_cache_salt()
        use_comp = (self.supports_compaction
                    and self.options.get_bool(
                        "tuplex.tpu.filterCompaction", True)
                    and stage.key() not in self._compaction_off)
        # intermediate stages keep per-leaf dict outputs so the device-
        # resident handoff can gather from them; every other stage packs
        # its transfers into one buffer per direction. `intermediate` is
        # False or the consumer kind ("stage"/"join"/"agg" — round 5 only
        # plain stages qualified; joins and aggregates round-tripped every
        # boundary, VERDICT §2)
        consumer = intermediate if isinstance(intermediate, str) else "stage"
        packed = True
        if intermediate:
            from ..runtime.jaxcfg import device_handoff_enabled as _dh

            packed = not _dh(consumer)
        if tier != "interpreter" and not self.interpret_only \
                and skey not in self._not_compilable \
                and in_schema is not None:
            device_fn, use_comp = self._build_stage_fn(
                stage, in_schema, skey, use_comp, packed=packed,
                force_cpu=(tier == "cpu"))

        out_parts: list[C.Partition] = []
        exceptions: list[ExceptionRecord] = []
        emitted_total = 0
        if intermediate:
            from ..runtime.jaxcfg import (device_handoff_budget_bytes,
                                          device_handoff_enabled)

            # fold enablement into the flag once per stage (not per
            # partition) and probe the HBM budget only when it matters
            intermediate = device_handoff_enabled(consumer)
            self._handoff_left = \
                device_handoff_budget_bytes() if intermediate else 0
        limit = stage.limit
        window_size = max(1, self.options.get_int(
            "tuplex.tpu.dispatchWindow", 3))
        window: deque = deque()

        from ..utils.signals import check_interrupted

        def collect_one():
            nonlocal emitted_total, device_fn, use_comp, skey
            part, outs, dispatch_s = window.popleft()
            if limit >= 0 and emitted_total >= limit:
                return  # limit met: drop already-dispatched work unprocessed
            if isinstance(outs, _DispatchFailed) \
                    and isinstance(outs.err, CQ.CompileTimeout):
                outs = _CompileTimedOut(outs.err)
            if isinstance(outs, _CompileTimedOut):
                # a blown compile deadline is NOT a task failure: retrying
                # the partition would re-burn the deadline and a per-
                # partition interpreter fallback would split the stage's
                # rows across tiers — restart the whole stage one rung down
                raise _TierRestart(self._next_tier(tier), outs.err)
            # registering a previous output may have spilled this partition
            # in the dispatch->collect gap; touch swaps it back in and the
            # pin keeps it resident against concurrent prefetch mm calls
            self.mm.pin(part)
            try:
                try:
                    if isinstance(outs, _DispatchFailed):
                        raise outs.err
                    outp, excs, m = self._collect_partition(
                        stage, part, outs, dispatch_s,
                        intermediate=intermediate)
                except Exception as e:
                    if outs is None:
                        raise   # interpreter failure is deterministic
                    # device-task failure: retry the dispatch once, then run
                    # the partition entirely on the interpreter — a failing
                    # DEVICE task degrades, never kills the job (reference:
                    # failure_log, AWSLambdaBackend.cc:410-474)
                    from ..utils.logging import get_logger

                    if CQ.deserialize_defect(e):
                        # the loads-but-cannot-run gap surfaced at the
                        # COLLECT site (async dispatch: nothing blocked
                        # between launch and fetch, e.g. devprof off).
                        # Pin the doomed specs + persist their .nodeser
                        # markers now so the retry below re-dispatches on
                        # a fresh in-process compile instead of the same
                        # defective executable
                        noted = getattr(device_fn, "note_async_defect",
                                        None)
                        if noted is not None and noted():
                            get_logger("exec").warning(
                                "deserialized executable failed at "
                                "collect (%s); recompiling in-process "
                                "before the retry", str(e)[:200])
                    self.failure_log.append({
                        "stage": skey[:16], "start_index": part.start_index,
                        "rows": part.num_rows, "attempt": 1,
                        "error": f"{type(e).__name__}: {e}",
                        "action": "retry"})
                    get_logger("exec").warning(
                        "partition task failed (%s: %s); retrying once",
                        type(e).__name__, e)
                    try:
                        _, outs2, d2 = self._dispatch_partition(
                            part, device_fn, skey, use_comp, stage,
                            packed=packed)
                        outp, excs, m = self._collect_partition(
                            stage, part, outs2, d2,
                            intermediate=intermediate)
                    except Exception as e2:
                        efn = self._elastic_stage_fn(stage, skey, in_schema)
                        outp = None
                        if efn is not None:
                            # elastic tier: the distributed dispatch is
                            # broken (lost device / wedged collective) —
                            # degrade to a non-mesh COMPILED fn for this
                            # and all later partitions of the stage
                            # (reference analog: Lambda re-invokes failed
                            # tasks on fresh workers)
                            self.failure_log.append({
                                "stage": skey[:16],
                                "start_index": part.start_index,
                                "rows": part.num_rows, "attempt": 2,
                                "error": f"{type(e2).__name__}: {e2}",
                                "action": "elastic"})
                            ekey = skey + "/elastic"
                            try:
                                _, outs3, d3 = self._dispatch_partition(
                                    part, efn, ekey, False, stage,
                                    packed=packed)
                                if outs3 is None:
                                    # elastic fn couldn't trace either:
                                    # demote the whole stage cleanly
                                    self._not_compilable.add(skey)
                                else:
                                    outp, excs, m = \
                                        self._collect_partition(
                                            stage, part, outs3, d3,
                                            intermediate=intermediate)
                                    # later partitions ride the elastic fn
                                    # UNDER ITS OWN bookkeeping key (the
                                    # mesh fn's traced-spec records must
                                    # not vouch for a different fn)
                                    device_fn, use_comp = efn, False
                                    skey = ekey
                                    # report the rung that actually fired
                                    # (the reduced-mesh tier logs an
                                    # 'elastic-mesh' entry; otherwise it
                                    # was the single-device fallback)
                                    rung = ("reduced-mesh execution"
                                            if any(r.get("action") ==
                                                   "elastic-mesh"
                                                   for r in
                                                   self.failure_log[-2:])
                                            else "single-device execution")
                                    get_logger("exec").warning(
                                        "mesh dispatch failed twice "
                                        "(%s: %s); stage degraded to %s",
                                        type(e2).__name__, e2, rung)
                            except Exception as e3:
                                self.failure_log.append({
                                    "stage": skey[:16],
                                    "start_index": part.start_index,
                                    "rows": part.num_rows, "attempt": 3,
                                    "error":
                                        f"{type(e3).__name__}: {e3}",
                                    "action": "elastic-failed"})
                                outp = None
                        if outp is None:
                            self.failure_log.append({
                                "stage": skey[:16],
                                "start_index": part.start_index,
                                "rows": part.num_rows, "attempt": 2,
                                "error": f"{type(e2).__name__}: {e2}",
                                "action": "interpreter"})
                            get_logger("exec").warning(
                                "retry failed (%s: %s); partition runs on "
                                "the interpreter", type(e2).__name__, e2)
                            outp, excs, m = self._collect_partition(
                                stage, part, None, 0.0,
                                intermediate=intermediate)
            finally:
                self.mm.unpin(part)
            self.mm.register(outp)
            metrics["fast_path_s"] += m.get("fast_path_s", 0.0)
            metrics["slow_path_s"] += m.get("slow_path_s", 0.0)
            metrics["general_path_s"] += m.get("general_path_s", 0.0)
            exceptions.extend(excs)
            if limit >= 0 and emitted_total + outp.num_rows > limit:
                outp = _truncate_partition(outp, limit - emitted_total)
            emitted_total += outp.num_rows
            out_parts.append(outp)
            if self.progress_cb is not None:
                try:    # live history event (webui liveness, VERDICT r3 #9)
                    self.progress_cb(len(out_parts), emitted_total)
                except Exception:
                    pass

        for part in stream:
            check_interrupted()
            if limit >= 0 and emitted_total >= limit:
                break
            if skey in self._not_compilable or tier == "interpreter":
                device_fn = None
            elif use_comp and stage.key() in self._compaction_off:
                # an earlier partition overflowed (or failed to trace) under
                # compaction: rebuild the plain fn instead of paying the
                # dispatch-then-redo cost for every remaining partition
                device_fn, use_comp = self._build_stage_fn(
                    stage, in_schema, skey, False, packed=packed,
                    force_cpu=(tier == "cpu"))
            self.mm.touch(part)
            try:
                window.append(self._dispatch_partition(part, device_fn,
                                                       skey, use_comp,
                                                       stage,
                                                       packed=packed))
            except Exception as e:
                # synchronous dispatch failure: enqueue for the collect
                # side's degrade ladder instead of killing the job
                window.append((part, _DispatchFailed(e), 0.0))
            if len(window) >= window_size:
                collect_one()
        while window:
            check_interrupted()
            collect_one()

        # per-stage compile seconds (JobMetrics.h discipline): whatever the
        # compile queue spent building THIS stage's executables — whether
        # inline at first dispatch or ahead-of-time on the pool — lands on
        # this stage's record; AOT/dedup hits cost 0 here by construction
        from . import compilequeue as _cq

        cs, cn = _cq.consume_tag(stage.key())
        metrics["compile_s"] += cs
        metrics["stage_compiles"] = cn
        # static-vetting attribution (compiler/graphlint): lint cost and
        # hazard verdicts for THIS stage — submission-time vetoes via the
        # queue's per-tag ledger, plan-time pre-degrades via the report
        # the planner left on the stage itself
        gl_ms, gl_found, gl_avoided = _cq.consume_graphlint(stage.key())
        rep = getattr(stage, "graph_report", None)
        if rep is not None:
            gl_ms += rep.elapsed_ms
        if getattr(stage, "hazard_rule", None):
            gl_found += 1
            gl_avoided += 1
            metrics["hazard_rule"] = stage.hazard_rule
        if gl_ms or gl_found:
            metrics["graphlint_ms"] = round(gl_ms, 3)
            metrics["hazards_found"] = gl_found
            metrics["hazards_avoided"] = gl_avoided
        # device-plane cost attribution (runtime/devprof): measured device
        # seconds, XLA flops/bytes/peak-memory and the roofline fraction
        # for THIS stage's dispatches, flat numeric keys riding the same
        # record compile_s does (bench JSON, history, Prometheus)
        try:
            # owner = this backend: concurrent serve jobs share stage
            # keys by design (isomorphic compile sharing) but must not
            # pool or steal each other's dispatch windows
            rep = DP.stage_report(stage.key(), mm_budget=self.mm.budget,
                                  owner=id(self))
            if rep:
                metrics.update(rep)
        except Exception:   # pragma: no cover - attribution best-effort
            pass
        # exception-plane accounting (runtime/excprof): rows seen, the
        # exception rate, unexpected-code rows and the per-tier retired
        # counts — flat numeric keys riding the same stage record
        try:
            exrep = EX.stage_report(stage.key(), owner=id(self))
            if exrep:
                metrics.update(exrep)
        except Exception:   # pragma: no cover - attribution best-effort
            pass
        # which tier this stage's rows ALL ran on (tier purity is the
        # contract the deadline-degrade restart enforces); task-failure
        # fallbacks within the ladder still show up in failure_log
        metrics["tier"] = {"device": "compiled", "cpu": "cpu-compiled",
                           "interpreter": "interpreter"}[tier]
        metrics["wall_s"] = time.perf_counter() - t0
        metrics["rows_out"] = emitted_total
        metrics["exception_rows"] = len(exceptions)
        # one failed task may log retry AND degrade entries: count tasks
        metrics["task_failures"] = sum(
            1 for e in self.failure_log[fl_snap:] if e.get("attempt") == 1)
        metrics.update(self.mm.metrics_delta(mm_snap))
        return StageResult(out_parts, exceptions, metrics)

    # ------------------------------------------------------------------
    def _attach_device_view(self, outp: C.Partition, pending_outs) -> None:
        """Keep a device-resident gathered view of this output partition so
        a downstream stage re-stages it without host copies + H2D (reference
        analog: hash intermediates passed by pointer as stage globals,
        LocalBackend.cc:903-908 — here the 'pointer' is a device buffer).
        Best-effort: any mismatch falls back to host staging."""
        try:
            from ..runtime.jaxcfg import jnp

            if not isinstance(pending_outs, dict):
                return   # packed results skip the device view (terminal path)
            expect = C.staged_keys(outp)
            if expect is None or not expect <= set(pending_outs):
                return
            m = outp.num_rows
            if m == 0:
                return
            b2 = C.bucket_size(m, self.bucket_mode)
            # charge the per-stage HBM budget BEFORE building the view: a
            # stage's whole output holds views until the next stage drains
            # them, so unbounded attachment would pin O(dataset) HBM
            est = b2 + sum(
                (pending_outs[k].nbytes // max(1, pending_outs[k].shape[0]))
                * b2 for k in expect)
            if est > getattr(self, "_handoff_left", 0):
                return
            self._handoff_left -= est
            src = np.zeros(b2, dtype=np.int32)
            src[:m] = outp._gather_src
            idx = jnp.asarray(src)
            arrays = {k: jnp.take(pending_outs[k], idx, axis=0)
                      for k in expect}
            rv = np.zeros(b2, dtype=np.bool_)
            rv[:m] = True
            arrays["#rowvalid"] = jnp.asarray(rv)
            arrays["#seed"] = C.partition_seed(outp)
            outp.device_batch = C.DeviceBatch(
                arrays=arrays, n=m, b=b2, schema=outp.schema)
        except Exception:   # pragma: no cover - purely an optimization
            outp.device_batch = None

    # ------------------------------------------------------------------
    def _lazy_merge(self, stage, part: C.Partition,
                    compiled_ok: np.ndarray, data_arrays: dict,
                    src_map: Optional[np.ndarray]) -> Optional[C.Partition]:
        """Fast-path merge that NEVER fetches the data columns: the output
        partition's host leaves are lazy (device-backed, materialized
        per-leaf only if some consumer needs host bytes) and a gathered
        device view feeds the next stage directly. Returns None when the
        layout can't go device-resident — the caller then runs the normal
        host merge. Best-effort by design: host semantics are identical
        either way."""
        try:
            import jax

            from ..plan.physical import runtime_output_columns
            from ..runtime import xferstats
            from ..runtime.jaxcfg import jnp

            if not data_arrays:
                return None
            comp_src = np.nonzero(compiled_ok)[0].astype(np.int64)
            m = int(comp_src.size)
            if src_map is not None:
                comp_src = src_map[comp_src]
            n_full = int(next(iter(data_arrays.values())).shape[0])
            if comp_src.size and int(comp_src.max()) >= n_full:
                return None
            # schema straight off the device arrays' keys/dtypes (no
            # transfer — type_from_result_arrays reads .dtype only)
            col_types = []
            while True:
                t = C.type_from_result_arrays(data_arrays,
                                              str(len(col_types)))
                if t is None:
                    break
                col_types.append(t)
            if not col_types:
                return None
            out_cols = runtime_output_columns(part.schema, stage.ops)
            names = tuple(out_cols) if out_cols \
                and len(out_cols) == len(col_types) \
                else tuple(f"_{i}" for i in range(len(col_types)))
            schema = T.row_of(names, col_types)
            leaf_types: dict[str, T.Type] = {}
            for ci, ct in enumerate(col_types):
                for pth, lt in C.flatten_type(ct, str(ci)):
                    leaf_types[pth] = lt
            expect: set = set()
            for pth in leaf_types:
                expect.update(C.result_keys_for_leaf(data_arrays, pth))
            if expect != set(data_arrays):
                return None      # keys the consumer wouldn't re-stage
            if m == 0:
                # fully-filtered partition: synthesize the empty output
                # straight from the arrays' dtypes — zero data bytes
                # cross the wire for a 0-row result
                arrs = {k: np.zeros((0,) + tuple(v.shape[1:]),
                                    np.dtype(v.dtype))
                        for k, v in data_arrays.items()}
                leaves = {pth: C.leaf_from_result_arrays(arrs, pth, lt, 0)
                          for pth, lt in leaf_types.items()}
                outp = C.Partition(schema=schema, num_rows=0,
                                   leaves=leaves,
                                   start_index=part.start_index)
                outp._gather_src = comp_src
                return outp
            # HBM budget: the raw outputs stay pinned until the lazy
            # leaves are dropped/forced, and the gathered view rides on
            # top — charge both against the per-stage cap
            b2 = C.bucket_size(m, self.bucket_mode)
            est = b2 + sum(
                (v.nbytes // max(1, int(v.shape[0]))) * b2
                for v in data_arrays.values())
            if est * 2 > getattr(self, "_handoff_left", 0):
                return None
            self._handoff_left -= est * 2

            src = np.zeros(b2, dtype=np.int32)
            src[:m] = comp_src
            idx = jnp.asarray(src)
            view = {k: jnp.take(data_arrays[k], idx, axis=0)
                    for k in expect}
            rv = np.zeros(b2, dtype=np.bool_)
            rv[:m] = True
            view["#rowvalid"] = jnp.asarray(rv)

            outp = C.Partition(schema=schema, num_rows=m, leaves={},
                               start_index=part.start_index)
            outp._gather_src = comp_src
            view["#seed"] = C.partition_seed(outp)
            gsrc = jnp.asarray(comp_src)

            def loader(pth):
                arrs = {}
                for k in C.result_keys_for_leaf(data_arrays, pth):
                    g = jnp.take(data_arrays[k], gsrc, axis=0)
                    h = np.asarray(jax.device_get(g))
                    xferstats.note_d2h(h.nbytes)
                    arrs[k] = h
                return C.leaf_from_result_arrays(arrs, pth,
                                                 leaf_types[pth], m)

            ll = C.LazyLeaves(leaf_types.keys(), loader, tag="stage")
            ll.nbytes_hint = est
            outp.leaves = ll
            outp.device_batch = C.DeviceBatch(arrays=view, n=m, b=b2,
                                              schema=schema)
            return outp
        except Exception:   # pragma: no cover - purely an optimization
            return None

    # ------------------------------------------------------------------
    def _elastic_stage_fn(self, stage, skey: str, in_schema):
        """Compiled fallback when the PRIMARY dispatch path is broken, or
        None (single-device backends have nothing between retry and the
        interpreter; the mesh backend degrades to a non-mesh executable)."""
        return None

    # ------------------------------------------------------------------
    def _next_tier(self, tier: str) -> str:
        """One rung down the stage-tier ladder after a compile deadline:
        device-compiled -> host-CPU-compiled (only where the host CPU is
        a DISTINCT backend — on a CPU default backend the same XLA:CPU
        compile would wedge again) -> interpreter."""
        if tier == "device" and type(self) is LocalBackend \
                and _cpu_device() is not None:
            from ..runtime.jaxcfg import jax as _jax

            if _jax.default_backend() != "cpu":
                return "cpu"
        return "interpreter"

    # ------------------------------------------------------------------
    def _build_stage_fn(self, stage, in_schema, skey: str, use_comp: bool,
                        packed: bool = True, force_cpu: bool = False):
        """Build + jit the fast-path fn. A build failure under compaction
        retries without it (an opt-in optimization must never demote the
        stage to the interpreter); only a plain build failure does that.
        ``force_cpu`` is the deadline-degrade 'cpu' tier: pin the compile
        to the host CPU backend regardless of the stage's plan-time
        ``cpu_compile`` flag (same mechanism as the split tuner's
        compile-budget degrade)."""
        cpu_pin = (force_cpu or getattr(stage, "cpu_compile", False)) and \
            _cpu_device() is not None
        if cpu_pin:
            from ..runtime.jaxcfg import jax as _jax

            cpu_pin = _jax.default_backend() != "cpu"
        while True:
            try:
                raw_fn = stage.build_device_fn(
                    in_schema, compaction=use_comp,
                    fused_fold=self.supports_fused_fold)
                if cpu_pin:
                    # compile-budget degrade (plan/splittuner) or the
                    # deadline-degrade 'cpu' tier: the stage compiles on
                    # the host CPU backend instead — device transfers
                    # still happen at the stage boundary, only the
                    # compute stays host-side. _CpuJit routes the compile
                    # through compilequeue.compile_traced (traced under
                    # the cpu pin), so it is counted into the stage's
                    # compile_s/stage_compiles, cached, reused — and
                    # still deadline-bounded (an XLA:CPU compile can
                    # wedge too; CompileTimeout propagates to the tier
                    # ladder's next rung).
                    deadline = self.options.get_float(
                        "tuplex.tpu.compileDeadlineS", 0.0)
                    return self.jit_cache.get_or_build(
                        ("stagefn", skey, use_comp, "cpupin"),
                        lambda: _CpuJit(raw_fn, tag=stage.key(),
                                        n_ops=len(stage.ops),
                                        deadline=deadline)), use_comp
                return self.jit_cache.get_or_build(
                    ("stagefn", skey, use_comp, packed),
                    lambda: self._jit_stage_fn(raw_fn, packed=packed,
                                               tag=stage.key(),
                                               n_ops=len(stage.ops))), \
                    use_comp
            except NotCompilable:
                self._not_compilable.add(skey)
                return None, use_comp
            except Exception as e:
                from ..utils.logging import get_logger

                if use_comp:
                    get_logger("exec").warning(
                        "stage build failed under compaction (%s: %s); "
                        "retrying without", type(e).__name__, e)
                    self._compaction_off.add(stage.key())
                    use_comp = False
                    continue
                get_logger("exec").warning(
                    "stage build failed (%s: %s); falling back to the "
                    "interpreter", type(e).__name__, e)
                self._not_compilable.add(skey)
                return None, use_comp

    # ------------------------------------------------------------------
    def _dispatch_partition(self, part: C.Partition, device_fn, skey: str,
                            use_comp: bool = False, stage=None,
                            packed: bool = True):
        """Stage the batch and launch the device call WITHOUT blocking
        (jax dispatch is async; the result is awaited in _collect_partition).
        Returns (part, pending_outs | None, dispatch_seconds)."""
        if device_fn is None or part.n_normal() == 0:
            return (part, None, 0.0)
        faults.maybe("dispatch")   # chaos checkpoint (runtime/faults): a
        # raise here rides the window as _DispatchFailed into the same
        # retry -> degrade ladder a real device failure takes
        t0 = time.perf_counter()
        with TR.span("partition:dispatch", "exec") as _sp:
            _sp.set("rows", part.num_rows).set("start", part.start_index)
            with TR.span("h2d:leaf-stage", "xfer") as _hsp:
                batch = C.stage_partition(part, self.bucket_mode)
                leaf_h2d = 0
                if not isinstance(device_fn, PackedStageFn):
                    # per-leaf staging: the jit call uploads the numpy
                    # arrays (packed dispatch notes its own single-buffer
                    # H2D; arrays already device-resident — the handoff
                    # view — cost 0). Counted AFTER the call succeeds — a
                    # first-call trace failure re-enters here via
                    # _redispatch_plain and would otherwise double-count
                    # an upload that never happened
                    leaf_h2d = sum(v.nbytes for v in batch.arrays.values()
                                   if isinstance(v, np.ndarray))
                _hsp.set("bytes", leaf_h2d)
            return self._dispatch_launch(part, device_fn, skey, use_comp,
                                         stage, packed, batch, t0,
                                         leaf_h2d=leaf_h2d)

    def _dispatch_launch(self, part, device_fn, skey, use_comp, stage,
                         packed, batch, t0, leaf_h2d: int = 0):
        # `packed` mirrors the build-cache key: a stage built in BOTH
        # variants (handoff toggled) must not let one variant's traced
        # specs vouch for the other — a first-call trace failure would
        # then raise instead of demoting to the interpreter (ADVICE r5)
        cache_key = ("stagefn", skey, use_comp, packed)
        spec = batch.spec()                     # jit retraces per shape
        first_call = not self.jit_cache.was_traced(cache_key, spec)
        try:
            # name formatted only when tracing is on — dispatch is the
            # per-partition hot path and the off-path must stay free.
            # The devprof gate is read ONCE: another thread flipping it
            # mid-dispatch (a new Context's apply_options) must not pair
            # a zero t_dev with a later record (a perf_counter-epoch
            # "sample" would poison the histograms and the tuner feed).
            dp_on = DP.enabled() and stage is not None
            t_dev = time.perf_counter() if dp_on else 0.0
            with TR.device_annotation(f"tpx:dispatch:{skey[:12]}"
                                      if TR.enabled() else ""):
                outs = device_fn(batch.arrays)
            # the async-return stamp: everything up to here is staging +
            # H2D + launch; the split tuner's BOUNDARY sample below must
            # use this, not a post-block stamp — with devprof on, the
            # block absorbs the stage's whole device execution and one
            # such sample persisted into the compile model would inflate
            # boundary_cost() ~1000x and weld every plan to k=1
            t_ret = time.perf_counter()
            if dp_on:
                # measured device time: wait for this dispatch's device
                # work (is_ready polling — see devprof.block_ready) and
                # record launch→ready per partition, cold (first call
                # spans the compile/AOT-load wait) vs warm. Costs the
                # dispatch/merge overlap — that is the price of
                # attribution; TUPLEX_DEVPROF=0 restores the fully-async
                # window with a single flag check here.
                DP.block_ready(outs)
                DP.record_dispatch(stage.key(),
                                   time.perf_counter() - t_dev,
                                   cold=first_call, rows=part.num_rows,
                                   owner=id(self))
            if leaf_h2d:
                xferstats.note_h2d(leaf_h2d, tag="leaf_stage")
            self.jit_cache.note_traced(cache_key, spec)
            if not first_call and stage is not None \
                    and stage.source is None \
                    and stage.key() not in self._boundary_sampled:
                # measured per-boundary dispatch tax (re-stage + H2D +
                # launch of a stage fed by a previous stage): one sample
                # per stage feeds the split tuner's boundary-cost side.
                # Only an ALREADY-TRACED spec qualifies (first_call spans
                # the inline XLA compile — minutes on the tunnel — and a
                # single poisoned sample would become the model's median,
                # steering the tuner back to mega-fused stages).
                self._boundary_sampled.add(stage.key())
                try:
                    from ..plan.splittuner import model_for

                    model_for().record_boundary(t_ret - t0)
                except Exception:
                    pass
        except NotCompilable:
            # surfaces at TRACE time (first call): drop compaction first if
            # it was on (it may be the culprit) and re-dispatch THIS
            # partition with the plain fn; only that failing too routes to
            # the interpreter
            if use_comp:
                return self._redispatch_plain(part, skey, stage, t0,
                                              packed=packed)
            self._not_compilable.add(skey)
            return (part, None, time.perf_counter() - t0)
        except CompileTimeout as e:
            # the executable's compile was killed at the deadline (or the
            # `.timeout` negative cache skipped it): NOT a per-partition
            # problem — ride the window as a sentinel so the collect side
            # restarts the WHOLE stage on one degraded tier
            return (part, _CompileTimedOut(e), time.perf_counter() - t0)
        except Exception as e:
            if not first_call:
                raise  # executed before: a real runtime failure
            from ..utils.logging import get_logger

            from . import compilequeue as CQ

            if CQ.deserialize_defect(e):
                # the fork-handback executable LOADED but its device
                # work failed when it actually ran — jax dispatch is
                # async, so the "Symbols not found" gap can surface at
                # the block/collect site, OUTSIDE AotJit.__call__'s
                # defect handler. Pin the doomed specs to the plain
                # in-process jit (persisting their `.nodeser` markers
                # for cold runs) and retry this partition once on the
                # recompiled path instead of demoting the stage to the
                # interpreter. A second failure finds nothing left to
                # pin and falls through to the normal degrade below.
                noted = getattr(device_fn, "note_async_defect", None)
                if noted is not None and noted():
                    get_logger("exec").warning(
                        "deserialized executable failed asynchronously "
                        "(%s); recompiling in-process and retrying the "
                        "dispatch", str(e)[:200])
                    return self._dispatch_partition(
                        part, device_fn, skey, use_comp=use_comp,
                        stage=stage, packed=packed)
            if use_comp:
                get_logger("exec").warning(
                    "stage trace failed under compaction (%s: %s); "
                    "disabling compaction for the stage",
                    type(e).__name__, e)
                return self._redispatch_plain(part, skey, stage, t0,
                                              packed=packed)
            get_logger("exec").warning(
                "stage trace failed (%s: %s); falling back to the "
                "interpreter", type(e).__name__, e)
            self._not_compilable.add(skey)
            return (part, None, time.perf_counter() - t0)
        return (part, outs, time.perf_counter() - t0)

    def _redispatch_plain(self, part: C.Partition, skey: str, stage, t0,
                          packed: bool = True):
        """Compaction couldn't trace: disable it for the stage and run the
        SAME partition through the plain compiled fn (an opt-in optimization
        must never demote work to the interpreter)."""
        self._compaction_off.add(skey.split("/", 1)[0])
        if stage is None:
            return (part, None, time.perf_counter() - t0)
        plain_fn, _ = self._build_stage_fn(stage, part.schema, skey, False,
                                           packed=packed)
        if plain_fn is None:
            return (part, None, time.perf_counter() - t0)
        res = self._dispatch_partition(part, plain_fn, skey, False, stage,
                                       packed=packed)
        return (res[0], res[1], time.perf_counter() - t0)

    # ------------------------------------------------------------------
    def _collect_partition(self, stage: TransformStage, part: C.Partition,
                           pending_outs, dispatch_s: float,
                           intermediate: bool = False):
        import jax

        metrics: dict[str, float] = {}
        n = part.num_rows
        # rows needing the interpreter: input fallback slots, plus device-err
        fallback_idx: set[int] = set(part.fallback.keys())
        compiled_ok = np.zeros(n, dtype=np.bool_)
        out_arrays: dict[str, np.ndarray] = {}

        # plan-time resolve-tier decision + per-code row buffers shaped by
        # the analyzer's exception inventory (plan/physical.ResolvePlan):
        # which tiers run, and which bucket each error row lands in, are
        # decided BEFORE the fetch instead of re-derived per row after D2H
        rplan = stage.resolve_plan()
        bufs = rplan.new_buffers() if pending_outs is not None else None

        # deferred exception-plane records (runtime/excprof): a device
        # failure inside this attempt (e.g. the general tier's compiled
        # re-run) aborts the whole collect and the task-failure ladder
        # re-runs the partition — accounting must only commit for the
        # attempt that succeeds, or the retry double-counts every row
        # into the stage stats and the drift windows
        ex_defer: list = []

        # device error evidence per fallback row: idx -> (code, operator id).
        # General-tier codes overwrite fast-path ones (supertype decode is
        # the authoritative python-semantics run).
        device_codes: dict[int, tuple[int, int]] = {}
        src_map = None
        device_outs = pending_outs     # arrays eligible for the device view
        lazy_data = None               # device-resident data columns (deferred)
        if pending_outs is not None:
            t0 = time.perf_counter()
            with TR.span("partition:collect-fast", "exec") as _sp:
                _sp.set("rows", n)
                if intermediate and isinstance(pending_outs, dict) \
                        and type(self) is LocalBackend:
                    # handoff-bound partition: pull ONLY the control arrays
                    # ('#err'/'#keep'/compaction/fold lattice — a few KB)
                    # and leave the data columns on device. They reach the
                    # host later only if a slow path actually needs them;
                    # the clean fast path hands them straight to the next
                    # consumer (this is the boundary that cost ~0.30 s of
                    # zillow's 0.73 s over the ~50 MB/s tunnel)
                    import jax

                    ctrl = {k: v for k, v in pending_outs.items()
                            if k.startswith("#")}
                    outs = {k: np.asarray(v)
                            for k, v in jax.device_get(ctrl).items()}
                    xferstats.note_d2h(
                        sum(v.nbytes for v in outs.values()),
                        tag="handoff_ctrl")
                    lazy_data = {k: v for k, v in pending_outs.items()
                                 if not k.startswith("#")}
                else:
                    outs = _get_outs(pending_outs)
            rowidx = outs.pop("#rowidx", None)
            ovf = outs.pop("#overflow", None)
            if rowidx is not None and bool(np.asarray(ovf)):
                # the sample under-estimated this filter's survivors and the
                # compaction bucket overflowed: results are unusable. Re-run
                # the partition without compaction and disable it for the
                # stage (reference analog: speculation failure -> general
                # path; here the failure is a SIZE speculation)
                from ..utils.logging import get_logger

                get_logger("exec").warning(
                    "compaction bucket overflow (stage %s); re-running "
                    "partition without compaction", stage.key()[:8])
                self._compaction_off.add(stage.key())
                packed = not intermediate   # keep the handoff's dict outs
                nkey = ("stagefn", stage.key() + "/" + part.schema.name,
                        False, packed)
                nfn = self.jit_cache.get_or_build(
                    nkey, lambda: self._jit_stage_fn(
                        stage.build_device_fn(part.schema,
                                              compaction=False),
                        packed=packed, tag=stage.key(),
                        n_ops=len(stage.ops)))
                batch = C.stage_partition(part, self.bucket_mode)
                pending2 = nfn(batch.arrays)
                outs = _get_outs(pending2)
                self.jit_cache.note_traced(nkey, batch.spec())
                outs.pop("#rowidx", None)
                outs.pop("#overflow", None)
                rowidx = None
                # the original compacted arrays overflowed and are garbage:
                # the device view must come from the re-run (and the
                # deferred-fetch fast path is off the table — the re-run
                # was fetched whole)
                device_outs = pending2
                lazy_data = None
            if rowidx is not None:
                # inverse map: original row i -> compact slot j (ascending
                # original order is preserved by compaction, so merge order
                # is unaffected)
                rowidx = np.asarray(rowidx)
                jpos = np.nonzero(rowidx < n)[0]
                src_map = np.full(n, -1, dtype=np.int64)
                src_map[rowidx[jpos]] = jpos
            metrics["fast_path_s"] = dispatch_s + time.perf_counter() - t0
            err = np.asarray(outs.pop("#err"))[:n]
            keep = np.asarray(outs.pop("#keep"))[:n]
            rowvalid = np.zeros(n, dtype=np.bool_)
            if part.normal_mask is None:
                rowvalid[:] = True
            else:
                rowvalid[:] = part.normal_mask
            err_rows = rowvalid & (err != 0)
            err_idx = np.nonzero(err_rows)[0]
            fallback_idx.update(err_idx.tolist())
            # packed lattice value: class code | operator id << 8. Read by
            # the no-resolver exact exit below AND the general-tier gate: a
            # row whose fast-path code is already an exact Python class
            # decoded fine under the normal case — the general re-run cannot
            # change its outcome, so it skips that tier either way.
            codes = err[err_idx]
            device_codes.update(
                zip(err_idx.tolist(), unpack_device_codes(codes)))
            bufs.add_many(err_idx, codes)
            if EX.enabled():
                # exception-plane unpack accounting (runtime/excprof):
                # the raw packed lattice carries code + operator id, so
                # per-stage x per-op x per-code counts come vectorized
                # off the same array the resolve buckets consumed
                ex_defer.append((EX.note_device, (stage.key(), n, codes),
                                 {"fallback_rows": len(part.fallback),
                                  "owner": id(self)}))
            compiled_ok = rowvalid & keep & (err == 0)
            fold_vals = []
            while f"#fold{len(fold_vals)}" in outs:
                fold_vals.append(outs.pop(f"#fold{len(fold_vals)}"))
            foldok = outs.pop("#foldok", None)
            out_arrays = {k: np.asarray(v) for k, v in outs.items()}
        else:
            # whole partition interpreted (UDF not compilable / forced /
            # no normal-case rows)
            metrics["fast_path_s"] = dispatch_s
            fallback_idx.update(range(n))
            if EX.enabled():
                ex_defer.append((EX.note_device, (stage.key(), n, None),
                                 {"fallback_rows": n, "owner": id(self)}))

        # ---- compiled general-case tier (ResolveTask resolve_f analog) ----
        # gated by the PLAN-time tier decision: when the inventory proves
        # the general tier can't retire anything (no widened decode in the
        # stage), the build attempt is skipped outright — it used to cost
        # one doomed NotCompilable trace per (stage, schema) to learn this
        resolved: dict[int, Row] = {}
        if fallback_idx and pending_outs is not None \
                and rplan.use_general and not self.interpret_only:
            t0 = time.perf_counter()
            n_before = len(fallback_idx)
            with TR.span("resolve:general", "exec") as _sp:
                _sp.set("rows", n_before)
                faults.maybe("resolve", point="general")   # chaos
                # checkpoint: a hang (delay=) INSIDE the span injects pure
                # resolve-path latency — the lever the latency-budget
                # acceptance uses to prove whyslow, the dashboard panel
                # and serve:slow-job all blame the same bucket
                # (runtime/critpath)
                self._general_case_pass(stage, part, fallback_idx, resolved,
                                        device_codes, buffers=bufs)
                _sp.set("resolved", len(resolved))
            dt = time.perf_counter() - t0
            metrics["general_path_s"] = dt
            if EX.enabled():
                ex_defer.append((EX.note_tier,
                                 (stage.key(), "general", n_before,
                                  n_before - len(fallback_idx), dt),
                                 {"owner": id(self)}))

        # ---- exact device exceptions (no-resolver fast exit) --------------
        # When the stage carries no resolver/ignore, a row whose device code
        # is an exact Python exception class (codes 1-9; internal/suspect
        # codes are >= 100) needs no interpreter re-run: class + operator
        # come straight off the lattice. The reference likewise emits
        # exception partitions from compiled code and only runs ResolveTask
        # when there is something to resolve.
        exc_by_row: dict[int, ExceptionRecord] = {}
        if fallback_idx and not stage.has_resolvers \
                and not self.interpret_only:
            if bufs is not None and not rplan.use_general:
                # the exact-class rows sit in their plan-time buckets
                # already — no per-row dict probe + class lookup here
                exact = [(i, op_id, code, exception_name(code))
                         for i, code, op_id in bufs.exact_rows()
                         if i in fallback_idx]
            else:
                # general tier ran: its verdicts superseded fast-path codes
                # in device_codes, so classify from there
                exact = []
                for i in sorted(fallback_idx):
                    code_op = device_codes.get(i)
                    if code_op is None:
                        continue
                    code, op_id = code_op
                    if exception_class_for_code(code) is not None:
                        exact.append((i, op_id, code,
                                      exception_name(code)))
            # decode a handful of rows so history previews stay informative;
            # counts only need the class name
            sample = {}
            if exact:
                sidx = [i for i, _, _, _ in exact[:5]]
                sample = dict(zip(sidx, C.decode_rows(part, sidx)))
            for i, op_id, code, name in exact:
                exc_by_row[i] = ExceptionRecord(op_id, name, sample.get(i))
                fallback_idx.discard(i)
            if EX.enabled() and exact:
                ex_defer.append((EX.note_outcomes,
                                 (stage.key(),
                                  [(code, op_id)
                                   for _, op_id, code, _ in exact],
                                  "exact-exit"), {"owner": id(self)}))
                for i, _op, code, _nm in exact[:5]:
                    if i in sample:
                        ex_defer.append((EX.sample_row,
                                         (stage.key(), code, sample[i]),
                                         {}))

        # ---- interpreter path (ResolveTask analog) ------------------------
        # one compiled closure chain per stage + bulk row decode: no per-row
        # op dispatch (reference: PythonPipelineBuilder.cc)
        t0 = time.perf_counter()
        if fallback_idx:
            with TR.span("resolve:interpreter", "exec") as _sp:
                _sp.set("rows", len(fallback_idx))
                pipeline = stage.python_pipeline(part.user_columns)
                order = sorted(fallback_idx)
                ex_on = EX.enabled()
                interp_pairs: list = []     # (final code, op_id) per row
                code_counts: dict = {}      # exc name -> n (span attr)
                n_exc = 0
                row_sample_budget = 16      # lock-taking sample_row calls
                # per partition (the per stage x code K-bound lives
                # inside excprof; this keeps a full-fallback partition
                # from probing the lock once per row)
                for i, row in zip(order, C.decode_rows(part, order)):
                    status, payload = pipeline(row)
                    if status == "ok":
                        resolved[i] = payload
                    elif status == "exc":
                        op_id, exc_name, value = payload[:3]
                        trace = payload[3] if len(payload) > 3 else None
                        exc_by_row[i] = ExceptionRecord(op_id, exc_name,
                                                        value, trace)
                        n_exc += 1
                        if ex_on:
                            code = EX.code_for_name(exc_name)
                            interp_pairs.append((code, op_id))
                            if row_sample_budget > 0:
                                row_sample_budget -= 1
                                ex_defer.append((EX.sample_row,
                                                 (stage.key(), code,
                                                  value), {}))
                            code_counts[exc_name] = \
                                code_counts.get(exc_name, 0) + 1
                        continue
                    if ex_on:
                        # retired on the interpreter (resolved or
                        # filtered): attribute the row's ORIGINAL device
                        # code to this tier — that is the code that fell
                        # all the way down
                        code, op_id = device_codes.get(
                            i, (int(ExceptionCode.PYTHON_FALLBACK), 0))
                        interp_pairs.append((code, op_id))
                        if row_sample_budget > 0:
                            # the INPUT row that fell to this tier even
                            # though it resolved — "why did row X reach
                            # the interpreter" from the dashboard
                            row_sample_budget -= 1
                            ex_defer.append((EX.sample_row,
                                             (stage.key(), code, row), {}))
                dt = time.perf_counter() - t0
                if ex_on:
                    ex_defer.append((EX.note_outcomes,
                                     (stage.key(), interp_pairs,
                                      "interpreter"), {"owner": id(self)}))
                    ex_defer.append((EX.note_tier,
                                     (stage.key(), "interpreter",
                                      len(order), len(order) - n_exc, dt),
                                     {"owner": id(self)}))
                if _sp is not TR.NOOP:
                    _sp.set("resolved", len(order) - n_exc)
                    if code_counts:
                        _sp.set("codes", ",".join(
                            f"{k}:{v}" for k, v in
                            sorted(code_counts.items())[:6]))
        exceptions = [exc_by_row[i] for i in sorted(exc_by_row)]
        metrics["slow_path_s"] = time.perf_counter() - t0

        outp = None
        with TR.span("partition:merge", "exec") as _msp:
            if lazy_data is not None and not resolved:
                # no python-spliced rows: the output partition can stay
                # device-resident end to end (lazy host leaves + gathered
                # view)
                outp = self._lazy_merge(stage, part, compiled_ok, lazy_data,
                                        src_map)
                _msp.set("lazy", outp is not None)
            if outp is None:
                if lazy_data is not None:
                    # a slow path touched this partition (or the lazy layout
                    # didn't qualify): pull the data columns after all
                    out_arrays = {k: np.asarray(v)
                                  for k, v in _get_outs(lazy_data).items()}
                outp = self._merge(stage, part, compiled_ok, out_arrays,
                                   resolved, src_map=src_map)
                if intermediate and device_outs is not None and not resolved \
                        and not outp.fallback \
                        and getattr(outp, "_gather_src", None) is not None:
                    self._attach_device_view(outp, device_outs)
            _msp.set("rows", outp.num_rows)
        if pending_outs is not None and fold_vals and foldok is not None \
                and not resolved and not outp.fallback \
                and getattr(stage, "fold_op", None) is not None:
            # fused aggregate partials are exact only when every output row
            # came off the device (python-resolved/boxed rows would be
            # missing from them)
            ok_np = np.asarray(foldok)[:n]
            badmask = compiled_ok & ~ok_np
            kept_rank = np.cumsum(compiled_ok) - 1
            outp.fold_partials = (
                stage.fold_op.id,
                tuple(v.item() for v in fold_vals),
                [int(r) for r in kept_rank[badmask]])
        # this attempt produced the partition's output: commit its
        # exception-plane records (a failure above left them unrecorded
        # for the task-failure ladder's re-run to record afresh)
        for fn, a, kw in ex_defer:
            fn(*a, **kw)
        return outp, exceptions, metrics

    # ------------------------------------------------------------------
    def _general_case_pass(self, stage: TransformStage, part: C.Partition,
                           fallback_idx: set, resolved: dict,
                           device_codes: Optional[dict] = None,
                           local_jit: bool = False,
                           buffers=None) -> None:
        """Compiled middle tier: re-run normal-case-violating rows through
        the stage fn traced under the GENERAL-CASE schema (Option/supertype
        widened decode). Rows it completes fold back like resolved python
        rows — but their compute stayed vectorized; only rows that STILL err
        reach the per-row interpreter (reference: StageBuilder.cc:1145
        generateResolveCodePath, ResolveTask.h resolve_f-before-interpreter).
        """
        import jax

        gkey = "general/" + stage.key() + "/" + part.schema.name \
            + ("/local" if local_jit else "")
        if gkey in self._not_compilable:
            return
        # input-boxed rows can't ride the columnar general path; rows whose
        # fast-path code is already an exact Python exception class decoded
        # fine under the normal case — a supertype re-run reproduces the
        # same exception, so they skip straight past this tier
        cand_info: dict[int, tuple] = {}   # idx -> (code, op_id) for the
        # exception-plane tier attribution (runtime/excprof)
        if buffers is not None:
            # plan-time buckets: the internal-coded candidate set was
            # grouped at D2H unpack, no per-row re-classification
            cand_info = {i: (code, op_id)
                         for i, code, op_id in buffers.internal_rows()
                         if i in fallback_idx and i not in part.fallback}
            cand = sorted(cand_info)
        else:
            dc = device_codes or {}
            cand = sorted(
                i for i in fallback_idx
                if i not in part.fallback
                and exception_class_for_code(dc.get(i, (0, 0))[0]) is None)
            cand_info = {i: dc.get(i, (0, 0)) for i in cand}
        if not cand:
            return
        # a small violation set on an accelerator backend resolves on the
        # HOST CPU executable instead: the fixed dispatch+transfer tax of
        # the device round-trip (~0.15 s on the tunneled TPU) dwarfs the
        # compute for a few thousand rows (reference contrast: resolve
        # tasks share the driver's threads, ResolveTask.h:31-98)
        host_resolve = (
            not local_jit and type(self) is LocalBackend
            and len(cand) <= self.options.get_int(
                "tuplex.tpu.hostResolveRows", 16384)
            and jax.default_backend() != "cpu" and _cpu_device() is not None)
        gckey = ("stagefn", gkey, "cpu") if host_resolve \
            else ("stagefn", gkey)
        try:
            # local_jit: the caller's rows are HOST-LOCAL (host-block
            # resolve) — the mesh dispatch would violate SPMD lockstep,
            # so build a plain single-host jit instead
            gfn = self.jit_cache.get_or_build(
                gckey,
                lambda: ((lambda f: _CpuJit(f, tag=stage.key()))
                         if host_resolve else
                         jax.jit if local_jit else
                         (lambda f: self._jit_stage_fn(
                             f, tag=stage.key())))(
                    stage.build_device_fn(part.schema, general=True)))
        except NotCompilable:
            self._not_compilable.add(gkey)
            return
        idx = np.asarray(cand, dtype=np.int64)
        k = len(idx)
        sub = C.gather_partition(part, np.arange(k, dtype=np.int64), idx, k)
        sub.fallback = {}
        sub.normal_mask = None
        batch = C.stage_partition(sub, self.bucket_mode)
        cache_key = gckey
        spec = batch.spec()
        first_call = not self.jit_cache.was_traced(cache_key, spec)
        try:
            outs = gfn(batch.arrays)
            self.jit_cache.note_traced(cache_key, spec)
        except Exception as e:
            if not first_call:
                raise
            from ..utils.logging import get_logger

            get_logger("exec").warning(
                "general-case trace failed (%s: %s); rows stay on the "
                "interpreter", type(e).__name__, e)
            self._not_compilable.add(gkey)
            return
        outs = _get_outs(outs)
        err = np.asarray(outs.pop("#err"))[:k]
        keep = np.asarray(outs.pop("#keep"))[:k]
        ok = err == 0
        if device_codes is not None and not stage.has_resolvers:
            # the general tier's verdict supersedes the fast path's: its
            # supertype decode removes normal-case artifacts
            bad_j = np.nonzero(~ok)[0]
            codes = err[bad_j]
            device_codes.update(
                zip(idx[bad_j].tolist(), unpack_device_codes(codes)))
        if not ok.any():
            return
        out_arrays = {kk: np.asarray(v) for kk, v in outs.items()}
        from ..plan.physical import runtime_output_columns

        out_cols = runtime_output_columns(part.schema, stage.ops)
        outp = C.partition_from_result_arrays(out_arrays, k,
                                              columns=out_cols)
        vals = C.partition_to_pylist(outp)
        cols = outp.user_columns
        single = len(outp.schema.types) == 1
        retired_pairs: list = []
        for j in range(k):
            if not ok[j]:
                continue
            i = int(idx[j])
            fallback_idx.discard(i)
            retired_pairs.append(cand_info.get(i, (0, 0)))
            if keep[j]:
                v = vals[j]
                resolved[i] = Row((v,), cols) if single else Row(v, cols)
            # else: filtered out on the general path — row emits nothing
        if retired_pairs and EX.enabled():
            # which codes the compiled general tier RETIRED (the
            # vectorized re-run absorbed them before the interpreter)
            EX.note_outcomes(stage.key(), retired_pairs, "general",
                             owner=id(self))

    # ------------------------------------------------------------------
    def _merge(self, stage: TransformStage, part: C.Partition,
               compiled_ok: np.ndarray, out_arrays: dict,
               resolved: dict[int, Row],
               src_map: np.ndarray | None = None) -> C.Partition:
        """Positional merge-in-order (reference: ResolveTask.cc:238-283).

        The output schema is derived from the ACTUAL device arrays (never the
        sample-speculated logical schema) so fast-path results can't be
        reinterpreted under a mismatched layout; with no compiled rows the
        resolved python rows are re-encoded from scratch."""
        n = part.num_rows
        if not resolved and out_arrays:
            # fast path (no python-resolved rows to splice): the emit set is
            # exactly the compiled_ok positions — skip the per-row loop
            # (0.3s/300k rows measured on TPC-H Q1)
            from ..plan.physical import runtime_output_columns

            comp_src = np.nonzero(compiled_ok)[0].astype(np.int64)
            m = int(comp_src.size)
            out_cols = runtime_output_columns(part.schema, stage.ops)
            n_full = n if src_map is None else \
                int(next(iter(out_arrays.values())).shape[0])
            full = C.partition_from_result_arrays(
                out_arrays, n_full, columns=out_cols,
                start_index=part.start_index)
            if src_map is not None and comp_src.size:
                comp_src = src_map[comp_src]
            outp = C.gather_partition(
                full, np.arange(m, dtype=np.int64), comp_src, m)
            outp._gather_src = comp_src   # device-view handoff indices
            return outp
        emit_rows: list[tuple[int, Optional[int], Optional[Row]]] = []
        # (orig_idx, compiled_src or None, resolved Row or None)
        for i in range(n):
            if i in resolved:
                emit_rows.append((i, None, resolved[i]))
            elif compiled_ok[i]:
                emit_rows.append((i, i, None))
        m = len(emit_rows)

        if not out_arrays:
            # interpreter-only: build straight from python rows. Schema
            # derives from the RUNTIME rows (their column names/types), not
            # sample speculation — projection/segmentation may have changed
            # the shape.
            values = [row.unwrap() if len(row.values) == 1
                      else tuple(row.values)
                      for (_, _, row) in emit_rows]
            rows_only = [row for (_, _, row) in emit_rows]
            schema = _schema_from_rows(rows_only) or \
                _normalized_output_schema(stage)
            outp = C.build_partition(values, schema,
                                     start_index=part.start_index)
            return outp

        from ..plan.physical import runtime_output_columns

        out_cols = runtime_output_columns(part.schema, stage.ops)
        n_full = n if src_map is None else \
            int(next(iter(out_arrays.values())).shape[0])
        full = C.partition_from_result_arrays(
            out_arrays, n_full, columns=out_cols,
            start_index=part.start_index)
        comp_out = np.asarray([k for k, (_, src, _) in enumerate(emit_rows)
                               if src is not None], dtype=np.int64)
        comp_src = np.asarray([src for (_, src, _) in emit_rows
                               if src is not None], dtype=np.int64)
        if src_map is not None and comp_src.size:
            # compacted device outputs: original position -> compact slot
            comp_src = src_map[comp_src]
        outp = C.gather_partition(full, comp_out, comp_src, m)
        out_schema = outp.schema

        res_ks = []
        res_vals = []
        for k, (_, src, row) in enumerate(emit_rows):
            if row is None:
                continue
            res_ks.append(k)
            res_vals.append(row.unwrap() if len(out_schema.columns) == 1
                            else tuple(row.values))
        if not res_ks:
            return outp
        if _bulk_fold_rows(outp.leaves, out_schema,
                           np.asarray(res_ks, dtype=np.int64), res_vals):
            return outp
        normal_mask = np.ones(m, dtype=np.bool_)
        fallback: dict[int, Any] = {}
        for k, value in zip(res_ks, res_vals):
            if _try_fold_row(outp.leaves, out_schema, k, value):
                continue
            normal_mask[k] = False
            fallback[k] = value
        if fallback:
            outp.normal_mask = normal_mask
            outp.fallback = fallback
        return outp


def _prefetch_iter(it, depth: int):
    """Producer-thread wrapper: source loading (Arrow read/decode) overlaps
    with device compute + merge (reference: Executor.h WorkQueue IO overlap;
    the interleaveIO analog). Bounded queue so memory stays capped."""
    import queue
    import threading

    q: "queue.Queue" = queue.Queue(maxsize=depth)
    stop = threading.Event()
    _END = object()
    # inherit the consumer thread's tenant scoping onto the producer:
    # span-stream tag (runtime/tracing) and counter scope (xferstats) are
    # THREAD-local, so source-load spans / ingest byte counters recorded
    # on this helper thread used to land untagged during serve — only
    # dispatch-path events were reliably tenant-tagged
    stream = TR.current_stream()
    scope = xferstats.current_scope()

    def put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def produce():
        if stream is not None:
            TR.set_stream(stream)
        if scope is not None:
            xferstats.set_scope(scope)
        try:
            for item in it:
                if not put(item):
                    return   # consumer stopped early (take-limit)
            put(_END)
        except BaseException as e:  # surface source errors on the consumer
            put(e)

    t = threading.Thread(target=produce, daemon=True,
                         name="tuplex-source-prefetch")
    t.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()   # unblock the producer if we exited early


def _schema_from_rows(rows: list[Row]) -> Optional[T.RowType]:
    """Normal-case schema speculated from actual interpreter-produced rows.

    Types from a bounded SAMPLE (speculation, like every other schema here):
    rows outside the sampled normal case are boxed by build_partition's
    fallback path, so a capped scan is safe and O(1) in dataset size."""
    rows = [r for r in rows if r is not None]
    if not rows:
        return None
    k = len(rows[0].values)
    if any(len(r.values) != k for r in rows):
        return None
    cols = rows[0].columns
    if cols is None or len(cols) != k:
        cols = tuple(f"_{i}" for i in range(k))
    sample = rows[:256]
    types = []
    for ci in range(k):
        nc, _, _ = T.normal_case_type([r.values[ci] for r in sample])
        if nc is T.UNKNOWN:
            return None
        types.append(nc)
    return T.row_of(cols, types)


def _normalized_output_schema(stage: TransformStage) -> T.RowType:
    """Logical output schema with the stage's user column names applied."""
    s = stage.output_schema
    cols = stage.output_columns
    if cols and len(cols) == len(s.types):
        return T.row_of(cols, s.types)
    return s


def _truncate_partition(p: C.Partition, k: int) -> C.Partition:
    if k >= p.num_rows:
        return p
    leaves = {}
    for path, leaf in p.leaves.items():
        if isinstance(leaf, C.NumericLeaf):
            leaves[path] = C.NumericLeaf(
                leaf.data[:k], None if leaf.valid is None else leaf.valid[:k])
        elif isinstance(leaf, C.StrLeaf):
            leaves[path] = C.StrLeaf(
                leaf.bytes[:k], leaf.lengths[:k],
                None if leaf.valid is None else leaf.valid[:k])
        elif isinstance(leaf, C.NullLeaf):
            leaves[path] = C.NullLeaf(k)
        else:
            leaves[path] = C.ObjectLeaf(leaf.values[:k])
    return C.Partition(
        schema=p.schema, num_rows=k, leaves=leaves,
        normal_mask=None if p.normal_mask is None else p.normal_mask[:k],
        fallback={i: v for i, v in p.fallback.items() if i < k},
        start_index=p.start_index)


def _bulk_fold_rows(leaves: dict, schema: T.RowType,
                    ks: "np.ndarray", values: list) -> bool:
    """All-or-nothing vectorized fold-back of resolved python rows into
    columnar slots. Returns False (writing nothing) when any value doesn't
    conform exactly — the caller then runs the per-row path, which handles
    partial conformance by boxing. ~5x cheaper than per-row _try_fold_row
    on dual-mode-heavy data (measured 0.57s/3.3k rows on flights)."""
    cols = schema.columns
    multi = len(cols) > 1
    rows = []
    for v in values:
        rt = v if multi else ((v,) if not (isinstance(v, tuple)
                                           and len(v) == 1) else v)
        if multi and not (isinstance(rt, tuple) and len(rt) == len(cols)):
            return False
        rows.append(rt)
    cols_cache: list = []
    bytes_cache: dict = {}
    for ci, ct in enumerate(schema.types):
        base = ct.without_option() if ct.is_optional() else ct
        if isinstance(base, T.TupleType):
            return False   # nested layouts: per-row path
        col = [r[ci] for r in rows]
        cols_cache.append(col)
        if not all(T.python_value_conforms(v, ct) for v in col):
            return False
        leaf = leaves[str(ci)]
        if isinstance(leaf, C.StrLeaf):
            bs = [b"" if v is None else v.encode("utf-8") for v in col]
            bytes_cache[ci] = bs
            if max(map(len, bs), default=0) > leaf.bytes.shape[1]:
                return False
        elif not isinstance(leaf, C.NumericLeaf):
            return False
    # every value conforms: write
    for ci, ct in enumerate(schema.types):
        leaf = leaves[str(ci)]
        col = cols_cache[ci]
        if isinstance(leaf, C.StrLeaf):
            bs = bytes_cache[ci]
            w = leaf.bytes.shape[1]
            block = np.zeros((len(bs), w), dtype=np.uint8)
            for j, b in enumerate(bs):
                if b:
                    block[j, : len(b)] = np.frombuffer(b, np.uint8)
            leaf.bytes[ks] = block
            leaf.lengths[ks] = np.fromiter(map(len, bs), np.int32,
                                           count=len(bs))
            if leaf.valid is not None:
                leaf.valid[ks] = np.fromiter(
                    (v is not None for v in col), np.bool_, count=len(col))
        else:
            if leaf.valid is not None:
                leaf.valid[ks] = np.fromiter(
                    (v is not None for v in col), np.bool_, count=len(col))
                leaf.data[ks] = np.asarray(
                    [0 if v is None else v for v in col], dtype=leaf.data.dtype)
            else:
                leaf.data[ks] = np.asarray(col, dtype=leaf.data.dtype)
    return True


def _try_fold_row(leaves: dict, schema: T.RowType, k: int, value: Any) -> bool:
    """Write a resolved python row into the columnar slots if it conforms."""
    multi = len(schema.columns) > 1
    row_tuple = value if multi else (value,)
    if multi and not (isinstance(row_tuple, tuple)
                      and len(row_tuple) == len(schema.columns)):
        return False
    if not multi and isinstance(value, tuple) and len(value) == 1:
        row_tuple = value
    for rv, ct in zip(row_tuple, schema.types):
        if not T.python_value_conforms(rv, ct):
            return False
    for ci, (ct, rv) in enumerate(zip(schema.types, row_tuple)):
        for p, lv in C._leaf_paths_for_value(str(ci), ct, rv):
            leaf = leaves[p]
            if isinstance(leaf, C.StrLeaf):
                b = lv.encode("utf-8") if lv is not None else b""
                if len(b) > leaf.bytes.shape[1]:
                    return False  # wider than the column: keep boxed
                leaf.bytes[k, :] = 0
                if b:
                    leaf.bytes[k, : len(b)] = np.frombuffer(b, np.uint8)
                leaf.lengths[k] = len(b)
                if leaf.valid is not None:
                    leaf.valid[k] = lv is not None
            elif isinstance(leaf, C.NumericLeaf):
                if leaf.valid is not None:
                    leaf.valid[k] = lv is not None
                    leaf.data[k] = 0 if lv is None else lv
                else:
                    leaf.data[k] = lv if not isinstance(lv, bool) or \
                        leaf.data.dtype == np.bool_ else int(lv)
    return True


# interpreter pipeline: see compiler/pypipeline.build_python_pipeline
# (PythonPipelineBuilder + ResolveTask analog), driven per stage above.
