"""Parallel + ahead-of-time stage compilation with content-addressed reuse.

The reference JITs a stage in milliseconds (TransformStage compile logged in
LocalBackend.cc:932-949; JobMetrics.h tracks compile seconds) because LLVM
codegen is local and cheap. Here a stage compile is an XLA compile — minutes
per stage over the remote TPU tunnel and superlinear in graph size — so the
compile pipeline itself needs engineering:

  * **trace != compile.** Tracing a stage fn to a jaxpr is milliseconds and
    pure; compiling the lowering is the expensive part. Every entry point
    here traces eagerly (cheap, and the canonical jaxpr is the content
    address) and treats the COMPILE as the cacheable/parallelizable unit.
  * **content addressing.** The fingerprint is a hash over the canonical
    jaxpr text, the trace-hoisted constant VALUES, the input avals, the
    effective platform (incl. the host-ISA tag for XLA:CPU artifacts), the
    donation spec and caller salts (packing flag, mesh epoch). Two stages
    that lower to the same jaxpr — flights' isomorphic join-probe segments,
    re-planned pipelines in a fresh process — share one executable.
  * **three stores.** (1) an in-process dict fingerprint -> executable (the
    isomorphic-stage dedup), (2) an on-disk artifact cache of serialized
    PJRT executables (cross-process AOT reuse: run 2 of a pipeline
    deserializes instead of compiling), (3) an in-flight table so a pool
    worker and a foreground dispatch never compile the same fingerprint
    twice concurrently.
  * **a compile pool.** Remote TPU compiles are I/O-bound on the tunnel;
    a small thread pool compiles all of a plan's stages concurrently and
    overlaps stage i+1's compile with stage i's execution (jax traces are
    thread-safe; XLA compiles release the GIL).

Everything is best-effort: any failure in the AOT machinery falls back to a
plain ``jax.jit`` so behavior (including NotCompilable propagation and the
local backend's trace-failure demotion ladder) is unchanged.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import queue
import threading
import time
from collections import OrderedDict
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FutureTimeout
from typing import Any, Optional

import numpy as np

from ..runtime import tracing as TR
from ..runtime import xferstats

# -- counters ---------------------------------------------------------------
# stage_compiles: actual lowered.compile() invocations (the expensive event;
#   the cross-process acceptance test asserts this is ZERO on a warm cache)
# aot_hits/aot_misses: on-disk artifact lookups
# dedup_hits: in-process fingerprint hits (isomorphic stages, re-dispatch)
# compile_s: summed wall seconds spent inside lowered.compile()
STATS: dict[str, Any] = {
    "stage_compiles": 0, "compile_s": 0.0,
    "aot_hits": 0, "aot_misses": 0, "aot_errors": 0,
    "dedup_hits": 0, "pool_jobs": 0, "traces": 0,
    "deadline_timeouts": 0, "deadline_skips": 0,
    "subprocess_compiles": 0, "compiles_killed": 0,
    "fork_deadlocks": 0,
    "nodeser_marks": 0, "nodeser_skips": 0,
    "background_compiles": 0,
    # pre-submission jaxpr vetting (compiler/graphlint): hazards_found =
    # fresh vetoes from a live analysis, hazards_avoided = every compile
    # the vet plane spared XLA (fresh vetoes + `.hazard` marker skips +
    # plan-time pre-degrades). compiles_killed staying at 0 while
    # hazards_avoided grows is the whole point: the wedge becomes a
    # prediction, not a survival story.
    "graphlint_ms": 0.0, "hazards_found": 0, "hazards_avoided": 0,
}

_LOCK = threading.Lock()
# fingerprint -> jax.stages.Compiled, LRU-bounded (TUPLEX_AOT_MEM_ENTRIES,
# default 256): an evicted executable's disk artifact remains, so a later
# request deserializes instead of recompiling — eviction costs a load, not
# a compile. Keeps a long-lived shell from pinning every executable the
# process ever built (the backend JitCache is bounded; this must be too).
_EXECS: "OrderedDict[str, Any]" = OrderedDict()
_PENDING: dict[str, Future] = {}     # fingerprint -> in-flight compile
_PENDING_T: dict[str, float] = {}    # fingerprint -> compile start (monotonic)
_TAG: dict[str, list] = {}           # tag -> [seconds, count] (unconsumed)
_POOL: Optional["_DaemonPool"] = None
_BG_POOL: Optional["_DaemonPool"] = None   # low-priority background lane
_BG_TLS = threading.local()          # background_lane() thread flag


def _mem_capacity() -> int:
    try:
        return max(8, int(os.environ.get("TUPLEX_AOT_MEM_ENTRIES", "256")))
    except ValueError:
        return 256


class CompileTimeout(Exception):
    """A stage compile exceeded the compile deadline (or a previous run's
    marker says it did). In fork-isolation mode the compile CHILD was
    SIGKILLed — nothing keeps burning — and the caller degrades the
    WHOLE stage to one slower tier (host-CPU compile or interpreter,
    exec/local's tier ladder) instead of wedging the job on a
    pathological XLA compile (observed: a 3-op / 2.2k-eqn string stage
    that XLA:CPU chews >20 min and >120 GB on)."""


class CompileHazard(CompileTimeout):
    """Static vetting (compiler/graphlint) vetoed this stage's compile
    BEFORE submission: the jaxpr matches a wedge-severity rule (or
    scores past ``tuplex.tpu.hazardThreshold``), so handing it to XLA
    would predictably burn the deadline and a SIGKILL. Subclassing
    CompileTimeout is deliberate — the veto rides the exact same
    whole-stage tier ladder (host-CPU compile → interpreter) the killed
    compile would have landed on, minus the kill. Unlike a plain
    CompileTimeout it must propagate even with the deadline disabled:
    falling back to an unbounded plain jit would re-introduce the very
    hang the veto predicts."""


_TIMEOUTS: set = set()               # fingerprints that timed out (process)


class _AotUnsupported(Exception):
    """The AOT plumbing itself is unavailable (e.g. a jax without
    jit().trace()) — callers fall back to a plain jit; never raised for a
    genuine trace error, which must propagate like jit's would."""


class _DaemonPool:
    """Minimal thread pool on DAEMON threads. concurrent.futures'
    ThreadPoolExecutor joins its (non-daemon) workers at interpreter exit,
    so queued speculative stage compiles — minutes each on the tunnel —
    would block a finished process from exiting. Speculative work must
    never outlive the job that asked for it: daemon workers die with the
    process, and pending queue items are simply dropped."""

    def __init__(self, workers: int, name: str = "tpx-compile"):
        self._q: "queue.Queue" = queue.Queue()
        for i in range(workers):
            t = threading.Thread(target=self._run, daemon=True,
                                 name=f"{name}-{i}")
            t.start()

    def _run(self) -> None:
        while True:
            fut, fn, args, kwargs, stream = self._q.get()
            if not fut.set_running_or_notify_cancel():
                continue
            # the submitter's span-stream tag (serve: the running job's
            # id) rides the queue item so compile/resolve-path spans
            # recorded on this pool thread stay tenant-tagged; workers
            # are reused, so the tag is always cleared afterwards
            if stream is not None:
                TR.set_stream(stream)
            try:
                fut.set_result(fn(*args, **kwargs))
            except BaseException as e:  # noqa: BLE001 - future carries it
                fut.set_exception(e)
            finally:
                if stream is not None:
                    TR.set_stream(None)

    def submit(self, fn, *args, **kwargs) -> Future:
        fut: Future = Future()
        self._q.put((fut, fn, args, kwargs, TR.current_stream()))
        return fut


def snapshot() -> dict:
    with _LOCK:
        return dict(STATS)


def delta(snap: dict) -> dict:
    with _LOCK:
        return {k: STATS[k] - snap.get(k, 0) for k in STATS}


def pending_info() -> dict:
    """In-flight compile pressure for telemetry/health: how many
    fingerprints are being compiled right now and the age of the OLDEST
    one (seconds). A compile that wedges XLA keeps its entry until it
    finishes or its owner abandons it, so a growing oldest age is the
    wedged-compile watchdog signal the health state machine reads
    (runtime/telemetry)."""
    now = time.monotonic()
    with _LOCK:
        oldest = min(_PENDING_T.values(), default=None)
        queued = _POOL._q.qsize() if _POOL is not None else 0
        bg_queued = _BG_POOL._q.qsize() if _BG_POOL is not None else 0
        return {
            "inflight": len(_PENDING),
            "inflight_oldest_age_seconds":
                (now - oldest) if oldest is not None else 0.0,
            "pool_queued": queued,
            "background_queued": bg_queued,
        }


def consume_tag(tag: str) -> tuple[float, int]:
    """Take (and reset) the compile seconds + count attributed to `tag`
    since the last consume — the per-stage ``compile_s`` metric. Pool
    compiles submitted during an earlier stage's window but tagged for a
    later stage land on the later stage's record (attribution follows the
    executable's owner, not the wall-clock window it compiled in)."""
    with _LOCK:
        s, n = _TAG.pop(tag, (0.0, 0))
        return s, n


def clear() -> None:
    """Drop the in-process executable store + counters (tests). Disk
    artifacts stay unless the cache dir itself is removed."""
    with _LOCK:
        _EXECS.clear()
        _TAG.clear()
        _NODESER.clear()        # the on-disk .nodeser markers remain
        _DESER.clear()
        for k in STATS:
            STATS[k] = type(STATS[k])()


def pool() -> "_DaemonPool":
    global _POOL
    with _LOCK:
        if _POOL is None:
            _POOL = _DaemonPool(_workers())
        return _POOL


# ---------------------------------------------------------------------------
# the background compile lane (serve/respec candidate compiles)
# ---------------------------------------------------------------------------
# Speculative RE-specialization compiles must never slow a paying job:
# they ride a separate low-priority pool (one daemon worker by default,
# TUPLEX_BG_COMPILE_WORKERS) so a foreground dispatch never finds its
# compile-queue slot occupied by a background candidate, and the
# foreground pool's queue never has a candidate ahead of a job's stage.
# The lanes still SHARE the content-addressed stores and the in-flight
# table: a foreground request for a fingerprint the background lane is
# already compiling joins that future instead of compiling twice — the
# one way background work may interact with foreground, because it only
# ever makes the foreground FASTER.


class background_lane:
    """Context manager: ``submit_compile`` calls made by this thread
    while inside route to the background pool. The flag is thread-local
    and does not propagate into the pool job itself (nested submits from
    a bg worker would deadlock a one-worker lane)."""

    def __enter__(self):
        _BG_TLS.active = getattr(_BG_TLS, "active", 0) + 1
        return self

    def __exit__(self, *exc):
        _BG_TLS.active = max(0, getattr(_BG_TLS, "active", 1) - 1)
        return False


def background_active() -> bool:
    return bool(getattr(_BG_TLS, "active", 0))


def _bg_workers() -> int:
    try:
        return max(1, int(os.environ.get("TUPLEX_BG_COMPILE_WORKERS", "1")))
    except ValueError:
        return 1


def bg_pool() -> "_DaemonPool":
    global _BG_POOL
    with _LOCK:
        if _BG_POOL is None:
            _BG_POOL = _DaemonPool(_bg_workers(), name="tpx-bgcompile")
        return _BG_POOL


def _workers() -> int:
    try:
        return max(1, int(os.environ.get("TUPLEX_COMPILE_WORKERS", "4")))
    except ValueError:
        return 4


def parallel_compile_enabled() -> bool:
    """Pool gate (README: parallel-compile env toggle). Remote compiles are
    I/O-bound on the tunnel, so the default worker count (4) exceeds the
    core count harmlessly. TUPLEX_PARALLEL_COMPILE=0 disables."""
    return os.environ.get("TUPLEX_PARALLEL_COMPILE", "1") != "0"


# ---------------------------------------------------------------------------
# fingerprinting
# ---------------------------------------------------------------------------

def _platform_salt() -> str:
    from ..runtime.jaxcfg import aot_platform_tag

    return aot_platform_tag()


def fingerprint_traced(traced, salt: str = "") -> str:
    """Content address of a traced stage fn: canonical jaxpr text (variable
    names are already canonical in jaxpr pretty-printing) + the VALUES of
    trace-hoisted constants (two stages with identical structure but a
    different captured lookup table must not share an executable) + input
    avals + platform/ISA/x64 + caller salt (donation, packing, mesh epoch).
    """
    h = hashlib.sha256()
    cj = traced.jaxpr                      # ClosedJaxpr
    h.update(str(cj.jaxpr).encode())
    for c in cj.consts:
        a = np.asarray(c)                  # device consts: one host fetch
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    for aval in getattr(traced, "in_avals", ()) or ():
        h.update(repr(aval).encode())
    # the OUTPUT pytree structure is not in the jaxpr (flat outputs) but
    # IS part of the executable's contract: two fns computing the same
    # values under different output dict keys must not share — the stored
    # out_tree would replay the wrong keys (silently mis-labeled columns)
    import jax

    out_info = getattr(traced, "out_info", None)
    if out_info is None:
        raise _AotUnsupported("traced.out_info unavailable")
    h.update(repr(jax.tree_util.tree_structure(out_info)).encode())
    h.update(_platform_salt().encode())
    h.update(salt.encode())
    return h.hexdigest()


def fingerprint_fn(fn, args: tuple, donate_argnums=(), salt: str = "") -> str:
    """Fingerprint a python fn against abstract args (compilestats / the
    isomorphic-dedup report use this without compiling anything)."""
    import jax

    traced = jax.jit(fn, donate_argnums=tuple(donate_argnums)).trace(*args)
    return fingerprint_traced(traced, salt=salt + f"/don{tuple(donate_argnums)}")


# ---------------------------------------------------------------------------
# on-disk artifact store
# ---------------------------------------------------------------------------

_ARTIFACT_VERSION = 1


def _artifact_path(fp: str) -> Optional[str]:
    from ..runtime.jaxcfg import aot_cache_dir

    d = aot_cache_dir()
    if not d:
        return None
    return os.path.join(d, fp + ".aot")


# ---------------------------------------------------------------------------
# condemnation markers (one helper for every negative-cache verdict)
# ---------------------------------------------------------------------------
# A marker is a small JSON verdict file next to (or content-addressed
# like) an AOT artifact: `.timeout` (compile blew the deadline),
# `.nodeser` (serialized executable cannot deserialize/run), the
# serve plane's `.respecquar` (quarantined re-specialization candidate,
# serve/respec.py) and `.hazard` (static vetting vetoed the compile
# BEFORE submission — compiler/graphlint — so later processes skip the
# analysis AND the compile). The first three used to be ad-hoc bare
# files; the shared
# helper records PROVENANCE — which defect class condemned the artifact,
# on which platform, when and why — and ``read_marker`` only honors a
# marker whose recorded kind matches the suffix it was found under, so a
# healthy artifact can never be condemned by a different defect class
# (a torn write, a buggy writer, a copied file). Markers written by
# earlier builds (bare platform text) still count for their own suffix.

MARKER_KINDS = ("timeout", "nodeser", "respecquar", "hazard")


def marker_path(base_path: str, kind: str) -> str:
    return base_path + "." + kind


def write_marker(base_path: Optional[str], kind: str, reason: str = "",
                 **prov) -> Optional[str]:
    """Persist one condemnation verdict (atomic; best-effort by the
    negative-cache contract). Returns the marker path or None when there
    is nowhere to write (no cache dir)."""
    if base_path is None:
        return None
    import json

    rec = {"kind": kind, "platform": _platform_salt(),
           "created": time.time(), "reason": str(reason)[:400]}
    rec.update(prov)
    path = marker_path(base_path, kind)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(rec, f)
        os.replace(tmp, path)
        return path
    except OSError:   # pragma: no cover - marker is best-effort
        return None


def read_marker(base_path: Optional[str], kind: str) -> Optional[dict]:
    """The verdict at ``base_path + '.' + kind``, or None when absent OR
    when the file's recorded kind contradicts the suffix (a different
    defect class must never condemn this artifact through a mislabeled
    file)."""
    if base_path is None:
        return None
    import json

    path = marker_path(base_path, kind)
    try:
        with open(path) as f:
            rec = json.load(f)
    except (OSError, ValueError):
        if not os.path.exists(path):
            return None
        # legacy marker (bare platform-salt text from earlier builds) or
        # torn write: the suffix it sits under still scopes it to ITS
        # kind, so it stands for that kind alone
        return {"kind": kind, "legacy": True}
    if not isinstance(rec, dict):
        return {"kind": kind, "legacy": True}
    if rec.get("kind") not in (None, kind):
        return None
    return rec


def _timeout_marker(fp: str):
    path = _artifact_path(fp)
    return None if path is None else path + ".timeout"


_NODESER: set = set()       # fingerprints with a known deserialize defect
_DESER: set = set()         # fps whose CURRENT _EXECS entry came from a
                            # deserialize (AOT disk hit / fork handback) —
                            # a fresh in-process compile discards the fp
                            # again. Provenance bound for the permanent
                            # .nodeser verdict: an async "Symbols not
                            # found" pins every live spec for safety, but
                            # only executables that actually rode the
                            # serialized-artifact path may durably mark
                            # their (possibly healthy) artifacts doomed


def _nodeser_marker(fp: str):
    path = _artifact_path(fp)
    return None if path is None else path + ".nodeser"


def _nodeser_known(fp: str) -> bool:
    """True when this fingerprint's serialized executable is known to be
    un-deserializable — it fails at LOAD, or loads but cannot RUN (both
    faces of the XLA:CPU "Symbols not found" gap) — in this process or,
    via the content-addressed on-disk marker, any earlier one. Cold runs
    then skip the doomed deserialize outright and compile in-process
    once, instead of paying load + failure + a recompile (the
    double-compile the ROADMAP residue names)."""
    if fp in _NODESER:
        return True
    return read_marker(_artifact_path(fp), "nodeser") is not None


def _note_nodeser(fp: str) -> None:
    """Record one fingerprint's deserialize defect: the in-process set
    plus the content-addressed on-disk ``.nodeser`` marker every later
    process consults before paying the doomed load."""
    with _LOCK:
        _NODESER.add(fp)
        STATS["nodeser_marks"] += 1
    write_marker(_artifact_path(fp), "nodeser",
                 reason="serialized executable cannot deserialize/run "
                        "(XLA 'Symbols not found' gap)", fp=fp)


def note_deserialize_defect(entry) -> None:
    """Persist the deserialize-defect verdict for the executable behind
    `entry` (the object AotJit/_CpuJit just watched fail with "Symbols
    not found"): drop it from the in-process store — later dedup hits
    would fail the same way — and write a ``.nodeser`` marker next to
    the artifact so every later process skips the load. The PERMANENT
    marker is provenance-bounded: only an entry that itself came off the
    serialized-artifact path may condemn its artifact — a fresh
    in-process compile swept up by a broad async pin
    (AotJit.note_async_defect covers every live spec) is dropped from
    the store but its perfectly good on-disk artifact stays loadable."""
    fps: list = []
    with _LOCK:
        for fp, c in list(_EXECS.items()):
            if c is entry:
                fps.append((fp, fp in _DESER))
                _EXECS.pop(fp, None)
    for fp, deserialized in fps:
        if deserialized:
            _note_nodeser(fp)


def _deadline_known_exceeded(fp: str) -> bool:
    """True when this fingerprint's compile already blew the deadline —
    in this process or (via the on-disk marker) any earlier one. A later
    SUCCESSFUL compile wins: the artifact is checked before the marker."""
    if fp in _TIMEOUTS:
        return True
    return read_marker(_artifact_path(fp), "timeout") is not None


def _note_deadline_exceeded(fp: str) -> None:
    _TIMEOUTS.add(fp)
    write_marker(_artifact_path(fp), "timeout",
                 reason="stage compile exceeded the deadline", fp=fp)


_HAZARDS: dict = {}          # fingerprint -> rule (this process)
_GL_TAG: dict = {}           # tag -> [lint_ms, hazards_found, hazards_avoided]


def _gl_tag_add(tag: str, ms: float = 0.0, found: int = 0,
                avoided: int = 0) -> None:
    with _LOCK:
        rec = _GL_TAG.setdefault(tag, [0.0, 0, 0])
        rec[0] += ms
        rec[1] += found
        rec[2] += avoided


def consume_graphlint(tag: str) -> tuple[float, int, int]:
    """Take (and reset) the static-vetting cost and hazard counts
    attributed to `tag` — the per-stage graphlint metrics, same
    attribution discipline as consume_tag()."""
    with _LOCK:
        ms, found, avoided = _GL_TAG.pop(tag, (0.0, 0, 0))
        return ms, found, avoided


def _graphlint_vet(traced, fp: str, tag: str, n_ops: int):
    """Pre-submission jaxpr vetting: runs compiler/graphlint over the
    REAL traced stage fn (the packed wrapper for packed dispatches —
    exactly what XLA would be handed) once per fingerprint. A wedge
    finding or a hazard score past ``tuplex.tpu.hazardThreshold`` writes
    the content-addressed ``.hazard`` marker and raises CompileHazard so
    the stage degrades tier-by-tier WITHOUT ever submitting the doomed
    compile. Returns the GraphReport (or None when the gate is off) for
    census-tagged tuner feedback. Called only when no artifact exists —
    an executable that compiled fine before outranks any static verdict,
    same contract as the `.timeout` negative cache."""
    from ..compiler import graphlint as GL

    if not GL.enabled():
        return None
    rule = _HAZARDS.get(fp)
    rec = None
    if rule is None:
        rec = read_marker(_artifact_path(fp), "hazard")
        if rec is not None:
            rule = rec.get("rule", "hazard")
    if rule is not None:
        with _LOCK:
            STATS["hazards_avoided"] += 1
        _gl_tag_add(tag, avoided=1)
        TR.instant("compile:hazard-skip", "compile",
                   {"tag": tag[:16], "fp": fp[:12], "rule": rule})
        raise CompileHazard(
            f"stage jaxpr previously vetoed by static vetting "
            f"(rule {rule}, {fp[:12]}…)")
    import jax

    report = GL.analyze(traced.jaxpr, n_ops=max(n_ops, 1),
                        platform=jax.default_backend())
    if report is None:
        return None
    with _LOCK:
        STATS["graphlint_ms"] += report.elapsed_ms
    _gl_tag_add(tag, ms=report.elapsed_ms)
    threshold = GL.hazard_threshold()
    if report.wedge or (threshold > 0
                        and report.hazard_score > threshold):
        rule = next((f.rule for f in report.findings
                     if f.severity == "wedge"), "hazard-threshold")
        detail = "; ".join(f.line() for f in report.findings
                           if f.severity == "wedge") or (
            f"hazard score {report.hazard_score:.1f}s > "
            f"threshold {threshold:.0f}s")
        _HAZARDS[fp] = rule
        write_marker(_artifact_path(fp), "hazard", reason=detail, fp=fp,
                     rule=rule, score=float(min(report.hazard_score,
                                                1e9)),
                     n_eqns=report.n_eqns, n_ops=report.n_ops)
        with _LOCK:
            STATS["hazards_found"] += 1
            STATS["hazards_avoided"] += 1
        _gl_tag_add(tag, found=1, avoided=1)
        TR.instant("compile:hazard-veto", "compile",
                   {"tag": tag[:16], "fp": fp[:12], "rule": rule,
                    "n_eqns": report.n_eqns})
        raise CompileHazard(
            f"static vetting vetoed the stage compile ({rule}: {detail})")
    return report


def _artifact_meta() -> dict:
    import jax

    return {"v": _ARTIFACT_VERSION, "platform": jax.default_backend(),
            "jax": jax.__version__, "created": time.time()}


def _disk_load(fp: str, path: Optional[str] = None):
    """Deserialize an AOT artifact, or None. A mismatched platform/jax
    version is a miss (prune_stale() reclaims such files). `path`
    overrides the content-addressed location (the subprocess-compile
    handback when no cache dir is configured)."""
    path = path if path is not None else _artifact_path(fp)
    if path is None or not os.path.exists(path):
        return None
    import jax
    from jax.experimental import serialize_executable as se

    with open(path, "rb") as f:
        rec = pickle.load(f)
    meta = rec.get("meta", {})
    if meta.get("v") != _ARTIFACT_VERSION \
            or meta.get("platform") != jax.default_backend() \
            or meta.get("jax") != jax.__version__:
        return None
    return se.deserialize_and_load(rec["payload"], rec["in_tree"],
                                   rec["out_tree"])


def _disk_store(fp: str, compiled, path: Optional[str] = None) -> None:
    path = path if path is not None else _artifact_path(fp)
    if path is None:
        return
    from jax.experimental import serialize_executable as se

    payload, in_tree, out_tree = se.serialize(compiled)
    rec = {"meta": _artifact_meta(), "payload": payload,
           "in_tree": in_tree, "out_tree": out_tree}
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        pickle.dump(rec, f)
    os.replace(tmp, path)                  # atomic vs concurrent writers


def prune_stale(cache_dir: Optional[str] = None) -> int:
    """Evict artifacts compiled for a different platform or jax version
    (a CPU artifact is useless — and on a different ISA dangerous — once
    the effective backend changes; fingerprints already partition them,
    this reclaims the disk). Returns the number of files removed."""
    import jax

    from ..runtime.jaxcfg import aot_cache_dir

    d = cache_dir or aot_cache_dir()
    if not d or not os.path.isdir(d):
        return 0
    removed = 0
    for name in os.listdir(d):
        if not name.endswith(".aot"):
            continue
        path = os.path.join(d, name)
        try:
            with open(path, "rb") as f:
                meta = pickle.load(f).get("meta", {})
            stale = meta.get("v") != _ARTIFACT_VERSION \
                or meta.get("platform") != jax.default_backend() \
                or meta.get("jax") != jax.__version__
        except Exception:
            stale = True                   # unreadable artifact: reclaim
        if stale:
            try:
                os.remove(path)
                removed += 1
            except OSError:
                pass
    return removed


# ---------------------------------------------------------------------------
# the compile core
# ---------------------------------------------------------------------------

def _compile_lowered(lowered):
    """The single expensive call — tests inject latency here to prove the
    pool actually runs compiles concurrently, and the fault harness
    (runtime/faults, TUPLEX_FAULTS="compile:...") injects hangs/raises
    here to prove a wedged compile is killed rather than waited out. In
    subprocess-isolation mode this body runs in the forked CHILD, so an
    injected hang is wedged exactly where a real XLA wedge would be."""
    from ..runtime import faults

    faults.maybe("compile")
    return lowered.compile()


# ---------------------------------------------------------------------------
# subprocess compile isolation
# ---------------------------------------------------------------------------
# A deadline is only honest if blowing it KILLS the work: abandoning a
# native XLA compile on a daemon thread leaves it burning CPU/RSS (the
# flights airport build-side wedge: >20 min, >120 GB on 3 ops) and can
# segfault interpreter teardown — which is why tuplex.tpu.compileDeadlineS
# shipped default-off for four PRs. Deadline-bearing compiles therefore
# run in a forked child: the parent traces and lowers (cheap, and the
# fingerprint needs the trace anyway), forks, and the child does the one
# expensive lowered.compile(), hands the executable back as a
# serialized-PJRT artifact through the content-addressed on-disk store,
# and _exits. A blown deadline SIGKILLs the child — the wedge dies WITH
# it — and the parent raises CompileTimeout into the normal whole-stage
# degrade ladder (exec/local: host-CPU compile or interpreter tier).
#
# Fork, not spawn: the lowered computation is not picklable (stage fns
# close over live plan state), while a forked child inherits it for
# free. The known risk — a lock held by another thread at fork time
# deadlocking the child — is covered by the same deadline that covers a
# real wedge: a deadlocked child is killed and the stage degrades.
# `auto` mode forks only on the CPU backend (forking a process that owns
# an accelerator client is undefined behavior in most PJRT plugins);
# accelerator backends keep the abandon-on-a-thread fallback.

_FORK_WARNED = False

# Forking while another thread sits inside native code (a jax trace or
# MLIR lower — both lock the shared MLIR context — an XLA compile, a
# PJRT executable (de)serialize) snapshots that thread's held C++ locks
# into the child, where no one will ever release them — the child
# deadlocks in lowered.compile() and burns its whole deadline before the
# kill (observed: a pool of 4 concurrent fork-compiles wedging one
# child on a futex). The gate serializes every fork() and every
# PARENT-side native phase of this module — trace, fingerprint (jaxpr
# pretty-print + const fetch), lower, artifact (de)serialize — so the
# fork snapshot is taken while compile-plane threads are only ever in
# Python-level waits. The forked CHILD inherits the gate in the held
# state and must never touch it (child code paths are gate-free).
# Residual risk (a non-compile thread inside native code at fork time,
# e.g. a serve dispatch executing a kernel) is covered by the deadline
# itself — the deadlocked child is killed and the stage degrades, which
# is the failure mode this layer exists to bound.
_FORK_GATE = threading.Lock()


def isolation_mode() -> str:
    """'fork' | 'thread' (TUPLEX_COMPILE_ISOLATION=auto|fork|thread;
    auto = fork on the CPU backend where os.fork exists)."""
    mode = os.environ.get("TUPLEX_COMPILE_ISOLATION", "auto").lower()
    if mode in ("thread", "0", "off", "none"):
        return "thread"
    if not hasattr(os, "fork"):
        return "thread"
    if mode == "fork":
        return "fork"
    try:
        import jax

        return "fork" if jax.default_backend() == "cpu" else "thread"
    except Exception:   # pragma: no cover - no jax backend yet
        return "thread"


# A forked child that snapshotted a foreign thread's held native lock
# deadlocks on a futex and STOPS accumulating cpu time (it may have
# burned a few seconds first — compiles can deadlock mid-flight); a
# genuinely wedged XLA compile (the thing the deadline exists for)
# burns cpu continuously for minutes. The distinction is readable from
# /proc/<pid>/stat, so the parent samples the child's cpu clock every
# second and kills a child that makes NO cpu progress for a whole grace
# window, then falls back to the in-thread compile — without writing a
# `.timeout` marker, because the compile itself was never the problem.
_DEADLOCK_GRACE_S = 5.0
_DEADLOCK_CPU_S = 0.2           # minimum cpu-seconds that count as
                                # progress between samples


def _child_cpu_s(pid: int):
    """The child's consumed cpu seconds (utime+stime), or None when
    /proc isn't available (non-Linux)."""
    try:
        with open(f"/proc/{pid}/stat") as f:
            fields = f.read().rsplit(") ", 1)[1].split()
        return (int(fields[11]) + int(fields[12])) \
            / os.sysconf("SC_CLK_TCK")
    except Exception:
        return None


def _kill_child(pid: int) -> None:
    import signal

    try:
        os.kill(pid, signal.SIGKILL)
    except OSError:     # already gone
        pass
    try:
        os.waitpid(pid, 0)          # reap — no zombie per killed compile
    except OSError:
        pass


def _compile_in_subprocess(fp: str, lowered, deadline_s: float,
                           n_ops: int):
    """Compile `lowered` in a killable forked child. Returns the compiled
    executable (deserialized from the artifact the child stored), None if
    the child failed for a non-deadline reason (caller falls back to the
    in-thread compile so the real error surfaces), or raises
    CompileTimeout after SIGKILLing a child that outlived the deadline."""
    path = _artifact_path(fp)
    ephemeral = None
    if path is None:                 # no cache dir: scratch handback file
        import tempfile

        ephemeral = os.path.join(
            tempfile.gettempdir(), f"tpx-aot-{os.getpid()}-{fp[:16]}.aot")
        path = ephemeral
    global _FORK_WARNED
    if not _FORK_WARNED:
        # jax warns on EVERY os.fork() from a threaded process; the
        # deadline is precisely the mitigation for the deadlock it warns
        # about (a deadlocked child is killed and the stage degrades), so
        # silence the repeat — once per process, message-scoped
        import warnings

        warnings.filterwarnings(
            "ignore", message=r".*os\.fork\(\) was called.*",
            category=RuntimeWarning)
        _FORK_WARNED = True
    with _FORK_GATE:
        t0 = time.perf_counter()   # deadline starts at the actual fork,
        pid = os.fork()            # not at the gate queue
    if pid == 0:
        # the child inherits _FORK_GATE in the HELD state (the parent
        # acquires it around fork()) — child code must never touch the
        # gate or any gated helper; _compile_lowered and the explicit-
        # path _disk_store below are gate-free by design
        code = 1
        try:
            # drop the inherited std fds FIRST: the child reports only
            # via its exit code, and an ORPHANED child (parent killed
            # mid-compile; a fork-deadlocked orphan can outlive it by
            # hours) holding the parent's stdout/stderr pipes keeps
            # every `cmd | consumer` harness waiting for EOF forever
            # (observed hanging a piped pytest run for 25 minutes)
            devnull = os.open(os.devnull, os.O_RDWR)
            for fd in (0, 1, 2):
                os.dup2(devnull, fd)
        except OSError:
            pass
        try:
            compiled = _compile_lowered(lowered)
            _disk_store(fp, compiled, path=path)
            code = 0
        except BaseException:        # noqa: BLE001 - child reports via rc
            code = 1
        finally:
            os._exit(code)           # no atexit/teardown in the child
    try:
        deadline = t0 + deadline_s if deadline_s and deadline_s > 0 \
            else None
        next_censor = t0 + _CENSOR_INTERVAL_S
        next_cpu_check = t0 + 1.0
        last_cpu = 0.0
        last_progress_t = t0
        while True:
            done, status = os.waitpid(pid, os.WNOHANG)
            if done:
                break
            now = time.perf_counter()
            if (deadline is None or now < deadline) \
                    and now >= next_cpu_check:
                next_cpu_check = now + 1.0
                cpu = _child_cpu_s(pid)
                if cpu is not None:
                    if cpu - last_cpu >= _DEADLOCK_CPU_S:
                        last_cpu = cpu
                        last_progress_t = now
                    elif now - last_progress_t >= _DEADLOCK_GRACE_S:
                        # cpu-stalled child = fork deadlock, not a
                        # wedge: kill it early and let the caller
                        # compile in-thread; no `.timeout` marker — the
                        # compile was never at fault
                        _kill_child(pid)
                        with _LOCK:
                            STATS["fork_deadlocks"] += 1
                        return None
            if deadline is not None and now >= deadline:
                _kill_child(pid)
                with _LOCK:
                    STATS["deadline_timeouts"] += 1
                    STATS["compiles_killed"] += 1
                _note_deadline_exceeded(fp)
                if n_ops > 0:
                    try:    # a killed compile still teaches the tuner
                        from ..plan.splittuner import model_for

                        model_for().record_running(n_ops, now - t0)
                    except Exception:
                        pass
                raise CompileTimeout(
                    f"stage compile exceeded the {deadline_s:g}s "
                    f"deadline ({fp[:12]}…); compile child killed")
            if n_ops > 0 and now >= next_censor:
                next_censor += _CENSOR_INTERVAL_S
                try:        # censored lower-bound obs, like the watchdog
                    from ..plan.splittuner import model_for

                    model_for().record_running(n_ops, now - t0)
                except Exception:
                    pass
            # fast compiles deserve a tight poll; long ones a cheap one
            time.sleep(min(0.05, max(0.002, (now - t0) / 20.0)))
        if not (os.WIFEXITED(status) and os.WEXITSTATUS(status) == 0):
            return None
        with _FORK_GATE:   # PJRT deserialize is native: see the gate
            try:
                return _disk_load(fp, path=path)
            except Exception as e:
                if not deserialize_defect(e):
                    raise
                # the child compiled fine but its serialized executable
                # cannot deserialize back into this parent (the XLA:CPU
                # "Symbols not found" gap at LOAD time). Persist the
                # `.nodeser` verdict — later calls and cold processes
                # then compile this fp in-process outright instead of
                # re-paying fork + doomed load — and return None: the
                # caller's in-thread fallback compiles inline, which is
                # deadline-safe (the finished child just proved this
                # compile terminates in time).
                _note_nodeser(fp)
                return None
    finally:
        if ephemeral is not None:
            try:
                os.remove(ephemeral)
            except OSError:
                pass


_CENSOR_INTERVAL_S = 60.0


def _compile_with_watchdog(lowered, n_ops: int):
    """Compile, and while the compile runs feed the split tuner CENSORED
    lower-bound observations (n_ops, seconds-so-far) every minute. A
    compile that wedges or is killed mid-flight — the flights 43-op
    XLA:CPU blowup ran >20 min before being killed — thereby still
    teaches the model it is expensive; finished compiles are exactly the
    ones the observation set would otherwise be biased toward."""
    if n_ops <= 0:
        return _compile_lowered(lowered)
    stop = threading.Event()
    t0 = time.perf_counter()

    def watch():
        while not stop.wait(_CENSOR_INTERVAL_S):
            try:
                from ..plan.splittuner import model_for

                model_for().record_running(
                    n_ops, time.perf_counter() - t0)
            except Exception:   # pragma: no cover - model is best-effort
                return

    t = threading.Thread(target=watch, daemon=True,
                         name="tpx-compile-watchdog")
    t.start()
    try:
        return _compile_lowered(lowered)
    finally:
        stop.set()


def _note_devprof(tag: str, fp: str, compiled) -> None:
    """Cost-attribution hook (runtime/devprof): harvest-or-recover XLA's
    cost/memory analysis for every executable that becomes visible here —
    fresh compiles, AOT disk hits, subprocess handbacks. Under the fork
    gate because cost_analysis()/memory_analysis() are native calls (see
    _FORK_GATE); best-effort by contract."""
    try:
        from ..runtime import devprof

        if devprof.enabled():
            with _FORK_GATE:
                devprof.note_compiled(tag, fp, compiled)
    except Exception:   # pragma: no cover - attribution is best-effort
        pass


def _note_compile(tag: str, dt: float, n_ops: int,
                  families: Optional[dict] = None) -> None:
    with _LOCK:
        STATS["stage_compiles"] += 1
        STATS["compile_s"] += dt
        rec = _TAG.setdefault(tag, [0.0, 0])
        rec[0] += dt
        rec[1] += 1
    xferstats.bump("stage_compiles", 1, tag=tag or None)
    if n_ops > 0:
        try:     # feed the measured point into the stage-split tuner curve
            from ..plan.splittuner import model_for

            # `families` (graphlint's primitive-family census of the
            # vetted jaxpr) rides along so the tuner can fit per-family
            # compile-cost terms alongside the op-count power law
            model_for().record_compile(n_ops, dt, families=families)
        except Exception:   # pragma: no cover - the model is best-effort
            pass


def default_deadline_s() -> float:
    """Hard ceiling on how long a dispatch will WAIT for one executable
    for callers that didn't pass one (tuplex.tpu.compileDeadlineS —
    default ON at 300 s — carries it down from the backend; env
    TUPLEX_COMPILE_DEADLINE_S for bare aot_jit users, default 0). The
    deadline became safe to default on once deadline-bearing compiles
    moved into a killable forked child (isolation_mode): a blown
    deadline SIGKILLs the compile instead of abandoning a native thread,
    and exec/local degrades the whole stage to ONE slower tier instead
    of splitting rows across compiled/interpreted mid-stage (the
    divergence that kept the old default off)."""
    try:
        return float(os.environ.get("TUPLEX_COMPILE_DEADLINE_S", "0"))
    except ValueError:
        return 0.0


def compile_traced(fn, args: tuple, donate_argnums=(), salt: str = "",
                   tag: str = "", n_ops: int = 0,
                   deadline_s: Optional[float] = None):
    """Trace `fn` against `args` (avals or concrete arrays) and return a
    compiled executable for it, via — in order — the in-process fingerprint
    store, the on-disk AOT artifact cache, or an actual XLA compile (counted,
    timed, tuner-fed, persisted to disk).

    Trace-time exceptions (NotCompilable, emitter rejections) propagate to
    the caller exactly as they would from ``jax.jit(fn)(args)`` — the local
    backend's first-call demotion ladder depends on that.
    """
    import jax

    from ..runtime.jaxcfg import aot_cache_enabled

    if deadline_s is None:
        deadline_s = default_deadline_s()
    donate = tuple(donate_argnums)
    jfn = jax.jit(fn, donate_argnums=donate)
    trace_m = getattr(jfn, "trace", None)
    if trace_m is None:     # jax without the AOT .trace() entry point
        raise _AotUnsupported("jax.jit(...).trace unavailable")
    # errors OUT of the trace itself (NotCompilable, emitter rejections)
    # propagate exactly as they would from jax.jit(fn)(*args) — the local
    # backend's first-call demotion ladder depends on that
    with TR.span("compile:trace", "compile") as _sp:
        _sp.set("tag", tag[:16])
        with _FORK_GATE:   # traces take the shared MLIR/C++ context
            traced = trace_m(*args)   # locks a fork must not snapshot
    with _LOCK:
        STATS["traces"] += 1
    try:
        with _FORK_GATE:   # jaxpr pretty-print + const fetch: native too
            fp = fingerprint_traced(traced, salt=salt + f"/don{donate}")
    except Exception:
        # content addressing unavailable for this trace (e.g. a const
        # that can't be fetched/hashed): compile without caching — still
        # counted and timed, never a behavior change
        t0 = time.perf_counter()
        with TR.span("compile:xla", "compile") as _sp:
            _sp.set("tag", tag[:16]).set("n_ops", n_ops) \
               .set("cache", "unaddressable")
            with _FORK_GATE:               # native lower: see the gate
                lowered = traced.lower()
            compiled = _compile_with_watchdog(lowered, n_ops)
        _note_compile(tag, time.perf_counter() - t0, n_ops)
        _note_devprof(tag, "", compiled)   # tag-only: no content address
        return compiled

    while True:
        with _LOCK:
            cached = _EXECS.get(fp)
            if cached is not None:
                _EXECS.move_to_end(fp)
                STATS["dedup_hits"] += 1
                fut = None
            else:
                fut = _PENDING.get(fp)
                if fut is None:
                    fut = Future()
                    _PENDING[fp] = fut
                    _PENDING_T[fp] = time.monotonic()
                    break
        if cached is not None:
            xferstats.bump("cache_hits", 1, tag="dedup")
            TR.instant("compile:cache-hit", "compile",
                       {"tag": tag[:16], "cache": "hit",
                        "store": "in-process", "fp": fp[:12]})
            try:     # dedup hit: the cost record exists; only the
                from ..runtime import devprof   # tag->fp edge is new

                devprof.note_tag(tag, fp)
            except Exception:   # pragma: no cover
                pass
            return cached
        try:            # someone else is compiling this very fingerprint
            with TR.span("compile:queue-wait", "compile") as _sp:
                _sp.set("tag", tag[:16]).set("join", "in-flight") \
                   .set("fp", fp[:12])
                joined = fut.result(
                    timeout=deadline_s if deadline_s else None)
            try:    # the owner's _publish noted ITS tag; the joiner's
                from ..runtime import devprof   # tag->fp edge is new

                devprof.note_tag(tag, fp)
            except Exception:   # pragma: no cover
                pass
            return joined
        except FutureTimeout:
            raise CompileTimeout(
                f"waited {deadline_s:.0f}s on an in-flight compile "
                f"({fp[:12]}…)") from None
        except Exception:
            continue    # their attempt failed; try to own it ourselves

    gl_report = None        # graphlint report of the vetted trace, if any

    def _publish(compiled):
        """Store a finished executable process-wide (+ disk happened in
        the job). Runs even when the waiting dispatch already gave up —
        a post-deadline completion still serves every later request."""
        with _LOCK:
            _EXECS[fp] = compiled
            _EXECS.move_to_end(fp)
            while len(_EXECS) > _mem_capacity():
                _EXECS.popitem(last=False)   # disk artifact remains
        # every executable that becomes dispatchable passes through here
        # (fresh compile, AOT disk hit, subprocess handback): the single
        # chokepoint where the cost-attribution layer sees it
        _note_devprof(tag, fp, compiled)
        return compiled

    def _compile_job():
        t0 = time.perf_counter()
        with TR.span("compile:lower", "compile") as _sp:
            _sp.set("tag", tag[:16])
            with _FORK_GATE:       # lowers are native code: see the gate
                lowered = traced.lower()
        with TR.span("compile:xla", "compile") as _sp:
            _sp.set("tag", tag[:16]).set("n_ops", n_ops) \
               .set("cache", "miss").set("fp", fp[:12])
            compiled = _compile_with_watchdog(lowered, n_ops)
        _note_compile(tag, time.perf_counter() - t0, n_ops,
                      families=gl_report.families if gl_report else None)
        if aot_cache_enabled():
            try:
                with _FORK_GATE:   # native serialize: see the gate
                    _disk_store(fp, compiled)
            except Exception:   # pragma: no cover - disk best-effort
                with _LOCK:
                    STATS["aot_errors"] += 1
        with _LOCK:
            _DESER.discard(fp)      # current entry is an in-process build
        return _publish(compiled)

    try:
        compiled = None
        if aot_cache_enabled() and _nodeser_known(fp):
            # negative cache for the deserialize-defect gap: this
            # fingerprint's artifact loads but cannot run ("Symbols not
            # found") — skip the doomed deserialize and compile fresh
            # in-process, once, instead of load + call-fail + recompile
            with _LOCK:
                STATS["nodeser_skips"] += 1
            TR.instant("compile:nodeser-skip", "compile",
                       {"tag": tag[:16], "fp": fp[:12]})
        elif aot_cache_enabled():
            try:
                with TR.span("compile:aot-load", "compile") as _sp:
                    _sp.set("tag", tag[:16]).set("fp", fp[:12])
                    with _FORK_GATE:   # native deserialize: see the gate
                        compiled = _disk_load(fp)
                    _sp.set("cache",
                            "aot-hit" if compiled is not None else "miss")
                if compiled is not None:
                    with _LOCK:
                        _DESER.add(fp)
            except Exception as e:
                compiled = None
                with _LOCK:
                    STATS["aot_errors"] += 1
                if deserialize_defect(e):
                    # doomed load at the aot leg: persist the verdict so
                    # this is the LAST process that pays it
                    _note_nodeser(fp)
            with _LOCK:
                STATS["aot_hits" if compiled is not None
                      else "aot_misses"] += 1
            xferstats.bump("cache_hits" if compiled is not None
                           else "cache_misses", 1, tag="aot")
            if compiled is not None:
                _publish(compiled)
        if compiled is None and deadline_s and deadline_s > 0 \
                and _deadline_known_exceeded(fp):
            # negative cache: this fingerprint's compile blew the deadline
            # before (this process or an earlier one's on-disk marker) and
            # no artifact ever appeared — route to the interpreter NOW
            # instead of re-burning the deadline every process. Gated on
            # the deadline being ENABLED: a run with the default (off)
            # config must compile normally — a stale marker from an
            # opted-in run must not force the interpreter on runs that
            # never opted in, and a successful unbounded compile then
            # lands the artifact that overrides the marker for everyone.
            with _LOCK:
                STATS["deadline_skips"] += 1
            raise CompileTimeout(
                f"compile of {fp[:12]}… previously exceeded the deadline")
        if compiled is None:
            # pre-submission static vetting (compiler/graphlint): runs on
            # every jaxpr XLA has never successfully compiled (an existing
            # artifact or in-process hit never reaches here). A veto
            # raises CompileHazard — same tier ladder as a killed
            # compile, zero kills.
            gl_report = _graphlint_vet(traced, fp, tag, n_ops)
        if compiled is None:
            if deadline_s and deadline_s > 0:
                # a known deserialize defect also rules out the FORK
                # path: its handback rides the same serialized-artifact
                # load that cannot work for this fp
                if isolation_mode() == "fork" and not _nodeser_known(fp):
                    # killable child: compile in a forked subprocess and
                    # hand the executable back through the on-disk
                    # artifact store; a blown deadline SIGKILLs the child
                    # (raising CompileTimeout from the helper) instead of
                    # abandoning a native thread
                    with TR.span("compile:lower", "compile") as _sp:
                        _sp.set("tag", tag[:16])
                        with _FORK_GATE:   # native lower: see the gate
                            lowered = traced.lower()
                    t0 = time.perf_counter()
                    with TR.span("compile:xla", "compile") as _sp:
                        _sp.set("tag", tag[:16]).set("n_ops", n_ops) \
                           .set("cache", "miss").set("fp", fp[:12]) \
                           .set("isolation", "subprocess")
                        compiled = _compile_in_subprocess(
                            fp, lowered, deadline_s, n_ops)
                    if compiled is not None:
                        _note_compile(tag, time.perf_counter() - t0,
                                      n_ops,
                                      families=gl_report.families
                                      if gl_report else None)
                        with _LOCK:
                            STATS["subprocess_compiles"] += 1
                            _DESER.add(fp)   # handback = deserialized
                        _publish(compiled)
                    # compiled None: the child died for a NON-deadline
                    # reason — fall through to the in-thread compile so
                    # the genuine error (an XLA rejection, a serializer
                    # gap) propagates exactly as it always did
                if compiled is None:
                    # abandon-on-a-thread fallback (no fork / accelerator
                    # backend / child failure): dedicated daemon thread
                    # (NOT the pool: a pool worker waiting on a nested
                    # pool job can deadlock the pool). A wedged compile
                    # keeps burning in background and publishes if it
                    # ever finishes, but the job moves on at the deadline
                    cfut: Future = Future()

                    def _runner():
                        try:
                            cfut.set_result(_compile_job())
                        except BaseException as e:  # noqa: BLE001
                            cfut.set_exception(e)

                    threading.Thread(target=_runner, daemon=True,
                                     name="tpx-compile-deadline").start()
                    try:
                        compiled = cfut.result(timeout=deadline_s)
                    except FutureTimeout:
                        _note_deadline_exceeded(fp)
                        with _LOCK:
                            STATS["deadline_timeouts"] += 1
                        raise CompileTimeout(
                            f"stage compile exceeded the "
                            f"{deadline_s:.0f}s deadline ({fp[:12]}…); "
                            f"falling back") from None
            else:
                compiled = _compile_job()
        with _LOCK:
            _PENDING.pop(fp, None)
            _PENDING_T.pop(fp, None)
        fut.set_result(compiled)
        return compiled
    except BaseException as e:
        with _LOCK:
            _PENDING.pop(fp, None)
            _PENDING_T.pop(fp, None)
        fut.set_exception(e)
        raise


def submit_compile(fn, args: tuple, donate_argnums=(), salt: str = "",
                   tag: str = "", n_ops: int = 0,
                   deadline_s=None) -> Future:
    """Queue a compile on the pool (ahead-of-time / overlapped with
    execution). Foreground dispatches of the same fingerprint join the
    in-flight future instead of compiling again. Inside a
    ``background_lane()`` the compile lands on the separate low-priority
    background pool instead — candidate re-specialization compiles never
    occupy a foreground slot or queue ahead of a job's stage compile."""
    bg = background_active()
    with _LOCK:
        STATS["pool_jobs"] += 1
        if bg:
            STATS["background_compiles"] += 1
    target = bg_pool() if bg else pool()
    if not TR.enabled():
        return target.submit(compile_traced, fn, args,
                             donate_argnums=donate_argnums, salt=salt,
                             tag=tag, n_ops=n_ops, deadline_s=deadline_s)

    t_sub = TR.now_us()

    def _pool_job():
        # the wait between submit and a worker picking the job up IS the
        # pool's queue pressure — record it as a real interval so a plan
        # whose compiles serialize behind each other shows the backlog
        TR.complete("compile:pool-queue-wait", "compile", t_sub,
                    TR.now_us() - t_sub,
                    {"tag": tag[:16], "lane": "bg" if bg else "fg"})
        return compile_traced(fn, args, donate_argnums=donate_argnums,
                              salt=salt, tag=tag, n_ops=n_ops,
                              deadline_s=deadline_s)

    return target.submit(_pool_job)


# ---------------------------------------------------------------------------
# the jit-compatible wrapper
# ---------------------------------------------------------------------------

def _leaf_aval(x):
    import jax

    return jax.ShapeDtypeStruct(np.shape(x), x.dtype)


def _args_avals(args: tuple):
    """Abstract (ShapeDtypeStruct) mirror of concrete call args, or None
    when a leaf has no array protocol (python scalar etc.) — such calls
    use the plain-jit fallback, whose weak-type semantics differ."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(args)
    if any(not hasattr(l, "dtype") for l in leaves):
        return None, None
    avals = jax.tree_util.tree_unflatten(
        treedef, [_leaf_aval(l) for l in leaves])
    key = (treedef, tuple((np.shape(l), str(l.dtype)) for l in leaves))
    return avals, key


_FALLBACK = object()


def deserialize_defect(e: BaseException) -> bool:
    """A deserialized PJRT executable that LOADED but cannot RUN — the
    known XLA:CPU gap where serialized executables of some fused kernels
    lose their jit-compiled symbol library ("Symbols not found: ...").
    Callers pin the affected spec to a plain in-process jit: correct,
    compiled, and — when the artifact came from the fork-isolation
    handback — safe to compile inline, because the killed-or-finished
    child already proved this compile terminates within the deadline."""
    return "Symbols not found" in str(e)


class AotJit:
    """Drop-in for ``jax.jit(fn)`` that routes per-input-spec compilation
    through the content-addressed store: dispatch never compiles an
    executable another stage (or another process) already built. Falls back
    to a plain jit on any AOT-machinery failure."""

    def __init__(self, fn, donate: bool = False, salt: str = "",
                 tag: str = "", n_ops: int = 0, deadline=None):
        self._fn = fn
        self._donate = (0,) if donate else ()
        self._salt = salt
        self._tag = tag
        self._n_ops = n_ops
        self._deadline = deadline
        self._by_spec: dict = {}
        self._jit = None

    def _plain(self):
        if self._jit is None:
            import jax

            self._jit = jax.jit(self._fn, donate_argnums=self._donate)
        return self._jit

    def __call__(self, *args):
        entry = None
        key = None
        try:
            avals, key = self._args_key(args)
        except Exception:
            avals = None
        if avals is not None:
            entry = self._by_spec.get(key)
            if entry is None:
                # trace-time errors must escape like jit's would; only the
                # compile/AOT plumbing itself may fall back
                try:
                    entry = compile_traced(
                        self._fn, avals, donate_argnums=self._donate,
                        salt=self._salt, tag=self._tag, n_ops=self._n_ops,
                        deadline_s=self._deadline)
                except _AotUnsupported:
                    entry = None
                self._by_spec[key] = entry if entry is not None else _FALLBACK
        if entry in (None, _FALLBACK):
            return self._plain()(*args)
        try:
            return entry(*args)
        except TypeError:
            # call-convention mismatch (aval/weak-type drift): pin this
            # spec to the plain jit, which retraces with jit's own rules
            self._by_spec[key] = _FALLBACK
            return self._plain()(*args)
        except Exception as e:
            if not deserialize_defect(e):
                raise
            # unloadable serialized executable (see deserialize_defect):
            # recompile this spec in-process via the plain jit instead of
            # demoting the stage to the interpreter; persist the verdict
            # so cold runs skip the doomed load (the `.nodeser` marker)
            note_deserialize_defect(entry)
            self._by_spec[key] = _FALLBACK
            return self._plain()(*args)

    def _args_key(self, args):
        avals, key = _args_avals(args)
        return avals, key

    def note_async_defect(self) -> bool:
        """The deserialize defect surfaced AFTER dispatch returned: jax
        dispatch is async, so a handback executable that loads-but-
        cannot-run may only fail when its device work actually executes
        — at the collect/block site, outside ``__call__``'s handler.
        Pin every live AOT entry to the plain in-process jit and persist
        their ``.nodeser`` verdicts. Returns True when something was
        pinned (the caller retries its dispatch once; a second failure
        finds nothing left to pin and degrades normally)."""
        hit = False
        for key, entry in list(self._by_spec.items()):
            if entry is not None and entry is not _FALLBACK:
                note_deserialize_defect(entry)
                self._by_spec[key] = _FALLBACK
                hit = True
        return hit


def aot_jit(fn, donate: bool = False, salt: str = "", tag: str = "",
            n_ops: int = 0, deadline=None):
    """The AOT-routed drop-in for ``jax.jit(fn)``; cached by the backend's
    JitCache exactly like a jit. Always the wrapper — disabling the disk
    cache (TUPLEX_AOT_CACHE=0) or the pool only turns those legs off,
    while compile counting, the in-process dedup store and the opt-in
    deadline keep working. TUPLEX_AOT_JIT=0 is the debugging escape hatch
    back to a bare jit (which silently drops all of the above)."""
    if os.environ.get("TUPLEX_AOT_JIT", "1") == "0":
        import jax

        return jax.jit(fn, donate_argnums=(0,) if donate else ())
    return AotJit(fn, donate=donate, salt=salt, tag=tag, n_ops=n_ops,
                  deadline=deadline)
