"""Multi-host pod bring-up helpers (reference analog:
python/tuplex/distributed.py:37-123 — the AWS one-time setup that creates
the IAM role, scratch bucket, and Lambda deployment before the first
distributed run; here the control plane is jax.distributed, so "setup"
means wiring N hosts to one coordinator and validating the pod).

On a real TPU pod slice, `jax.distributed.initialize()` auto-detects the
topology from the TPU metadata — `init_multihost()` with no arguments is
the whole setup. These helpers cover everything else: CPU/GPU clusters
(explicit coordinator), launch-plan generation for N hosts, and a
preflight that catches the classic bring-up mistakes before a job wedges
in a collective.
"""

from __future__ import annotations

import os
import socket
from typing import Optional

from ..utils.logging import get_logger

log = get_logger("deploy")


def default_coordinator(port: int = 8476) -> str:
    """Coordinator address for process 0: first non-loopback address of
    this host (the analog of the reference's default_scratch_dir
    convenience — a sane default the caller can override)."""
    host = socket.gethostname()
    try:
        addr = socket.gethostbyname(host)
        if addr.startswith("127."):
            # hostname resolves to loopback: derive the egress interface
            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            try:
                s.connect(("10.255.255.255", 1))
                addr = s.getsockname()[0]
            finally:
                s.close()
    except OSError:
        addr = "127.0.0.1"
    return f"{addr}:{port}"


def launch_plan(num_hosts: int, coordinator: Optional[str] = None,
                workdir: str = ".", backend: str = "multihost") -> list[str]:
    """One shell command per host that brings up the SPMD job — the
    operator-facing artifact the reference's setup prints for Lambda
    deployment. Every host runs the SAME driver script; only
    TUPLEX_PROCESS_ID differs."""
    coordinator = coordinator or default_coordinator()
    cmds = []
    for pid in range(num_hosts):
        cmds.append(
            f"cd {workdir} && "
            f"TUPLEX_COORDINATOR={coordinator} "
            f"TUPLEX_NUM_PROCESSES={num_hosts} "
            f"TUPLEX_PROCESS_ID={pid} "
            f"python -c 'from tuplex_tpu.exec.deploy import init_from_env; "
            f"init_from_env(); "
            f"# ... your pipeline (tuplex.backend={backend}) ...'"
            f"  # host {pid}")
    return cmds


def init_from_env() -> None:
    """Initialize jax.distributed from TUPLEX_COORDINATOR /
    TUPLEX_NUM_PROCESSES / TUPLEX_PROCESS_ID (set by launch_plan's
    commands), or auto-detect when none are set (TPU pod metadata)."""
    from .multihost import init_multihost

    coord = os.environ.get("TUPLEX_COORDINATOR")
    if coord is None:
        init_multihost()        # TPU pod: topology auto-detection
        return
    nproc = os.environ.get("TUPLEX_NUM_PROCESSES")
    pid = os.environ.get("TUPLEX_PROCESS_ID")
    # partial env is a configuration mistake worth naming precisely — a
    # raw KeyError would not say which knob is missing
    if (nproc is None) != (pid is None):
        raise RuntimeError(
            "set BOTH TUPLEX_NUM_PROCESSES and TUPLEX_PROCESS_ID with "
            "TUPLEX_COORDINATOR (or none of the three for pod "
            "auto-detection)")
    init_multihost(coord,
                   None if nproc is None else int(nproc),
                   None if pid is None else int(pid))


def preflight(expected_processes: Optional[int] = None,
              expected_devices_per_process: Optional[int] = None) -> dict:
    """Post-init sanity report (raises on the classic bring-up mistakes).
    Call AFTER init_from_env()/init_multihost() on every host."""
    import jax

    info = {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
        "platform": jax.devices()[0].platform,
    }
    if expected_processes is not None and \
            info["process_count"] != expected_processes:
        raise RuntimeError(
            f"pod has {info['process_count']} processes, expected "
            f"{expected_processes} — a host failed to join the coordinator")
    if expected_devices_per_process is not None and \
            info["local_devices"] != expected_devices_per_process:
        raise RuntimeError(
            f"process {info['process_index']} sees "
            f"{info['local_devices']} local devices, expected "
            f"{expected_devices_per_process}")
    if info["global_devices"] != \
            info["local_devices"] * info["process_count"]:
        log.warning("uneven device/process split: %d global, %d local x %d "
                    "processes", info["global_devices"],
                    info["local_devices"], info["process_count"])
    return info
