"""CSV output (reference: FileOutputOperator + buildWithCSVRowWriter,
core/include/physical/PipelineBuilder.h:238 — rows stream to the file from
the compiled pipeline, never boxed into the driver language).

`write_partitions_csv` streams columnar partitions straight into Arrow's CSV
writer: numeric leaves wrap as Arrow arrays zero-copy, string leaves pack
their byte matrices into Arrow string buffers with vectorized numpy — no
python tuple ever materializes for normal-case rows. Partitions carrying
boxed fallback rows (rare) fall back to python formatting to keep row order
exact. Remote URIs stream through the VFS backends."""

from __future__ import annotations

import csv
import os
from typing import Optional, Sequence

import numpy as np

from ..core import typesys as T
from ..runtime import columns as C
from .vfs import VirtualFileSystem


def _resolve_path(path: str) -> str:
    if VirtualFileSystem._scheme(path) != "file":
        return path
    p = VirtualFileSystem._strip(path)
    if path.endswith("/") or os.path.isdir(p):
        os.makedirs(p, exist_ok=True)
        return os.path.join(p, "part0.csv")
    parent = os.path.dirname(p)
    if parent:
        os.makedirs(parent, exist_ok=True)
    return p


def write_csv(path: str, rows: list, columns: Optional[Sequence[str]] = None,
              delimiter: str = ",") -> None:
    """Boxed-row writer (small results / compatibility path)."""
    path = _resolve_path(path)
    with VirtualFileSystem.open_write(path) as bp:
        import io as _io

        fp = _io.TextIOWrapper(bp, newline="", encoding="utf-8")
        w = csv.writer(fp, delimiter=delimiter)
        if columns:
            w.writerow(columns)
        for r in rows:
            w.writerow(list(r) if isinstance(r, tuple) else [r])
        fp.flush()
        fp.detach()


def _leaf_to_arrow(part: C.Partition, ci: int, ct: T.Type):
    """One output column as an Arrow array, built WITHOUT boxing; None if
    the column shape needs the python path (nested tuples etc.)."""
    import pyarrow as pa

    base = ct.without_option() if ct.is_optional() else ct
    n = part.num_rows
    if isinstance(base, T.TupleType) or base is T.EMPTYTUPLE:
        return None
    leaf = part.leaves.get(str(ci))
    if isinstance(leaf, C.NumericLeaf):
        mask = None if leaf.valid is None else ~leaf.valid[:n]
        data = np.asarray(leaf.data[:n])
        if data.dtype == np.bool_:
            # python's csv writer renders True/False; Arrow writes
            # true/false — keep one casing across both paths
            svals = np.where(data, "True", "False")
            return pa.array(svals, mask=mask)
        return pa.array(data, mask=mask)
    if isinstance(leaf, C.StrLeaf):
        lens = leaf.lengths[:n].astype(np.int64)
        inside = np.arange(leaf.bytes.shape[1])[None, :] < lens[:, None]
        flat = np.ascontiguousarray(leaf.bytes[:n])[inside]
        offsets = np.zeros(n + 1, dtype=np.int32)
        np.cumsum(lens, out=offsets[1:])
        arr = pa.StringArray.from_buffers(
            n, pa.py_buffer(offsets.tobytes()), pa.py_buffer(flat.tobytes()))
        if leaf.valid is not None:
            import pyarrow.compute as pc

            arr = pc.if_else(pa.array(leaf.valid[:n]), arr,
                             pa.scalar(None, pa.string()))
        return arr
    if isinstance(leaf, C.NullLeaf):
        return pa.nulls(n)
    return None


def write_partitions_csv(path: str, partitions: list,
                         columns: Optional[Sequence[str]] = None,
                         delimiter: str = ",", backend=None) -> None:
    """Stream partitions to ONE csv file without materializing python rows."""
    import pyarrow as pa
    import pyarrow.csv as pacsv

    import io as _io

    def header_bytes(cols) -> bytes:
        txt = _io.StringIO()
        csv.writer(txt, delimiter=delimiter,
                   lineterminator="\r\n").writerow(list(cols))
        return txt.getvalue().encode("utf-8")

    path = _resolve_path(path)
    opts = pacsv.WriteOptions(include_header=False, delimiter=delimiter)
    with VirtualFileSystem.open_write(path) as sink:
        header_written = False
        if columns:
            # known upfront: empty results still get a header-only file
            sink.write(header_bytes(columns))
            header_written = True
        for part in partitions:
            if backend is not None:
                backend.mm.touch(part)
            if part.num_rows == 0:
                continue
            cols = columns or part.user_columns or \
                [f"_{i}" for i in range(len(part.schema.types))]
            if not header_written:
                header_written = True
                sink.write(header_bytes(cols))
            arrays = None
            if not part.fallback:
                arrays = [_leaf_to_arrow(part, ci, ct)
                          for ci, ct in enumerate(part.schema.types)]
                if any(a is None for a in arrays):
                    arrays = None
            if arrays is None:
                # boxed / nested partitions (rare): python formatting keeps
                # row order exact
                txt = _io.StringIO()
                w = csv.writer(txt, delimiter=delimiter,
                               lineterminator="\r\n")
                for r in C.partition_to_pylist(part):
                    w.writerow(list(r) if isinstance(r, tuple) else [r])
                sink.write(txt.getvalue().encode("utf-8"))
                continue
            table = pa.table(dict(zip([str(i) for i in range(len(arrays))],
                                      arrays)))
            buf = pa.BufferOutputStream()
            pacsv.write_csv(table, buf, opts)
            sink.write(buf.getvalue().to_pybytes())
