"""CSV output (reference: FileOutputOperator + buildWithCSVRowWriter,
core/include/physical/PipelineBuilder.h:238)."""

from __future__ import annotations

import csv
import os
from typing import Optional, Sequence


def write_csv(path: str, rows: list, columns: Optional[Sequence[str]] = None,
              delimiter: str = ",") -> None:
    if path.endswith("/") or os.path.isdir(path):
        os.makedirs(path, exist_ok=True)
        path = os.path.join(path, "part0.csv")
    else:
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
    with open(path, "w", newline="") as fp:
        w = csv.writer(fp, delimiter=delimiter)
        if columns:
            w.writerow(columns)
        for r in rows:
            w.writerow(list(r) if isinstance(r, tuple) else [r])
